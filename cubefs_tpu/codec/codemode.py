"""Codemode registry: declarative EC layouts and stripe geometry.

Mirrors the reference's public codemode surface (blobstore/common/
codemode/codemode.go:29-87 constants and Tactic fields; stripe geometry
helpers GetECLayoutByAZ/LocalStripeInAZ/GlobalStripe at codemode.go:
301-380) so a reference user finds the same modes, quorums and layouts.
The values are the protocol constants of the system, not code.
"""

from __future__ import annotations

import enum
import math
from dataclasses import dataclass

ALIGN_0B = 0
ALIGN_512B = 512
ALIGN_2KB = 2048


class CodeMode(enum.IntEnum):
    EC15P12 = 1
    EC6P6 = 2
    EC16P20L2 = 3
    EC6P10L2 = 4
    EC6P3L3 = 5
    EC6P6Align0 = 6
    EC6P6Align512 = 7
    EC4P4L2 = 8
    EC12P4 = 9
    EC16P4 = 10
    EC3P3 = 11
    EC10P4 = 12
    EC6P3 = 13
    EC12P9 = 14
    EC24P8 = 15
    EC6P6MSR = 16
    EC6P6MSROneAZ = 17
    Replica3 = 100
    Replica3OneAZ = 101
    # test-only modes
    EC6P6L9 = 200
    EC6P8L10 = 201
    Replica4TwoAZ = 202
    EC4P4MSR = 203


@dataclass(frozen=True)
class Tactic:
    """Constant strategy of one CodeMode: N data / M global parity /
    L local parity shards over az_count AZs; put_quorum must keep data
    recoverable with one AZ down (ignoring local shards).

    scheme selects the code family: "rs" (Reed-Solomon / LRC) or "msr"
    (product-matrix MSR regenerating code, ops/msr.py). MSR tactics
    carry d — the helper count of a single-shard repair: each helper
    ships one beta = S/alpha sub-shard (alpha = d-n+1) instead of its
    full shard, cutting repair traffic n*alpha/d-fold."""

    n: int
    m: int
    l: int = 0
    az_count: int = 1
    put_quorum: int = 0
    get_quorum: int = 0
    min_shard_size: int = 0
    scheme: str = "rs"
    d: int = 0

    def __post_init__(self):
        # ec_layout_by_az slices with integer division: a shard count
        # not divisible by az_count would silently drop shards from
        # every stripe map, so reject the geometry at construction
        if self.az_count < 1:
            raise ValueError(f"az_count must be >= 1, got {self.az_count}")
        for name, v in (("n", self.n), ("m", self.m), ("l", self.l)):
            if v % self.az_count:
                raise ValueError(
                    f"Tactic {name}={v} is not divisible by "
                    f"az_count={self.az_count}: ec_layout_by_az would "
                    f"silently truncate shards")
        if self.scheme not in ("rs", "msr"):
            raise ValueError(f"unknown code scheme {self.scheme!r}")
        if self.scheme == "rs":
            if self.d:
                raise ValueError("d (helper count) is only meaningful "
                                 "for scheme='msr'")
            return
        self._validate_msr()

    def _validate_msr(self) -> None:
        """Reject MSR geometries the product-matrix construction cannot
        build or the blob plane cannot repair (pure arithmetic — the
        heavyweight matrix build in ops/msr.py re-validates)."""
        if self.l:
            raise ValueError(
                "MSR tactics do not compose with LRC local parity: the "
                "sub-shard repair protocol replaces the local stripe")
        k, total, d = self.n, self.n + self.m, self.d
        if k < 2:
            raise ValueError(f"MSR needs k >= 2 data shards, got k={k}")
        if d < k:
            raise ValueError(
                f"MSR d={d} < k={k}: a regenerating repair needs at "
                f"least as many helpers as a conventional decode")
        if d >= total:
            raise ValueError(
                f"MSR d={d} >= total={total}: helpers must be "
                f"surviving shards, so d can be at most total-1")
        if d < 2 * k - 2:
            raise ValueError(
                f"product-matrix MSR exists only for d >= 2k-2 = "
                f"{2 * k - 2}, got d={d}")
        alpha = d - k + 1
        nbar = total + (d - (2 * k - 2))
        if nbar > 255 // math.gcd(alpha, 255):
            raise ValueError(
                f"GF(256) admits only {255 // math.gcd(alpha, 255)} "
                f"nodes with distinct lambda^{alpha} values; this "
                f"geometry needs {nbar}")
        if self.az_count > 1:
            # helpers are elected AZ-local-first: the failed slot's
            # per_az-1 AZ peers, then the rest spread over the remote
            # AZs. An uneven remainder would hot-spot one remote AZ's
            # egress on every repair, so reject the geometry.
            local = total // self.az_count - 1
            cross = d - local
            if cross < 0 or cross % (self.az_count - 1):
                raise ValueError(
                    f"MSR d={d} is AZ-indivisible: after the {local} "
                    f"AZ-local survivors, {cross} cross-AZ helpers "
                    f"cannot spread evenly over {self.az_count - 1} "
                    f"remote AZs")

    @property
    def alpha(self) -> int:
        """Sub-shards per shard (MSR); 1 for RS/LRC tactics."""
        return self.d - self.n + 1 if self.scheme == "msr" else 1

    def is_msr(self) -> bool:
        return self.scheme == "msr"

    @property
    def total(self) -> int:
        return self.n + self.m + self.l

    def is_replicate(self) -> bool:
        return self.m == 0 and self.l == 0

    def ec_layout_by_az(self) -> list[list[int]]:
        """Shard indices per AZ: each AZ gets a contiguous slice of data,
        global-parity and local-parity index ranges."""
        n, m, l = self.n // self.az_count, self.m // self.az_count, self.l // self.az_count
        stripes = []
        for az in range(self.az_count):
            stripe = [az * n + i for i in range(n)]
            stripe += [self.n + az * m + i for i in range(m)]
            stripe += [self.n + self.m + az * l + i for i in range(l)]
            stripes.append(stripe)
        return stripes

    def global_stripe(self) -> tuple[list[int], int, int]:
        return list(range(self.n + self.m)), self.n, self.m

    def local_stripe_in_az(self, az: int) -> tuple[list[int], int, int]:
        if self.l == 0:
            return [], 0, 0
        n, m, l = self.n // self.az_count, self.m // self.az_count, self.l // self.az_count
        stripes = self.ec_layout_by_az()
        if not 0 <= az < len(stripes):
            return [], 0, 0
        return stripes[az], n + m, l

    def local_stripe(self, index: int) -> tuple[list[int], int, int]:
        if self.l == 0:
            return [], 0, 0
        n, m, l = self.n // self.az_count, self.m // self.az_count, self.l // self.az_count
        if index < self.n:
            az = index // n
        elif index < self.n + self.m:
            az = (index - self.n) // m
        elif index < self.total:
            az = (index - self.n - self.m) // l
        else:
            return [], 0, 0
        return self.local_stripe_in_az(az)

    def all_local_stripes(self) -> tuple[list[list[int]], int, int]:
        if self.l == 0:
            return [], 0, 0
        n, m, l = self.n // self.az_count, self.m // self.az_count, self.l // self.az_count
        return self.ec_layout_by_az(), n + m, l


TACTICS: dict[CodeMode, Tactic] = {
    # three az
    CodeMode.EC15P12: Tactic(15, 12, 0, 3, 24, 0, ALIGN_2KB),
    CodeMode.EC6P6: Tactic(6, 6, 0, 3, 11, 0, ALIGN_2KB),
    CodeMode.EC12P9: Tactic(12, 9, 0, 3, 20, 0, ALIGN_2KB),
    # two az
    CodeMode.EC16P20L2: Tactic(16, 20, 2, 2, 34, 0, ALIGN_2KB),
    CodeMode.EC6P10L2: Tactic(6, 10, 2, 2, 14, 0, ALIGN_2KB),
    # single az
    CodeMode.EC12P4: Tactic(12, 4, 0, 1, 15, 0, ALIGN_2KB),
    CodeMode.EC16P4: Tactic(16, 4, 0, 1, 19, 0, ALIGN_2KB),
    CodeMode.EC3P3: Tactic(3, 3, 0, 1, 5, 0, ALIGN_2KB),
    CodeMode.EC10P4: Tactic(10, 4, 0, 1, 13, 0, ALIGN_2KB),
    CodeMode.EC6P3: Tactic(6, 3, 0, 1, 8, 0, ALIGN_2KB),
    CodeMode.EC24P8: Tactic(24, 8, 0, 1, 30, 0, ALIGN_2KB),
    # product-matrix MSR regenerating codes (sub-shard repair): same
    # footprint as EC6P6 but a single-shard repair pulls d beta-sized
    # helper reads (d*S/alpha bytes) instead of 6 full shards
    CodeMode.EC6P6MSR: Tactic(6, 6, 0, 3, 11, 0, ALIGN_2KB,
                              scheme="msr", d=11),
    CodeMode.EC6P6MSROneAZ: Tactic(6, 6, 0, 1, 11, 0, ALIGN_2KB,
                                   scheme="msr", d=10),
    # env-test modes
    CodeMode.EC6P3L3: Tactic(6, 3, 3, 3, 9, 0, ALIGN_2KB),
    CodeMode.EC6P6Align0: Tactic(6, 6, 0, 3, 11, 0, ALIGN_0B),
    CodeMode.EC6P6Align512: Tactic(6, 6, 0, 3, 11, 0, ALIGN_512B),
    CodeMode.EC4P4L2: Tactic(4, 4, 2, 2, 6, 0, ALIGN_2KB),
    CodeMode.EC6P6L9: Tactic(6, 6, 9, 3, 11, 0, ALIGN_2KB),
    CodeMode.EC6P8L10: Tactic(6, 8, 10, 2, 13, 0, ALIGN_0B),
    CodeMode.Replica4TwoAZ: Tactic(4, 0, 0, 2, 3),
    CodeMode.EC4P4MSR: Tactic(4, 4, 0, 1, 6, 0, ALIGN_0B,
                              scheme="msr", d=6),
    # replicate
    CodeMode.Replica3: Tactic(3, 0, 0, 3, 3),
    CodeMode.Replica3OneAZ: Tactic(3, 0, 0, 1, 3),
}


def tactic(mode: CodeMode | int | str) -> Tactic:
    if isinstance(mode, str):
        mode = CodeMode[mode]
    return TACTICS[CodeMode(mode)]


@dataclass
class Policy:
    """Size-class policy used by access to pick a codemode per object
    size (reference: blobstore/common/codemode/policy.go)."""

    mode_name: str
    min_size: int = 0
    max_size: int = 1 << 62
    size_ratio: float = 0.0
    enable: bool = True


def select_codemode(policies: list[Policy], size: int) -> CodeMode:
    for p in policies:
        if p.enable and p.min_size <= size <= p.max_size:
            return CodeMode[p.mode_name]
    raise ValueError(f"no enabled codemode policy covers size {size}")
