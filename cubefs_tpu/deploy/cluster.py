"""Cluster launcher: the docker-compose analog.

Role parity: docker/docker-compose.yml (3 masters, N metanodes/datanodes,
objectnodes, monitoring) and blobstore/run_docker.sh — one topology JSON
spawns every role as a local process (master, metanodes, datanodes,
optional blob plane, objectnode, codec sidecar, fsgateway, console),
waits for liveness, creates the
initial volume, and writes a state file with all addresses.

  python -m cubefs_tpu.deploy.cluster --topo topo.json --workdir /tmp/c1

Topology JSON (all counts optional):
  {"metanodes": 3, "datanodes": 4, "blobnodes": 1, "disks_per_blobnode": 9,
   "objectnode": true, "access": true, "scheduler": false, "codec": false,
   "volume": {"name": "vol1", "mp_count": 3, "dp_count": 4},
   "blob_azs": 3}

blob_azs spreads blobnodes across failure domains round-robin: an int
yields AZ names az0..azN-1, a list supplies the names. Multi-AZ LRC
codemodes then place each local stripe inside one AZ
(cubefs_tpu/blob/topology.py).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


class Proc:
    def __init__(self, role: str, cfg: dict, workdir: str):
        self.role = role
        path = os.path.join(workdir, f"{cfg.get('name', role)}.json")
        with open(path, "w") as f:
            json.dump(cfg, f)
        self.log_path = os.path.join(workdir, f"{cfg.get('name', role)}.log")
        self.log = open(self.log_path, "w")
        self.p = subprocess.Popen(
            [sys.executable, "-m", "cubefs_tpu.cmd", "-c", path],
            stdout=self.log, stderr=subprocess.STDOUT,
        )
        self.addr: str | None = None

    def wait_addr(self, timeout: float = 60.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            for line in open(self.log_path):
                if "listening on" in line or "S3 on" in line:
                    self.addr = line.strip().rsplit(" ", 1)[-1]
                    return self.addr
            if self.p.poll() is not None:
                raise RuntimeError(
                    f"{self.role} exited: {open(self.log_path).read()[-800:]}"
                )
            time.sleep(0.3)
        raise TimeoutError(f"{self.role} did not come up; log: {self.log_path}")


class Cluster:
    def __init__(self, topo: dict, workdir: str):
        self.topo = topo
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.procs: list[Proc] = []
        self.state: dict = {"roles": {}}

    def _spawn(self, role: str, cfg: dict) -> str:
        cfg["role"] = role
        p = Proc(role, cfg, self.workdir)
        self.procs.append(p)
        addr = p.wait_addr()
        self.state["roles"].setdefault(role, []).append(addr)
        return addr

    def up(self) -> dict:
        t = self.topo
        master = self._spawn("master", {
            "replicas": t.get("replicas", 3),
            "allow_single_node": t.get("datanodes", 4) < t.get("replicas", 3),
        })
        for i in range(t.get("metanodes", 3)):
            self._spawn("metanode", {
                "name": f"metanode{i}", "node_id": i, "master_addr": master,
                "data_dir": os.path.join(self.workdir, f"meta{i}")})
        for i in range(t.get("datanodes", 4)):
            self._spawn("datanode", {
                "name": f"datanode{i}", "node_id": i, "master_addr": master,
                "data_dir": os.path.join(self.workdir, f"data{i}")})
        from ..utils import rpc

        # nodes print "listening" before their register RPC lands; wait
        # until the master actually sees the full topology
        deadline = time.time() + 60
        want_meta, want_data = t.get("metanodes", 3), t.get("datanodes", 4)
        while time.time() < deadline:
            st = rpc.call(master, "stat")[0]
            if st["metanodes"] >= want_meta and st["datanodes"] >= want_data:
                break
            time.sleep(0.3)
        else:
            raise TimeoutError(f"nodes never registered: {st}")

        vol = t.get("volume", {"name": "vol1"})
        rpc.call(master, "create_volume", {
            "name": vol.get("name", "vol1"),
            "mp_count": vol.get("mp_count", 3),
            "dp_count": vol.get("dp_count", 4)})
        self.state["volume"] = vol.get("name", "vol1")

        cm = None
        if t.get("blobnodes"):
            cm = self._spawn("clustermgr", {
                "allow_colocated_units": t.get("blobnodes", 1) == 1,
                "data_dir": os.path.join(self.workdir, "cm")})
            azs = t.get("blob_azs")
            az_names = ([f"az{j}" for j in range(azs)]
                        if isinstance(azs, int) else list(azs or ()))
            for i in range(t["blobnodes"]):
                dirs = [os.path.join(self.workdir, f"bn{i}d{d}")
                        for d in range(t.get("disks_per_blobnode", 9))]
                bn_cfg = {"name": f"blobnode{i}", "node_id": i,
                          "clustermgr_addr": cm, "data_dirs": dirs}
                if az_names:
                    # round-robin AZ assignment; each node is its own rack
                    bn_cfg["az"] = az_names[i % len(az_names)]
                    bn_cfg["rack"] = f"{bn_cfg['az']}-r{i // len(az_names)}"
                self._spawn("blobnode", bn_cfg)
            if t.get("access", True):
                access_cfg = {"clustermgr_addr": cm,
                              "blob_size": t.get("blob_size", 8 << 20)}
                if az_names:
                    access_cfg["az"] = az_names[0]
                self._spawn("access", access_cfg)
        if t.get("objectnode"):
            self._spawn("objectnode", {
                "master_addr": master,
                "vols": {t.get("bucket", "bkt"): self.state["volume"]},
                "users": t.get("users", [])})
        if t.get("codec"):
            self._spawn("codec", {})
        if t.get("fsgateway"):
            self._spawn("fsgateway", {"master_addr": master,
                                      "vol": self.state["volume"]})
        if t.get("console"):
            console_cfg = {"master_addr": master}
            if cm is not None:
                console_cfg["clustermgr_addr"] = cm
            self._spawn("console", console_cfg)
        with open(os.path.join(self.workdir, "cluster.json"), "w") as f:
            json.dump(self.state, f, indent=2)
        return self.state

    def down(self) -> None:
        for p in self.procs:
            p.p.terminate()
        for p in self.procs:
            try:
                p.p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.p.kill()


def main(argv=None):
    ap = argparse.ArgumentParser(prog="cubefs-tpu-cluster")
    ap.add_argument("--topo", help="topology JSON file (defaults built in)")
    ap.add_argument("--workdir", required=True)
    args = ap.parse_args(argv)
    topo = json.load(open(args.topo)) if args.topo else {}
    c = Cluster(topo, args.workdir)
    state = c.up()
    print(json.dumps(state, indent=2), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        c.down()


if __name__ == "__main__":
    main()
