"""Flagship pipeline: the blobnode repair-worker step as one jitted graph.

This is the end-to-end compute of the reference's disk-repair hot path
(blobstore/blobnode/worker_slice_recover.go:458 RecoverShards →
engine.Reconstruct at :865, followed by CRC verification of the
reconstructed shards, worker_slice_recover.go:20/45 crc-conflict
checks) — fused into a single TPU step over a BATCH of stripes:

    surviving shards ──► GF reconstruct (bit-matmul) ──► recovered shards
                     └─► parity re-check (bit-matmul, equality) ─► ok?
    recovered shards ──► batched CRC32 ──► shard CRCs

Single-chip (`repair_step`) and mesh-sharded (`sharded_repair_step`,
dp/tp/sp with psum/shift-combine collectives) variants share the same
math and produce bit-identical output.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..ops import crc32_kernel, rs_kernel
from ..parallel import sharded_codec


@dataclass(frozen=True)
class RepairPlan:
    """Static description of one erasure pattern for a codemode: which
    shard indices survive (first n_data used) and which to recover."""

    n_data: int
    n_total: int
    present: tuple[int, ...]
    wanted: tuple[int, ...]

    @property
    def rows(self) -> np.ndarray:
        return rs_kernel.reconstruct_rows(
            self.n_data, self.n_total, list(self.present), list(self.wanted)
        )


def make_plan(n_data: int, n_parity: int, bad: list[int]) -> RepairPlan:
    total = n_data + n_parity
    present = tuple(i for i in range(total) if i not in set(bad))
    return RepairPlan(n_data, total, present, tuple(sorted(set(bad))))


@functools.lru_cache(maxsize=None)
def _repair_fn(plan: RepairPlan, chunk_len: int):
    rec_rows = plan.rows
    # Integrity leg: the extra survivors beyond the first n_data are an
    # independent linear view of the same data — reconstruct them from the
    # first n_data and compare with what was actually read. (A check that
    # only re-derives shards already inside the solving set would be a
    # tautology: the derivation functional collapses to the identity.)
    extras = plan.present[plan.n_data :]
    extra_rows = (
        rs_kernel.reconstruct_rows(
            plan.n_data, plan.n_total, list(plan.present), list(extras)
        )
        if extras
        else None
    )

    @jax.jit
    def step(surviving: jax.Array):
        """surviving: (B, P, S) uint8 — ALL present shards in ascending
        shard-index order (P = len(plan.present) >= n_data).

        Returns (recovered (B, W, S), crcs (B, W) uint32, ok (B,) bool).
        ok compares the extra survivors against their reconstruction from
        the first n_data — the worker's pre-writeback consistency check
        (vacuously True when no extra shards were read).
        """
        solve = surviving[:, : plan.n_data, :]
        # gf_matrix_apply dispatches to the fused Pallas kernel on TPU
        recovered = rs_kernel.gf_matrix_apply(rec_rows, solve)
        if extra_rows is not None:
            re_extra = rs_kernel.gf_matrix_apply(extra_rows, solve)
            ok = jnp.all(
                re_extra == surviving[:, plan.n_data :, :], axis=(-1, -2)
            )
        else:
            ok = jnp.ones((surviving.shape[0],), dtype=bool)
        b, w, s = recovered.shape
        crcs = crc32_kernel.crc32_blocks(
            recovered.reshape(b * w, s), chunk_len=chunk_len
        ).reshape(b, w)
        return recovered, crcs, ok

    return step


def repair_step(plan: RepairPlan, surviving: jax.Array, chunk_len: int = 1024):
    """Single-chip fused repair: reconstruct + integrity-check + CRC.

    surviving holds all present shards (B, len(plan.present), S)."""
    return _repair_fn(plan, chunk_len)(surviving)


@functools.lru_cache(maxsize=None)
def _sharded_repair_fn(mesh: Mesh, plan: RepairPlan, seg_len: int, chunk_len: int):
    rec = sharded_codec.gf_matrix_apply_sharded(mesh, plan.rows, plan.n_data)
    crc = sharded_codec.crc32_sharded(mesh, seg_len, chunk_len)
    n_wanted = len(plan.wanted)

    @jax.jit
    def step(surviving: jax.Array):
        recovered = rec(surviving)  # (B, W, S) sharded (dp, None, sp)
        b = recovered.shape[0]
        crcs = crc(recovered.reshape(b * n_wanted, seg_len)).reshape(b, n_wanted)
        return recovered, crcs

    return step


def sharded_repair_step(
    mesh: Mesh, plan: RepairPlan, surviving: jax.Array, chunk_len: int = 512
):
    """Mesh-sharded repair: stripes over dp, shards over tp, bytes over
    sp; reconstruct XOR-combines via psum(tp), CRC combines via
    shift-matrix psum(sp).

    Contract differs from repair_step: surviving is (B, n_data, S) —
    exactly the first n_data present shards (n_data must divide by the
    mesh's tp), and there is NO integrity output (extras don't shard
    evenly over tp; run the extras check host-side or via repair_step).
    Returns (recovered (B, W, S), crcs (B, W) uint32).
    """
    if int(surviving.shape[-2]) != plan.n_data:
        raise ValueError(
            f"sharded repair takes exactly n_data={plan.n_data} shards, "
            f"got {int(surviving.shape[-2])} (drop the extra survivors)"
        )
    seg_len = int(surviving.shape[-1])
    return _sharded_repair_fn(mesh, plan, seg_len, chunk_len)(surviving)
