"""ObjectNode: S3-compatible HTTP gateway over the FS client.

Role parity: objectnode/ — S3 REST semantics (PutObject/GetObject/
DeleteObject/HeadObject/ListObjectsV2/CreateBucket, fs adapter
fs_volume.go:617 PutObject / :1684 ReadFile). Buckets map to volumes;
object keys map to nested paths (directories are created on demand and
pruned on delete, the same key<->path adaptation the reference's volume
adapter performs). Signature validation (V4) is pluggable via an
authenticator callable; the default accepts all (auth service lands with
the authnode component).
"""

from __future__ import annotations

import hashlib
import threading
import time
import urllib.parse
import xml.sax.saxutils as xs
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import json

from . import metanode as mn
from . import s3policy
from . import s3version
from ..utils import qos
from .client import FileSystem, FsError


def _http_date(unix: float) -> str:
    import email.utils

    return email.utils.formatdate(unix, usegmt=True)


def _parse_http_date(s: str) -> float | None:
    import email.utils

    try:
        return email.utils.parsedate_to_datetime(s).timestamp()
    except (TypeError, ValueError):
        return None


class ObjectNode:
    def __init__(self, volumes: dict[str, FileSystem], host="127.0.0.1", port=0,
                 authenticator=None, audit_sinks=None, qos_gate=None):
        from . import s3ext

        self.volumes = dict(volumes)
        self.auth = authenticator
        # per-tenant admission (tenant = authenticated principal);
        # None = the process-wide gate, CUBEFS_QOS=0 no-ops it
        self.qos = qos_gate or qos.DEFAULT
        # access-audit fan-out (audit_webhook.go / audit_kafka.go role):
        # every reply emits one event to each sink, fire-and-forget
        self.audit_sinks = list(audit_sinks or [])
        # STS issuer: ONE instance shared with the authenticator, so
        # tokens issued here validate on later requests (sts.go role) —
        # an authenticator constructed with its own (e.g. multi-gateway
        # shared-key) Sts wins
        self.sts = getattr(authenticator, "sts", None) or s3ext.Sts()
        if authenticator is not None and getattr(
                authenticator, "sts", None) is None:
            authenticator.sts = self.sts
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            # ---- helpers ----
            def _split(self):
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.lstrip("/").split("/", 1)
                bucket = parts[0]
                key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
                query = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)
                return bucket, key, query

            def _fs(self, bucket) -> FileSystem | None:
                return outer.volumes.get(bucket)

            def _key_reserved(self, key: str) -> bool:
                # the multipart staging and version archive areas are
                # internal: direct key ops on them would expose/corrupt
                # other clients' uploads / version history
                return key.split("/", 1)[0] in (".multipart",
                                                s3version.VDIR)

            def _bypass_governance(self) -> bool:
                return (self.headers.get(
                    "x-amz-bypass-governance-retention", "")
                    .lower() == "true")

            def _audit(self, code: int, bytes_out: int) -> None:
                if not outer.audit_sinks:
                    return
                # emitted BEFORE the response write: a client hangup must
                # not suppress the audit trail of a committed mutation
                bucket, key = getattr(self, "_route", None) or \
                    self._split()[:2]
                event = {
                    "ts": round(time.time(), 3),
                    "method": self.command, "bucket": bucket,
                    "key": key, "code": code,
                    "principal": getattr(self, "_principal", None),
                    "tenant": getattr(self, "_tenant", None),
                    "bytes_out": bytes_out,
                    "bytes_in": len(getattr(self, "_stashed_body",
                                            b"") or b""),
                    "remote": self.client_address[0],
                }
                for sink in outer.audit_sinks:
                    sink.emit(event)

            def _reply(self, code, body=b"", ctype="application/xml",
                       headers=None):
                # HEAD never writes the body: audit the bytes actually
                # sent, or egress accounting over-counts every HEAD error
                self._audit(code,
                            0 if self.command == "HEAD" else len(body))
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                # RFC 9110: a HEAD response carries headers only — writing
                # the body would desync keep-alive clients
                if body and self.command != "HEAD":
                    self.wfile.write(body)

            def _error(self, code, s3code, msg):
                body = (
                    f"<?xml version='1.0'?><Error><Code>{s3code}</Code>"
                    f"<Message>{xs.escape(msg)}</Message></Error>"
                ).encode()
                self._reply(code, body)

            def _admit_qos(self) -> bool:
                """QoS admission for the authenticated request (tenant =
                principal). On shed, replies 429 SlowDown with a
                Retry-After hint and returns False. The admission slot
                is released in handle_one_request's finally."""
                tenant = self._principal or "anonymous"
                self._tenant = tenant
                try:
                    self._admission = outer.qos.admit(
                        f"s3.{self.command.lower()}", tenant=tenant,
                        cost=max(1, len(self._stashed_body)), svc="s3")
                except qos.QosRejected as e:
                    body = (
                        f"<?xml version='1.0'?><Error><Code>SlowDown"
                        f"</Code><Message>{xs.escape(e.message)}"
                        f"</Message></Error>").encode()
                    self._reply(429, body, headers={
                        "Retry-After": f"{e.retry_after:.3f}"})
                    return False
                return True

            def handle_one_request(self):
                try:
                    super().handle_one_request()
                finally:
                    adm = getattr(self, "_admission", None)
                    if adm is not None:
                        self._admission = None
                        adm.release()

            def _begin(self):
                """Drain+stash the body, authenticate, and pass QoS
                admission. Returns the (bucket, key, query) triple, or
                None if a 403/429 was already sent. Sets
                self._principal (None = anonymous) and self._tenant."""
                # the handler object lives for a whole keep-alive
                # connection: bucket config must be re-read per REQUEST
                # or an ACL/policy revocation never reaches it — and the
                # audit fields (principal, body, route) must never leak
                # from the previous request into this one's events
                self._conf_cache = None
                self._via_token = False
                self._principal = None
                self._tenant = None
                self._admission = None
                self._stashed_body = b""
                self._route = self._split()[:2]
                if outer.auth is None:
                    from . import s3ext

                    n = int(self.headers.get("Content-Length") or 0)
                    self._stashed_body = self.rfile.read(n) if n else b""
                    if (self.headers.get("x-amz-content-sha256")
                            == s3ext.STREAMING_PAYLOAD):
                        # no keys to verify the chain against: strip the
                        # aws-chunked framing so the payload lands intact
                        self._stashed_body = s3ext.strip_aws_chunked(
                            self._stashed_body)
                    self._principal = None
                    if not self._admit_qos():
                        return None
                    return self._split()
                ok, who, reason = outer.auth.authenticate(self)
                if not ok:
                    # AWS-conformant denial codes: clients switch on
                    # these (see ceph/s3-tests); a flat AccessDenied
                    # hides key-vs-signature failures from SDK retries
                    reason = reason or "bad signature"
                    if "signature mismatch" in reason:
                        code = "SignatureDoesNotMatch"
                    elif "unknown access key" in reason:
                        code = "InvalidAccessKeyId"
                    elif "session token" in reason:
                        code = "ExpiredToken"
                    else:  # incl. presigned expiry (AWS: AccessDenied)
                        code = "AccessDenied"
                    self._error(403, code, reason)
                    return None
                self._principal = who
                if not self._admit_qos():
                    return None
                return self._split()

            def _bucket_conf(self, bucket) -> dict:
                """ACL/policy/CORS config for the bucket — ONE root-inode
                fetch per request, cached on the handler."""
                cache = getattr(self, "_conf_cache", None)
                if cache is not None and cache[0] == bucket:
                    return cache[1]
                conf: dict = {}
                fs = self._fs(bucket)
                if fs is not None:
                    try:
                        xattr = fs.meta.inode_get(fs.resolve("/"))["xattr"]
                        conf = {k: xattr.get(k) for k in
                                (s3policy.XA_ACL, s3policy.XA_POLICY,
                                 s3policy.XA_CORS, s3policy.XA_LIFECYCLE,
                                 s3version.XA_VERSIONING)}
                    except FsError:
                        conf = {}
                self._conf_cache = (bucket, conf)
                return conf

            def _allowed(self, action, bucket, key="") -> bool:
                """Pure authorization decision (policy -> ACL -> user
                grant); no reply side effects."""
                if outer.auth is None:
                    return True
                conf = self._bucket_conf(bucket)
                acl = conf.get(s3policy.XA_ACL)
                policy = None
                raw = conf.get(s3policy.XA_POLICY)
                if raw:
                    try:
                        policy = json.loads(raw)
                    except json.JSONDecodeError:
                        policy = None
                write = action not in s3policy.READ_ACTIONS
                grant = outer.auth.grant_ok(self._principal, bucket, write)
                if action.endswith(("BucketPolicy", "BucketAcl",
                                    "BucketCors", "BucketLifecycle",
                                    "BucketVersioning",
                                    "ObjectLockConfiguration")):
                    # bucket configuration is owner-only: policy/ACL
                    # cannot grant it away
                    return grant
                return s3policy.authorize(
                    action, bucket, key, self._principal, acl, policy,
                    grant)

            def _check(self, action, bucket, key="") -> bool:
                """_allowed + a 403 reply on denial."""
                allowed = self._allowed(action, bucket, key)
                if not allowed:
                    self._error(403, "AccessDenied", f"{action} denied")
                return allowed

            def _cors(self, bucket) -> dict:
                """CORS response headers for the request's Origin."""
                origin = self.headers.get("Origin")
                if not origin or self._fs(bucket) is None:
                    return {}
                raw = self._bucket_conf(bucket).get(s3policy.XA_CORS)
                rules = json.loads(raw) if raw else None
                rule = s3policy.cors_match(rules, origin, self.command)
                return s3policy.cors_headers(rule, origin) if rule else {}

            def do_OPTIONS(self):
                # CORS preflight: allowlisted from QoS admission (no
                # data path; shedding it would break browser clients)
                self._conf_cache = None
                self._principal = None
                self._tenant = None
                self._stashed_body = b""
                bucket, key, _ = self._split()
                self._route = (bucket, key)
                origin = self.headers.get("Origin", "")
                method = self.headers.get("Access-Control-Request-Method", "")
                fs = self._fs(bucket)
                if fs is None:
                    return self._error(404, "NoSuchBucket", bucket)
                raw = self._bucket_conf(bucket).get(s3policy.XA_CORS)
                rules = json.loads(raw) if raw else None
                rule = s3policy.cors_match(rules, origin, method)
                if rule is None:
                    return self._error(403, "AccessDenied",
                                       "CORS rules do not allow this origin")
                self._reply(200, headers=s3policy.cors_headers(rule, origin))

            # ---- verbs ----
            def do_PUT(self):
                # the body is drained BEFORE any reply (leftover bytes
                # desync HTTP/1.1 keep-alive clients): _begin stashes it
                # as part of signature hashing
                begun = self._begin()
                if begun is None:
                    return
                bucket, key, query = begun
                data = getattr(self, "_stashed_body", b"")
                fs = self._fs(bucket)
                if fs is None:
                    return self._error(404, "NoSuchBucket", bucket)
                # bucket subresources: ?acl / ?policy / ?cors
                if not key and "acl" in query:
                    if not self._check("s3:PutBucketAcl", bucket):
                        return
                    canned = self.headers.get("x-amz-acl", "private")
                    if canned not in s3policy.CANNED_ACLS:
                        return self._error(400, "InvalidArgument",
                                           f"unsupported ACL {canned!r}")
                    outer._bucket_cfg_set(fs, s3policy.XA_ACL, canned)
                    return self._reply(200)
                if not key and "policy" in query:
                    if not self._check("s3:PutBucketPolicy", bucket):
                        return
                    try:
                        s3policy.parse_policy(data)
                    except s3policy.S3ConfigError as e:
                        return self._error(400, "MalformedPolicy", str(e))
                    outer._bucket_cfg_set(fs, s3policy.XA_POLICY,
                                          data.decode())
                    return self._reply(200)
                if not key and "cors" in query:
                    if not self._check("s3:PutBucketCors", bucket):
                        return
                    try:
                        rules = s3policy.parse_cors(data)
                    except s3policy.S3ConfigError as e:
                        return self._error(400, "MalformedXML", str(e))
                    outer._bucket_cfg_set(fs, s3policy.XA_CORS,
                                          json.dumps(rules))
                    return self._reply(200)
                if not key and "lifecycle" in query:
                    if not self._check("s3:PutBucketLifecycle", bucket):
                        return
                    try:
                        rules = s3policy.parse_lifecycle(data)
                    except s3policy.S3ConfigError as e:
                        return self._error(400, "MalformedXML", str(e))
                    outer._bucket_cfg_set(fs, s3policy.XA_LIFECYCLE,
                                          json.dumps(rules))
                    return self._reply(200)
                if not key and "versioning" in query:  # PutBucketVersioning
                    if not self._check("s3:PutBucketVersioning", bucket):
                        return
                    try:
                        status = outer._parse_versioning_xml(data)
                        s3version.VersionStore(fs).set_status(status)
                    except s3version.S3VersionError as e:
                        return self._error(e.http, e.code, str(e))
                    return self._reply(200)
                if not key and "object-lock" in query:  # PutObjectLockConfiguration
                    if not self._check("s3:PutBucketObjectLockConfiguration",
                                       bucket):
                        return
                    try:
                        conf = outer._parse_objlock_xml(data)
                        s3version.VersionStore(fs).set_lock_config(conf)
                    except s3version.S3VersionError as e:
                        return self._error(e.http, e.code, str(e))
                    return self._reply(200)
                if key and "retention" in query:  # PutObjectRetention
                    if self._key_reserved(key):
                        return self._error(403, "AccessDenied",
                                           "reserved namespace")
                    if not self._check("s3:PutObjectRetention", bucket, key):
                        return
                    vid = query.get("versionId", [None])[0]
                    try:
                        mode, until = outer._parse_retention_xml(data)
                        s3version.VersionStore(fs).set_retention(
                            key, vid, mode, until,
                            self._bypass_governance())
                    except s3version.S3VersionError as e:
                        return self._error(e.http, e.code, str(e))
                    except FsError:
                        return self._error(404, "NoSuchKey", key)
                    return self._reply(200)
                if key and "legal-hold" in query:  # PutObjectLegalHold
                    if self._key_reserved(key):
                        return self._error(403, "AccessDenied",
                                           "reserved namespace")
                    if not self._check("s3:PutObjectLegalHold", bucket, key):
                        return
                    vid = query.get("versionId", [None])[0]
                    try:
                        on = outer._parse_legal_hold_xml(data)
                        s3version.VersionStore(fs).set_legal_hold(
                            key, vid, on)
                    except s3version.S3VersionError as e:
                        return self._error(e.http, e.code, str(e))
                    except FsError:
                        return self._error(404, "NoSuchKey", key)
                    return self._reply(200)
                if not key:  # CreateBucket
                    if not self._check("s3:CreateBucket", bucket):
                        return
                    if bucket not in outer.volumes:
                        return self._error(404, "NoSuchBucket",
                                           f"no volume backs {bucket}")
                    return self._reply(200)
                if self._key_reserved(key):
                    return self._error(403, "AccessDenied",
                                       ".multipart is a reserved namespace")
                if "tagging" in query:  # PutObjectTagging
                    if not self._check("s3:PutObjectTagging", bucket, key):
                        return
                    try:
                        tags = s3policy.parse_tagging(data)
                    except s3policy.S3ConfigError as e:
                        return self._error(400, "MalformedXML", str(e))
                    try:
                        fs.setxattr("/" + key, s3policy.XA_TAGS,
                                    json.dumps(tags))
                    except FsError:
                        return self._error(404, "NoSuchKey", key)
                    return self._reply(200)
                if "acl" in query:  # PutObjectAcl (canned, like buckets)
                    if not self._check("s3:PutObjectAcl", bucket, key):
                        return
                    canned = self.headers.get("x-amz-acl", "private")
                    if canned not in s3policy.CANNED_ACLS:
                        return self._error(400, "InvalidArgument", canned)
                    try:
                        fs.setxattr("/" + key, s3policy.XA_ACL, canned)
                    except FsError:
                        return self._error(404, "NoSuchKey", key)
                    return self._reply(200)
                if not self._check("s3:PutObject", bucket, key):
                    return
                if "uploadId" in query and "partNumber" in query:  # UploadPart
                    if self.headers.get("x-amz-copy-source"):
                        # refusing beats silently storing the empty body
                        return self._error(501, "NotImplemented",
                                           "UploadPartCopy is not supported")
                    upload_id = query["uploadId"][0]
                    try:
                        part = int(query["partNumber"][0])
                    except ValueError:
                        return self._error(400, "InvalidPart",
                                           "partNumber must be an integer")
                    if not 1 <= part <= 10000:  # S3's own part limit
                        return self._error(400, "InvalidPart",
                                           f"partNumber {part} out of range")
                    try:
                        etag = outer._put_part(fs, upload_id, part, data)
                    except FsError as e:
                        return self._error(404, "NoSuchUpload", str(e))
                    return self._reply(200, headers={"ETag": f'"{etag}"'})
                src = self.headers.get("x-amz-copy-source", "")
                is_copy = bool(src)
                if is_copy:  # CopyObject: data comes from /bucket/key
                    sb, _, sk = src.lstrip("/").partition("/")
                    sk = urllib.parse.unquote(sk)
                    sfs = self._fs(sb)
                    if sfs is None or not sk:
                        return self._error(404, "NoSuchBucket", sb)
                    if self._key_reserved(sk):
                        return self._error(403, "AccessDenied",
                                           ".multipart is a reserved namespace")
                    # the caller must be allowed to READ the source too,
                    # or copy becomes cross-bucket exfiltration
                    if not self._check("s3:GetObject", sb, sk):
                        return
                    try:
                        data = sfs.read_file("/" + sk)
                    except FsError as e:
                        if e.errno == mn.EISDIR:  # folder-marker copy
                            data = b""
                        else:
                            return self._error(404, "NoSuchKey", sk)
                # lock headers validate BEFORE the write: a rejected
                # PUT must not have replaced the object already
                lock_mode = self.headers.get("x-amz-object-lock-mode")
                lock_until = None
                if lock_mode:
                    if lock_mode not in ("GOVERNANCE", "COMPLIANCE"):
                        return self._error(400, "InvalidArgument",
                                           f"bad lock mode {lock_mode!r}")
                    until_s = self.headers.get(
                        "x-amz-object-lock-retain-until-date", "")
                    try:
                        lock_until = s3version.parse_iso8601(until_s)
                    except Exception:
                        return self._error(
                            400, "InvalidArgument",
                            f"bad retain-until date {until_s!r}")
                etag = hashlib.md5(data).hexdigest()
                try:
                    vid = outer._put_object_versioned(
                        fs, key, data, etag, self._bypass_governance())
                except s3version.S3VersionError as e:
                    return self._error(e.http, e.code, str(e))
                except FsError as e:
                    if e.errno in (mn.ENOSPC, mn.EDQUOT):
                        return self._error(507, "QuotaExceeded", str(e))
                    return self._error(500, "InternalError", str(e))
                # Content-Type + x-amz-meta-* persist with the object;
                # CopyObject defaults to COPY of the source's metadata
                # unless the directive says REPLACE (AWS semantics)
                if is_copy and self.headers.get(
                        "x-amz-metadata-directive", "COPY") != "REPLACE":
                    rec = outer._obj_meta(sfs, sk)
                    outer._obj_meta_save(fs, key, rec.get("ct"),
                                         rec.get("meta") or {}, etag=etag)
                else:
                    ct_in, meta_in = outer._req_obj_meta(self.headers)
                    outer._obj_meta_save(fs, key, ct_in, meta_in,
                                         etag=etag)
                # PUT-time object-lock headers apply to the version just
                # written (AWS: x-amz-object-lock-{mode,retain-until-date,
                # legal-hold} on PutObject); validated above
                if lock_mode:
                    try:
                        s3version.VersionStore(fs).set_retention(
                            key, vid, lock_mode, lock_until,
                            self._bypass_governance())
                    except s3version.S3VersionError as e:
                        return self._error(e.http, e.code, str(e))
                if self.headers.get(
                        "x-amz-object-lock-legal-hold", "").upper() == "ON":
                    try:
                        s3version.VersionStore(fs).set_legal_hold(
                            key, vid, True)
                    except s3version.S3VersionError as e:
                        return self._error(e.http, e.code, str(e))
                vid_hdr = {"x-amz-version-id": vid} if vid else {}
                if is_copy:
                    body = (f"<?xml version='1.0'?><CopyObjectResult>"
                            f"<ETag>\"{etag}\"</ETag></CopyObjectResult>").encode()
                    return self._reply(200, body, headers=vid_hdr)
                self._reply(200, headers={"ETag": f'"{etag}"',
                                          **vid_hdr,
                                          **self._cors(bucket)})

            def do_POST(self):
                # multipart lifecycle: InitiateMultipartUpload (?uploads)
                # and CompleteMultipartUpload (?uploadId=...), plus the
                # STS action surface (POST /) and browser POST policy
                # uploads (multipart/form-data to the bucket)
                begun = self._begin()
                if begun is None:
                    return
                bucket, key, query = begun
                if not bucket:
                    return self._sts_action()
                fs = self._fs(bucket)
                if fs is None:
                    return self._error(404, "NoSuchBucket", bucket)
                ctype = self.headers.get("Content-Type", "")
                if not key and ctype.startswith("multipart/form-data"):
                    return self._post_policy_upload(bucket, fs, ctype)
                if key and self._key_reserved(key):
                    return self._error(403, "AccessDenied",
                                       ".multipart is a reserved namespace")
                if not key and "delete" in query:  # DeleteObjects (batch)
                    return self._delete_objects(bucket, fs)
                if not self._check("s3:PutObject", bucket, key):
                    return
                if "uploads" in query:
                    if not key:
                        return self._error(400, "InvalidRequest",
                                           "multipart upload needs a key")
                    upload_id = outer._initiate_multipart(fs, key,
                                                          self.headers)
                    body = (
                        f"<?xml version='1.0'?><InitiateMultipartUploadResult>"
                        f"<Bucket>{bucket}</Bucket><Key>{xs.escape(key)}</Key>"
                        f"<UploadId>{upload_id}</UploadId>"
                        f"</InitiateMultipartUploadResult>"
                    ).encode()
                    return self._reply(200, body)
                if "uploadId" in query:
                    try:
                        etag = outer._complete_multipart(
                            fs, key, query["uploadId"][0]
                        )
                    except s3version.S3VersionError as e:
                        # e.g. Locked: completing onto a retained null
                        # version must 403, not drop the connection
                        return self._error(e.http, e.code, str(e))
                    except FsError as e:
                        return self._error(404, "NoSuchUpload", str(e))
                    body = (
                        f"<?xml version='1.0'?><CompleteMultipartUploadResult>"
                        f"<Key>{xs.escape(key)}</Key><ETag>\"{etag}\"</ETag>"
                        f"</CompleteMultipartUploadResult>"
                    ).encode()
                    return self._reply(200, body)
                self._error(400, "InvalidRequest", "unsupported POST")

            def do_GET(self):
                begun = self._begin()
                if begun is None:
                    return
                bucket, key, query = begun
                fs = self._fs(bucket)
                if fs is None:
                    return self._error(404, "NoSuchBucket", bucket)
                if key and self._key_reserved(key):
                    return self._error(403, "AccessDenied",
                                       ".multipart is a reserved namespace")
                if not key and "acl" in query:  # GetBucketAcl
                    if not self._check("s3:GetBucketAcl", bucket):
                        return
                    acl = (self._bucket_conf(bucket).get(s3policy.XA_ACL)
                           or "private")
                    owner = self._principal or "owner"
                    return self._reply(200, s3policy.acl_to_xml(acl, owner))
                if not key and "uploads" in query:  # ListMultipartUploads
                    if not self._check("s3:ListBucketMultipartUploads",
                                       bucket):
                        return
                    prefix = query.get("prefix", [""])[0]
                    ups = []
                    try:
                        staging_root = fs.readdir("/.multipart")
                    except FsError:
                        staging_root = {}
                    for upload_id in sorted(staging_root):
                        try:
                            k = fs.getxattr(f"/.multipart/{upload_id}",
                                            "s3.key") or ""
                        except FsError:
                            continue
                        if k.startswith(prefix):
                            ups.append((k, upload_id))
                    ups.sort()
                    body = (
                        "<?xml version='1.0'?><ListMultipartUploadsResult>"
                        f"<Bucket>{bucket}</Bucket>"
                        f"<Prefix>{xs.escape(prefix)}</Prefix>"
                        "<IsTruncated>false</IsTruncated>"
                        + "".join(
                            f"<Upload><Key>{xs.escape(k)}</Key>"
                            f"<UploadId>{u}</UploadId></Upload>"
                            for k, u in ups)
                        + "</ListMultipartUploadsResult>").encode()
                    return self._reply(200, body)
                if key and "acl" in query:  # GetObjectAcl
                    if not self._check("s3:GetObjectAcl", bucket, key):
                        return
                    try:
                        canned = fs.getxattr("/" + key, s3policy.XA_ACL)
                    except FsError:
                        return self._error(404, "NoSuchKey", key)
                    owner = self._principal or "owner"
                    return self._reply(
                        200, s3policy.acl_to_xml(canned or "private", owner))
                if key and "uploadId" in query:  # ListParts
                    if not self._check("s3:ListMultipartUploadParts",
                                       bucket, key):
                        return
                    upload_id = query["uploadId"][0]
                    staging = f"/.multipart/{upload_id}"
                    try:
                        if fs.getxattr(staging, "s3.key") != key:
                            return self._error(404, "NoSuchUpload",
                                               upload_id)
                        names = sorted(fs.readdir(staging))
                    except FsError:
                        return self._error(404, "NoSuchUpload", upload_id)
                    parts_xml = []
                    for n in names:
                        try:
                            st = fs.stat(f"{staging}/{n}")
                            etag = fs.getxattr(f"{staging}/{n}",
                                               "s3.etag") or ""
                        except FsError:
                            continue
                        parts_xml.append(
                            f"<Part><PartNumber>{int(n)}</PartNumber>"
                            f"<ETag>\"{etag}\"</ETag>"
                            f"<Size>{st['size']}</Size></Part>")
                    return self._reply(
                        200,
                        (f"<?xml version='1.0'?><ListPartsResult>"
                         f"<Bucket>{bucket}</Bucket>"
                         f"<Key>{xs.escape(key)}</Key>"
                         f"<UploadId>{upload_id}</UploadId>"
                         f"{''.join(parts_xml)}</ListPartsResult>").encode())
                if not key and "policy" in query:  # GetBucketPolicy
                    if not self._check("s3:GetBucketPolicy", bucket):
                        return
                    raw = self._bucket_conf(bucket).get(s3policy.XA_POLICY)
                    if not raw:
                        return self._error(404, "NoSuchBucketPolicy", bucket)
                    return self._reply(200, raw.encode(),
                                       ctype="application/json")
                if not key and "cors" in query:  # GetBucketCors
                    if not self._check("s3:GetBucketCors", bucket):
                        return
                    raw = self._bucket_conf(bucket).get(s3policy.XA_CORS)
                    if not raw:
                        return self._error(404,
                                           "NoSuchCORSConfiguration", bucket)
                    rules = json.loads(raw)
                    body = "".join(
                        "<CORSRule>"
                        + "".join(f"<AllowedOrigin>{xs.escape(o)}"
                                  f"</AllowedOrigin>" for o in r["origins"])
                        + "".join(f"<AllowedMethod>{xs.escape(m)}"
                                  f"</AllowedMethod>"
                                  for m in r["methods"])
                        + "".join(f"<AllowedHeader>{xs.escape(h)}"
                                  f"</AllowedHeader>" for h in r["headers"])
                        + (f"<MaxAgeSeconds>{r['max_age']}</MaxAgeSeconds>"
                           if r["max_age"] else "")
                        + "</CORSRule>"
                        for r in rules
                    )
                    return self._reply(
                        200,
                        (f"<?xml version='1.0'?><CORSConfiguration>{body}"
                         f"</CORSConfiguration>").encode())
                if not key and "lifecycle" in query:  # GetBucketLifecycle
                    if not self._check("s3:GetBucketLifecycle", bucket):
                        return
                    raw = self._bucket_conf(bucket).get(
                        s3policy.XA_LIFECYCLE)
                    if not raw:
                        return self._error(
                            404, "NoSuchLifecycleConfiguration", bucket)
                    return self._reply(
                        200, s3policy.lifecycle_to_xml(json.loads(raw)))
                if not key and "versioning" in query:  # GetBucketVersioning
                    if not self._check("s3:GetBucketVersioning", bucket):
                        return
                    st = s3version.VersionStore(fs).status()
                    inner = f"<Status>{st}</Status>" if st else ""
                    return self._reply(
                        200,
                        (f"<?xml version='1.0'?><VersioningConfiguration>"
                         f"{inner}</VersioningConfiguration>").encode())
                if not key and "object-lock" in query:  # GetObjectLockConfiguration
                    if not self._check("s3:GetBucketObjectLockConfiguration",
                                       bucket):
                        return
                    conf = s3version.VersionStore(fs).lock_config()
                    if conf is None:
                        return self._error(
                            404, "ObjectLockConfigurationNotFoundError",
                            bucket)
                    return self._reply(200, outer._objlock_to_xml(conf))
                if not key and "versions" in query:  # ListObjectVersions
                    if not self._check("s3:ListBucketVersions", bucket):
                        return
                    return outer._list_versions_reply(self, bucket, fs,
                                                      query)
                if key and "retention" in query:  # GetObjectRetention
                    if not self._check("s3:GetObjectRetention", bucket, key):
                        return
                    vid = query.get("versionId", [None])[0]
                    try:
                        ret = s3version.VersionStore(fs).get_retention(
                            key, vid)
                    except s3version.S3VersionError as e:
                        return self._error(e.http, e.code, str(e))
                    except FsError:
                        return self._error(404, "NoSuchKey", key)
                    if ret is None:
                        return self._error(
                            404, "NoSuchObjectLockConfiguration", key)
                    return self._reply(
                        200,
                        (f"<?xml version='1.0'?><Retention>"
                         f"<Mode>{ret['mode']}</Mode>"
                         f"<RetainUntilDate>"
                         f"{s3version.iso8601(ret['until'])}"
                         f"</RetainUntilDate></Retention>").encode())
                if key and "legal-hold" in query:  # GetObjectLegalHold
                    if not self._check("s3:GetObjectLegalHold", bucket, key):
                        return
                    vid = query.get("versionId", [None])[0]
                    try:
                        on = s3version.VersionStore(fs).get_legal_hold(
                            key, vid)
                    except s3version.S3VersionError as e:
                        return self._error(e.http, e.code, str(e))
                    except FsError:
                        return self._error(404, "NoSuchKey", key)
                    return self._reply(
                        200,
                        (f"<?xml version='1.0'?><LegalHold><Status>"
                         f"{'ON' if on else 'OFF'}</Status>"
                         f"</LegalHold>").encode())
                if key and "tagging" in query:  # GetObjectTagging
                    if not self._check("s3:GetObjectTagging", bucket, key):
                        return
                    try:
                        raw = fs.getxattr("/" + key, s3policy.XA_TAGS)
                    except FsError:
                        return self._error(404, "NoSuchKey", key)
                    tags = json.loads(raw) if raw else {}
                    return self._reply(200, s3policy.tagging_to_xml(tags))
                if not key:  # ListObjects V1/V2 (+ delimiter, pagination)
                    if not self._check("s3:ListBucket", bucket):
                        return
                    prefix = query.get("prefix", [""])[0]
                    delimiter = query.get("delimiter", [""])[0]
                    try:
                        max_keys = int(query.get("max-keys", ["1000"])[0])
                    except ValueError:
                        return self._error(400, "InvalidArgument",
                                           "max-keys must be an integer")
                    if max_keys < 1:
                        return self._error(400, "InvalidArgument",
                                           "max-keys must be positive")
                    v2 = query.get("list-type", [""])[0] == "2"
                    # V1's `marker` is "start after this key" — the same
                    # contract as our V2 continuation token
                    token = (query.get("continuation-token", [""])[0]
                             if v2 else query.get("marker", [""])[0])
                    keys, prefixes, next_token, truncated = outer._list_v2(
                        fs, prefix, delimiter, max_keys, token
                    )
                    def _entry(k, sz, mt, et):
                        # sync tools key their change detection on
                        # ETag + LastModified in listings
                        tag = f"<ETag>\"{et}\"</ETag>" if et else ""
                        return (f"<Contents><Key>{xs.escape(k)}</Key>"
                                f"<Size>{sz}</Size>"
                                f"<LastModified>"
                                f"{s3version.iso8601(mt)}</LastModified>"
                                f"{tag}</Contents>")

                    items = "".join(_entry(*t) for t in keys)
                    cps = "".join(
                        f"<CommonPrefixes><Prefix>{xs.escape(p)}</Prefix>"
                        f"</CommonPrefixes>"
                        for p in prefixes
                    )
                    if v2:
                        extra = (f"<KeyCount>{len(keys) + len(prefixes)}"
                                 f"</KeyCount>")
                        if next_token:
                            extra += (f"<NextContinuationToken>"
                                      f"{xs.escape(next_token)}"
                                      f"</NextContinuationToken>")
                    else:  # V1: Marker/NextMarker shapes
                        extra = (f"<Marker>{xs.escape(token)}</Marker>")
                        if truncated:
                            extra += (f"<NextMarker>"
                                      f"{xs.escape(next_token)}"
                                      f"</NextMarker>")
                    body = (
                        f"<?xml version='1.0'?><ListBucketResult>"
                        f"<Name>{bucket}</Name><Prefix>{xs.escape(prefix)}</Prefix>"
                        f"<Delimiter>{xs.escape(delimiter)}</Delimiter>"
                        f"<MaxKeys>{max_keys}</MaxKeys>"
                        f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
                        f"{extra}{items}{cps}"
                        f"</ListBucketResult>"
                    ).encode()
                    return self._reply(200, body)
                if not self._check("s3:GetObject", bucket, key):
                    return
                vid_q = query.get("versionId", [""])[0]
                if vid_q:  # GetObject of a specific version
                    try:
                        data, vmeta = s3version.VersionStore(
                            fs).read_version(key, vid_q)
                    except s3version.S3VersionError as e:
                        return self._error(e.http, e.code, str(e))
                    # conditionals evaluate against the ADDRESSED
                    # version's etag/mtime — revalidating a cached copy
                    # of version V must 304 on V's etag, and an
                    # If-Match pinned to V must not 412 just because
                    # the live object moved on
                    vstate = outer._version_meta_state(fs, vmeta)
                    vct, vhdrs = outer._version_reply_headers(
                        fs, vmeta, state=vstate)
                    cond = outer._conditional(self.headers, *vstate)
                    if cond == 304:
                        return self._reply(304, headers=vhdrs)
                    if cond == 412:
                        return self._error(412, "PreconditionFailed", key)
                    return self._reply(
                        200, data, ctype=vct,
                        headers={**vhdrs, **self._cors(bucket)})
                mrec, mst = outer._obj_meta_state(fs, key)
                cond = outer._conditional(self.headers, mrec, mst)
                if cond == 304:
                    _, mh = outer._meta_reply_headers(mrec, mst)
                    return self._reply(304, headers=mh)
                if cond == 412:
                    return self._error(412, "PreconditionFailed", key)
                rng_hdr = self.headers.get("Range", "")
                span = None
                if rng_hdr.startswith("bytes=") and "," not in rng_hdr:
                    try:
                        lo_s, _, hi_s = rng_hdr[6:].partition("-")
                        span = ((int(lo_s) if lo_s else None),
                                (int(hi_s) if hi_s else None))
                        if span == (None, None):
                            span = None
                    except ValueError:
                        # RFC 9110 / S3: an unparseable Range header is
                        # IGNORED (full 200 body), never an error
                        span = None
                try:
                    if span is not None and mst is not None:
                        size = mst["size"]  # the inode already fetched
                        lo, hi = span
                        if lo is None:  # suffix range: last N bytes
                            lo, hi = max(0, size - hi), size - 1
                        else:
                            hi = size - 1 if hi is None else min(hi, size - 1)
                        if lo > hi or lo >= size:
                            return self._reply(
                                416,
                                b"<?xml version='1.0'?><Error>"
                                b"<Code>InvalidRange</Code></Error>",
                                headers={"Content-Range": f"bytes */{size}"})
                        data = fs.read_file("/" + key, offset=lo,
                                            length=hi - lo + 1)
                        mct, mhdrs = outer._meta_reply_headers(mrec, mst)
                        mhdrs = outer._null_vid_backfill(self, bucket,
                                                         mhdrs)
                        return self._reply(
                            206, data, ctype=mct,
                            headers={"Content-Range":
                                     f"bytes {lo}-{hi}/{size}",
                                     **mhdrs})
                    data = fs.read_file("/" + key)
                except FsError as e:
                    if e.errno == mn.EISDIR:  # folder-marker key GET
                        return self._reply(200, b"",
                                           ctype="application/octet-stream",
                                           headers=self._cors(bucket))
                    if s3version.VersionStore(fs).latest_is_marker(key):
                        # the newest version is a delete marker: 404
                        # that SAYS so, per the S3 API
                        return self._reply(
                            404,
                            b"<?xml version='1.0'?><Error>"
                            b"<Code>NoSuchKey</Code></Error>",
                            headers={"x-amz-delete-marker": "true"})
                    return self._error(404, "NoSuchKey", key)
                mct, mhdrs = outer._meta_reply_headers(mrec, mst)
                mhdrs = outer._null_vid_backfill(self, bucket, mhdrs)
                self._reply(200, data, ctype=mct,
                            headers={**mhdrs, **self._cors(bucket)})

            def _delete_objects(self, bucket, fs):
                """POST /bucket?delete — batch DeleteObjects: per-key
                authorization, per-key outcome in one DeleteResult."""
                import xml.etree.ElementTree as ET

                data = getattr(self, "_stashed_body", b"")
                try:
                    root = ET.fromstring(data)
                except ET.ParseError as e:
                    return self._error(400, "MalformedXML", str(e))
                # AWS SDKs send the namespaced document
                # (xmlns=http://s3.amazonaws.com/doc/2006-03-06/):
                # match by local name
                # "{*}name" matches any namespace including none
                keys = [o.findtext("{*}Key") or ""
                        for o in root.findall("{*}Object")]
                if not keys or len(keys) > 1000:  # S3's batch limit
                    return self._error(400, "MalformedXML",
                                       "1..1000 Object keys required")
                deleted, errors = [], []
                vs = s3version.VersionStore(fs)
                versioned = bool(vs.status())
                for k in keys:
                    if not k:
                        errors.append((k, "UserKeyMustBeSpecified"))
                        continue
                    if self._key_reserved(k):
                        errors.append((k, "AccessDenied"))
                        continue
                    if not self._allowed("s3:DeleteObject", bucket, k):
                        errors.append((k, "AccessDenied"))
                        continue
                    try:
                        if versioned:
                            # versioned bucket: batch delete adds markers
                            vs.delete(k)
                        else:
                            fs.unlink("/" + k)
                        outer._prune_empty_dirs(fs, k)
                        deleted.append(k)
                    except s3version.S3VersionError:
                        errors.append((k, "AccessDenied"))
                    except FsError as e:
                        if e.errno == mn.ENOENT:
                            # S3 treats delete-of-missing as success
                            deleted.append(k)
                        else:
                            errors.append((k, "InternalError"))
                body = ("<?xml version='1.0'?><DeleteResult>"
                        + "".join(f"<Deleted><Key>{xs.escape(k)}</Key>"
                                  f"</Deleted>" for k in deleted)
                        + "".join(f"<Error><Key>{xs.escape(k)}</Key>"
                                  f"<Code>{c}</Code></Error>"
                                  for k, c in errors)
                        + "</DeleteResult>").encode()
                self._reply(200, body)

            def _sts_action(self):
                """POST / with Action=AssumeRole|GetSessionToken: issue
                temporary credentials for the AUTHENTICATED caller
                (sts.go role). Anonymous or policy-denied callers get
                nothing."""
                if outer.auth is not None and self._principal is None:
                    return self._error(403, "AccessDenied",
                                       "STS requires signed credentials")
                if getattr(self, "_via_token", False):
                    # temp creds must not mint fresh tokens, or a leaked
                    # short-lived credential chains itself past expiry
                    return self._error(403, "AccessDenied",
                                       "cannot call STS with temporary "
                                       "credentials")
                form = urllib.parse.parse_qs(
                    self._stashed_body.decode("utf-8", "replace"))
                action = (form.get("Action") or [""])[0]
                if action not in ("AssumeRole", "GetSessionToken"):
                    return self._error(400, "InvalidAction",
                                       action or "missing Action")
                try:
                    duration = int((form.get("DurationSeconds")
                                    or ["3600"])[0])
                except ValueError:
                    return self._error(400, "InvalidRequest",
                                       "malformed DurationSeconds")
                cred = outer.sts.issue(self._principal or "anonymous",
                                       duration)
                import time as _time

                exp_iso = _time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                         _time.gmtime(cred["expiration"]))
                body = (
                    f"<?xml version='1.0'?><{action}Response>"
                    f"<{action}Result><Credentials>"
                    f"<AccessKeyId>{cred['access_key']}</AccessKeyId>"
                    f"<SecretAccessKey>{cred['secret_key']}</SecretAccessKey>"
                    f"<SessionToken>{cred['session_token']}</SessionToken>"
                    f"<Expiration>{exp_iso}</Expiration>"
                    f"</Credentials></{action}Result>"
                    f"</{action}Response>"
                ).encode()
                self._reply(200, body)

            def _post_policy_upload(self, bucket, fs, ctype):
                """Browser form upload (post_policy.go): authorization is
                the policy document's signature, not the Authorization
                header — verify it, honor its conditions, store `file`
                under `key`."""
                from . import s3ext

                fields = s3ext.parse_multipart(self._stashed_body, ctype)
                if "key" not in fields or "file" not in fields:
                    return self._error(400, "InvalidRequest",
                                       "form needs key and file fields")
                # S3 substitutes ${filename} with the file part's
                # client-supplied name BEFORE evaluating conditions, so
                # an eq/starts-with on $key sees the final key
                filename = fields.get(".filename.file", b"upload").decode(
                    "utf-8", "replace") or "upload"
                key = fields["key"].decode("utf-8", "replace").replace(
                    "${filename}", filename.rsplit("/", 1)[-1])
                fields = {**fields, "key": key.encode()}
                if self._key_reserved(key):
                    return self._error(403, "AccessDenied",
                                       ".multipart is a reserved namespace")
                if outer.auth is not None:
                    ok, who = s3ext.verify_post_policy(
                        fields, outer.auth.users.secret_for,
                        implicit={"bucket": bucket})
                    if not ok:
                        return self._error(403, "AccessDenied", who)
                    if not outer.auth.grant_ok(who, bucket, write=True):
                        return self._error(403, "AccessDenied",
                                           "no write grant for bucket")
                try:
                    outer._put_object(fs, key, fields["file"])
                except FsError as e:
                    if e.errno in (mn.ENOSPC, mn.EDQUOT):
                        return self._error(507, "QuotaExceeded", str(e))
                    return self._error(500, "InternalError", str(e))
                status = 204
                raw = fields.get("success_action_status")
                if raw in (b"200", b"201", b"204"):
                    status = int(raw)
                etag = hashlib.md5(fields["file"]).hexdigest()
                # the ETag persists like every other write path, so
                # GET/HEAD/conditionals work on POST-uploaded objects
                ct_field = fields.get("Content-Type")
                outer._obj_meta_save(
                    fs, key,
                    ct_field.decode() if ct_field else None, {}, etag=etag)
                body = b""
                if status == 201:
                    body = (
                        f"<?xml version='1.0'?><PostResponse>"
                        f"<Bucket>{bucket}</Bucket><Key>{xs.escape(key)}"
                        f"</Key><ETag>\"{etag}\"</ETag></PostResponse>"
                    ).encode()
                self._reply(status, body,
                            headers={"ETag": f'"{etag}"',
                                     **self._cors(bucket)})

            def do_HEAD(self):
                begun = self._begin()
                if begun is None:
                    return
                bucket, key, query = begun
                if not key:  # HeadBucket
                    if self._fs(bucket) is None:
                        return self._error(404, "NoSuchBucket", bucket)
                    if not self._check("s3:ListBucket", bucket):
                        return
                    return self._reply(200)
                if not self._check("s3:GetObject", bucket, key):
                    return
                fs = self._fs(bucket)
                if fs is None:
                    return self._error(404, "NoSuchBucket", bucket)
                if self._key_reserved(key):
                    return self._error(403, "AccessDenied",
                                       ".multipart is a reserved namespace")
                vid_q = query.get("versionId", [""])[0]
                if vid_q:
                    try:
                        vmeta = s3version.VersionStore(fs).find(key, vid_q)
                    except s3version.S3VersionError as e:
                        return self._error(e.http, e.code, str(e))
                    if vmeta["dm"]:
                        return self._error(405, "MethodNotAllowed",
                                           "version is a delete marker")
                    st = {"size": vmeta["size"]}
                    # the VERSION's metadata, not the current object's:
                    # HEAD ?versionId must agree with GET ?versionId —
                    # including 304/412, which evaluate against the
                    # addressed version's etag/mtime
                    vstate = outer._version_meta_state(fs, vmeta)
                    mct, mhdrs = outer._version_reply_headers(
                        fs, vmeta, state=vstate)
                    cond = outer._conditional(self.headers, *vstate)
                    if cond == 412:
                        return self._error(412, "PreconditionFailed", key)
                else:
                    mrec, mst = outer._obj_meta_state(fs, key)
                    if mst is None:
                        if s3version.VersionStore(fs).latest_is_marker(key):
                            return self._reply(
                                404,
                                b"<?xml version='1.0'?><Error>"
                                b"<Code>NoSuchKey</Code></Error>",
                                headers={"x-amz-delete-marker": "true"})
                        return self._error(404, "NoSuchKey", key)
                    st = mst  # one inode fetch covers size + headers
                    cond = outer._conditional(self.headers, mrec, mst)
                    if cond == 412:
                        return self._error(412, "PreconditionFailed", key)
                    mct, mhdrs = outer._meta_reply_headers(mrec, mst)
                    mhdrs = outer._null_vid_backfill(self, bucket, mhdrs)
                # HEAD: standard Content-Length describes what GET would
                # return; no body follows (RFC 9110)
                code = 304 if cond == 304 else 200
                self._audit(code, 0)
                self.send_response(code)
                self.send_header("Content-Type", mct)
                self.send_header("Content-Length", str(st["size"]))
                for hk, hv in mhdrs.items():
                    self.send_header(hk, hv)
                self.end_headers()

            def do_DELETE(self):
                begun = self._begin()
                if begun is None:
                    return
                bucket, key, query = begun
                fs = self._fs(bucket)
                if fs is None:
                    return self._error(404, "NoSuchBucket", bucket)
                if not key and "policy" in query:  # DeleteBucketPolicy
                    if not self._check("s3:DeleteBucketPolicy", bucket):
                        return
                    outer._bucket_cfg_set(fs, s3policy.XA_POLICY, None)
                    return self._reply(204)
                if not key and "lifecycle" in query:  # DeleteBucketLifecycle
                    if not self._check("s3:DeleteBucketLifecycle", bucket):
                        return
                    outer._bucket_cfg_set(fs, s3policy.XA_LIFECYCLE, None)
                    return self._reply(204)
                if not key and "cors" in query:  # DeleteBucketCors
                    if not self._check("s3:DeleteBucketCors", bucket):
                        return
                    outer._bucket_cfg_set(fs, s3policy.XA_CORS, None)
                    return self._reply(204)
                if "uploadId" in query:  # AbortMultipartUpload
                    if not self._check("s3:PutObject", bucket, key):
                        return
                    outer._abort_multipart(fs, query["uploadId"][0])
                    return self._reply(204)
                if self._key_reserved(key):
                    return self._error(403, "AccessDenied",
                                       ".multipart is a reserved namespace")
                if key and "tagging" in query:  # DeleteObjectTagging
                    if not self._check("s3:DeleteObjectTagging", bucket, key):
                        return
                    try:
                        fs.setxattr("/" + key, s3policy.XA_TAGS, None)
                    except FsError:
                        return self._error(404, "NoSuchKey", key)
                    return self._reply(204)
                if not self._check("s3:DeleteObject", bucket, key):
                    return
                vs = s3version.VersionStore(fs)
                vid_q = query.get("versionId", [""])[0]
                if vid_q:  # permanent delete of ONE version
                    try:
                        was_marker = vs.delete_version(
                            key, vid_q, self._bypass_governance())
                    except s3version.S3VersionError as e:
                        return self._error(e.http, e.code, str(e))
                    except FsError as e:
                        return self._error(500, "InternalError", str(e))
                    outer._prune_empty_dirs(fs, key)
                    hdrs = {"x-amz-version-id": vid_q}
                    if was_marker:
                        hdrs["x-amz-delete-marker"] = "true"
                    return self._reply(204, headers=hdrs)
                if vs.status():  # versioned delete: add a marker
                    try:
                        marker_vid = vs.delete(key)
                    except s3version.S3VersionError as e:
                        return self._error(e.http, e.code, str(e))
                    except FsError as e:
                        return self._error(500, "InternalError", str(e))
                    outer._prune_empty_dirs(fs, key)
                    return self._reply(204, headers={
                        "x-amz-delete-marker": "true",
                        "x-amz-version-id": marker_vid})
                try:
                    fs.unlink("/" + key)
                    outer._prune_empty_dirs(fs, key)
                except FsError:
                    return self._error(404, "NoSuchKey", key)
                self._reply(204)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.addr = f"{host}:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)

    # ---- bucket configuration (xattrs on the volume root) ----
    def _bucket_cfg(self, fs: FileSystem, xa_key: str) -> str | None:
        try:
            return fs.getxattr("/", xa_key)
        except FsError:
            return None

    def _bucket_cfg_set(self, fs: FileSystem, xa_key: str,
                        value: str | None) -> None:
        fs.setxattr("/", xa_key, value)

    # ---- multipart (staged under /.multipart/<uploadId>/) ----
    def _initiate_multipart(self, fs: FileSystem, key: str,
                            headers=None) -> str:
        import secrets

        upload_id = secrets.token_hex(12)
        for d in ("/.multipart", f"/.multipart/{upload_id}"):
            try:
                fs.mkdir(d)
            except FsError as e:
                if e.errno != mn.EEXIST:
                    raise
        fs.setxattr(f"/.multipart/{upload_id}", "s3.key", key)
        if headers is not None:
            # metadata named at initiate lands on the final object
            ct, meta = self._req_obj_meta(headers)
            if ct or meta:
                fs.setxattr(f"/.multipart/{upload_id}", s3policy.XA_META,
                            json.dumps({"ct": ct or "", "meta": meta}))
        return upload_id

    def _put_part(self, fs: FileSystem, upload_id: str, part: int,
                  data: bytes) -> str:
        import hashlib as _h

        fs.resolve(f"/.multipart/{upload_id}")  # 404 if unknown upload
        path = f"/.multipart/{upload_id}/{part:05d}"
        fs.write_file(path, data)
        etag = _h.md5(data).hexdigest()
        # persisted so ListParts is O(parts), not O(uploaded bytes)
        fs.setxattr(path, "s3.etag", etag)
        return etag

    def _complete_multipart(self, fs: FileSystem, key: str,
                            upload_id: str) -> str:
        import hashlib as _h

        staging = f"/.multipart/{upload_id}"
        initiated_for = fs.getxattr(staging, "s3.key")
        if initiated_for != key:
            raise FsError(22, f"upload {upload_id} was initiated for "
                              f"{initiated_for!r}, not {key!r}")
        parts = sorted(fs.readdir(staging))
        body = b"".join(fs.read_file(f"{staging}/{p}") for p in parts)
        etag = _h.md5(body).hexdigest()
        meta_raw = fs.getxattr(staging, s3policy.XA_META)
        # versioned buckets version multipart completions too
        self._put_object_versioned(fs, key, body, etag, bypass=False)
        if meta_raw:  # metadata captured at initiate
            rec = json.loads(meta_raw)
            self._obj_meta_save(fs, key, rec.get("ct"),
                                rec.get("meta") or {}, etag=etag)
        else:
            self._obj_meta_save(fs, key, None, {}, etag=etag)
        self._abort_multipart(fs, upload_id)  # clear staging
        return etag

    def _abort_multipart(self, fs: FileSystem, upload_id: str) -> None:
        staging = f"/.multipart/{upload_id}"
        try:
            for p in list(fs.readdir(staging)):
                fs.unlink(f"{staging}/{p}")
            fs.unlink(staging)
        except FsError:
            pass

    # ---- versioning glue (s3version.py owns the semantics) ----
    def _put_object_versioned(self, fs: FileSystem, key: str, data: bytes,
                              etag: str, bypass: bool) -> str | None:
        """PutObject through the version store when the bucket versions;
        returns the new version id (None on unversioned buckets)."""
        vs = s3version.VersionStore(fs)
        if not vs.status():
            self._put_object(fs, key, data)
            return None
        return vs.put(key, lambda: self._put_object(fs, key, data),
                      etag, bypass_governance=bypass)

    @staticmethod
    def _xml_root(data: bytes):
        import xml.etree.ElementTree as ET

        try:
            return ET.fromstring(data)
        except ET.ParseError as e:
            raise s3version.S3VersionError(400, "MalformedXML", str(e))

    def _parse_versioning_xml(self, data: bytes) -> str:
        root = self._xml_root(data)
        status = root.findtext("{*}Status") or ""
        if status not in ("Enabled", "Suspended"):
            raise s3version.S3VersionError(
                400, "MalformedXML", f"bad Status {status!r}")
        return status

    def _parse_objlock_xml(self, data: bytes) -> dict:
        root = self._xml_root(data)
        if (root.findtext("{*}ObjectLockEnabled") or "") != "Enabled":
            raise s3version.S3VersionError(
                400, "MalformedXML", "ObjectLockEnabled must be Enabled")
        conf: dict = {"enabled": True, "default": None}
        ret = root.find("{*}Rule/{*}DefaultRetention")
        if ret is not None:
            mode = ret.findtext("{*}Mode") or ""
            if mode not in ("GOVERNANCE", "COMPLIANCE"):
                raise s3version.S3VersionError(
                    400, "MalformedXML", f"bad retention Mode {mode!r}")
            days = ret.findtext("{*}Days")
            years = ret.findtext("{*}Years")
            if bool(days) == bool(years):  # exactly one, like AWS
                raise s3version.S3VersionError(
                    400, "MalformedXML",
                    "DefaultRetention needs Days XOR Years")
            conf["default"] = {"mode": mode,
                               "days": int(days) if days else 0,
                               "years": int(years) if years else 0}
        return conf

    @staticmethod
    def _objlock_to_xml(conf: dict) -> bytes:
        rule = ""
        d = conf.get("default")
        if d:
            span = (f"<Days>{d['days']}</Days>" if d.get("days")
                    else f"<Years>{d['years']}</Years>")
            rule = (f"<Rule><DefaultRetention><Mode>{d['mode']}</Mode>"
                    f"{span}</DefaultRetention></Rule>")
        return (f"<?xml version='1.0'?><ObjectLockConfiguration>"
                f"<ObjectLockEnabled>Enabled</ObjectLockEnabled>{rule}"
                f"</ObjectLockConfiguration>").encode()

    def _parse_retention_xml(self, data: bytes) -> tuple[str, float]:
        root = self._xml_root(data)
        mode = root.findtext("{*}Mode") or ""
        raw = root.findtext("{*}RetainUntilDate") or ""
        try:
            until = s3version.parse_iso8601(raw)
        except ValueError:
            raise s3version.S3VersionError(
                400, "MalformedXML", f"bad RetainUntilDate {raw!r}")
        return mode, until

    def _parse_legal_hold_xml(self, data: bytes) -> bool:
        status = self._xml_root(data).findtext("{*}Status") or ""
        if status not in ("ON", "OFF"):
            raise s3version.S3VersionError(
                400, "MalformedXML", f"bad LegalHold Status {status!r}")
        return status == "ON"

    def _list_versions_reply(self, handler, bucket: str, fs: FileSystem,
                             query: dict) -> None:
        prefix = query.get("prefix", [""])[0]
        try:
            max_keys = int(query.get("max-keys", ["1000"])[0])
        except ValueError:
            return handler._error(400, "InvalidArgument",
                                  "max-keys must be an integer")
        if max_keys < 1:
            return handler._error(400, "InvalidArgument",
                                  "max-keys must be positive")
        key_marker = query.get("key-marker", [""])[0]
        vid_marker = query.get("version-id-marker", [""])[0]
        vs = s3version.VersionStore(fs)
        page, truncated, nk, nv = vs.list_versions(
            lambda p: self._list_objects(fs, p), prefix, max_keys,
            key_marker, vid_marker)
        parts = []
        for e in page:
            latest = "true" if e["is_latest"] else "false"
            lm = s3version.iso8601(e["vts"] / 1e9)
            if e["dm"]:
                parts.append(
                    f"<DeleteMarker><Key>{xs.escape(e['key'])}</Key>"
                    f"<VersionId>{e['vid']}</VersionId>"
                    f"<IsLatest>{latest}</IsLatest>"
                    f"<LastModified>{lm}</LastModified></DeleteMarker>")
            else:
                parts.append(
                    f"<Version><Key>{xs.escape(e['key'])}</Key>"
                    f"<VersionId>{e['vid']}</VersionId>"
                    f"<IsLatest>{latest}</IsLatest>"
                    f"<LastModified>{lm}</LastModified>"
                    f"<Size>{e['size']}</Size>"
                    f"<ETag>\"{e['etag']}\"</ETag></Version>")
        markers = ""
        if truncated:
            markers = (f"<NextKeyMarker>{xs.escape(nk)}</NextKeyMarker>"
                       f"<NextVersionIdMarker>{nv}"
                       f"</NextVersionIdMarker>")
        body = (
            f"<?xml version='1.0'?><ListVersionsResult>"
            f"<Name>{bucket}</Name><Prefix>{xs.escape(prefix)}</Prefix>"
            f"<MaxKeys>{max_keys}</MaxKeys>"
            f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
            f"{markers}{''.join(parts)}</ListVersionsResult>"
        ).encode()
        handler._reply(200, body)

    # ---- object metadata (fs_volume.go xattr-backed metadata role) ----
    def _obj_meta_save(self, fs: FileSystem, key: str,
                       ctype: str | None, meta: dict,
                       etag: str | None = None) -> None:
        """Persist Content-Type + x-amz-meta-* + ETag beside the object
        (an xattr, like the reference stores OSS metadata in inode
        xattrs). An overwrite PUT always rewrites the record — stale
        metadata from a previous version of the key must not survive."""
        if ctype or meta or etag:
            fs.setxattr("/" + key, s3policy.XA_META,
                        json.dumps({"ct": ctype or "", "meta": meta,
                                    "etag": etag or ""}))
        else:
            try:
                fs.setxattr("/" + key, s3policy.XA_META, None)
            except FsError:
                pass

    def _obj_meta(self, fs: FileSystem, key: str) -> dict:
        try:
            raw = fs.getxattr("/" + key, s3policy.XA_META)
        except FsError:
            return {}
        return json.loads(raw) if raw else {}

    def _req_obj_meta(self, headers) -> tuple[str | None, dict]:
        """(content-type, user metadata) from request headers."""
        meta = {k.lower()[len("x-amz-meta-"):]: v
                for k, v in headers.items()
                if k.lower().startswith("x-amz-meta-")}
        return headers.get("Content-Type"), meta

    def _obj_meta_state(self, fs: FileSystem, key: str) -> tuple[dict, dict | None]:
        """ONE inode fetch supplying everything the reply needs —
        metadata record, size/mtime AND the live version id (its xattr
        rides the same inode) — shared by conditional evaluation and
        reply-header construction. Replaces what used to be two
        resolve+inode_get pairs per GET/HEAD."""
        try:
            inode = fs.stat("/" + key)  # walk with stat=True: ONE RPC
        except FsError:
            return {}, None
        xa = inode.get("xattr") or {}
        raw = xa.get(s3policy.XA_META)
        try:
            rec = json.loads(raw) if raw else {}
        except ValueError:
            rec = {}  # corrupt record degrades to missing metadata
        vid = xa.get(s3version.XA_VID)
        if vid:
            rec = {**rec, "_vid": vid}
        return rec, {"size": inode["size"], "mtime": inode["mtime"]}

    def _null_vid_backfill(self, handler, bucket: str, hdrs: dict) -> dict:
        """AWS: on a versioning-configured bucket, plain GET/HEAD of a
        pre-versioning object reports x-amz-version-id: null — the same
        id ListObjectVersions and GET ?versionId=null use for it."""
        if "x-amz-version-id" not in hdrs and handler._bucket_conf(
                bucket).get(s3version.XA_VERSIONING):
            hdrs = {**hdrs, "x-amz-version-id": "null"}
        return hdrs

    def _meta_reply_headers(self, rec: dict,
                            st: dict | None) -> tuple[str, dict]:
        """(content-type, extra reply headers) for GET/HEAD — user
        metadata, ETag and Last-Modified (clients and SDKs condition on
        both; see _conditional)."""
        ctype = rec.get("ct") or "application/octet-stream"
        hdrs = {f"x-amz-meta-{k}": v
                for k, v in (rec.get("meta") or {}).items()}
        if rec.get("etag"):
            hdrs["ETag"] = f'"{rec["etag"]}"'
        if rec.get("_vid"):
            # versioned buckets return the LIVE version's id on plain
            # GET/HEAD (AWS behavior sync tools rely on)
            hdrs["x-amz-version-id"] = rec["_vid"]
        if st is not None:
            hdrs["Last-Modified"] = _http_date(st["mtime"])
        return ctype, hdrs

    def _obj_meta_headers(self, fs: FileSystem, key: str) -> tuple[str, dict]:
        return self._meta_reply_headers(*self._obj_meta_state(fs, key))

    def _version_meta_state(self, fs: FileSystem,
                            vmeta: dict) -> tuple[dict, dict | None]:
        """(rec, st) for a SPECIFIC version — the same shape
        `_obj_meta_state` returns for the live object, so
        `_conditional` evaluates 304/412 against the ADDRESSED
        version's etag/mtime, not the current generation's."""
        try:
            raw = fs.getxattr(vmeta["path"], s3policy.XA_META)
            rec = json.loads(raw) if raw else {}
        except (FsError, ValueError):
            rec = {}
        if not rec.get("etag") and vmeta.get("etag"):
            rec = {**rec, "etag": vmeta["etag"]}
        st = ({"mtime": vmeta["vts"] / 1e9} if vmeta.get("vts") else None)
        return rec, st

    def _version_reply_headers(self, fs: FileSystem, vmeta: dict,
                               state: tuple | None = None
                               ) -> tuple[str, dict]:
        """(content-type, headers) for a SPECIFIC version: the archived
        object file carries its XA_META xattr (xattrs travel with the
        rename), so versions serve the same Content-Type / user
        metadata / ETag a plain GET of that generation would — incl.
        the 'null' version of a pre-versioning object, whose etag lives
        only in XA_META. Pass `state` when the caller already fetched
        `_version_meta_state` (avoids a second xattr round-trip)."""
        rec, st = (state if state is not None
                   else self._version_meta_state(fs, vmeta))
        ctype, hdrs = self._meta_reply_headers(rec, st)
        hdrs["x-amz-version-id"] = vmeta["vid"]
        return ctype, hdrs

    def _conditional(self, req_headers, rec: dict,
                     st: dict | None) -> int | None:
        """RFC 7232 / S3 conditional requests for GET/HEAD: returns 304,
        412 or None (proceed). Precedence per the RFC: If-Match and
        If-Unmodified-Since fail first (412); If-None-Match overrides
        If-Modified-Since (304)."""
        if st is None:
            return None  # the caller's 404 path owns missing keys
        etag = rec.get("etag") or ""
        # HTTP dates carry whole seconds; comparing the raw fractional
        # mtime against them breaks revalidation with our OWN
        # Last-Modified (always "modified since" by the fraction)
        mtime = int(st["mtime"])

        def match(header_val: str) -> bool:
            vals = [v.strip().strip('"') for v in header_val.split(",")]
            return "*" in vals or (bool(etag) and etag in vals)

        im = req_headers.get("If-Match")
        if im is not None and not match(im):
            return 412
        ius = req_headers.get("If-Unmodified-Since")
        if ius is not None:
            t = _parse_http_date(ius)
            if t is not None and mtime > t:
                return 412
        inm = req_headers.get("If-None-Match")
        if inm is not None:
            return 304 if match(inm) else None
        ims = req_headers.get("If-Modified-Since")
        if ims is not None:
            t = _parse_http_date(ims)
            if t is not None and mtime <= t:
                return 304
        return None

    # ---- key <-> path adaptation ----
    def _put_object(self, fs: FileSystem, key: str, data: bytes) -> None:
        parts = [p for p in key.split("/") if p]
        path = ""
        for d in parts[:-1]:
            path += "/" + d
            try:
                fs.mkdir(path)
            except FsError as e:
                if e.errno != mn.EEXIST:
                    raise
        fs.write_file("/" + key, data)

    def _list_objects(self, fs: FileSystem, prefix: str) -> list[tuple]:
        """Sorted (key, size, mtime, etag) for every object under
        prefix — everything a listing entry needs, from the ONE inode
        fetch the walk already performs."""
        out: list[tuple] = []

        def walk(path: str, keybase: str):
            for name, ino in sorted(fs.readdir(path or "/").items()):
                if not path and name in (".multipart", s3version.VDIR):
                    continue  # internal areas are not object namespace
                inode = fs.meta.inode_get(ino)
                k = f"{keybase}{name}"
                if inode["type"] == mn.DIR:
                    walk(f"{path}/{name}", f"{k}/")
                elif k.startswith(prefix):
                    raw = inode.get("xattr", {}).get(s3policy.XA_META)
                    try:
                        etag = (json.loads(raw).get("etag") or ""
                                ) if raw else ""
                    except ValueError:
                        etag = ""  # one corrupt record must not 500 listings
                    out.append((k, inode["size"], inode["mtime"], etag))

        walk("", "")
        return sorted(out)

    def _list_v2(self, fs: FileSystem, prefix: str, delimiter: str,
                 max_keys: int, token: str):
        """ListObjectsV2 semantics: delimiter groups keys into
        CommonPrefixes (one entry per group, the whole group consumed in
        the same page); the continuation token is the last RAW key the
        page consumed, so pagination resumes after a full group and is
        stable under concurrent writes."""
        all_keys = sorted(self._list_objects(fs, prefix))  # global order
        if token:
            all_keys = [t for t in all_keys if t[0] > token]
        keys: list = []
        prefixes: list = []
        last_raw = ""
        truncated = False
        i = 0
        while i < len(all_keys):
            if len(keys) + len(prefixes) >= max_keys:
                truncated = True
                break
            k, sz, mt, et = all_keys[i]
            if delimiter:
                rest = k[len(prefix):]
                d = rest.find(delimiter)
                if d >= 0:
                    cp = prefix + rest[: d + len(delimiter)]
                    prefixes.append(cp)
                    # consume the WHOLE group now so a truncation after
                    # this entry never re-yields the same CommonPrefix
                    while i < len(all_keys) and all_keys[i][0].startswith(cp):
                        last_raw = all_keys[i][0]
                        i += 1
                    continue
            keys.append((k, sz, mt, et))
            last_raw = k
            i += 1
        next_token = last_raw if truncated else ""
        return keys, prefixes, next_token, truncated

    def _prune_empty_dirs(self, fs: FileSystem, key: str) -> None:
        parts = [p for p in key.split("/") if p][:-1]
        while parts:
            path = "/" + "/".join(parts)
            try:
                if fs.meta.dentry_count(fs.resolve(path)) == 0:
                    fs.unlink(path)
                else:
                    break
            except FsError:
                break
            parts.pop()

    def start(self) -> "ObjectNode":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        for sink in self.audit_sinks:
            try:
                sink.close()  # flush buffered audit events
            except Exception:
                pass
