"""ObjectNode: S3-compatible HTTP gateway over the FS client.

Role parity: objectnode/ — S3 REST semantics (PutObject/GetObject/
DeleteObject/HeadObject/ListObjectsV2/CreateBucket, fs adapter
fs_volume.go:617 PutObject / :1684 ReadFile). Buckets map to volumes;
object keys map to nested paths (directories are created on demand and
pruned on delete, the same key<->path adaptation the reference's volume
adapter performs). Signature validation (V4) is pluggable via an
authenticator callable; the default accepts all (auth service lands with
the authnode component).
"""

from __future__ import annotations

import hashlib
import threading
import urllib.parse
import xml.sax.saxutils as xs
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import metanode as mn
from .client import FileSystem, FsError


class ObjectNode:
    def __init__(self, volumes: dict[str, FileSystem], host="127.0.0.1", port=0,
                 authenticator=None):
        self.volumes = dict(volumes)
        self.auth = authenticator
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            # ---- helpers ----
            def _split(self):
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.lstrip("/").split("/", 1)
                bucket = parts[0]
                key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
                query = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)
                return bucket, key, query

            def _fs(self, bucket) -> FileSystem | None:
                return outer.volumes.get(bucket)

            def _key_reserved(self, key: str) -> bool:
                # the multipart staging area is internal: direct key ops
                # on it would expose/corrupt other clients' uploads
                return key.split("/", 1)[0] == ".multipart"

            def _reply(self, code, body=b"", ctype="application/xml",
                       headers=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def _error(self, code, s3code, msg):
                body = (
                    f"<?xml version='1.0'?><Error><Code>{s3code}</Code>"
                    f"<Message>{xs.escape(msg)}</Message></Error>"
                ).encode()
                self._reply(code, body)

            def _authorized(self) -> bool:
                if outer.auth is None:
                    return True
                return outer.auth(self)

            # ---- verbs ----
            def do_PUT(self):
                # drain the body BEFORE any reply: leftover body bytes
                # desync HTTP/1.1 keep-alive clients. The authenticator
                # drains (and stashes) it as part of signature hashing.
                if outer.auth is None:
                    n = int(self.headers.get("Content-Length") or 0)
                    data = self.rfile.read(n)
                else:
                    if not self._authorized():
                        return self._error(403, "AccessDenied", "bad signature")
                    data = getattr(self, "_stashed_body", b"")
                bucket, key, query = self._split()
                if not key:  # CreateBucket
                    if bucket not in outer.volumes:
                        return self._error(404, "NoSuchBucket",
                                           f"no volume backs {bucket}")
                    return self._reply(200)
                fs = self._fs(bucket)
                if fs is None:
                    return self._error(404, "NoSuchBucket", bucket)
                if self._key_reserved(key):
                    return self._error(403, "AccessDenied",
                                       ".multipart is a reserved namespace")
                if "uploadId" in query and "partNumber" in query:  # UploadPart
                    if self.headers.get("x-amz-copy-source"):
                        # refusing beats silently storing the empty body
                        return self._error(501, "NotImplemented",
                                           "UploadPartCopy is not supported")
                    upload_id = query["uploadId"][0]
                    try:
                        part = int(query["partNumber"][0])
                    except ValueError:
                        return self._error(400, "InvalidPart",
                                           "partNumber must be an integer")
                    if not 1 <= part <= 10000:  # S3's own part limit
                        return self._error(400, "InvalidPart",
                                           f"partNumber {part} out of range")
                    try:
                        etag = outer._put_part(fs, upload_id, part, data)
                    except FsError as e:
                        return self._error(404, "NoSuchUpload", str(e))
                    return self._reply(200, headers={"ETag": f'"{etag}"'})
                src = self.headers.get("x-amz-copy-source", "")
                is_copy = bool(src)
                if is_copy:  # CopyObject: data comes from /bucket/key
                    sb, _, sk = src.lstrip("/").partition("/")
                    sk = urllib.parse.unquote(sk)
                    sfs = self._fs(sb)
                    if sfs is None or not sk:
                        return self._error(404, "NoSuchBucket", sb)
                    if self._key_reserved(sk):
                        return self._error(403, "AccessDenied",
                                           ".multipart is a reserved namespace")
                    try:
                        data = sfs.read_file("/" + sk)
                    except FsError:
                        return self._error(404, "NoSuchKey", sk)
                try:
                    outer._put_object(fs, key, data)
                except FsError as e:
                    return self._error(500, "InternalError", str(e))
                etag = hashlib.md5(data).hexdigest()
                if is_copy:
                    body = (f"<?xml version='1.0'?><CopyObjectResult>"
                            f"<ETag>\"{etag}\"</ETag></CopyObjectResult>").encode()
                    return self._reply(200, body)
                self._reply(200, headers={"ETag": f'"{etag}"'})

            def do_POST(self):
                # multipart lifecycle: InitiateMultipartUpload (?uploads)
                # and CompleteMultipartUpload (?uploadId=...)
                if outer.auth is None:
                    n = int(self.headers.get("Content-Length") or 0)
                    self.rfile.read(n)
                elif not self._authorized():
                    return self._error(403, "AccessDenied", "bad signature")
                bucket, key, query = self._split()
                fs = self._fs(bucket)
                if fs is None:
                    return self._error(404, "NoSuchBucket", bucket)
                if "uploads" in query:
                    if not key:
                        return self._error(400, "InvalidRequest",
                                           "multipart upload needs a key")
                    upload_id = outer._initiate_multipart(fs, key)
                    body = (
                        f"<?xml version='1.0'?><InitiateMultipartUploadResult>"
                        f"<Bucket>{bucket}</Bucket><Key>{xs.escape(key)}</Key>"
                        f"<UploadId>{upload_id}</UploadId>"
                        f"</InitiateMultipartUploadResult>"
                    ).encode()
                    return self._reply(200, body)
                if "uploadId" in query:
                    try:
                        etag = outer._complete_multipart(
                            fs, key, query["uploadId"][0]
                        )
                    except FsError as e:
                        return self._error(404, "NoSuchUpload", str(e))
                    body = (
                        f"<?xml version='1.0'?><CompleteMultipartUploadResult>"
                        f"<Key>{xs.escape(key)}</Key><ETag>\"{etag}\"</ETag>"
                        f"</CompleteMultipartUploadResult>"
                    ).encode()
                    return self._reply(200, body)
                self._error(400, "InvalidRequest", "unsupported POST")

            def do_GET(self):
                if not self._authorized():
                    return self._error(403, "AccessDenied", "bad signature")
                bucket, key, query = self._split()
                fs = self._fs(bucket)
                if fs is None:
                    return self._error(404, "NoSuchBucket", bucket)
                if key and self._key_reserved(key):
                    return self._error(403, "AccessDenied",
                                       ".multipart is a reserved namespace")
                if not key:  # ListObjectsV2 (+ delimiter and pagination)
                    prefix = query.get("prefix", [""])[0]
                    delimiter = query.get("delimiter", [""])[0]
                    try:
                        max_keys = int(query.get("max-keys", ["1000"])[0])
                    except ValueError:
                        return self._error(400, "InvalidArgument",
                                           "max-keys must be an integer")
                    if max_keys < 1:
                        return self._error(400, "InvalidArgument",
                                           "max-keys must be positive")
                    token = query.get("continuation-token", [""])[0]
                    keys, prefixes, next_token, truncated = outer._list_v2(
                        fs, prefix, delimiter, max_keys, token
                    )
                    items = "".join(
                        f"<Contents><Key>{xs.escape(k)}</Key>"
                        f"<Size>{sz}</Size></Contents>"
                        for k, sz in keys
                    )
                    cps = "".join(
                        f"<CommonPrefixes><Prefix>{xs.escape(p)}</Prefix>"
                        f"</CommonPrefixes>"
                        for p in prefixes
                    )
                    nt = (f"<NextContinuationToken>{xs.escape(next_token)}"
                          f"</NextContinuationToken>") if next_token else ""
                    body = (
                        f"<?xml version='1.0'?><ListBucketResult>"
                        f"<Name>{bucket}</Name><Prefix>{xs.escape(prefix)}</Prefix>"
                        f"<Delimiter>{xs.escape(delimiter)}</Delimiter>"
                        f"<MaxKeys>{max_keys}</MaxKeys>"
                        f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
                        f"<KeyCount>{len(keys) + len(prefixes)}</KeyCount>"
                        f"{items}{cps}{nt}"
                        f"</ListBucketResult>"
                    ).encode()
                    return self._reply(200, body)
                rng_hdr = self.headers.get("Range", "")
                span = None
                if rng_hdr.startswith("bytes=") and "," not in rng_hdr:
                    try:
                        lo_s, _, hi_s = rng_hdr[6:].partition("-")
                        span = ((int(lo_s) if lo_s else None),
                                (int(hi_s) if hi_s else None))
                        if span == (None, None):
                            span = None
                    except ValueError:
                        # RFC 9110 / S3: an unparseable Range header is
                        # IGNORED (full 200 body), never an error
                        span = None
                try:
                    if span is not None:
                        st = fs.stat("/" + key)
                        size = st["size"]
                        lo, hi = span
                        if lo is None:  # suffix range: last N bytes
                            lo, hi = max(0, size - hi), size - 1
                        else:
                            hi = size - 1 if hi is None else min(hi, size - 1)
                        if lo > hi or lo >= size:
                            return self._reply(
                                416,
                                b"<?xml version='1.0'?><Error>"
                                b"<Code>InvalidRange</Code></Error>",
                                headers={"Content-Range": f"bytes */{size}"})
                        data = fs.read_file("/" + key, offset=lo,
                                            length=hi - lo + 1)
                        return self._reply(
                            206, data, ctype="application/octet-stream",
                            headers={"Content-Range":
                                     f"bytes {lo}-{hi}/{size}"})
                    data = fs.read_file("/" + key)
                except FsError:
                    return self._error(404, "NoSuchKey", key)
                self._reply(200, data, ctype="application/octet-stream")

            def do_HEAD(self):
                if not self._authorized():
                    return self._error(403, "AccessDenied", "bad signature")
                bucket, key, _ = self._split()
                fs = self._fs(bucket)
                if fs is None:
                    return self._error(404, "NoSuchBucket", bucket)
                if self._key_reserved(key):
                    return self._error(403, "AccessDenied",
                                       ".multipart is a reserved namespace")
                try:
                    st = fs.stat("/" + key)
                except FsError:
                    return self._error(404, "NoSuchKey", key)
                # HEAD: standard Content-Length describes what GET would
                # return; no body follows (RFC 9110)
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(st["size"]))
                self.end_headers()

            def do_DELETE(self):
                if not self._authorized():
                    return self._error(403, "AccessDenied", "bad signature")
                bucket, key, query = self._split()
                fs = self._fs(bucket)
                if fs is None:
                    return self._error(404, "NoSuchBucket", bucket)
                if "uploadId" in query:  # AbortMultipartUpload
                    outer._abort_multipart(fs, query["uploadId"][0])
                    return self._reply(204)
                if self._key_reserved(key):
                    return self._error(403, "AccessDenied",
                                       ".multipart is a reserved namespace")
                try:
                    fs.unlink("/" + key)
                    outer._prune_empty_dirs(fs, key)
                except FsError:
                    return self._error(404, "NoSuchKey", key)
                self._reply(204)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.addr = f"{host}:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)

    # ---- multipart (staged under /.multipart/<uploadId>/) ----
    def _initiate_multipart(self, fs: FileSystem, key: str) -> str:
        import secrets

        upload_id = secrets.token_hex(12)
        for d in ("/.multipart", f"/.multipart/{upload_id}"):
            try:
                fs.mkdir(d)
            except FsError as e:
                if e.errno != mn.EEXIST:
                    raise
        fs.setxattr(f"/.multipart/{upload_id}", "s3.key", key)
        return upload_id

    def _put_part(self, fs: FileSystem, upload_id: str, part: int,
                  data: bytes) -> str:
        import hashlib as _h

        fs.resolve(f"/.multipart/{upload_id}")  # 404 if unknown upload
        fs.write_file(f"/.multipart/{upload_id}/{part:05d}", data)
        return _h.md5(data).hexdigest()

    def _complete_multipart(self, fs: FileSystem, key: str,
                            upload_id: str) -> str:
        import hashlib as _h

        staging = f"/.multipart/{upload_id}"
        initiated_for = fs.getxattr(staging, "s3.key")
        if initiated_for != key:
            raise FsError(22, f"upload {upload_id} was initiated for "
                              f"{initiated_for!r}, not {key!r}")
        parts = sorted(fs.readdir(staging))
        body = b"".join(fs.read_file(f"{staging}/{p}") for p in parts)
        self._put_object(fs, key, body)
        self._abort_multipart(fs, upload_id)  # clear staging
        return _h.md5(body).hexdigest()

    def _abort_multipart(self, fs: FileSystem, upload_id: str) -> None:
        staging = f"/.multipart/{upload_id}"
        try:
            for p in list(fs.readdir(staging)):
                fs.unlink(f"{staging}/{p}")
            fs.unlink(staging)
        except FsError:
            pass

    # ---- key <-> path adaptation ----
    def _put_object(self, fs: FileSystem, key: str, data: bytes) -> None:
        parts = [p for p in key.split("/") if p]
        path = ""
        for d in parts[:-1]:
            path += "/" + d
            try:
                fs.mkdir(path)
            except FsError as e:
                if e.errno != mn.EEXIST:
                    raise
        fs.write_file("/" + key, data)

    def _list_objects(self, fs: FileSystem, prefix: str) -> list[tuple[str, int]]:
        out: list[tuple[str, int]] = []

        def walk(path: str, keybase: str):
            for name, ino in sorted(fs.readdir(path or "/").items()):
                if not path and name == ".multipart":
                    continue  # staging area is not object namespace
                inode = fs.meta.inode_get(ino)
                k = f"{keybase}{name}"
                if inode["type"] == mn.DIR:
                    walk(f"{path}/{name}", f"{k}/")
                elif k.startswith(prefix):
                    out.append((k, inode["size"]))

        walk("", "")
        return sorted(out)

    def _list_v2(self, fs: FileSystem, prefix: str, delimiter: str,
                 max_keys: int, token: str):
        """ListObjectsV2 semantics: delimiter groups keys into
        CommonPrefixes (one entry per group, the whole group consumed in
        the same page); the continuation token is the last RAW key the
        page consumed, so pagination resumes after a full group and is
        stable under concurrent writes."""
        all_keys = sorted(self._list_objects(fs, prefix))  # global order
        if token:
            all_keys = [(k, sz) for k, sz in all_keys if k > token]
        keys: list = []
        prefixes: list = []
        last_raw = ""
        truncated = False
        i = 0
        while i < len(all_keys):
            if len(keys) + len(prefixes) >= max_keys:
                truncated = True
                break
            k, sz = all_keys[i]
            if delimiter:
                rest = k[len(prefix):]
                d = rest.find(delimiter)
                if d >= 0:
                    cp = prefix + rest[: d + len(delimiter)]
                    prefixes.append(cp)
                    # consume the WHOLE group now so a truncation after
                    # this entry never re-yields the same CommonPrefix
                    while i < len(all_keys) and all_keys[i][0].startswith(cp):
                        last_raw = all_keys[i][0]
                        i += 1
                    continue
            keys.append((k, sz))
            last_raw = k
            i += 1
        next_token = last_raw if truncated else ""
        return keys, prefixes, next_token, truncated

    def _prune_empty_dirs(self, fs: FileSystem, key: str) -> None:
        parts = [p for p in key.split("/") if p][:-1]
        while parts:
            path = "/" + "/".join(parts)
            try:
                if fs.meta.dentry_count(fs.resolve(path)) == 0:
                    fs.unlink(path)
                else:
                    break
            except FsError:
                break
            parts.pop()

    def start(self) -> "ObjectNode":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
