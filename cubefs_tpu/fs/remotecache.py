"""RemoteCache: distributed read-acceleration cache.

Role parity: remotecache/ — flashnode (cache engine serving hot extent
blocks, cachengine/engine.go:42) + flashgroupmanager (slot-routed flash
groups, flashgroupmanager/cluster.go) + the client read hook
(sdk/data/stream/stream_remote_cache.go) with consistent-hash slot
routing (proto/distributed_cache.go).

FlashNode: LRU of (dp, extent, block) -> bytes with a capacity budget.
FlashGroupManager: slot ring mapping cache keys to flash groups.
CachedReader: ExtentClient wrapper that consults the ring before the
datanode and populates on miss.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

from ..utils import metrics, rpc

CACHE_BLOCK = 128 << 10

cache_ops = metrics.DEFAULT.counter(
    "cubefs_flashcache_ops_total", "flash cache results", ("result",)
)


class FlashNode:
    """In-RAM LRU cache engine (tmpfs-class tier of the reference)."""

    def __init__(self, capacity_bytes: int = 256 << 20):
        self.capacity = capacity_bytes
        self._lock = threading.Lock()
        self._lru: OrderedDict[str, bytes] = OrderedDict()
        self._used = 0

    def get(self, key: str) -> bytes | None:
        with self._lock:
            data = self._lru.get(key)
            if data is not None:
                self._lru.move_to_end(key)
            return data

    def put(self, key: str, data: bytes) -> None:
        with self._lock:
            old = self._lru.pop(key, None)
            if old is not None:
                self._used -= len(old)
            self._lru[key] = data
            self._used += len(data)
            while self._used > self.capacity and self._lru:
                _, evicted = self._lru.popitem(last=False)
                self._used -= len(evicted)

    def stats(self) -> dict:
        with self._lock:
            return {"items": len(self._lru), "bytes": self._used,
                    "capacity": self.capacity}

    # ---------------- RPC surface ----------------
    def rpc_cache_get(self, args, body):
        data = self.get(args["key"])
        if data is None:
            raise rpc.RpcError(404, "cache miss")
        return {}, data

    def rpc_cache_put(self, args, body):
        self.put(args["key"], body)
        return {}

    def rpc_stats(self, args, body):
        return self.stats()


class FlashGroupManager:
    """Slot ring: SLOTS hash slots spread over flash groups (each group =
    a set of flashnode addrs; reads hit the first healthy member)."""

    SLOTS = 1024

    def __init__(self):
        self._lock = threading.Lock()
        self.groups: dict[int, list[str]] = {}

    def register_group(self, group_id: int, addrs: list[str]) -> None:
        with self._lock:
            self.groups[group_id] = list(addrs)

    def ring(self) -> dict[int, list[str]]:
        with self._lock:
            return {g: list(a) for g, a in self.groups.items()}

    @classmethod
    def slot_of(cls, key: str) -> int:
        return int.from_bytes(hashlib.md5(key.encode()).digest()[:4], "big") % cls.SLOTS

    def group_for(self, key: str) -> list[str]:
        with self._lock:
            if not self.groups:
                return []
            ids = sorted(self.groups)
            gid = ids[self.slot_of(key) % len(ids)]
            return list(self.groups[gid])

    # ---------------- RPC surface ----------------
    def rpc_register_group(self, args, body):
        self.register_group(args["group_id"], args["addrs"])
        return {}

    def rpc_ring(self, args, body):
        return {"groups": {str(k): v for k, v in self.ring().items()}}


class CachedReader:
    """Read-through wrapper for ExtentClient: flash ring first, datanode
    on miss, then populate (the client hook in stream_remote_cache.go)."""

    def __init__(self, extent_client, fgm: FlashGroupManager, node_pool):
        self.inner = extent_client
        self.fgm = fgm
        self.nodes = node_pool
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _key(dp_id: int, extent_id: int, block: int) -> str:
        return f"{dp_id}/{extent_id}/{block}"

    def read_block(self, dp: dict, extent_id: int, block: int,
                   length: int, fetch_len: int) -> bytes:
        """length = bytes the caller needs from block start; fetch_len =
        the block's valid span in the extent (tail blocks are short, and
        replicas reject short-read requests beyond the span)."""
        key = self._key(dp["dp_id"], extent_id, block)
        for addr in self.fgm.group_for(key):
            try:
                _, data = self.nodes.get(addr).call("cache_get", {"key": key})
                if len(data) >= length:  # stale short entry -> refetch
                    self.hits += 1
                    cache_ops.inc(result="hit")
                    return data[:length]
            except rpc.RpcError:
                continue
        self.misses += 1
        cache_ops.inc(result="miss")
        data = self.inner._read_replicated(
            dp, extent_id, block * CACHE_BLOCK, fetch_len
        )
        for addr in self.fgm.group_for(key):
            try:
                self.nodes.get(addr).call("cache_put", {"key": key}, data)
                break
            except rpc.RpcError:
                continue
        return data[:length]

    def read(self, inode: dict, offset: int, length: int) -> bytes:
        """Cache-block-aligned read of one inode's bytes."""
        size = inode["size"]
        if offset >= size:
            return b""
        length = min(length, size - offset)
        out = bytearray(length)
        for ek in inode["extents"]:
            lo = max(offset, ek["file_offset"])
            hi = min(offset + length, ek["file_offset"] + ek["size"])
            if lo >= hi:
                continue
            dp = self.inner._dp_by_id(ek["dp_id"])
            ext_end = ek["ext_offset"] + ek["size"]  # extent's valid span
            pos = lo
            while pos < hi:
                ext_pos = ek["ext_offset"] + (pos - ek["file_offset"])
                block = ext_pos // CACHE_BLOCK
                in_block = ext_pos % CACHE_BLOCK
                take = min(hi - pos, CACHE_BLOCK - in_block)
                fetch = min(CACHE_BLOCK, ext_end - block * CACHE_BLOCK)
                blk = self.read_block(dp, ek["extent_id"], block,
                                      in_block + take, fetch)
                out[pos - offset : pos - offset + take] = blk[in_block : in_block + take]
                pos += take
        return bytes(out)
