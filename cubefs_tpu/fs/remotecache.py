"""RemoteCache: distributed read-acceleration cache.

Role parity: remotecache/ — flashnode (cache engine serving hot extent
blocks, cachengine/engine.go:42) + flashgroupmanager (slot-routed flash
groups, flashgroupmanager/cluster.go) + the client read hook
(sdk/data/stream/stream_remote_cache.go) with consistent-hash slot
routing (proto/distributed_cache.go).

FlashNode: LRU of (dp, extent, block) -> bytes with a capacity budget.
FlashGroupManager: slot ring mapping cache keys to flash groups.
CachedReader: ExtentClient wrapper that consults the ring before the
datanode and populates on miss.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import OrderedDict

from ..utils import metrics, rpc
from ..utils.fsm import ReplicatedFsm

CACHE_BLOCK = 128 << 10

cache_ops = metrics.DEFAULT.counter(
    "cubefs_flashcache_ops_total", "flash cache results", ("result",)
)


class FlashNode:
    """In-RAM LRU cache engine (tmpfs-class tier of the reference)."""

    def __init__(self, capacity_bytes: int = 256 << 20):
        self.capacity = capacity_bytes
        self._lock = threading.Lock()
        self._lru: OrderedDict[str, bytes] = OrderedDict()
        self._used = 0

    def get(self, key: str) -> bytes | None:
        with self._lock:
            data = self._lru.get(key)
            if data is not None:
                self._lru.move_to_end(key)
            return data

    def put(self, key: str, data: bytes) -> None:
        with self._lock:
            old = self._lru.pop(key, None)
            if old is not None:
                self._used -= len(old)
            self._lru[key] = data
            self._used += len(data)
            while self._used > self.capacity and self._lru:
                _, evicted = self._lru.popitem(last=False)
                self._used -= len(evicted)

    def stats(self) -> dict:
        with self._lock:
            return {"items": len(self._lru), "bytes": self._used,
                    "capacity": self.capacity}

    # ---------------- RPC surface ----------------
    def rpc_cache_get(self, args, body):
        data = self.get(args["key"])
        if data is None:
            raise rpc.RpcError(404, "cache miss")
        return {}, data

    def rpc_cache_put(self, args, body):
        self.put(args["key"], body)
        return {}

    def rpc_stats(self, args, body):
        return self.stats()


class FlashGroupManager(ReplicatedFsm):
    """Flash-group control service (remotecache/flashgroupmanager/
    cluster.go analog): a raft/wal-replicated registry of flash groups
    (each group = a set of flashnode addrs owning a share of the hash
    slot ring), with flashnode heartbeats deciding member health. Group
    membership mutations flow through the ONE replicated commit door;
    the ring view carries an epoch so clients can cache and refresh."""

    SLOTS = 1024
    HEARTBEAT_TIMEOUT = 10.0

    def __init__(self, data_dir: str | None = None, me: str | None = None,
                 peers: list[str] | None = None, node_pool=None):
        self._lock = threading.RLock()
        self.groups: dict[int, dict] = {}  # gid -> {addrs, status}
        self.epoch = 0
        self._hb: dict[str, float] = {}  # flashnode addr -> last heartbeat
        self._init_fsm("fgm", data_dir, me, peers, node_pool)

    # ---- FSM contract ----
    def _state_dict(self) -> dict:
        return {"groups": {str(g): v for g, v in self.groups.items()},
                "epoch": self.epoch}

    def _load_state_dict(self, st: dict) -> None:
        self.groups = {int(g): v for g, v in st["groups"].items()}
        self.epoch = st.get("epoch", 0)

    def _state_bytes(self) -> bytes:
        with self._lock:
            return json.dumps(self._state_dict()).encode()

    def _restore_bytes(self, data: bytes) -> None:
        with self._lock:
            self._load_state_dict(json.loads(data))

    def _apply(self, rec: dict):
        rec = dict(rec)
        op = rec.pop("op")
        with self._lock:
            self.epoch += 1
            return getattr(self, f"_apply_{op}")(**rec)

    def _apply_put_group(self, group_id: int, addrs: list[str],
                         status: str = "active") -> None:
        self.groups[int(group_id)] = {"addrs": list(addrs), "status": status}

    def _apply_remove_group(self, group_id: int) -> None:
        self.groups.pop(int(group_id), None)

    def _apply_set_status(self, group_id: int, status: str) -> None:
        g = self.groups.get(int(group_id))
        if g is not None:  # tolerate replay after a concurrent removal
            g["status"] = status

    # ---- admin / heartbeat ----
    def register_group(self, group_id: int, addrs: list[str]) -> None:
        self._commit({"op": "put_group", "group_id": group_id,
                      "addrs": list(addrs)})

    def remove_group(self, group_id: int) -> None:
        self._commit({"op": "remove_group", "group_id": group_id})

    def set_group_status(self, group_id: int, status: str) -> None:
        if status not in ("active", "inactive"):
            raise ValueError(f"bad status {status!r}")
        with self._lock:
            if int(group_id) not in self.groups:
                raise ValueError(f"unknown flash group {group_id}")
        self._commit({"op": "set_status", "group_id": group_id,
                      "status": status})

    def flashnode_heartbeat(self, addr: str) -> None:
        with self._lock:
            self._hb[addr] = time.time()

    def _member_alive(self, addr: str) -> bool:
        hb = self._hb.get(addr)
        # never-heartbeated members count as alive (static deployments
        # without the heartbeat loop keep working)
        return hb is None or time.time() - hb <= self.HEARTBEAT_TIMEOUT

    def ring(self) -> dict[int, list[str]]:
        """Active groups with their LIVE members only."""
        with self._lock:
            out = {}
            for g, info in self.groups.items():
                if info.get("status") != "active":
                    continue
                live = [a for a in info["addrs"] if self._member_alive(a)]
                if live:
                    out[g] = live
            return out

    @classmethod
    def slot_of(cls, key: str) -> int:
        return int.from_bytes(hashlib.md5(key.encode()).digest()[:4], "big") % cls.SLOTS

    def group_for(self, key: str) -> list[str]:
        ring = self.ring()
        if not ring:
            return []
        ids = sorted(ring)
        gid = ids[self.slot_of(key) % len(ids)]
        return list(ring[gid])

    # ---------------- RPC surface ----------------
    def rpc_register_group(self, args, body):
        self._leader_gate()
        self.register_group(args["group_id"], args["addrs"])
        return {}

    def rpc_remove_group(self, args, body):
        self._leader_gate()
        self.remove_group(args["group_id"])
        return {}

    def rpc_set_group_status(self, args, body):
        self._leader_gate()
        self.set_group_status(args["group_id"], args["status"])
        return {}

    def rpc_flashnode_heartbeat(self, args, body):
        self.flashnode_heartbeat(args["addr"])
        return {}

    def rpc_ring(self, args, body):
        with self._lock:
            epoch = self.epoch
        return {"groups": {str(k): v for k, v in self.ring().items()},
                "epoch": epoch}


class CachedReader:
    """Read-through wrapper for ExtentClient: flash ring first, datanode
    on miss, then populate (the client hook in stream_remote_cache.go)."""

    def __init__(self, extent_client, fgm: FlashGroupManager, node_pool):
        self.inner = extent_client
        self.fgm = fgm
        self.nodes = node_pool
        self.hits = 0
        self.misses = 0

    def _flash_client(self, addr: str):
        # NodePool.get already caches one Client per addr and stays
        # current across rebinds; FlashClient is a stateless wrapper
        from ..sdk.clients import FlashClient

        return FlashClient(self.nodes.get(addr))

    @staticmethod
    def _key(dp_id: int, extent_id: int, block: int) -> str:
        return f"{dp_id}/{extent_id}/{block}"

    def read_block(self, dp: dict, extent_id: int, block: int,
                   length: int, fetch_len: int) -> bytes:
        """length = bytes the caller needs from block start; fetch_len =
        the block's valid span in the extent (tail blocks are short, and
        replicas reject short-read requests beyond the span)."""
        key = self._key(dp["dp_id"], extent_id, block)
        for addr in self.fgm.group_for(key):
            try:
                data = self._flash_client(addr).cache_get(key)
                if len(data) >= length:  # stale short entry -> refetch
                    self.hits += 1
                    cache_ops.inc(result="hit")
                    return data[:length]
            except rpc.RpcError:
                continue
        self.misses += 1
        cache_ops.inc(result="miss")
        data = self.inner._read_replicated(
            dp, extent_id, block * CACHE_BLOCK, fetch_len
        )
        for addr in self.fgm.group_for(key):
            try:
                self._flash_client(addr).cache_put(key, data)
                break
            except rpc.RpcError:
                continue
        return data[:length]

    def read(self, inode: dict, offset: int, length: int) -> bytes:
        """Cache-block-aligned read of one inode's bytes."""
        size = inode["size"]
        if offset >= size:
            return b""
        length = min(length, size - offset)
        out = bytearray(length)
        for ek in inode["extents"]:
            lo = max(offset, ek["file_offset"])
            hi = min(offset + length, ek["file_offset"] + ek["size"])
            if lo >= hi:
                continue
            dp = self.inner._dp_by_id(ek["dp_id"])
            ext_end = ek["ext_offset"] + ek["size"]  # extent's valid span
            pos = lo
            while pos < hi:
                ext_pos = ek["ext_offset"] + (pos - ek["file_offset"])
                block = ext_pos // CACHE_BLOCK
                in_block = ext_pos % CACHE_BLOCK
                take = min(hi - pos, CACHE_BLOCK - in_block)
                fetch = min(CACHE_BLOCK, ext_end - block * CACHE_BLOCK)
                blk = self.read_block(dp, ek["extent_id"], block,
                                      in_block + take, fetch)
                out[pos - offset : pos - offset + take] = blk[in_block : in_block + take]
                pos += take
        return bytes(out)
