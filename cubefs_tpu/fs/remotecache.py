"""RemoteCache: distributed read-acceleration cache.

Role parity: remotecache/ — flashnode (cache engine serving hot extent
blocks, cachengine/engine.go:42) + flashgroupmanager (slot-routed flash
groups, flashgroupmanager/cluster.go) + the client read hook
(sdk/data/stream/stream_remote_cache.go) with consistent-hash slot
routing (proto/distributed_cache.go).

FlashNode: LRU of (dp, extent, block) -> bytes with a capacity budget.
FlashGroupManager: slot ring mapping cache keys to flash groups.
CachedReader: ExtentClient wrapper that consults the ring before the
datanode and populates on miss.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import OrderedDict

from ..utils import lockwitness, metrics, qos, rpc, trace
from ..utils.fsm import ReplicatedFsm
from ..utils.retry import CircuitBreaker

CACHE_BLOCK = 128 << 10

cache_ops = metrics.DEFAULT.counter(
    "cubefs_flashcache_ops_total", "flash cache results", ("result",)
)


class FlashNode:
    """In-RAM LRU cache engine (tmpfs-class tier of the reference).

    Eviction is burn-rate-informed: entries carry the request family
    (`path`) that populated them, and when the budget is exceeded the
    node samples the EVICT_SAMPLE oldest entries and evicts the one
    whose path is healthiest (lowest brownout level) — a path that is
    burning SLO budget keeps its working set warm at the expense of
    paths with latency headroom. Untagged entries and an all-healthy
    gate degrade to plain LRU (oldest wins every tie), so the default
    behavior is bit-identical to the pre-change cache."""

    EVICT_SAMPLE = 8

    def __init__(self, capacity_bytes: int = 256 << 20, *, gate=None):
        self.capacity = capacity_bytes
        self._gate = gate  # None -> qos.DEFAULT, lazily
        self._lock = lockwitness.make_lock("FlashNode._lock")
        self._lru: OrderedDict[str, bytes] = OrderedDict()
        self._paths: dict[str, str] = {}  # key -> populating path
        self._used = 0

    def get(self, key: str) -> bytes | None:
        with self._lock:
            data = self._lru.get(key)
            if data is not None:
                self._lru.move_to_end(key)
            return data

    def _evict_one(self) -> None:
        cands = []
        for k in self._lru:  # OrderedDict iterates oldest-first
            cands.append(k)
            if len(cands) >= self.EVICT_SAMPLE:
                break
        victim = cands[0]
        if len(cands) > 1 and any(self._paths.get(k) for k in cands):
            if self._gate is None:
                self._gate = qos.DEFAULT
            best_lvl = None
            for k in cands:
                p = self._paths.get(k)
                lvl = self._gate.level(p) if p else 0
                if best_lvl is None or lvl < best_lvl:
                    best_lvl, victim = lvl, k
        evicted = self._lru.pop(victim)
        self._paths.pop(victim, None)
        self._used -= len(evicted)

    def put(self, key: str, data: bytes, path: str | None = None) -> None:
        with self._lock:
            old = self._lru.pop(key, None)
            if old is not None:
                self._used -= len(old)
            self._lru[key] = data
            if path is not None:
                self._paths[key] = path
            else:
                self._paths.pop(key, None)
            self._used += len(data)
            while self._used > self.capacity and self._lru:
                self._evict_one()

    def delete(self, key: str) -> bool:
        with self._lock:
            old = self._lru.pop(key, None)
            self._paths.pop(key, None)
            if old is not None:
                self._used -= len(old)
            return old is not None

    def stats(self) -> dict:
        with self._lock:
            return {"items": len(self._lru), "bytes": self._used,
                    "capacity": self.capacity}

    # ---------------- RPC surface ----------------
    def rpc_cache_get(self, args, body):
        data = self.get(args["key"])
        if data is None:
            raise rpc.RpcError(404, "cache miss")
        return {}, data

    def rpc_cache_put(self, args, body):
        self.put(args["key"], body, path=args.get("path"))
        return {}

    def rpc_cache_delete(self, args, body):
        # idempotent by construction: deleting an absent key is a no-op
        return {"deleted": self.delete(args["key"])}

    def rpc_stats(self, args, body):
        return self.stats()


class FlashGroupManager(ReplicatedFsm):
    """Flash-group control service (remotecache/flashgroupmanager/
    cluster.go analog): a raft/wal-replicated registry of flash groups
    (each group = a set of flashnode addrs owning a share of the hash
    slot ring), with flashnode heartbeats deciding member health. Group
    membership mutations flow through the ONE replicated commit door;
    the ring view carries an epoch so clients can cache and refresh."""

    SLOTS = 1024
    HEARTBEAT_TIMEOUT = 10.0

    def __init__(self, data_dir: str | None = None, me: str | None = None,
                 peers: list[str] | None = None, node_pool=None):
        self._lock = lockwitness.make_rlock("FlashGroupManager._lock")
        self.groups: dict[int, dict] = {}  # gid -> {addrs, status}
        self.epoch = 0
        self._hb: dict[str, float] = {}  # flashnode addr -> last heartbeat
        self._init_fsm("fgm", data_dir, me, peers, node_pool)

    # ---- FSM contract ----
    def _state_dict(self) -> dict:
        return {"groups": {str(g): v for g, v in self.groups.items()},
                "epoch": self.epoch}

    def _load_state_dict(self, st: dict) -> None:
        self.groups = {int(g): v for g, v in st["groups"].items()}
        self.epoch = st.get("epoch", 0)

    def _state_bytes(self) -> bytes:
        with self._lock:
            return json.dumps(self._state_dict()).encode()

    def _restore_bytes(self, data: bytes) -> None:
        with self._lock:
            self._load_state_dict(json.loads(data))

    def _apply(self, rec: dict):
        rec = dict(rec)
        op = rec.pop("op")
        with self._lock:
            self.epoch += 1
            return getattr(self, f"_apply_{op}")(**rec)

    def _apply_put_group(self, group_id: int, addrs: list[str],
                         status: str = "active",
                         az: str | None = None) -> None:
        self.groups[int(group_id)] = {"addrs": list(addrs),
                                      "status": status, "az": az}

    def _apply_remove_group(self, group_id: int) -> None:
        self.groups.pop(int(group_id), None)

    def _apply_set_status(self, group_id: int, status: str) -> None:
        g = self.groups.get(int(group_id))
        if g is not None:  # tolerate replay after a concurrent removal
            g["status"] = status

    # ---- admin / heartbeat ----
    def register_group(self, group_id: int, addrs: list[str],
                       az: str | None = None) -> None:
        self._commit({"op": "put_group", "group_id": group_id,
                      "addrs": list(addrs), "az": az})

    def remove_group(self, group_id: int) -> None:
        self._commit({"op": "remove_group", "group_id": group_id})

    def set_group_status(self, group_id: int, status: str) -> None:
        if status not in ("active", "inactive"):
            raise ValueError(f"bad status {status!r}")
        with self._lock:
            if int(group_id) not in self.groups:
                raise ValueError(f"unknown flash group {group_id}")
        self._commit({"op": "set_status", "group_id": group_id,
                      "status": status})

    def flashnode_heartbeat(self, addr: str) -> None:
        with self._lock:
            self._hb[addr] = time.time()

    def _member_alive(self, addr: str) -> bool:
        hb = self._hb.get(addr)
        # never-heartbeated members count as alive (static deployments
        # without the heartbeat loop keep working)
        return hb is None or time.time() - hb <= self.HEARTBEAT_TIMEOUT

    def ring_info(self) -> dict[int, dict]:
        """Active groups with their LIVE members only, plus AZ labels."""
        with self._lock:
            out = {}
            for g, info in self.groups.items():
                if info.get("status") != "active":
                    continue
                live = [a for a in info["addrs"] if self._member_alive(a)]
                if live:
                    out[g] = {"addrs": live, "az": info.get("az")}
            return out

    def ring(self) -> dict[int, list[str]]:
        """Active groups with their LIVE members only."""
        return {g: list(v["addrs"]) for g, v in self.ring_info().items()}

    @classmethod
    def slot_of(cls, key: str) -> int:
        return int.from_bytes(hashlib.md5(key.encode()).digest()[:4], "big") % cls.SLOTS

    def elect_group(self, key: str,
                    client_az: str | None = None) -> tuple[list[str], str]:
        """AZ-local flash-group election: slot-route over the client
        AZ's active groups first; fall back to the full ring only when
        every local group is dead. Returns (member addrs, scope) where
        scope is ``az_local`` or ``cross_az`` relative to the client
        (unlabeled groups/clients count as local — there is no locality
        information to violate)."""
        ring = self.ring_info()
        if not ring:
            return [], "az_local"
        if client_az is not None:
            local = sorted(g for g, v in ring.items()
                           if v["az"] == client_az)
            if local:
                gid = local[self.slot_of(key) % len(local)]
                return list(ring[gid]["addrs"]), "az_local"
        ids = sorted(ring)
        gid = ids[self.slot_of(key) % len(ids)]
        g_az = ring[gid]["az"]
        scope = ("az_local" if client_az is None or g_az is None
                 or g_az == client_az else "cross_az")
        return list(ring[gid]["addrs"]), scope

    def group_for(self, key: str) -> list[str]:
        return self.elect_group(key)[0]

    # ---------------- RPC surface ----------------
    def rpc_register_group(self, args, body):
        self._leader_gate()
        self.register_group(args["group_id"], args["addrs"],
                            az=args.get("az"))
        return {}

    def rpc_remove_group(self, args, body):
        self._leader_gate()
        self.remove_group(args["group_id"])
        return {}

    def rpc_set_group_status(self, args, body):
        self._leader_gate()
        self.set_group_status(args["group_id"], args["status"])
        return {}

    def rpc_flashnode_heartbeat(self, args, body):
        self.flashnode_heartbeat(args["addr"])
        return {}

    def rpc_ring(self, args, body):
        with self._lock:
            epoch = self.epoch
        info = self.ring_info()
        return {"groups": {str(k): list(v["addrs"]) for k, v in info.items()},
                "azs": {str(k): v["az"] for k, v in info.items()},
                "epoch": epoch}


class _Flight:
    """One in-flight datanode fill: followers park on the event and
    reuse the leader's bytes (singleflight)."""

    __slots__ = ("event", "data", "error")

    def __init__(self):
        self.event = threading.Event()
        self.data: bytes | None = None
        self.error: BaseException | None = None


class CachedReader:
    """Read-through wrapper for ExtentClient: flash ring first, datanode
    on miss, then populate (the client hook in stream_remote_cache.go).

    The hot-read tier layers four policies over the plain read path:

      * AZ-local election — flash groups in the client's AZ own the
        slot ring first; the full ring serves only when every local
        group is dead (``cubefs_readcache_serves_total{scope}``)
      * singleflight — concurrent misses of one block collapse onto a
        single datanode read
      * hotness admission — a block earns a flash slot only after
        ``hotness_threshold`` misses, so one streaming scan cannot
        flush the hot set
      * a per-flashnode circuit breaker — transport failures (NOT clean
        404 misses) open it, and an open breaker routes straight to the
        datanode instead of timing out against a dead cache
    """

    HEAT_TRACK = 4096  # per-block miss counters kept (LRU-bounded)
    FILL_WAIT = 30.0   # follower park bound; the leader always signals

    def __init__(self, extent_client, fgm: FlashGroupManager, node_pool,
                 *, client_az: str | None = None,
                 hotness_threshold: int = 1,
                 breaker: CircuitBreaker | None = None):
        self.inner = extent_client
        self.fgm = fgm
        self.nodes = node_pool
        self.client_az = client_az
        self.hotness_threshold = max(1, int(hotness_threshold))
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.hits = 0
        self.misses = 0
        self._sf_lock = lockwitness.make_lock("CachedReader._sf_lock")
        self._inflight: dict[str, _Flight] = {}
        self._heat: OrderedDict[str, int] = OrderedDict()

    def _flash_client(self, addr: str):
        # NodePool.get already caches one Client per addr and stays
        # current across rebinds; FlashClient is a stateless wrapper
        from ..sdk.clients import FlashClient

        return FlashClient(self.nodes.get(addr))

    @staticmethod
    def _key(dp_id: int, extent_id: int, block: int) -> str:
        return f"{dp_id}/{extent_id}/{block}"

    # ---- lookup / fill / admission ----
    def _cache_lookup(self, key: str, length: int):
        addrs, scope = self.fgm.elect_group(key, self.client_az)
        for addr in addrs:
            if not self.breaker.allow(addr):
                continue
            try:
                data = self._flash_client(addr).cache_get(key)
            except rpc.RpcError as e:
                if e.code == 404:
                    self.breaker.record_success(addr)  # clean miss
                else:
                    self.breaker.record_failure(addr)
                continue
            self.breaker.record_success(addr)
            if len(data) >= length:  # stale short entry -> refetch
                return data, scope
        return None, scope

    def _heat_up(self, key: str) -> int:
        with self._sf_lock:
            n = self._heat.pop(key, 0) + 1
            self._heat[key] = n
            while len(self._heat) > self.HEAT_TRACK:
                self._heat.popitem(last=False)
            return n

    def _populate(self, key: str, data: bytes) -> None:
        addrs, _ = self.fgm.elect_group(key, self.client_az)
        for addr in addrs:
            if not self.breaker.allow(addr):
                continue
            try:
                self._flash_client(addr).cache_put(key, data,
                                                   path="fs.read")
            except rpc.RpcError:
                self.breaker.record_failure(addr)
                continue
            self.breaker.record_success(addr)
            metrics.readcache_fills.inc(outcome="populated")
            return
        metrics.readcache_fills.inc(outcome="failed")

    def _fill(self, key: str, dp: dict, extent_id: int, block: int,
              length: int, fetch_len: int) -> bytes:
        with self._sf_lock:
            fl = self._inflight.get(key)
            leader = fl is None
            if leader:
                fl = self._inflight[key] = _Flight()
        if not leader:
            metrics.readcache_singleflight.inc()
            fl.event.wait(self.FILL_WAIT)
            if fl.error is None and fl.data is not None \
                    and len(fl.data) >= length:
                return fl.data
            # leader failed (or fetched a shorter span): read on our own
            with trace.stage("datanode_read", path="fs.read"):
                return self.inner._read_replicated(
                    dp, extent_id, block * CACHE_BLOCK, fetch_len)
        try:
            with trace.stage("datanode_read", path="fs.read"):
                data = self.inner._read_replicated(
                    dp, extent_id, block * CACHE_BLOCK, fetch_len)
            fl.data = data
        except BaseException as e:
            fl.error = e
            raise
        finally:
            with self._sf_lock:
                self._inflight.pop(key, None)
            fl.event.set()
        # the fetch may span several cache blocks (read() coalesces a
        # run of missing blocks into ONE datanode round trip — a miss
        # must never cost more cross-AZ hops than the plain path);
        # admission is still judged per block
        off = 0
        b = block
        while off < len(data):
            piece = data[off:off + CACHE_BLOCK]
            k = key if b == block else self._key(
                dp["dp_id"], extent_id, b)
            if qos.fill_suppressed():
                # brownout: cache population is deferrable work — stop
                # copying datanode blocks into the flash tier while any
                # path burns SLO budget (reads still hit existing cache)
                metrics.readcache_fills.inc(outcome="suppressed")
            elif self._heat_up(k) >= self.hotness_threshold:
                with trace.stage("cache_fill", path="fs.read"):
                    self._populate(k, piece)
            else:
                metrics.readcache_fills.inc(outcome="skipped_cold")
            off += CACHE_BLOCK
            b += 1
        return data

    def read_block(self, dp: dict, extent_id: int, block: int,
                   length: int, fetch_len: int) -> bytes:
        """length = bytes the caller needs from block start; fetch_len =
        the block's valid span in the extent (tail blocks are short, and
        replicas reject short-read requests beyond the span)."""
        key = self._key(dp["dp_id"], extent_id, block)
        with trace.stage("cache_lookup", path="fs.read"):
            data, scope = self._cache_lookup(key, length)
        if data is not None:
            self.hits += 1
            cache_ops.inc(result="hit")
            metrics.readcache_serves.inc(scope=scope)
            return data[:length]
        self.misses += 1
        cache_ops.inc(result="miss")
        data = self._fill(key, dp, extent_id, block, length, fetch_len)
        return data[:length]

    # ---- write-path invalidation ----
    @staticmethod
    def keys_for_extents(extents: list[dict]) -> list[str]:
        keys: list[str] = []
        for ek in extents:
            if not ek["size"]:
                continue
            first = ek["ext_offset"] // CACHE_BLOCK
            last = (ek["ext_offset"] + ek["size"] - 1) // CACHE_BLOCK
            for b in range(first, last + 1):
                keys.append(f"{ek['dp_id']}/{ek['extent_id']}/{b}")
        return keys

    def invalidate(self, extents: list[dict]) -> int:
        """Evict every flash copy of the blocks covered by `extents`.
        AZ-local election means one key may be cached once PER AZ, so
        deletes broadcast to every active group (cheap: writes are rare
        on this tier and delete-of-absent is a no-op). Returns the
        number of blocks invalidated."""
        keys = self.keys_for_extents(extents)
        if not keys:
            return 0
        groups = self.fgm.ring_info()
        for key in keys:
            for g in groups.values():
                for addr in g["addrs"]:
                    if not self.breaker.allow(addr):
                        continue
                    try:
                        self._flash_client(addr).cache_delete(key)
                    except rpc.RpcError:
                        self.breaker.record_failure(addr)
        metrics.readcache_invalidations.inc(len(keys))
        return len(keys)

    def read(self, inode: dict, offset: int, length: int) -> bytes:
        """Cache-block-aligned read of one inode's bytes.

        Two phases per extent: look every covered block up in flash,
        then fetch each contiguous RUN of missing blocks from the
        datanode in ONE round trip (populating each block from the
        span). Block-granular caching must not amplify a cold read
        into per-block cross-AZ hops the plain path wouldn't pay."""
        size = inode["size"]
        if offset >= size:
            return b""
        length = min(length, size - offset)
        out = bytearray(length)
        for ek in inode["extents"]:
            lo = max(offset, ek["file_offset"])
            hi = min(offset + length, ek["file_offset"] + ek["size"])
            if lo >= hi:
                continue
            dp = self.inner._dp_by_id(ek["dp_id"])
            ext_end = ek["ext_offset"] + ek["size"]  # extent's valid span
            first = ek["ext_offset"] + (lo - ek["file_offset"])
            last = ek["ext_offset"] + (hi - 1 - ek["file_offset"])
            b0, b1 = first // CACHE_BLOCK, last // CACHE_BLOCK
            blocks: dict[int, bytes] = {}
            missing: list[int] = []
            for b in range(b0, b1 + 1):
                # bytes of this block the read actually uses, measured
                # from block start (a short cached entry is a miss)
                need = min(last + 1, ext_end) - b * CACHE_BLOCK \
                    if b == b1 else min(CACHE_BLOCK,
                                        ext_end - b * CACHE_BLOCK)
                key = self._key(dp["dp_id"], ek["extent_id"], b)
                with trace.stage("cache_lookup", path="fs.read"):
                    data, scope = self._cache_lookup(key, need)
                if data is not None:
                    self.hits += 1
                    cache_ops.inc(result="hit")
                    metrics.readcache_serves.inc(scope=scope)
                    blocks[b] = data
                else:
                    self.misses += 1
                    cache_ops.inc(result="miss")
                    missing.append(b)
            i = 0
            while i < len(missing):
                j = i
                while j + 1 < len(missing) and \
                        missing[j + 1] == missing[j] + 1:
                    j += 1
                rb0, rb1 = missing[i], missing[j]
                fetch = min((rb1 + 1) * CACHE_BLOCK, ext_end) \
                    - rb0 * CACHE_BLOCK
                key = self._key(dp["dp_id"], ek["extent_id"], rb0)
                span = self._fill(key, dp, ek["extent_id"], rb0,
                                  fetch, fetch)
                for b in range(rb0, rb1 + 1):
                    o = (b - rb0) * CACHE_BLOCK
                    blocks[b] = span[o:o + CACHE_BLOCK]
                i = j + 1
            pos = lo
            while pos < hi:
                ext_pos = ek["ext_offset"] + (pos - ek["file_offset"])
                b = ext_pos // CACHE_BLOCK
                in_block = ext_pos % CACHE_BLOCK
                take = min(hi - pos, CACHE_BLOCK - in_block)
                blk = blocks[b]
                out[pos - offset:pos - offset + take] = \
                    blk[in_block:in_block + take]
                pos += take
        return bytes(out)
