"""Extent store facade (datanode disk engine, native-backed).

Role parity: datanode/storage — 128MiB extents, random-offset writes,
per-128KiB-block CRC32 headers, whole-extent crc-of-crcs
(extent_store.go:665 Write / Read:765, extent.go CRC header,
autoComputeExtentCrc:718). The TPU tie-in: block CRC tables read out via
block_crcs() feed the batched CRC kernel for scrub/repair verification
(a whole disk's blocks re-CRC'd as one device batch).

Every native call (and its error fetch) runs under a store-level lock
with a liveness check, so close() racing in-flight ops — e.g. a raft
apply arriving while the node shuts down — raises ExtentError instead
of handing the C engine a freed handle.
"""

from __future__ import annotations

import ctypes
import os
import time
import zlib

import numpy as np

from ..runtime import build as rt
from ..utils import lockwitness

BLOCK_SIZE = 128 * 1024


class ExtentError(Exception):
    pass


class BlockCrcError(ExtentError):
    pass


class ExtentStore:
    def __init__(self, directory: str):
        self._lib = rt.load()
        self._lock = lockwitness.make_rlock("ExtentStore._lock")
        # lint: allow[CFL101] local-disk open, no network; DataNode holds its lock here precisely to make registration atomic with the open
        self._h = self._lib.es_open(directory.encode())
        if not self._h:
            raise ExtentError(f"cannot open extent store at {directory}")
        self.directory = directory

    def _err(self) -> str:
        # caller holds self._lock with the handle verified live
        # lint: allow[CFL101] es_last_error is a pure in-memory errno formatter — safe under any lock
        return (self._lib.es_last_error(self._h) or b"").decode()

    def _handle(self):
        h = self._h
        if not h:
            raise ExtentError(f"extent store {self.directory} is closed")
        return h

    @property
    def handle(self):
        """Raw native store handle for the C++ read plane (dataserve.cc)
        — the registrar must ds_drop the partition BEFORE close()."""
        return self._h

    def close(self) -> None:
        with self._lock:
            if self._h:
                # lint: allow[CFL003] lock IS the close() guard — es_* on a freed handle is use-after-free; bounded local disk I/O, no cross-plane reader
                self._lib.es_close(self._h)
                self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def create(self, extent_id: int) -> None:
        with self._lock:
            # lint: allow[CFL003] lock IS the close() guard — es_* on a freed handle is use-after-free; bounded local disk I/O, no cross-plane reader
            if self._lib.es_create(self._handle(), extent_id) != 0:
                raise ExtentError(self._err())

    def write(self, extent_id: int, offset: int, data: bytes | np.ndarray) -> None:
        buf = data.tobytes() if isinstance(data, np.ndarray) else bytes(data)
        if not buf:
            return  # es_write dereferences the payload even at len 0
        with self._lock:
            # lint: allow[CFL003] lock IS the close() guard — es_* on a freed handle is use-after-free; bounded local disk I/O, no cross-plane reader
            if self._lib.es_write(self._handle(), extent_id, offset, buf,
                                  len(buf)) != 0:
                raise ExtentError(self._err())

    def read(self, extent_id: int, offset: int, length: int) -> bytes:
        buf = ctypes.create_string_buffer(length)
        with self._lock:
            # lint: allow[CFL003] lock IS the close() guard — es_* on a freed handle is use-after-free; bounded local disk I/O, no cross-plane reader
            rc = self._lib.es_read(self._handle(), extent_id, offset, buf,
                                   length)
            err = self._err() if rc < 0 else None
        if rc == -2:
            raise BlockCrcError(err)
        if rc < 0:
            raise ExtentError(err)
        return buf.raw[:rc]

    def size(self, extent_id: int) -> int:
        with self._lock:
            # lint: allow[CFL003] lock IS the close() guard — es_* on a freed handle is use-after-free; bounded local disk I/O, no cross-plane reader
            return self._lib.es_size(self._handle(), extent_id)

    def block_crcs(self, extent_id: int) -> np.ndarray:
        n = (self.size(extent_id) + BLOCK_SIZE - 1) // BLOCK_SIZE
        out = np.zeros(max(n, 1), dtype=np.uint32)
        with self._lock:
            # lint: allow[CFL003] lock IS the close() guard — es_* on a freed handle is use-after-free; bounded local disk I/O, no cross-plane reader
            got = self._lib.es_block_crcs(
                self._handle(), extent_id,
                out.ctypes.data_as(ctypes.c_void_p), out.size
            )
            err = self._err() if got < 0 else None
        if got < 0:
            raise ExtentError(err)
        return out[:got]

    def extent_crc(self, extent_id: int) -> int:
        """CRC-of-block-CRCs: the whole-extent fingerprint used for
        replica diffing (repair decides by comparing these)."""
        return zlib.crc32(self.block_crcs(extent_id).tobytes())

    def list_extents(self) -> list[int]:
        """Extent ids present on disk (replica-rebuild work list)."""
        import os

        out = []
        for name in os.listdir(self.directory):
            if name.startswith("e_") and name.endswith(".data"):
                out.append(int(name[2:-5], 16))
        return sorted(out)

    def extent_age(self, extent_id: int) -> float:
        """Seconds since the extent's data file was last written (orphan
        reclaim uses this as the in-flight-write grace signal)."""
        path = os.path.join(self.directory, f"e_{extent_id:016x}.data")
        try:
            return max(0.0, time.time() - os.stat(path).st_mtime)
        except OSError:
            return 0.0  # unknown: treat as brand new (never reclaim)

    def delete(self, extent_id: int) -> None:
        with self._lock:
            # lint: allow[CFL003] lock IS the close() guard — es_* on a freed handle is use-after-free; bounded local disk I/O, no cross-plane reader
            if self._lib.es_delete(self._handle(), extent_id) != 0:
                raise ExtentError(self._err())

    def sync(self, extent_id: int) -> None:
        with self._lock:
            # lint: allow[CFL003] lock IS the close() guard — es_* on a freed handle is use-after-free; bounded local disk I/O, no cross-plane reader
            if self._lib.es_sync(self._handle(), extent_id) != 0:
                raise ExtentError(self._err())


def verified_read(store: ExtentStore, extent_id: int, offset: int,
                  length: int, *, node_addr: str | None = None,
                  disk_id: int = 0, unit: str | None = None,
                  source: str = "read") -> bytes:
    """The ONE sanctioned at-rest payload read outside this module
    (lint family CFI): the native per-128KiB-block CRC check runs on
    every read, planted at-rest chaos faults surface the same way, and
    every mismatch lands in
    cubefs_integrity_corruptions_detected_total{plane="fs"} before the
    BlockCrcError propagates to the 409 failover path."""
    from ..utils import faultinject, metrics

    if node_addr is not None and unit is not None:
        plan = faultinject.current()
        if plan is not None:
            kind = plan.at_rest_fault(node_addr, disk_id, unit)
            if kind is not None:
                metrics.integrity_corruptions_detected.inc(
                    plane="fs", source=source)
                raise BlockCrcError(
                    f"extent {extent_id}: at-rest {kind} on {unit}")
    try:
        return store.read(extent_id, offset, length)
    except BlockCrcError:
        metrics.integrity_corruptions_detected.inc(
            plane="fs", source=source)
        raise
