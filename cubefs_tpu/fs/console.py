"""Console: web dashboard over the admin APIs.

Role parity: console/ (GraphQL proxy dashboard over master APIs) — here
a dependency-free HTML status page aggregating master/clustermgr stats,
volume tables and per-service metric links.
"""

from __future__ import annotations

import html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils import rpc


class Console:
    def __init__(self, master_addr: str | None = None,
                 clustermgr_addr: str | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.master = master_addr
        self.cm = clustermgr_addr
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/api/state":
                    body = json.dumps(outer.state()).encode()
                    ctype = "application/json"
                else:
                    body = outer.render().encode()
                    ctype = "text/html; charset=utf-8"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.addr = f"{host}:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)

    def state(self) -> dict:
        out: dict = {}
        for name, addr in (("master", self.master), ("clustermgr", self.cm)):
            if not addr:
                continue
            try:
                out[name] = {"addr": addr, "stat": rpc.call(addr, "stat", timeout=5)[0]}
            except Exception as e:
                out[name] = {"addr": addr, "error": str(e)}
        return out

    def render(self) -> str:
        st = self.state()
        rows = []
        for name, info in st.items():
            detail = json.dumps(info.get("stat") or info.get("error"), indent=1)
            rows.append(
                f"<h2>{html.escape(name)} @ {html.escape(info['addr'])}"
                f" <a href='http://{html.escape(info['addr'])}/metrics'>metrics</a></h2>"
                f"<pre>{html.escape(detail)}</pre>"
            )
        return (
            "<!doctype html><title>cubefs-tpu console</title>"
            "<h1>cubefs-tpu cluster</h1>" + "".join(rows)
            + "<p><a href='/api/state'>JSON</a></p>"
        )

    def start(self) -> "Console":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
