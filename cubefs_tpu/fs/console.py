"""Console: web dashboard + authenticated management APIs.

Role parity: console/ (GraphQL proxy dashboard over master APIs,
console/service/) and master's GraphQL admin surface
(master/gapi_user.go: createUser/deleteUser/grant/revoke...). Read
panels are open JSON/HTML; management rides POST /api/graphql — a
dependency-free GraphQL subset (one operation, scalar arguments,
selection sets used as output filters) — behind POST /api/login, which
verifies AK/SK against the master's replicated user registry and issues
an HMAC session token.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import html
import json
import re
import secrets
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils import rpc


class ConsoleAuthError(Exception):
    pass


class GraphqlError(Exception):
    pass


# one operation with scalar args: mutation { grant(ak: "x", volume: "v",
# perm: "rw") { ok } }  /  query { users { userId accessKey } }
_GQL_RE = re.compile(
    r"^\s*(query|mutation)?\s*(?:\w+\s*)?\{\s*(\w+)\s*"
    r"(?:\(([^)]*)\))?\s*(?:\{([^}]*)\})?\s*\}\s*$")
_ARG_RE = re.compile(r"(\w+)\s*:\s*(\"(?:[^\"\\]|\\.)*\"|\$\w+|-?\d+|true|false)")


class Console:
    def __init__(self, master_addr: str | None = None,
                 clustermgr_addr: str | None = None,
                 scheduler_addr: str | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 admin_ids: set[str] | None = None):
        self.master = master_addr
        self.cm = clustermgr_addr
        self.scheduler = scheduler_addr
        # user_ids allowed to run mutations (gapi_user.go's admin gate)
        self.admin_ids = admin_ids if admin_ids is not None else {
            "root", "admin"}
        self._session_key = secrets.token_bytes(32)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                routes = {
                    "/api/state": outer.state,
                    "/api/nodes": outer.nodes,
                    "/api/volumes": outer.volumes,
                    "/api/tasks": outer.tasks,
                }
                fn = routes.get(self.path)
                if fn is not None:
                    try:
                        body = json.dumps(fn()).encode()
                        code = 200
                    except Exception as e:
                        body = json.dumps({"error": str(e)}).encode()
                        code = 502
                    ctype = "application/json"
                else:
                    body = outer.render().encode()
                    code, ctype = 200, "text/html; charset=utf-8"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError:
                    return self._json(400, {"error": "malformed JSON body"})
                if self.path == "/api/login":
                    try:
                        token = outer.login(req.get("access_key", ""),
                                            req.get("secret_key", ""))
                    except ConsoleAuthError as e:
                        return self._json(403, {"error": str(e)})
                    except Exception as e:  # master outage != bad creds
                        return self._json(502, {"error": str(e)})
                    return self._json(200, {"token": token})
                if self.path == "/api/graphql":
                    tok = self.headers.get("X-Console-Token", "")
                    try:
                        who = outer.check_token(tok)
                        data = outer.graphql(req.get("query", ""),
                                             req.get("variables") or {},
                                             principal=who)
                    except ConsoleAuthError as e:
                        return self._json(403, {"error": str(e)})
                    except GraphqlError as e:
                        return self._json(200, {"errors": [str(e)]})
                    except Exception as e:
                        return self._json(502, {"error": str(e)})
                    return self._json(200, {"data": data})
                self._json(404, {"error": f"no such endpoint {self.path}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.addr = f"{host}:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)

    # ---------------- authenticated management (gapi_user.go role) ----
    SESSION_TTL = 3600.0
    _MAC_LEN = 32  # fixed-width suffix: the raw digest may contain any
    #                byte, so delimiter-splitting it would be ambiguous

    def _mc(self):
        from ..sdk import MasterClient

        return MasterClient(self.master)

    def login(self, ak: str, sk: str) -> str:
        """Verify AK/SK against the master's user registry; return an
        HMAC session token (ak|user_id|exp + MAC) for /api/graphql."""
        if not self.master:
            raise ConsoleAuthError("console has no master configured")
        try:
            info = self._call(self.master, "user_auth_info", {"ak": ak})
        except rpc.RpcError as e:
            if 400 <= e.code < 500:
                raise ConsoleAuthError("unknown access key") from None
            raise  # master outage is a 502, not 'bad credentials'
        if not info or not hmac.compare_digest(info.get("sk") or "", sk):
            raise ConsoleAuthError("bad credentials")
        exp = int(time.time() + self.SESSION_TTL)
        payload = f"{ak}|{info.get('user_id', '')}|{exp}".encode()
        mac = hmac.new(self._session_key, payload, hashlib.sha256).digest()
        return base64.b64encode(payload + mac).decode()

    def check_token(self, token: str) -> tuple[str, str]:
        """Returns (access_key, user_id) of a valid session."""
        try:
            raw = base64.b64decode(token)
            payload, mac = raw[:-self._MAC_LEN], raw[-self._MAC_LEN:]
            ak, user_id, exp = payload.decode().rsplit("|", 2)
        except (ValueError, TypeError):
            raise ConsoleAuthError("malformed token") from None
        want = hmac.new(self._session_key, payload, hashlib.sha256).digest()
        if len(raw) <= self._MAC_LEN or not hmac.compare_digest(mac, want):
            raise ConsoleAuthError("invalid token")
        if int(exp) < time.time():
            raise ConsoleAuthError("session expired")
        return ak, user_id

    def graphql(self, query: str, variables: dict,
                principal: tuple[str, str]) -> dict:
        """Execute one GraphQL-subset operation against the master.
        Queries need any valid session; MUTATIONS need an admin
        principal (user_id in admin_ids — gapi_user.go gates its
        mutations on the admin user the same way)."""
        m = _GQL_RE.match(query or "")
        if m is None:
            raise GraphqlError("unsupported query shape")
        op_kind, field, raw_args, selection = m.groups()
        op_kind = op_kind or "query"
        if op_kind == "mutation" and principal[1] not in self.admin_ids:
            raise ConsoleAuthError(
                f"user {principal[1]!r} may not run mutations")
        args = {}
        for k, v in _ARG_RE.findall(raw_args or ""):
            if v.startswith("$"):
                if v[1:] not in variables:
                    raise GraphqlError(f"undefined variable {v}")
                args[k] = variables[v[1:]]
            elif v.startswith('"'):
                args[k] = json.loads(v)
            elif v in ("true", "false"):
                args[k] = v == "true"
            else:
                args[k] = int(v)
        resolver = self._RESOLVERS.get((op_kind, field))
        if resolver is None:
            raise GraphqlError(f"unknown field {field!r}")
        out = resolver(self, args)
        if selection and isinstance(out, dict):
            keys = selection.split()
            out = {k: v for k, v in out.items() if k in keys}
        return {field: out}

    # resolvers (master/gapi_user.go + console/service vol ops), through
    # the typed MasterClient — no hand-rolled method strings
    def _gq_users(self, args):
        return self._mc().list_users()

    def _gq_volumes(self, args):
        return self.volumes()

    def _gq_nodes(self, args):
        return self.nodes()

    def _gq_cluster(self, args):
        return self._mc().stat()

    def _gq_create_user(self, args):
        return self._mc().create_user(args["userId"])

    def _gq_delete_user(self, args):
        self._mc().delete_user(args["ak"])
        return {"ok": True}

    def _gq_grant(self, args):
        self._mc().grant(args["ak"], args["volume"],
                         args.get("perm", "rw"))
        return {"ok": True}

    def _gq_revoke(self, args):
        self._mc().revoke(args["ak"], args["volume"])
        return {"ok": True}

    def _gq_create_volume(self, args):
        return self._mc().create_volume(args["name"],
                                        mp_count=args.get("mpCount", 3),
                                        dp_count=args.get("dpCount", 4))

    def _gq_set_capacity(self, args):
        self._mc().set_vol_capacity(args["name"], args["capacity"])
        return {"ok": True}

    _RESOLVERS = {
        ("query", "users"): _gq_users,
        ("query", "volumes"): _gq_volumes,
        ("query", "nodes"): _gq_nodes,
        ("query", "clusterStat"): _gq_cluster,
        ("mutation", "createUser"): _gq_create_user,
        ("mutation", "deleteUser"): _gq_delete_user,
        ("mutation", "grant"): _gq_grant,
        ("mutation", "revoke"): _gq_revoke,
        ("mutation", "createVolume"): _gq_create_volume,
        ("mutation", "setVolCapacity"): _gq_set_capacity,
    }

    # ---------------- data panels ----------------
    def _call(self, addr: str, method: str, args: dict | None = None):
        return rpc.call(addr, method, args, timeout=5)[0]

    def state(self) -> dict:
        out: dict = {}
        for name, addr in (("master", self.master), ("clustermgr", self.cm),
                           ("scheduler", self.scheduler)):
            if not addr:
                continue
            try:
                out[name] = {"addr": addr,
                             "stat": self._call(addr, "stat")}
            except Exception as e:
                out[name] = {"addr": addr, "error": str(e)}
        return out

    def nodes(self) -> dict:
        if not self.master:
            return {}
        return self._call(self.master, "node_list")

    def volumes(self) -> dict:
        if not self.master:
            return {}
        stat = self._call(self.master, "stat")
        out = {}
        for name in stat.get("volumes", []):
            try:
                view = self._call(self.master, "client_view",
                                  {"name": name})["volume"]
                out[name] = {
                    "mps": len(view["mps"]),
                    "dps": len(view["dps"]),
                    "quotas": len(view.get("quotas") or {}),
                    "packet_nodes": len(view.get("packet_addrs") or {}),
                }
            except Exception as e:
                out[name] = {"error": str(e)}
        return out

    def tasks(self) -> dict:
        if not self.scheduler:
            return {}
        return self._call(self.scheduler, "task_switch", {"action": "list"})

    # ---------------- HTML ----------------
    @staticmethod
    def _table(title: str, headers: list[str], rows: list[list]) -> str:
        head = "".join(f"<th>{html.escape(h)}</th>" for h in headers)
        body = "".join(
            "<tr>" + "".join(f"<td>{html.escape(str(c))}</td>" for c in r)
            + "</tr>"
            for r in rows
        )
        return (f"<h2>{html.escape(title)}</h2>"
                f"<table border=1 cellpadding=4 cellspacing=0>"
                f"<tr>{head}</tr>{body}</table>")

    def render(self) -> str:
        parts = ["<!doctype html><title>cubefs-tpu console</title>"
                 "<h1>cubefs-tpu cluster</h1>"]
        st = self.state()
        for name, info in st.items():
            detail = json.dumps(info.get("stat") or info.get("error"),
                                indent=1)
            parts.append(
                f"<h2>{html.escape(name)} @ {html.escape(info['addr'])}"
                f" <a href='http://{html.escape(info['addr'])}/metrics'>"
                f"metrics</a></h2><pre>{html.escape(detail)}</pre>")
        try:
            nodes = self.nodes()
        except Exception:
            nodes = {}
        for kind in ("datanodes", "metanodes"):
            if nodes.get(kind):
                parts.append(self._table(
                    kind, ["addr", "zone", "live", "decommissioned"],
                    [[a, i["zone"], i["live"], i["decommissioned"]]
                     for a, i in sorted(nodes[kind].items())]))
        try:
            vols = self.volumes()
        except Exception:
            vols = {}
        if vols:
            parts.append(self._table(
                "volumes", ["name", "mps", "dps", "quotas", "packet nodes"],
                [[n, v.get("mps", "?"), v.get("dps", "?"),
                  v.get("quotas", "?"), v.get("packet_nodes", "?")]
                 for n, v in sorted(vols.items())]))
        try:
            tasks = self.tasks()
        except Exception:
            tasks = {}
        if tasks.get("switches"):
            parts.append(self._table(
                "background task switches", ["kind", "enabled"],
                sorted(tasks["switches"].items())))
        parts.append("<p>JSON: <a href='/api/state'>state</a> · "
                     "<a href='/api/nodes'>nodes</a> · "
                     "<a href='/api/volumes'>volumes</a> · "
                     "<a href='/api/tasks'>tasks</a></p>")
        return "".join(parts)

    def start(self) -> "Console":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
