"""Console: web dashboard over the admin APIs.

Role parity: console/ (GraphQL proxy dashboard over master APIs) — here
a dependency-free HTML dashboard + JSON API aggregating master and
clustermgr state: cluster stats, node topology (zones, liveness,
decommission, packet planes), volume tables (partitions, capacity,
usage, quotas), scheduler task switches, and per-service metric links.
"""

from __future__ import annotations

import html
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils import rpc


class Console:
    def __init__(self, master_addr: str | None = None,
                 clustermgr_addr: str | None = None,
                 scheduler_addr: str | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.master = master_addr
        self.cm = clustermgr_addr
        self.scheduler = scheduler_addr
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                routes = {
                    "/api/state": outer.state,
                    "/api/nodes": outer.nodes,
                    "/api/volumes": outer.volumes,
                    "/api/tasks": outer.tasks,
                }
                fn = routes.get(self.path)
                if fn is not None:
                    try:
                        body = json.dumps(fn()).encode()
                        code = 200
                    except Exception as e:
                        body = json.dumps({"error": str(e)}).encode()
                        code = 502
                    ctype = "application/json"
                else:
                    body = outer.render().encode()
                    code, ctype = 200, "text/html; charset=utf-8"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.addr = f"{host}:{self._httpd.server_address[1]}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)

    # ---------------- data panels ----------------
    def _call(self, addr: str, method: str, args: dict | None = None):
        return rpc.call(addr, method, args, timeout=5)[0]

    def state(self) -> dict:
        out: dict = {}
        for name, addr in (("master", self.master), ("clustermgr", self.cm),
                           ("scheduler", self.scheduler)):
            if not addr:
                continue
            try:
                out[name] = {"addr": addr,
                             "stat": self._call(addr, "stat")}
            except Exception as e:
                out[name] = {"addr": addr, "error": str(e)}
        return out

    def nodes(self) -> dict:
        if not self.master:
            return {}
        return self._call(self.master, "node_list")

    def volumes(self) -> dict:
        if not self.master:
            return {}
        stat = self._call(self.master, "stat")
        out = {}
        for name in stat.get("volumes", []):
            try:
                view = self._call(self.master, "client_view",
                                  {"name": name})["volume"]
                out[name] = {
                    "mps": len(view["mps"]),
                    "dps": len(view["dps"]),
                    "quotas": len(view.get("quotas") or {}),
                    "packet_nodes": len(view.get("packet_addrs") or {}),
                }
            except Exception as e:
                out[name] = {"error": str(e)}
        return out

    def tasks(self) -> dict:
        if not self.scheduler:
            return {}
        return self._call(self.scheduler, "task_switch", {"action": "list"})

    # ---------------- HTML ----------------
    @staticmethod
    def _table(title: str, headers: list[str], rows: list[list]) -> str:
        head = "".join(f"<th>{html.escape(h)}</th>" for h in headers)
        body = "".join(
            "<tr>" + "".join(f"<td>{html.escape(str(c))}</td>" for c in r)
            + "</tr>"
            for r in rows
        )
        return (f"<h2>{html.escape(title)}</h2>"
                f"<table border=1 cellpadding=4 cellspacing=0>"
                f"<tr>{head}</tr>{body}</table>")

    def render(self) -> str:
        parts = ["<!doctype html><title>cubefs-tpu console</title>"
                 "<h1>cubefs-tpu cluster</h1>"]
        st = self.state()
        for name, info in st.items():
            detail = json.dumps(info.get("stat") or info.get("error"),
                                indent=1)
            parts.append(
                f"<h2>{html.escape(name)} @ {html.escape(info['addr'])}"
                f" <a href='http://{html.escape(info['addr'])}/metrics'>"
                f"metrics</a></h2><pre>{html.escape(detail)}</pre>")
        try:
            nodes = self.nodes()
        except Exception:
            nodes = {}
        for kind in ("datanodes", "metanodes"):
            if nodes.get(kind):
                parts.append(self._table(
                    kind, ["addr", "zone", "live", "decommissioned"],
                    [[a, i["zone"], i["live"], i["decommissioned"]]
                     for a, i in sorted(nodes[kind].items())]))
        try:
            vols = self.volumes()
        except Exception:
            vols = {}
        if vols:
            parts.append(self._table(
                "volumes", ["name", "mps", "dps", "quotas", "packet nodes"],
                [[n, v.get("mps", "?"), v.get("dps", "?"),
                  v.get("quotas", "?"), v.get("packet_nodes", "?")]
                 for n, v in sorted(vols.items())]))
        try:
            tasks = self.tasks()
        except Exception:
            tasks = {}
        if tasks.get("switches"):
            parts.append(self._table(
                "background task switches", ["kind", "enabled"],
                sorted(tasks["switches"].items())))
        parts.append("<p>JSON: <a href='/api/state'>state</a> · "
                     "<a href='/api/nodes'>nodes</a> · "
                     "<a href='/api/volumes'>volumes</a> · "
                     "<a href='/api/tasks'>tasks</a></p>")
        return "".join(parts)

    def start(self) -> "Console":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
