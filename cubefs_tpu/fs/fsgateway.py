"""FsGateway: path-level RPC surface for the native POSIX C ABI.

Role parity: client/libsdk (libcfs.so embeds the whole Go SDK via cgo,
libsdk.go:289-840 exporting cfs_open/cfs_read/...). This framework's
native boundary is a daemon instead of an embedded runtime (the
blockcache-daemon pattern): the C client (runtime/src/native_client.cc
cfs_fs_* / cfs_open family) speaks the framework's RPC wire to this
gateway, which runs the Python SDK (FileSystem facade). Stat results
travel as a fixed-layout binary record so the C side needs no JSON
parser.
"""

from __future__ import annotations

import struct

from ..utils import rpc
from . import metanode as mn
from .client import FileSystem, FsError

# fixed binary stat record: u64 size, u32 mode, u32 type, u64 mtime_sec
STAT_FMT = "<QIIQ"
TYPE_CODES = {mn.FILE: 0, mn.DIR: 1, mn.SYMLINK: 2}


def _err(e: FsError) -> rpc.RpcError:
    return rpc.errno_error(e.errno, str(e))


class FsGateway:
    """One mounted volume served to native clients."""

    def __init__(self, fs: FileSystem):
        self.fs = fs

    # ---- metadata ----
    def rpc_fs_stat(self, args, body):
        try:
            st = self.fs.stat(args["path"])
        except FsError as e:
            raise _err(e) from None
        rec = struct.pack(STAT_FMT, st["size"], st["mode"],
                          TYPE_CODES.get(st["type"], 0), int(st["mtime"]))
        return {"size": st["size"], "type": st["type"]}, rec

    def rpc_fs_mkdir(self, args, body):
        try:
            ino = self.fs.mkdir(args["path"], args.get("mode", 0o755))
        except FsError as e:
            raise _err(e) from None
        return {"ino": ino}

    def rpc_fs_create(self, args, body):
        try:
            ino = self.fs.create(args["path"], args.get("mode", 0o644))
        except FsError as e:
            raise _err(e) from None
        return {"ino": ino}

    def rpc_fs_readdir(self, args, body):
        try:
            entries = self.fs.readdir(args["path"])
        except FsError as e:
            raise _err(e) from None
        return {"count": len(entries)}, "\n".join(sorted(entries)).encode()

    def rpc_fs_unlink(self, args, body):
        try:
            self.fs.unlink(args["path"])
        except FsError as e:
            raise _err(e) from None
        return {}

    def rpc_fs_rename(self, args, body):
        try:
            self.fs.rename(args["old"], args["new"])
        except FsError as e:
            raise _err(e) from None
        return {}

    def rpc_fs_truncate(self, args, body):
        try:
            self.fs.truncate_file(args["path"], args["size"])
        except FsError as e:
            raise _err(e) from None
        return {}

    # ---- data ----
    def rpc_fs_read(self, args, body):
        try:
            data = self.fs.read_file(args["path"], offset=args.get("offset", 0),
                                     length=args.get("length"))
        except FsError as e:
            raise _err(e) from None
        return {"n": len(data)}, data

    def rpc_fs_write(self, args, body):
        try:
            self.fs.pwrite_file(args["path"], args.get("offset", 0), body)
        except FsError as e:
            raise _err(e) from None
        return {"n": len(body)}
