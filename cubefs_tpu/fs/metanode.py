"""MetaNode: partitioned in-RAM filesystem metadata.

Role parity: metanode/ — a MetaPartition owns an inode-id range and
keeps inode/dentry trees in memory (partition.go:484-524, btree.go),
mutations flow through a single submit→apply door (partition_op_inode.go
:205 submit, partition_fsm.go:38 Apply) and persist as an op-log +
CRC'd snapshot with an apply-id watermark (partition_store.go). The
apply stream is the replication interface: peers (and later raft) replay
the same records.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib

from ..utils import rpc

ROOT_INO = 1

# inode types
DIR = "dir"
FILE = "file"
SYMLINK = "symlink"


class MetaError(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = code


ENOENT = 2
EEXIST = 17
ENOTDIR = 20
ENOTEMPTY = 39


class MetaPartition:
    """One inode-range shard: [start, end)."""

    def __init__(self, pid: int, start: int, end: int, data_dir: str | None = None):
        self.pid = pid
        self.start = start
        self.end = end
        self._lock = threading.RLock()
        self.inodes: dict[int, dict] = {}
        self.dentries: dict[int, dict[str, int]] = {}  # parent -> name -> ino
        self.apply_id = 0
        self._next_ino = start
        self._op_cache: dict[str, tuple] = {}  # op_id -> (result, err)
        self.data_dir = data_dir
        self._oplog = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._load()
            self._oplog = open(os.path.join(data_dir, "oplog.jsonl"), "a")
        if self.start <= ROOT_INO < self.end and ROOT_INO not in self.inodes:
            self.apply({"op": "mk_inode", "ino": ROOT_INO, "type": DIR, "mode": 0o755})

    # ---------------- apply door (replication interface) ----------------
    def submit(self, record: dict) -> dict:
        """Validate + apply + log one mutation; returns the result."""
        with self._lock:
            result = self.apply(record)
            if self._oplog is not None:
                self._oplog.write(json.dumps(record) + "\n")
                self._oplog.flush()
            return result

    OP_CACHE_SIZE = 4096

    def apply(self, record: dict) -> dict:
        """Apply one mutation. Records carrying an op_id are idempotent:
        a client retry of an already-applied op (lost response, replica
        failover) returns the cached outcome instead of re-applying —
        the cache is part of the FSM, so replicas stay identical."""
        with self._lock:
            op_id = record.get("op_id")
            if op_id is not None and op_id in self._op_cache:
                result, err = self._op_cache[op_id]
                if err is not None:
                    raise MetaError(err[0], err[1])
                return result
            self.apply_id += 1
            op = record["op"]
            try:
                result = getattr(self, f"_apply_{op}")(record)
                outcome = (result, None)
            except MetaError as e:
                outcome = (None, (e.code, str(e)))
                self._remember(op_id, outcome)
                raise
            self._remember(op_id, outcome)
            return result

    def _remember(self, op_id, outcome) -> None:
        if op_id is None:
            return
        self._op_cache[op_id] = outcome
        if len(self._op_cache) > self.OP_CACHE_SIZE:
            # drop oldest half (insertion-ordered dict)
            for k in list(self._op_cache)[: self.OP_CACHE_SIZE // 2]:
                del self._op_cache[k]

    # ---------------- raft FSM snapshot interface ----------------
    def state_bytes(self) -> bytes:
        """Serialize the whole partition state (raft snapshot payload)."""
        with self._lock:
            return json.dumps({
                "apply_id": self.apply_id, "next_ino": self._next_ino,
                "inodes": {str(k): v for k, v in self.inodes.items()},
                "dentries": {str(k): v for k, v in self.dentries.items()},
            }).encode()

    def restore_state(self, data: bytes) -> None:
        with self._lock:
            st = json.loads(data)
            self.apply_id = st["apply_id"]
            self._next_ino = st["next_ino"]
            self.inodes = {int(k): v for k, v in st["inodes"].items()}
            self.dentries = {int(k): v for k, v in st["dentries"].items()}

    # ---------------- snapshot / recovery ----------------
    def snapshot(self) -> None:
        if not self.data_dir:
            return
        with self._lock:
            state = json.dumps({
                "pid": self.pid, "start": self.start, "end": self.end,
                "apply_id": self.apply_id, "next_ino": self._next_ino,
                "inodes": {str(k): v for k, v in self.inodes.items()},
                "dentries": {str(k): v for k, v in self.dentries.items()},
            }).encode()
            crc = zlib.crc32(state)
            tmp = os.path.join(self.data_dir, "snap.tmp")
            with open(tmp, "wb") as f:
                f.write(crc.to_bytes(4, "little") + state)
            os.replace(tmp, os.path.join(self.data_dir, "snap.bin"))
            open(os.path.join(self.data_dir, "oplog.jsonl"), "w").close()
            if self._oplog is not None:
                self._oplog.close()
            self._oplog = open(os.path.join(self.data_dir, "oplog.jsonl"), "a")

    def _load(self) -> None:
        snap = os.path.join(self.data_dir, "snap.bin")
        if os.path.exists(snap):
            raw = open(snap, "rb").read()
            crc, state = int.from_bytes(raw[:4], "little"), raw[4:]
            if zlib.crc32(state) != crc:
                raise MetaError(5, f"snapshot crc mismatch for mp {self.pid}")
            st = json.loads(state)
            self.apply_id = st["apply_id"]
            self._next_ino = st["next_ino"]
            self.inodes = {int(k): v for k, v in st["inodes"].items()}
            self.dentries = {int(k): v for k, v in st["dentries"].items()}
        oplog = os.path.join(self.data_dir, "oplog.jsonl")
        if os.path.exists(oplog):
            for line in open(oplog):
                line = line.strip()
                if line:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break
                    try:
                        self.apply(rec)
                    except MetaError:
                        pass  # op failed identically at original apply time

    # ---------------- inode ops ----------------
    def alloc_ino(self) -> int:
        with self._lock:
            while self._next_ino in self.inodes or self._next_ino == ROOT_INO:
                self._next_ino += 1
            if self._next_ino >= self.end:
                raise MetaError(28, f"mp {self.pid} inode range exhausted")
            ino = self._next_ino
            self._next_ino += 1  # reserve: concurrent creates stay unique
            return ino

    def _apply_mk_inode(self, r: dict) -> dict:
        ino = r["ino"]
        if ino in self.inodes:
            raise MetaError(EEXIST, f"inode {ino} exists")
        now = r.get("ts", time.time())
        self.inodes[ino] = {
            "ino": ino, "type": r["type"], "mode": r.get("mode", 0o644),
            "size": 0, "nlink": 2 if r["type"] == DIR else 1,
            "uid": r.get("uid", 0), "gid": r.get("gid", 0),
            "mtime": now, "ctime": now, "atime": now,
            "extents": [], "xattr": {}, "target": r.get("target"),
        }
        if r["type"] == DIR:
            self.dentries.setdefault(ino, {})
        self._next_ino = max(self._next_ino, ino + 1)
        return {"ino": ino}

    def _apply_rm_inode(self, r: dict) -> dict:
        ino = r["ino"]
        inode = self.inodes.pop(ino, None)
        self.dentries.pop(ino, None)
        return {"extents": inode["extents"] if inode else []}

    def _apply_mk_dentry(self, r: dict) -> dict:
        parent, name = r["parent"], r["name"]
        d = self.dentries.get(parent)
        if d is None:
            raise MetaError(ENOENT, f"parent dir {parent} not here")
        if name in d:
            raise MetaError(EEXIST, f"{name!r} exists in {parent}")
        d[name] = r["ino"]
        return {}

    def _apply_rm_dentry(self, r: dict) -> dict:
        parent, name = r["parent"], r["name"]
        d = self.dentries.get(parent)
        if d is None or name not in d:
            raise MetaError(ENOENT, f"{name!r} not in {parent}")
        ino = d.pop(name)
        return {"ino": ino}

    def _apply_append_extents(self, r: dict) -> dict:
        inode = self.inodes.get(r["ino"])
        if inode is None:
            raise MetaError(ENOENT, f"inode {r['ino']}")
        inode["extents"].extend(r["extents"])
        inode["size"] = max(inode["size"], r.get("size", inode["size"]))
        inode["mtime"] = r.get("ts", time.time())
        return {}

    def _apply_set_attr(self, r: dict) -> dict:
        inode = self.inodes.get(r["ino"])
        if inode is None:
            raise MetaError(ENOENT, f"inode {r['ino']}")
        for k in ("mode", "uid", "gid", "size", "mtime", "atime", "nlink"):
            if k in r:
                inode[k] = r[k]
        inode["ctime"] = r.get("ts", time.time())
        return {}

    def _apply_set_xattr(self, r: dict) -> dict:
        inode = self.inodes.get(r["ino"])
        if inode is None:
            raise MetaError(ENOENT, f"inode {r['ino']}")
        if r.get("value") is None:
            inode["xattr"].pop(r["key"], None)
        else:
            inode["xattr"][r["key"]] = r["value"]
        return {}

    def _apply_truncate(self, r: dict) -> dict:
        inode = self.inodes.get(r["ino"])
        if inode is None:
            raise MetaError(ENOENT, f"inode {r['ino']}")
        inode["size"] = r["size"]
        if r["size"] == 0:
            old = inode["extents"]
            inode["extents"] = []
            return {"extents": old}
        return {"extents": []}

    # ---------------- reads (no apply) ----------------
    def inode_get(self, ino: int) -> dict:
        with self._lock:
            inode = self.inodes.get(ino)
            if inode is None:
                raise MetaError(ENOENT, f"inode {ino}")
            return dict(inode)

    def lookup(self, parent: int, name: str) -> int:
        with self._lock:
            d = self.dentries.get(parent)
            if d is None or name not in d:
                raise MetaError(ENOENT, f"{name!r} not in {parent}")
            return d[name]

    def readdir(self, parent: int) -> dict[str, int]:
        with self._lock:
            d = self.dentries.get(parent)
            if d is None:
                raise MetaError(ENOTDIR, f"{parent} is not a dir here")
            return dict(d)

    def dentry_count(self, parent: int) -> int:
        with self._lock:
            return len(self.dentries.get(parent, {}))


class MetaNode:
    """Hosts many MetaPartitions; RPC surface for the meta SDK.

    With peers configured, each partition is a raft group member
    (multi-raft: one RaftNode per partition, handlers mounted on this
    node's live route table) — mutations commit through raft before
    applying, so any majority of metanode replicas preserves the trees.
    """

    REDIRECT = 421  # "not leader; retry at meta['leader']"

    def __init__(self, node_id: int, data_dir: str | None = None,
                 addr: str | None = None, node_pool=None):
        self.node_id = node_id
        self.data_dir = data_dir
        self.addr = addr
        self.pool = node_pool
        self.partitions: dict[int, MetaPartition] = {}
        self.rafts: dict[int, object] = {}  # pid -> RaftNode
        self.extra_routes: dict = {}  # live raft handlers (rpc.resolve_route)
        self._lock = threading.RLock()

    def create_partition(self, pid: int, start: int, end: int,
                         peers: list[str] | None = None) -> MetaPartition:
        with self._lock:
            if pid not in self.partitions:
                replicated = bool(peers and len(peers) > 1)
                # replicated partitions persist via the raft wal (replayed
                # into apply on restart) — a second mp-level oplog would
                # double-apply; standalone partitions keep their own oplog
                pdir = (os.path.join(self.data_dir, f"mp_{pid}")
                        if self.data_dir and not replicated else None)
                mp = MetaPartition(pid, start, end, pdir)
                self.partitions[pid] = mp
                if replicated:
                    if not self.addr or self.pool is None:
                        raise rpc.RpcError(
                            500,
                            f"metanode {self.node_id} got replicated partition "
                            f"{pid} but has no addr/node_pool configured",
                        )
                    from ..parallel import raft as raftlib

                    node = raftlib.RaftNode(
                        f"mp{pid}", self.addr, peers, mp.apply, self.pool,
                        data_dir=os.path.join(self.data_dir, f"mp_{pid}_raft")
                        if self.data_dir else None,
                        snapshot_fn=mp.state_bytes,
                        restore_fn=mp.restore_state,
                    )
                    raftlib.register_routes(self.extra_routes, node)
                    self.rafts[pid] = node.start()
            return self.partitions[pid]

    def _mp(self, pid: int) -> MetaPartition:
        mp = self.partitions.get(pid)
        if mp is None:
            raise rpc.RpcError(404, f"meta partition {pid} not on node {self.node_id}")
        return mp

    def _mp_leader(self, pid: int) -> MetaPartition:
        """Leader-routed access: replicated partitions serve reads and
        ino allocation from the raft leader only (followers apply
        asynchronously; serving them would allow stale reads right after
        a committed write)."""
        mp = self._mp(pid)
        node = self.rafts.get(pid)
        if node is not None:
            st = node.status()
            if st["role"] != "leader":
                raise rpc.RpcError(self.REDIRECT, f"leader={st['leader'] or ''}")
        return mp

    def stop(self) -> None:
        for r in self.rafts.values():
            r.stop()

    # ---------------- RPC surface ----------------
    def rpc_create_partition(self, args, body):
        self.create_partition(args["pid"], args["start"], args["end"],
                              args.get("peers"))
        return {}

    def rpc_submit(self, args, body):
        pid = args["pid"]
        raft_node = self.rafts.get(pid)
        try:
            if raft_node is None:
                res = self._mp(pid).submit(args["record"])
            else:
                from ..parallel.raft import NotLeaderError

                try:
                    res = raft_node.propose(args["record"])
                except NotLeaderError as e:
                    raise rpc.RpcError(self.REDIRECT,
                                       f"leader={e.leader or ''}") from None
        except MetaError as e:
            raise rpc.RpcError(400 + e.code, str(e)) from None
        return {"result": res}

    def rpc_alloc_ino(self, args, body):
        return {"ino": self._mp_leader(args["pid"]).alloc_ino()}

    def rpc_inode_get(self, args, body):
        try:
            return {"inode": self._mp_leader(args["pid"]).inode_get(args["ino"])}
        except MetaError as e:
            raise rpc.RpcError(400 + e.code, str(e)) from None

    def rpc_lookup(self, args, body):
        try:
            return {"ino": self._mp_leader(args["pid"]).lookup(args["parent"], args["name"])}
        except MetaError as e:
            raise rpc.RpcError(400 + e.code, str(e)) from None

    def rpc_readdir(self, args, body):
        try:
            return {"entries": self._mp_leader(args["pid"]).readdir(args["parent"])}
        except MetaError as e:
            raise rpc.RpcError(400 + e.code, str(e)) from None

    def rpc_dentry_count(self, args, body):
        return {"count": self._mp_leader(args["pid"]).dentry_count(args["parent"])}

    def rpc_snapshot(self, args, body):
        self._mp(args["pid"]).snapshot()
        return {}
