"""MetaNode: partitioned in-RAM filesystem metadata.

Role parity: metanode/ — a MetaPartition owns an inode-id range and
keeps inode/dentry trees in memory (partition.go:484-524, btree.go),
mutations flow through a single submit→apply door (partition_op_inode.go
:205 submit, partition_fsm.go:38 Apply) and persist as an op-log +
CRC'd snapshot with an apply-id watermark (partition_store.go). The
apply stream is the replication interface: peers (and later raft) replay
the same records.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
import zlib

from ..utils import lockwitness, metrics, rpc
from ..utils import trace as tracelib

ROOT_INO = 1

# inode types
DIR = "dir"
FILE = "file"
SYMLINK = "symlink"


class MetaError(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = code


EPERM = 1
ENOENT = 2
EEXIST = 17
EBUSY = 16
EISDIR = 21
ENOTDIR = 20
ENOSPC = 28
ENOTEMPTY = 39
EDQUOT = 122
# live range migration (fs/split.py): the inode the op routes by sits
# in a sub-range that is frozen for, or already handed off by, a
# metapartition split/merge. >= 99 so it rides the 499 "errno=NN"
# encoding; the message carries "pid=<target>" and the sdk re-routes
# exactly like the 453 transport code (rpc.RANGE_MOVED).
EMOVED = 121


def _rpc_err(e: "MetaError") -> "rpc.RpcError":
    return rpc.errno_error(e.code, str(e))


def _record_inos(r: dict) -> list[int]:
    """The inode ids whose state THIS record mutates on this partition —
    the keys the range-migration fences check. Dentry-plane ops
    (mk_dentry/rm_dentry/mknod/unlink2/rename) live under their parent
    keys: the child "ino" they may also carry can legitimately belong
    to ANOTHER partition (classic alloc-elsewhere create), so gating on
    it would bounce valid cross-partition ops. Pure inode ops carry no
    parent and gate on "ino". tx records carry theirs per sub-op."""
    out = [v for k in ("parent", "src_parent", "dst_parent")
           if isinstance((v := r.get(k)), int)]
    if r.get("op") in ("tx_prepare", "tx_commit", "tx_abort"):
        for o in r.get("ops") or []:
            v = o.get("parent")
            if isinstance(v, int):
                out.append(v)
    if not out and isinstance((v := r.get("ino")), int):
        out.append(v)
    return out


class MetaPartition:
    """One inode-range shard: [start, end)."""

    TX_TTL = 30.0  # seconds a prepared tx may stay undecided
    TX_COMMIT_TTL = 3600.0  # how long commit decisions stay queryable

    def __init__(self, pid: int, start: int, end: int, data_dir: str | None = None):
        self.pid = pid
        self.start = start
        self.end = end
        self._lock = lockwitness.make_rlock("MetaPartition._lock")
        self.inodes: dict[int, dict] = {}
        self.dentries: dict[int, dict[str, int]] = {}  # parent -> name -> ino
        # two-phase transactions (metanode/transaction.go analog):
        # prepared sub-ops hold dentry locks until commit/abort; commit
        # decisions stay queryable so participants can roll forward
        self.tx_pending: dict[str, dict] = {}  # tx_id -> {ops, ts, coord}
        self.tx_committed: dict[str, dict] = {}  # tx_id -> {victims, ts}
        # deferred-deletion free list (partition_free_list.go analog):
        # unlink/truncate move freed extent keys HERE (replicated FSM
        # state) instead of trusting the client to delete them; the
        # metanode's background scan deletes them from datanodes and
        # retires entries via the free_done op. A client crash right
        # after unlink can no longer leak datanode space.
        self.freelist: dict[str, dict] = {}  # key -> {extents, ts}
        # deferred blob deletion (the cold-tier mirror of the extent
        # freelist): any apply that makes a blob location unreachable —
        # fenced migration, overwrite, unlink of a cold file — queues it
        # HERE instead of trusting a client to delete it. The tiering
        # engine's orphan reaper deletes from the blob plane and retires
        # entries via blob_free_done, so no crash point strands a blob.
        self.blob_freelist: dict[str, dict] = {}  # key -> {location, ts}
        self.apply_id = 0
        self._next_ino = start
        self._dirty: set[str] = set(self._SEGMENTS)
        self._seg_crcs: dict[str, int] = {}
        self._oplog_records = 0
        self._op_cache: dict[str, tuple] = {}  # op_id -> (result, err)
        self._alloc_cache: dict[str, int] = {}  # alloc op_id -> ino
        # advisory enforcement flags pushed by the master's quota sweep
        # (meta_quota_manager.go analog) — NOT part of the FSM: they gate
        # the leader's submit door, never the deterministic apply
        self.enforce = {"vol_full": False, "exceeded": set()}
        # geo-replication hooks (fs/georepl.py). The tap fires post-
        # apply under self._lock on the serving side so the shipped
        # sequence mirrors commit order; follower mode bounces every
        # mutation with GeoRedirect while reads keep serving locally.
        # All None/off by default: with CUBEFS_GEO shut nothing here
        # ever fires and the FSM digest is byte-identical to pre-geo.
        self.geo_tap = None
        self.geo_mode: str | None = None
        self.geo_primary: str | None = None
        # live range migration (fs/split.py). `frozen`/`moved` are FSM
        # state (replicated + checkpointed in the "range" segment):
        # frozen sub-ranges bounce mutations with EMOVED while the
        # handoff copies them; moved sub-ranges redirect forever (the
        # inodes live on the target partition now). `_range_taps` is
        # leader-local scratch — the delta tap registered by
        # range_export, drained at freeze — never serialized.
        self.frozen: dict[str, dict] = {}  # split_id -> {lo, hi, target_pid}
        self.moved: dict[str, int] = {}  # "lo-hi" -> target_pid
        self._range_taps: dict[str, dict] = {}
        self.data_dir = data_dir
        # native read-plane mirror (runtime/src/metaserve.cc): when
        # attached, every apply re-states its tree mutation into the C++
        # store under this same lock, so the native server always serves
        # what a leader-routed Python read would
        self._mir = None  # (ctypes lib, MetaServe handle)
        self._last_tx_ops = None  # mirror hint from _apply_tx_commit
        self._oplog = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._load()
            self._oplog = open(os.path.join(data_dir, "oplog.jsonl"), "a")
        if self.start <= ROOT_INO < self.end and ROOT_INO not in self.inodes:
            # fixed ts: the bootstrap root is applied LOCALLY on every
            # replica (it precedes the raft log), so a wall-clock stamp
            # would make freshly-born replicas bitwise-divergent
            self.apply({"op": "mk_inode", "ino": ROOT_INO, "type": DIR,
                        "mode": 0o755, "ts": 0.0})

    # ---------------- apply door (replication interface) ----------------
    def _geo_gate(self) -> None:
        """Follower fence: a geo-follower partition serves reads but
        bounces every mutation to the primary region with GeoRedirect
        (452, "primary=<addr>") — the ONE mutation choke point on this
        class (lint CFG002 pins its presence in the commit doors).
        Shipped records from the primary enter through `geo_apply`,
        never here."""
        if self.geo_mode == "follower":
            metrics.geo_redirects.inc(
                part=getattr(self, "geo_part", str(self.pid)))
            raise rpc.RpcError(rpc.GEO_REDIRECT,
                               f"primary={self.geo_primary or ''}")

    def geo_apply(self, record: dict) -> dict:
        """The GeoApplier's sanctioned commit door on a follower
        partition (lint CFG001): same apply+oplog discipline as submit,
        minus the follower fence (shipped records ARE the primary's
        committed mutations — they must land) and minus the shipper tap
        (a follower never echoes the stream back). Records arrive with
        the primary's ts stamped; op_id dedup absorbs stream replays."""
        with self._lock:
            result = self.apply(dict(record))
            if self._oplog is not None:
                self._oplog.write(json.dumps(
                    {"aid": self.apply_id, **record}) + "\n")
                self._oplog.flush()
                self._oplog_records += 1
                if self._oplog_records >= self.SNAPSHOT_EVERY:
                    self.snapshot()
            return result

    def submit(self, record: dict) -> dict:
        """Validate + apply + log one mutation; returns the result.
        Auto-checkpoints every SNAPSHOT_EVERY records so oplog replay
        stays bounded without O(partition) work per external call.

        The wall clock is read HERE (proposer side) and travels in the
        record: apply handlers must never read it themselves, or
        replicas/WAL replays stamp divergent mtimes (fsm-purity CFM001).
        Records arriving via oplog replay or raft already carry ts."""
        self._geo_gate()
        record.setdefault("ts", time.time())
        with self._lock:
            result = self.apply(record)
            if self._oplog is not None:
                # the record carries the apply-id it landed at: replay
                # after a crash between watermark commit and oplog
                # truncation skips records the checkpoint already holds
                # (double-applying appends would garble extent layouts)
                self._oplog.write(json.dumps(
                    {"aid": self.apply_id, **record}) + "\n")
                self._oplog.flush()
                self._oplog_records += 1
                if self._oplog_records >= self.SNAPSHOT_EVERY:
                    self.snapshot()
            if self.geo_tap is not None:
                # under the partition lock, post-apply: the shipper's
                # per-partition sequence mirrors commit order
                self.geo_tap(record)
            return result

    def submit_many(self, records: list[dict]) -> list:
        """Standalone batch door: apply an ordered batch in sequence
        under ONE lock acquisition and land the oplog append as one
        write+flush. Each constituent still logs as its own record with
        its own apply-id — a batch is a commit-door optimization, not a
        WAL format, so crash replay is identical to N separate submits.
        Returns per-op outcomes [[result, None] | [None, [code, msg]]]."""
        self._geo_gate()
        now = time.time()
        for rec in records:
            rec.setdefault("ts", now)  # one proposer-side clock read
        with self._lock:
            outs = []
            lines = []
            for rec in records:
                try:
                    outs.append([self.apply(rec), None])
                    # failed constituents are NOT logged — same as the
                    # single-op door, whose replay assumes every oplog
                    # record re-applies cleanly
                    lines.append(json.dumps({"aid": self.apply_id, **rec}))
                    if self.geo_tap is not None:
                        # per ok constituent, in apply order: the geo
                        # stream has no batch framing, only sequence
                        self.geo_tap(rec)
                except MetaError as e:
                    outs.append([None, [e.code, str(e)]])
            if self._oplog is not None and lines:
                self._oplog.write("".join(ln + "\n" for ln in lines))
                self._oplog.flush()
                self._oplog_records += len(lines)
                if self._oplog_records >= self.SNAPSHOT_EVERY:
                    self.snapshot()
            return outs

    OP_CACHE_SIZE = 4096

    def apply(self, record: dict) -> dict:
        """Apply one mutation. Records carrying an op_id are idempotent:
        a client retry of an already-applied op (lost response, replica
        failover) returns the cached outcome instead of re-applying —
        the cache is part of the FSM, so replicas stay identical.

        A `__batch__` record is an ordered batch of mutations coalesced
        into ONE raft entry by the submit-plane group commit: every
        constituent applies in sequence through this same door (per-op
        op_id dedup intact — a batch boundary is invisible to replay and
        retries), and the batch's FSM result is the per-op outcome list
        [[result, None] | [None, [code, msg]], ...] so replicas agree
        even when some constituents fail deterministically."""
        with self._lock:
            if record.get("op") == "__batch__":
                outs = []
                for sub in record["records"]:
                    try:
                        outs.append([self.apply(sub), None])
                    except MetaError as e:
                        outs.append([None, [e.code, str(e)]])
                return outs
            op_id = record.get("op_id")
            if op_id is not None and op_id in self._op_cache:
                result, err = self._op_cache[op_id]
                if err is not None:
                    raise MetaError(err[0], err[1])
                return result
            self.apply_id += 1
            op = record["op"]
            try:
                if (self.frozen or self.moved) and \
                        op not in self._RANGE_EXEMPT_OPS:
                    # apply-side fence: records already in the raft
                    # queue when the freeze landed must not mutate the
                    # migrating sub-range (the proposer-side gate can't
                    # see an in-flight freeze) — deterministic, so
                    # replicas agree the op bounced
                    self._range_check(record)
                result = getattr(self, f"_apply_{op}")(record)
                self._dirty |= self._DIRTY_MAP.get(op, set(self._SEGMENTS))
                if self._range_taps:
                    # post-apply, under the partition lock: the delta
                    # tap sees mutations in commit order, exactly like
                    # the geo tap below the submit doors
                    self._tap_record(record, result)
                if self._mir is not None:
                    self._mirror_op(record, result)
                outcome = (result, None)
            except MetaError as e:
                outcome = (None, (e.code, str(e)))
                self._remember(op_id, outcome)
                raise
            self._remember(op_id, outcome)
            return result

    def _remember(self, op_id, outcome) -> None:
        if op_id is None:
            return
        self._op_cache[op_id] = outcome
        if len(self._op_cache) > self.OP_CACHE_SIZE:
            # drop oldest half (insertion-ordered dict)
            for k in list(self._op_cache)[: self.OP_CACHE_SIZE // 2]:
                del self._op_cache[k]

    # ---------------- live range migration (fs/split.py) ----------------
    # The donor side of a metapartition split/merge: range_export
    # captures a scoped snapshot + registers a leader-local delta tap,
    # range_freeze fences the migrating sub-range (mutations outside it
    # never stall), range_drop hands the sub-range off for good. The
    # target side loads via range_load and claims the range via
    # range_activate. All five are ordinary FSM applies — replicated,
    # op_id-idempotent, replayed from the oplog/WAL like any mutation.

    # ops the apply-side range fence skips: the migration's own applies,
    # plus background reclamation/tx-bookkeeping that carries no
    # client-visible tree mutation for the migrating inodes
    _RANGE_EXEMPT_OPS = frozenset({
        "range_freeze", "range_thaw", "range_load", "range_activate",
        "range_drop", "free_done", "blob_free_done",
        "blob_reconcile_enqueue", "tx_finish", "tx_commit", "tx_abort",
    })
    RANGE_TAP_MAX = 50000  # delta records before the tap poisons itself

    @staticmethod
    def _key_ino(key: str) -> int:
        """Owner ino of a freelist/blob_freelist key ("<ino>" or
        "<ino>:t<aid>"/"<ino>:b<aid>")."""
        try:
            return int(key.split(":", 1)[0])
        except ValueError:
            return -1

    def range_moved_target(self, ino: int) -> int | None:
        for key, tpid in self.moved.items():
            lo, hi = key.split("-")
            if int(lo) <= ino < int(hi):
                return tpid
        return None

    def range_target(self, ino: int) -> int | None:
        """Target pid when `ino` sits in a moved OR frozen sub-range;
        None when this partition still serves it."""
        t = self.range_moved_target(ino)
        if t is not None:
            return t
        for f in self.frozen.values():
            if f["lo"] <= ino < f["hi"]:
                return f["target_pid"]
        return None

    def _range_check(self, r: dict) -> None:
        for ino in _record_inos(r):
            t = self.range_target(ino)
            if t is not None:
                raise MetaError(
                    EMOVED,
                    f"inode {ino} range moved from mp {self.pid} (pid={t})")

    def _tap_record(self, r: dict, result) -> None:
        """Feed one successfully-applied record to every registered
        delta tap. Records are normalized so they replay verbatim on the
        target: mknod (allocates inside apply) becomes explicit
        mk_inode/mk_dentry, unlink2 splits into its dentry/inode halves.
        A record that straddles the migrating boundary (rename with one
        parent inside, a tx touching the range) POISONS the tap — the
        engine aborts that split attempt cleanly rather than replay a
        record whose other half isn't on the target."""
        op = r.get("op")
        if op in ("range_freeze", "range_thaw", "range_load",
                  "range_activate", "range_drop", "free_done",
                  "blob_free_done", "blob_reconcile_enqueue", "tx_finish"):
            return
        for tap in self._range_taps.values():
            lo, hi = tap["lo"], tap["hi"]
            if tap.get("poisoned"):
                continue

            def inr(v):
                return isinstance(v, int) and lo <= v < hi

            base = r.get("op_id") or f"rtap-{tap['split_id']}-{self.apply_id}"
            if op in ("tx_prepare", "tx_commit", "tx_abort"):
                if any(inr(o.get("parent")) for o in r.get("ops") or []):
                    tap["poisoned"] = f"tx {op} touched the migrating range"
                continue
            if op == "rename_local":
                sp, dp = r.get("src_parent"), r.get("dst_parent")
                if inr(sp) and inr(dp):
                    tap["records"].append(dict(r))
                elif inr(sp) or inr(dp):
                    tap["poisoned"] = "rename straddles the migrating range"
                continue
            if op == "mknod":
                ino = result["ino"]
                if inr(ino):
                    tap["records"].append({
                        "op": "mk_inode", "ino": ino, "type": r["type"],
                        "mode": r.get("mode", 0o644),
                        "uid": r.get("uid", 0), "gid": r.get("gid", 0),
                        "target": r.get("target"),
                        "quota_ids": list(r.get("quota_ids") or []),
                        "ts": r.get("ts", 0.0), "op_id": base + "#i"})
                if inr(r["parent"]):
                    tap["records"].append({
                        "op": "mk_dentry", "parent": r["parent"],
                        "name": r["name"], "ino": ino,
                        "ts": r.get("ts", 0.0), "op_id": base + "#d"})
                continue
            if op == "unlink2":
                if inr(r.get("parent")):
                    tap["records"].append({
                        "op": "rm_dentry", "parent": r["parent"],
                        "name": r["name"], "ts": r.get("ts", 0.0),
                        "op_id": base + "#d"})
                if inr(result.get("ino")):
                    half = ({"op": "rm_inode", "ino": result["ino"]}
                            if result.get("removed", True)
                            else {"op": "dec_nlink", "ino": result["ino"]})
                    tap["records"].append({**half, "ts": r.get("ts", 0.0),
                                           "op_id": base + "#r"})
                continue
            # same owner-key rule as _record_inos: a dentry op's child
            # "ino" may be foreign — only ops whose state lives in the
            # range belong in the delta
            if any(inr(i) for i in _record_inos(r)):
                tap["records"].append(dict(r))
        for tap in self._range_taps.values():
            if (not tap.get("poisoned")
                    and len(tap["records"]) > self.RANGE_TAP_MAX):
                tap["poisoned"] = "delta outran the copy (tap overflow)"

    def range_export(self, lo: int, hi: int, split_id: str) -> tuple[bytes, int]:
        """Scoped snapshot of [lo, hi): inodes in range, dentry maps of
        in-range parents, freelist entries owned by in-range inodes —
        serialized as CRC-framed records (utils/fsm.frame_records, one
        CRC per record) so a torn chunk is refused, not loaded. Captured
        under ONE lock acquisition together with the delta-tap
        registration, so the tap sees exactly the mutations the
        snapshot missed. Refuses while a prepared tx holds the range —
        its outcome could not replay on the target."""
        with self._lock:
            for tx in self.tx_pending.values():
                if any(isinstance((p := o.get("parent")), int)
                       and lo <= p < hi for o in tx.get("ops") or []):
                    raise MetaError(
                        EBUSY, f"prepared tx holds [{lo},{hi}) on mp "
                               f"{self.pid}; retry the split later")
            recs: list[dict] = [{
                "k": "head", "lo": lo, "hi": hi, "split_id": split_id,
                "next_ino": self._next_ino,
            }]
            recs.extend({"k": "inode", "v": v}
                        for i, v in self.inodes.items() if lo <= i < hi)
            recs.extend({"k": "dent", "parent": p, "entries": d}
                        for p, d in self.dentries.items() if lo <= p < hi)
            recs.extend({"k": "free", "key": k, "v": v}
                        for k, v in self.freelist.items()
                        if lo <= self._key_ino(k) < hi)
            recs.extend({"k": "bfree", "key": k, "v": v}
                        for k, v in self.blob_freelist.items()
                        if lo <= self._key_ino(k) < hi)
            from ..utils import fsm as fsmlib

            payload = fsmlib.frame_records(recs)
            # (re-)register the tap: an idempotent re-export resets it
            self._range_taps[split_id] = {
                "split_id": split_id, "lo": lo, "hi": hi,
                "records": [], "poisoned": None}
            return payload, self.apply_id

    def range_drain(self, split_id: str) -> tuple[list[dict], str | None]:
        """Close the delta tap (called right after the freeze apply
        landed — nothing can mutate the range anymore) and hand back the
        collected delta, or the poison reason."""
        with self._lock:
            tap = self._range_taps.pop(split_id, None)
            if tap is None:
                return [], "no delta tap registered (donor leader moved?)"
            return tap["records"], tap.get("poisoned")

    def _apply_range_freeze(self, r: dict) -> dict:
        self.frozen[r["split_id"]] = {
            "lo": r["lo"], "hi": r["hi"], "target_pid": r["target_pid"]}
        return {}

    def _apply_range_thaw(self, r: dict) -> dict:
        self.frozen.pop(r["split_id"], None)
        self._range_taps.pop(r["split_id"], None)
        return {}

    def _apply_range_load(self, r: dict) -> dict:
        """Target-side bulk import of a shipped range snapshot. The
        whole decoded state rides IN the record, so replicas (and the
        oplog/WAL replay) load identical bytes through the ordinary
        commit door. Does NOT claim the range — range_activate does,
        after the delta replay, so readers never see a stale copy."""
        st = r["state"]
        for k, v in st.get("inodes", {}).items():
            self.inodes[int(k)] = v
        for k, v in st.get("dentries", {}).items():
            self.dentries[int(k)] = v
        self.freelist.update(st.get("freelist", {}))
        self.blob_freelist.update(st.get("blob_freelist", {}))
        self._next_ino = max(self._next_ino,
                             int(st.get("next_ino", 0)), r["lo"])
        if self._mir is not None:
            self._mirror_full()
        return {"inodes": len(st.get("inodes", {}))}

    def _apply_range_activate(self, r: dict) -> dict:
        lo, hi = r["lo"], r["hi"]
        self.end = max(self.end, hi)
        # a range can come BACK (split handed it away, a later merge
        # returns it): tombstones covering the re-claimed span would
        # shadow the live trees with redirects to a retired partition
        for k in [k for k in self.moved
                  if not (int(k.split("-")[1]) <= lo
                          or hi <= int(k.split("-")[0]))]:
            del self.moved[k]
        for sid in [s for s, f in self.frozen.items()
                    if not (f["hi"] <= lo or hi <= f["lo"])]:
            del self.frozen[sid]
            self._range_taps.pop(sid, None)
        return {"start": self.start, "end": self.end}

    def _apply_range_drop(self, r: dict) -> dict:
        """Donor-side handoff: forget the migrated sub-range and shrink
        the served range. The moved marker makes every later touch of
        these inos redirect (EMOVED/453) instead of lying ENOENT to a
        client holding a pre-split partition map."""
        lo, hi, tpid = r["lo"], r["hi"], r["target_pid"]
        for ino in [i for i in self.inodes if lo <= i < hi]:
            del self.inodes[ino]
        for p in [p for p in self.dentries if lo <= p < hi]:
            del self.dentries[p]
        for k in [k for k in self.freelist if lo <= self._key_ino(k) < hi]:
            del self.freelist[k]
        for k in [k for k in self.blob_freelist
                  if lo <= self._key_ino(k) < hi]:
            del self.blob_freelist[k]
        if self.end == hi:
            self.end = lo
        for sid in [s for s, f in self.frozen.items()
                    if lo <= f["lo"] and f["hi"] <= hi]:
            del self.frozen[sid]
            self._range_taps.pop(sid, None)
        self.moved[f"{lo}-{hi}"] = tpid
        if self._mir is not None:
            self._mirror_full()
        return {"start": self.start, "end": self.end}

    # ---------------- raft FSM snapshot interface ----------------
    def _state_dict(self) -> dict:
        """The ONE serialized form of the FSM state — used by raft
        snapshots and the on-disk checkpoint alike, so a new field can
        never be persisted in one path and dropped in the other."""
        return {
            "apply_id": self.apply_id, "next_ino": self._next_ino,
            "inodes": {str(k): v for k, v in self.inodes.items()},
            "dentries": {str(k): v for k, v in self.dentries.items()},
            "tx_pending": self.tx_pending,
            "tx_committed": self.tx_committed,
            "freelist": self.freelist,
            "blob_freelist": self.blob_freelist,
            "frozen": self.frozen,
            "moved": self.moved,
            "range_start": self.start,
            "range_end": self.end,
        }

    def _load_state_dict(self, st: dict) -> None:
        self.apply_id = st["apply_id"]
        self._next_ino = st["next_ino"]
        self.inodes = {int(k): v for k, v in st["inodes"].items()}
        self.dentries = {int(k): v for k, v in st["dentries"].items()}
        self.tx_pending = st.get("tx_pending", {})
        self.tx_committed = st.get("tx_committed", {})
        self.freelist = st.get("freelist", {})
        self.blob_freelist = st.get("blob_freelist", {})
        self.frozen = st.get("frozen", {})
        self.moved = st.get("moved", {})
        # a range apply may have shifted [start, end) past what the
        # creator knew (a raft snapshot install on a freshly re-created
        # member, a checkpoint reload mid-migration)
        self.start = st.get("range_start", self.start)
        self.end = st.get("range_end", self.end)

    def export_state(self) -> tuple[bytes, int]:
        """(serialized state, apply_id) captured under ONE lock
        acquisition, so the manifest id always matches the payload. The
        single owner of state serialization — raft snapshots
        (state_bytes) and the export RPC both come through here."""
        with self._lock:
            return json.dumps(self._state_dict()).encode(), self.apply_id

    def state_bytes(self) -> bytes:
        """Serialize the whole partition state (raft snapshot payload)."""
        return self.export_state()[0]

    def restore_state(self, data: bytes) -> None:
        with self._lock:
            self._load_state_dict(json.loads(data))
            self._dirty = set(self._SEGMENTS)  # checkpoint must re-dump
            if self._mir is not None:
                self._mirror_full()

    # ---------------- native read-plane mirror ----------------
    def attach_mirror(self, lib, handle) -> None:
        with self._lock:
            self._mir = (lib, handle)
            self._mirror_full()

    def _mirror_full(self) -> None:
        lib, h = self._mir
        # lint: allow[CFL101] ms_* mirror writes are local-memory ops, no blocking IO; the partition lock is what keeps the native read plane atomic with the FSM
        lib.ms_clear(h, self.pid)
        for ino in self.inodes:
            self._mirror_inode(ino)
        for parent, d in self.dentries.items():
            lib.ms_ensure_dir(h, self.pid, parent)
            for name, ino in d.items():
                nb = name.encode()
                lib.ms_put_dentry(h, self.pid, parent, nb, len(nb), ino)

    def _mirror_inode(self, ino: int) -> None:
        lib, h = self._mir
        inode = self.inodes.get(ino)
        if inode is None:
            lib.ms_del_inode(h, self.pid, ino)
        else:
            blob = json.dumps(inode).encode()
            lib.ms_put_inode(h, self.pid, ino, blob, len(blob))

    def _mirror_dentry(self, parent: int, name: str) -> None:
        """Re-state one dentry from current tree state (self-correcting:
        works for link, replace and remove alike)."""
        lib, h = self._mir
        nb = name.encode()
        ino = self.dentries.get(parent, {}).get(name)
        if ino is None:
            lib.ms_del_dentry(h, self.pid, parent, nb, len(nb))
        else:
            lib.ms_put_dentry(h, self.pid, parent, nb, len(nb), ino)

    def _mirror_op(self, r: dict, result) -> None:
        """Called under the partition lock right after a successful
        apply; mirrors exactly the trees the op touched."""
        lib, h = self._mir
        op = r["op"]
        if op in ("mk_inode", "mknod"):
            ino = r["ino"] if op == "mk_inode" else result["ino"]
            self._mirror_inode(ino)
            if r["type"] == DIR:
                # lint: allow[CFL101] ms_* mirror writes are local-memory ops, no blocking IO; the partition lock is what keeps the native read plane atomic with the FSM
                lib.ms_ensure_dir(h, self.pid, ino)
            if op == "mknod":
                self._mirror_dentry(r["parent"], r["name"])
        elif op == "rm_inode":
            lib.ms_del_inode(h, self.pid, r["ino"])
            lib.ms_del_dir(h, self.pid, r["ino"])
        elif op in ("inc_nlink", "dec_nlink"):
            self._mirror_inode(r["ino"])  # handles removal (None) too
            if op == "dec_nlink" and result.get("removed"):
                lib.ms_del_dir(h, self.pid, r["ino"])
        elif op == "unlink2":
            self._mirror_dentry(r["parent"], r["name"])
            if result.get("removed", True):
                lib.ms_del_inode(h, self.pid, result["ino"])
                lib.ms_del_dir(h, self.pid, result["ino"])
            else:  # a hardlink remains: the inode changed (nlink)
                self._mirror_inode(result["ino"])
        elif op in ("mk_dentry", "rm_dentry"):
            self._mirror_dentry(r["parent"], r["name"])
        elif op == "rename_local":
            # add-before-delete: put the dst dentry first, then drop the
            # src. The native read plane sees each mirror call
            # individually — delete-first opens a window where the file
            # is reachable under NEITHER name (a native lookup racing
            # the rename gets spurious ENOENT)
            self._mirror_dentry(r["dst_parent"], r["dst_name"])
            self._mirror_dentry(r["src_parent"], r["src_name"])
            # the apply bumps the moved inode's gen (tiering fence)
            moved = self.dentries.get(r["dst_parent"], {}).get(r["dst_name"])
            if moved is not None:
                self._mirror_inode(moved)
        elif op in ("append_extents", "set_attr", "set_xattr", "truncate"):
            self._mirror_inode(r["ino"])
        elif op == "tx_commit":
            # same add-before-delete discipline for cross-partition
            # renames: replay the dst links before the src removals so
            # native readers never observe the no-name window
            ops = [o for o in self._last_tx_ops or ()
                   if o["kind"] not in ("guard_empty_dir", "mutex")]
            for o in sorted(ops, key=lambda o: o["kind"] != "link"):
                self._mirror_dentry(o["parent"], o["name"])
            self._last_tx_ops = None

    # ---------------- snapshot / recovery ----------------
    # Segmented checkpoint (partition_store.go analog: each tree dumps
    # to its own CRC'd file; the applyID watermark file commits the set
    # LAST). Only trees dirtied since the previous checkpoint are
    # rewritten — an append-only workload re-dumps inodes but never the
    # dentry tree. The oplog is truncated at checkpoint; auto-checkpoint
    # fires every SNAPSHOT_EVERY records, so per-op cost is amortized
    # O(1) instead of O(partition) on every external snapshot call.
    SNAPSHOT_EVERY = 4096
    _SEGMENTS = ("inodes", "dentries", "tx", "freelist", "range")
    _DIRTY_MAP = {
        "range_freeze": {"range"},
        "range_thaw": {"range"},
        "range_load": {"inodes", "dentries", "freelist"},
        "range_activate": {"range"},
        "range_drop": {"inodes", "dentries", "freelist", "range"},
        "mk_inode": {"inodes", "dentries"},
        "rm_inode": {"inodes", "dentries", "freelist"},
        "inc_nlink": {"inodes"},
        "dec_nlink": {"inodes", "dentries", "freelist"},
        "mk_dentry": {"dentries"},
        "rm_dentry": {"dentries"},
        "rename_local": {"dentries", "inodes"},  # gen bump fences tiering
        "append_extents": {"inodes"},
        "set_attr": {"inodes"},
        "set_xattr": {"inodes"},
        "truncate": {"inodes", "freelist"},
        "free_done": {"freelist"},
        "blob_free_done": {"freelist"},
        "blob_reconcile_enqueue": {"freelist"},
        "tiering_prepare": {"inodes"},
        "tiering_blob_written": {"inodes", "freelist"},
        "tiering_commit": {"inodes", "freelist"},
        "tiering_finish": {"inodes"},
        "tiering_abort": {"inodes", "freelist"},
        "untier_commit": {"inodes", "freelist"},
        "tx_prepare": {"tx"},
        "tx_abort": {"tx"},
        "tx_finish": {"tx"},
        "tx_commit": {"tx", "dentries"},
    }

    def _seg_payload(self, name: str) -> dict:
        if name == "inodes":
            return {"inodes": {str(k): v for k, v in self.inodes.items()},
                    "next_ino": self._next_ino}
        if name == "dentries":
            return {"dentries": {str(k): v for k, v in self.dentries.items()}}
        if name == "freelist":
            return {"freelist": self.freelist,
                    "blob_freelist": self.blob_freelist}
        if name == "range":
            return {"frozen": self.frozen, "moved": self.moved,
                    "range_start": self.start, "range_end": self.end}
        return {"tx_pending": self.tx_pending,
                "tx_committed": self.tx_committed}

    def _mark_dirty(self, name: str) -> None:
        self._dirty.add(name)

    def snapshot(self) -> None:
        if not self.data_dir:
            return
        with self._lock:
            seg_crcs = dict(getattr(self, "_seg_crcs", {}))
            for name in self._SEGMENTS:
                if name in seg_crcs and name not in self._dirty:
                    continue  # unchanged since the last checkpoint
                payload = json.dumps(self._seg_payload(name)).encode()
                crc = zlib.crc32(payload)
                # content-addressed filename: a dirty segment writes a NEW
                # file and the old one stays intact until the watermark
                # flips — a crash mid-checkpoint always leaves a fully
                # consistent (old or new) set referenced by the watermark
                fname = f"{name}.{crc:08x}.seg"
                tmp = os.path.join(self.data_dir, fname + ".tmp")
                with open(tmp, "wb") as f:
                    f.write(payload)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, os.path.join(self.data_dir, fname))
                seg_crcs[name] = crc
            # the watermark commits the segment set atomically, LAST
            meta = json.dumps({
                "pid": self.pid, "start": self.start, "end": self.end,
                "apply_id": self.apply_id, "seg_crcs": seg_crcs,
            }).encode()
            tmp = os.path.join(self.data_dir, "apply.meta.tmp")
            with open(tmp, "wb") as f:
                f.write(meta)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.data_dir, "apply.meta"))
            # GC segment files the committed watermark no longer references
            live = {f"{n}.{c:08x}.seg" for n, c in seg_crcs.items()}
            for f in os.listdir(self.data_dir):
                if f.endswith(".seg") and f not in live:
                    try:
                        os.unlink(os.path.join(self.data_dir, f))
                    except OSError:
                        pass
            self._seg_crcs = seg_crcs
            self._dirty = set()
            self._oplog_records = 0
            open(os.path.join(self.data_dir, "oplog.jsonl"), "w").close()
            if self._oplog is not None:
                self._oplog.close()
            self._oplog = open(os.path.join(self.data_dir, "oplog.jsonl"), "a")

    def _load(self) -> None:
        self._dirty = set(self._SEGMENTS)
        self._oplog_records = 0
        watermark = os.path.join(self.data_dir, "apply.meta")
        legacy = os.path.join(self.data_dir, "snap.bin")
        if os.path.exists(watermark):
            wm = json.loads(open(watermark, "rb").read())
            state: dict = {"apply_id": wm["apply_id"], "next_ino": self.start,
                           "inodes": {}, "dentries": {}}
            for name, crc in wm["seg_crcs"].items():
                path = os.path.join(self.data_dir, f"{name}.{crc:08x}.seg")
                if not os.path.exists(path):
                    # a referenced-but-missing segment is CORRUPTION, not
                    # an empty tree: booting without it would silently
                    # drop every record it held
                    raise MetaError(
                        5, f"segment {name} missing for mp {self.pid}")
                payload = open(path, "rb").read()
                if zlib.crc32(payload) != crc:
                    raise MetaError(
                        5, f"segment {name} crc mismatch for mp {self.pid}")
                state.update(json.loads(payload))
            self._load_state_dict(state)
            self._seg_crcs = {n: c for n, c in wm["seg_crcs"].items()}
            self._dirty = set()
        elif os.path.exists(legacy):
            raw = open(legacy, "rb").read()
            crc, state = int.from_bytes(raw[:4], "little"), raw[4:]
            if zlib.crc32(state) != crc:
                raise MetaError(5, f"snapshot crc mismatch for mp {self.pid}")
            self._load_state_dict(json.loads(state))
        if self.start <= ROOT_INO < self.end and ROOT_INO not in self.inodes:
            # bootstrap root BEFORE oplog replay: the first records of a
            # checkpoint-less root partition are creates under "/", and
            # replaying them against a rootless tree would drop them all
            # (ENOENT reads as "failed identically at apply time" below)
            self.apply({"op": "mk_inode", "ino": ROOT_INO, "type": DIR,
                        "mode": 0o755})
        oplog = os.path.join(self.data_dir, "oplog.jsonl")
        if os.path.exists(oplog):
            for line in open(oplog):
                line = line.strip()
                if line:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break
                    aid = rec.pop("aid", None)
                    if aid is not None and aid <= self.apply_id:
                        continue  # checkpoint already contains this op
                    try:
                        self.apply(rec)
                    except MetaError:
                        pass  # op failed identically at original apply time

    # ---------------- inode ops ----------------
    def alloc_ino(self, op_id: str | None = None) -> int:
        """Reserve the next free inode number. The reservation is local
        (not replicated — the ino only becomes durable via the mk_inode
        submit), but a transport retry must get the SAME ino back, or
        the lost first reservation leaks a number from the range and
        the client may observe two different inos for one create.

        Exercised dynamically by tests/test_chaos.py: an injected
        drop-after-execute / duplicate delivery (faultinject.FaultPlan)
        on alloc_ino must mint exactly one ino — the _alloc_cache door
        here is what makes the rpc.call idempotency contract hold."""
        self._geo_gate()
        with self._lock:
            if op_id is not None and op_id in self._alloc_cache:
                return self._alloc_cache[op_id]
            while self._next_ino in self.inodes or self._next_ino == ROOT_INO:
                self._next_ino += 1
            if self._next_ino >= self.end:
                raise MetaError(28, f"mp {self.pid} inode range exhausted")
            if self.range_target(self._next_ino) is not None:
                # the allocation cursor sits in a frozen/moved sub-range:
                # this partition can't mint inos anymore — same fallback
                # contract as a genuinely exhausted range
                raise MetaError(
                    28, f"mp {self.pid} alloc cursor inside a migrating range")
            ino = self._next_ino
            self._next_ino += 1  # reserve: concurrent creates stay unique
            if op_id is not None:
                self._alloc_cache[op_id] = ino
                if len(self._alloc_cache) > self.OP_CACHE_SIZE:
                    for k in list(self._alloc_cache)[: self.OP_CACHE_SIZE // 2]:
                        del self._alloc_cache[k]
            return ino

    def _apply_mk_inode(self, r: dict) -> dict:
        ino = r["ino"]
        if ino in self.inodes:
            raise MetaError(EEXIST, f"inode {ino} exists")
        now = r.get("ts", 0.0)
        self.inodes[ino] = {
            "ino": ino, "type": r["type"], "mode": r.get("mode", 0o644),
            "size": 0, "nlink": 2 if r["type"] == DIR else 1,
            "uid": r.get("uid", 0), "gid": r.get("gid", 0),
            "mtime": now, "ctime": now, "atime": now,
            "extents": [], "xattr": {}, "target": r.get("target"),
            "quota_ids": list(r.get("quota_ids") or []),
        }
        if r["type"] == DIR:
            self.dentries.setdefault(ino, {})
        self._next_ino = max(self._next_ino, ino + 1)
        return {"ino": ino}

    def _apply_rm_inode(self, r: dict) -> dict:
        ino = r["ino"]
        inode = self.inodes.pop(ino, None)
        self.dentries.pop(ino, None)
        exts = inode["extents"] if inode else []
        deferred = [ek for ek in exts if not ek.get("tiny")]
        if deferred:
            # server-side deferred deletion: the background free scan
            # (MetaNode._free_scan) owns reclaiming these from datanodes
            self.freelist[str(ino)] = {
                "extents": deferred, "ts": r.get("ts", 0.0)}
        if inode is not None:
            self._reap_inode_blobs(inode, r.get("ts", 0.0))
        return {"extents": exts, "deferred": bool(deferred)}

    def _apply_mk_dentry(self, r: dict) -> dict:
        parent, name = r["parent"], r["name"]
        self._check_unlocked(parent, name)
        d = self.dentries.get(parent)
        if d is None:
            raise MetaError(ENOENT, f"parent dir {parent} not here")
        if name in d:
            raise MetaError(EEXIST, f"{name!r} exists in {parent}")
        d[name] = r["ino"]
        return {}

    def _apply_mknod(self, r: dict) -> dict:
        """Compound create: inode + dentry in ONE commit (the dominant
        create cost in the deployed A/B was two raft commits + three
        client round trips per file). The inode is allocated from the
        PARENT's partition — locality-preserving placement; callers
        fall back to the two-op path when this range is exhausted
        (MetaError 28). Allocation happens inside apply, so replicas
        allocate identically."""
        parent, name = r["parent"], r["name"]
        self._check_unlocked(parent, name)
        d = self.dentries.get(parent)
        if d is None:
            raise MetaError(ENOENT, f"parent dir {parent} not here")
        if name in d:
            raise MetaError(EEXIST, f"{name!r} exists in {parent}")
        while self._next_ino in self.inodes or self._next_ino == ROOT_INO:
            self._next_ino += 1
        if self._next_ino >= self.end:
            raise MetaError(28, f"mp {self.pid} inode range exhausted")
        if self.range_target(self._next_ino) is not None:
            # deterministic apply-side fence: a compound create must not
            # mint an ino inside a frozen/moved sub-range (replicas all
            # refuse identically; the client falls back to alloc-elsewhere)
            raise MetaError(
                28, f"mp {self.pid} alloc cursor inside a migrating range")
        ino = self._next_ino
        self._next_ino += 1
        now = r.get("ts", 0.0)
        self.inodes[ino] = {
            "ino": ino, "type": r["type"], "mode": r.get("mode", 0o644),
            "size": 0, "nlink": 2 if r["type"] == DIR else 1,
            "uid": r.get("uid", 0), "gid": r.get("gid", 0),
            "mtime": now, "ctime": now, "atime": now,
            "extents": [], "xattr": {}, "target": r.get("target"),
            "quota_ids": list(r.get("quota_ids") or []),
        }
        if r["type"] == DIR:
            self.dentries.setdefault(ino, {})
        d[name] = ino
        return {"ino": ino}

    def _apply_unlink2(self, r: dict) -> dict:
        """Compound unlink: dentry + inode removal in ONE commit when
        the child inode lives in the same partition as the dentry (the
        mknod placement). Raises EXDEV-ish (code 18) when the child is
        foreign — the caller falls back to the two-op path."""
        parent, name = r["parent"], r["name"]
        self._check_unlocked(parent, name)
        d = self.dentries.get(parent)
        if d is None or name not in d:
            raise MetaError(ENOENT, f"{name!r} not in {parent}")
        ino = d[name]
        t = self.range_target(ino)
        if t is not None:
            # the dentry's parent stayed but the child inode is in a
            # migrating sub-range (the generic fence only sees the
            # parent): refuse the compound removal with the SAME errno
            # as a foreign child — the client's two-op fallback routes
            # the rm_inode half by the child ino, and the 453 chase
            # lands it on the new owner
            raise MetaError(
                18, f"inode {ino} migrating off mp {self.pid} "
                    f"(pid={t})")
        inode = self.inodes.get(ino)
        if inode is None:
            raise MetaError(18, f"inode {ino} not in mp {self.pid}")
        if inode["type"] == DIR and self.dentries.get(ino):
            raise MetaError(ENOTEMPTY, f"{name!r} not empty")
        del d[name]
        if inode["type"] != DIR and inode.get("nlink", 1) > 1:
            # other hardlinks remain: drop this dentry + one link only
            inode["nlink"] -= 1
            inode["ctime"] = r.get("ts", 0.0)
            return {"ino": ino, "extents": [], "deferred": False,
                    "removed": False}
        self.inodes.pop(ino)
        self.dentries.pop(ino, None)
        exts = inode["extents"]
        deferred = [ek for ek in exts if not ek.get("tiny")]
        if deferred:
            self.freelist[str(ino)] = {
                "extents": deferred, "ts": r.get("ts", 0.0)}
        self._reap_inode_blobs(inode, r.get("ts", 0.0))
        return {"ino": ino, "extents": exts, "deferred": bool(deferred),
                "removed": True}

    def _apply_inc_nlink(self, r: dict) -> dict:
        """Hardlink support (metanode CreateLink role): bump the link
        count; the dentry itself lands via mk_dentry on the PARENT's
        partition (two commits client-side; a crash between them leaks
        an overcounted nlink for fsck, never a dangling dentry)."""
        inode = self.inodes.get(r["ino"])
        if inode is None:
            raise MetaError(ENOENT, f"inode {r['ino']}")
        if inode["type"] == DIR:
            raise MetaError(EPERM,
                            "hardlinks to directories are not allowed")
        inode["nlink"] = inode.get("nlink", 1) + 1
        inode["ctime"] = r.get("ts", 0.0)
        return {"nlink": inode["nlink"]}

    def _apply_dec_nlink(self, r: dict) -> dict:
        """Drop one link; the inode (and its extents, via the deferred
        freelist) goes only when the LAST link goes. Directories never
        carry extra links, so a dec removes them outright."""
        ino = r["ino"]
        inode = self.inodes.get(ino)
        if inode is None:
            raise MetaError(ENOENT, f"inode {ino}")
        if inode["type"] != DIR and inode.get("nlink", 1) > 1:
            inode["nlink"] -= 1
            inode["ctime"] = r.get("ts", 0.0)
            return {"removed": False, "nlink": inode["nlink"]}
        return {"removed": True, **self._apply_rm_inode(r)}

    def _apply_rm_dentry(self, r: dict) -> dict:
        parent, name = r["parent"], r["name"]
        self._check_unlocked(parent, name)
        d = self.dentries.get(parent)
        if d is None or name not in d:
            raise MetaError(ENOENT, f"{name!r} not in {parent}")
        ino = d.pop(name)
        return {"ino": ino}

    # ---------------- transactions (metanode/transaction.go analog) ----
    # Two-phase protocol for multi-partition atomicity (rename across
    # parents). Prepare validates the sub-ops and locks their dentry
    # keys; commit applies them; abort releases. One involved partition
    # is the COORDINATOR (the reference's TM): the client commits there
    # first, and its durable commit decision is what participants (RMs)
    # consult when an undecided prepared tx expires — roll forward if the
    # coordinator committed, roll back otherwise. Reference:
    # metanode/transaction.go:1, partition_fsmop_transaction.go.

    def _tx_lock_owner(self, parent: int, name: str) -> str | None:
        for tx_id, tx in self.tx_pending.items():
            for op in tx["ops"]:
                if op["parent"] == parent and (
                    op["name"] == name or op["kind"] == "guard_empty_dir"
                ):
                    # a guard op locks the WHOLE parent (no new children
                    # may appear under a dir being replaced)
                    return tx_id
        return None

    def _check_unlocked(self, parent: int, name: str, tx_id: str | None = None):
        owner = self._tx_lock_owner(parent, name)
        if owner is not None and owner != tx_id:
            raise MetaError(
                EBUSY, f"dentry ({parent}, {name!r}) locked by tx {owner}"
            )

    def _gc_tx(self, now: float) -> None:
        # commit records that name participants are GC'd only by
        # tx_finish (after every participant has provably resolved) — a
        # TTL here would let a long-partitioned participant later read
        # "unknown" and roll BACK a committed tx. Recordless (local)
        # commits expire by TTL.
        for k in [k for k, v in self.tx_committed.items()
                  if not v.get("parts") and now - v["ts"] > self.TX_COMMIT_TTL]:
            del self.tx_committed[k]

    def _apply_tx_prepare(self, r: dict) -> dict:
        """r: {tx_id, ops: [...], coord, parts?, ts}. Op kinds:
          * ``link`` — install parent/name -> ino, replacing the target
            the client validated (`victim` = expected current ino or
            None; asserted here, and the key stays locked until commit,
            so the target cannot change in between).
          * ``rm`` — remove; with `ino`, assert the dentry still points
            at it.
          * ``guard_empty_dir`` — assert the dir's local dentry map is
            empty and lock the whole parent so no child can be created
            while a replace-over-dir tx is in flight.
        On the COORDINATOR, `parts` lists the participant partitions so
        its scanner can push the decision and only GC the commit record
        once every participant has resolved."""
        tx_id = r["tx_id"]
        now = r.get("ts", 0.0)
        self._gc_tx(now)
        if tx_id in self.tx_pending or tx_id in self.tx_committed:
            return {}  # idempotent retry
        for op in r["ops"]:
            self._check_unlocked(op["parent"], op["name"], tx_id)
            if op["kind"] == "mutex":
                # pure named lock (no dentry semantics): held from
                # prepare to commit/abort — the cluster-wide
                # serialization primitive for cross-directory dir
                # renames (the kernel's s_vfs_rename_mutex analog)
                continue
            if op["kind"] == "guard_empty_dir":
                children = self.dentries.get(op["parent"])
                if children:
                    raise MetaError(
                        ENOTEMPTY, f"dir {op['parent']} not empty")
                continue
            d = self.dentries.get(op["parent"])
            if d is None:
                raise MetaError(ENOENT, f"parent dir {op['parent']} not here")
            if op["kind"] == "rm":
                if op["name"] not in d:
                    raise MetaError(ENOENT, f"{op['name']!r} not in {op['parent']}")
                if op.get("ino") is not None and d[op["name"]] != op["ino"]:
                    raise MetaError(ENOENT, f"{op['name']!r} changed under tx")
            elif op["kind"] == "link":
                if op.get("noreplace") and d.get(op["name"]) is not None:
                    raise MetaError(
                        EEXIST, f"{op['name']!r} exists (NOREPLACE)")
                if "victim" in op and d.get(op["name"]) != op["victim"]:
                    raise MetaError(
                        ENOENT, f"target {op['name']!r} changed under tx")
        self.tx_pending[tx_id] = {
            "ops": r["ops"], "ts": now, "coord": r.get("coord"),
            "parts": r.get("parts"),
        }
        return {}

    def _apply_tx_commit(self, r: dict) -> dict:
        tx_id = r["tx_id"]
        self._last_tx_ops = None  # idempotent retry must not replay hints
        done = self.tx_committed.get(tx_id)
        if done is not None:
            return {"victims": done["victims"]}  # idempotent retry
        tx = self.tx_pending.pop(tx_id, None)
        if tx is None:
            raise MetaError(ENOENT, f"tx {tx_id} not prepared here")
        self._last_tx_ops = tx["ops"]  # mirror hint (not FSM state)
        victims: list[int] = []
        for op in tx["ops"]:
            if op["kind"] in ("guard_empty_dir", "mutex"):
                continue
            d = self.dentries.setdefault(op["parent"], {})
            if op["kind"] == "rm":
                d.pop(op["name"], None)
            else:
                old = d.get(op["name"])
                if old is not None and old != op["ino"]:
                    victims.append(old)
                d[op["name"]] = op["ino"]
        self.tx_committed[tx_id] = {
            "victims": victims, "ts": r.get("ts", 0.0),
            "parts": tx.get("parts"),
        }
        return {"victims": victims}

    def _apply_tx_finish(self, r: dict) -> dict:
        """Coordinator-only: every participant has resolved — the commit
        record is no longer needed for recovery and can be dropped."""
        self.tx_committed.pop(r["tx_id"], None)
        return {}

    def _apply_tx_abort(self, r: dict) -> dict:
        self.tx_pending.pop(r["tx_id"], None)
        return {}

    def _apply_rename_local(self, r: dict) -> dict:
        """Atomic same-partition rename: unlink src and (re)link dst in
        ONE fsm apply — no intermediate double-link or missing-link state
        is ever visible or persisted. Returns the replaced victim inode
        (or None). The client validates POSIX type rules and passes its
        expectations ("ino" for src, "victim" for dst); the apply
        re-asserts them, so a concurrent mutation between validation and
        apply fails cleanly instead of silently clobbering."""
        sp, sn = r["src_parent"], r["src_name"]
        dp, dn = r["dst_parent"], r["dst_name"]
        self._check_unlocked(sp, sn)
        self._check_unlocked(dp, dn)
        sd = self.dentries.get(sp)
        if sd is None or sn not in sd:
            raise MetaError(ENOENT, f"{sn!r} not in {sp}")
        if r.get("ino") is not None and sd[sn] != r["ino"]:
            raise MetaError(ENOENT, f"{sn!r} changed under rename")
        dd = self.dentries.get(dp)
        if dd is None:
            raise MetaError(ENOENT, f"parent dir {dp} not here")
        victim = dd.get(dn)
        if r.get("noreplace") and victim is not None:
            # RENAME_NOREPLACE: asserted INSIDE the atomic apply, so a
            # concurrent create can never be silently clobbered
            raise MetaError(EEXIST, f"{dn!r} exists (NOREPLACE)")
        if "victim" in r and victim != r["victim"]:
            raise MetaError(ENOENT, f"target {dn!r} changed under rename")
        if victim is not None and self.dentries.get(victim):
            # victim is a dir with local children: re-assert emptiness
            # inside the atomic apply (the client's check raced)
            raise MetaError(ENOTEMPTY, f"target dir {victim} not empty")
        ino = sd.pop(sn)
        if victim == ino:
            victim = None
        dd[dn] = ino
        moved = self.inodes.get(ino)
        if moved is not None:
            # namespace identity changed: fence any in-flight migration
            # that resolved this inode by its old path
            moved["gen"] = moved.get("gen", 0) + 1
        return {"victim": victim}

    def tx_status(self, tx_id: str) -> str:
        with self._lock:
            if tx_id in self.tx_committed:
                return "committed"
            if tx_id in self.tx_pending:
                return "pending"
            return "unknown"

    def expired_txs(self, now: float | None = None) -> list[tuple[str, dict]]:
        now = time.time() if now is None else now
        with self._lock:
            return [(tx_id, dict(tx)) for tx_id, tx in self.tx_pending.items()
                    if now - tx["ts"] > self.TX_TTL]

    def _apply_append_extents(self, r: dict) -> dict:
        inode = self.inodes.get(r["ino"])
        if inode is None:
            raise MetaError(ENOENT, f"inode {r['ino']}")
        inode["extents"].extend(r["extents"])
        inode["size"] = max(inode["size"], r.get("size", inode["size"]))
        inode["mtime"] = r.get("ts", 0.0)
        # generation counter: every data mutation bumps it, so a tiering
        # commit prepared against an older gen fences instead of
        # dropping this write (`.get` keeps pre-gen snapshots loadable)
        inode["gen"] = inode.get("gen", 0) + 1
        return {}

    def _apply_set_attr(self, r: dict) -> dict:
        inode = self.inodes.get(r["ino"])
        if inode is None:
            raise MetaError(ENOENT, f"inode {r['ino']}")
        for k in ("mode", "uid", "gid", "size", "mtime", "atime", "nlink"):
            if k in r:
                inode[k] = r[k]
        if "size" in r:  # length change is a data mutation: fence tiering
            inode["gen"] = inode.get("gen", 0) + 1
        inode["ctime"] = r.get("ts", 0.0)
        return {}

    def _apply_set_xattr(self, r: dict) -> dict:
        inode = self.inodes.get(r["ino"])
        if inode is None:
            raise MetaError(ENOENT, f"inode {r['ino']}")
        if r.get("value") is None:
            inode["xattr"].pop(r["key"], None)
        else:
            inode["xattr"][r["key"]] = r["value"]
        return {}

    def _apply_truncate(self, r: dict) -> dict:
        inode = self.inodes.get(r["ino"])
        if inode is None:
            raise MetaError(ENOENT, f"inode {r['ino']}")
        size = r["size"]
        inode["size"] = size
        inode["gen"] = inode.get("gen", 0) + 1
        if size == 0:
            old = inode["extents"]
            inode["extents"] = []
            self._defer_free(r["ino"], old, r.get("ts", 0.0))
            # truncating a cold (or mid-migration) file to zero makes
            # its blob copy unreachable: queue it for the orphan reaper
            self._reap_inode_blobs(inode, r.get("ts", 0.0))
            for k in ("tiering.state", "tiering.gen", "tiering.ts"):
                inode["xattr"].pop(k, None)
            return {"extents": old}
        # shrink: drop keys entirely past the new EOF (freed for GC) and
        # clip a straddling key's mapped length — reads in [size, later
        # writes) then fall into an uncovered gap and return zeros, never
        # resurrected pre-truncate bytes
        kept, freed = [], []
        for ek in inode["extents"]:
            fo = ek["file_offset"]
            if fo >= size:
                freed.append(ek)
            elif fo + ek["size"] > size:
                clipped = dict(ek)
                clipped["size"] = size - fo
                kept.append(clipped)  # physical tail stays allocated
            else:
                kept.append(ek)
        inode["extents"] = kept
        self._defer_free(r["ino"], freed, r.get("ts", 0.0))
        return {"extents": freed}

    def _defer_free(self, ino: int, extents: list, ts: float) -> None:
        """Queue non-tiny freed extents for the background deletion scan
        (tiny extents are shared across files, never reclaimed here).
        Keyed by apply_id so repeated truncates of one inode never
        collide; apply_id is part of the FSM, so replicas agree."""
        deferred = [ek for ek in extents if not ek.get("tiny")]
        if deferred:
            self.freelist[f"{ino}:t{self.apply_id}"] = {
                "extents": deferred, "ts": ts}

    def _apply_free_done(self, r: dict) -> dict:
        self.freelist.pop(r["key"], None)
        return {}

    def freelist_entries(self) -> list[tuple[str, dict]]:
        with self._lock:
            return [(k, dict(v)) for k, v in self.freelist.items()]

    # ---------------- cold-tier two-phase migration FSM ----------------
    # The fs->blob bridge persists its state IN the inode (xattrs), so
    # WAL replay, raft failover, and lcnode restarts all see exactly
    # where a migration stopped:
    #
    #   (hot) --tiering_prepare--> PREPARE --tiering_blob_written-->
    #   BLOB_WRITTEN --tiering_commit--> COMMITTED --tiering_finish-->
    #   (cold: cold.location set, extents released)
    #
    # Every step fences on the generation counter captured at prepare:
    # a write/truncate/rename racing the migration bumps gen, and the
    # fenced step queues the now-orphaned blob onto blob_freelist and
    # rolls the inode back to hot — the RACING WRITE WINS, the blob
    # copy loses. Fence failures mutate state (rollback + blob enqueue)
    # and must therefore RETURN {"ok": False} instead of raising:
    # apply() skips segment dirtying on MetaError, so a mutate-then-
    # raise would leave checkpoints missing the rollback.

    _TIER_XATTRS = ("tiering.state", "tiering.gen", "tiering.ts")

    def _defer_blob_free(self, ino: int, location, ts: float) -> None:
        """Queue one unreachable blob location for the orphan reaper.
        Keyed by apply_id (FSM state) so repeated enqueues for one
        inode never collide and replicas agree on the key."""
        if not location or location.get("empty"):
            return  # empty-file sentinel: nothing stored in the blob plane
        self.blob_freelist[f"{ino}:b{self.apply_id}"] = {
            "location": location, "ts": ts}

    def _reap_inode_blobs(self, inode: dict, ts: float) -> None:
        """Queue every blob an inode references (committed cold.location
        and/or mid-migration tiering.pending) onto blob_freelist —
        called from any apply that makes the payload unreachable."""
        xa = inode.get("xattr") or {}
        cold = xa.pop("cold.location", None)
        if cold:
            self._defer_blob_free(
                inode["ino"],
                json.loads(cold) if isinstance(cold, str) else cold, ts)
        pending = xa.pop("tiering.pending", None)
        if pending:
            self._defer_blob_free(inode["ino"], pending, ts)

    def _clear_tiering(self, inode: dict) -> None:
        for k in self._TIER_XATTRS:
            inode["xattr"].pop(k, None)

    def _apply_tiering_prepare(self, r: dict) -> dict:
        inode = self.inodes.get(r["ino"])
        if inode is None:
            raise MetaError(ENOENT, f"inode {r['ino']}")
        if inode["type"] != FILE:
            raise MetaError(EISDIR, f"inode {r['ino']} is not a file")
        xa = inode["xattr"]
        st = xa.get("tiering.state")
        if st is not None:
            raise MetaError(EBUSY, f"inode {r['ino']} migration in {st}")
        if xa.get("cold.location"):
            raise MetaError(EEXIST, f"inode {r['ino']} already cold")
        gen = inode.get("gen", 0)
        xa["tiering.state"] = "PREPARE"
        xa["tiering.gen"] = gen
        xa["tiering.ts"] = r.get("ts", 0.0)
        return {"gen": gen, "size": inode["size"]}

    def _apply_tiering_blob_written(self, r: dict) -> dict:
        """Phase 2: the blob copy is durable (and CRC-verified by the
        engine); pin its location as tiering.pending. A fence failure
        (racing write bumped gen, or the file vanished) queues the blob
        for reaping and rolls back — the hot data was never touched."""
        ts = r.get("ts", 0.0)
        inode = self.inodes.get(r["ino"])
        if inode is None:
            self._defer_blob_free(r["ino"], r["location"], ts)
            return {"ok": False, "reason": "unlinked"}
        xa = inode["xattr"]
        if (xa.get("tiering.state") != "PREPARE"
                or inode.get("gen", 0) != r["gen"]):
            self._defer_blob_free(r["ino"], r["location"], ts)
            self._clear_tiering(inode)
            return {"ok": False, "reason": "fenced"}
        xa["tiering.state"] = "BLOB_WRITTEN"
        xa["tiering.pending"] = r["location"]
        return {"ok": True}

    def _apply_tiering_commit(self, r: dict) -> dict:
        """Phase 3, the point of no return — in ONE atomic apply: the
        pending location becomes cold.location and the hot extents move
        to the deferred freelist. Until this apply lands, every crash
        leaves the hot copy fully intact; after it, the blob copy is
        the single source of truth."""
        ts = r.get("ts", 0.0)
        inode = self.inodes.get(r["ino"])
        if inode is None:
            # unlink raced: _reap_inode_blobs already queued the pending
            return {"ok": False, "reason": "unlinked"}
        xa = inode["xattr"]
        st = xa.get("tiering.state")
        if st == "COMMITTED":
            # crash between commit and finish; the rescan just finishes
            return {"ok": True, "already": True}
        if st != "BLOB_WRITTEN" or inode.get("gen", 0) != r["gen"]:
            pending = xa.pop("tiering.pending", None)
            if pending:
                self._defer_blob_free(r["ino"], pending, ts)
            self._clear_tiering(inode)
            return {"ok": False, "reason": "fenced"}
        pending = xa.pop("tiering.pending")
        xa["cold.location"] = json.dumps(pending)
        old = inode["extents"]
        inode["extents"] = []
        self._defer_free(r["ino"], old, ts)
        xa["tiering.state"] = "COMMITTED"
        return {"ok": True, "released": len(old)}

    def _apply_tiering_finish(self, r: dict) -> dict:
        """Clear the transition markers, keeping cold.location — pure
        bookkeeping, idempotent at any point past commit."""
        inode = self.inodes.get(r["ino"])
        if inode is None:
            return {"ok": True}
        if inode["xattr"].get("tiering.state") == "COMMITTED":
            self._clear_tiering(inode)
        return {"ok": inode["xattr"].get("tiering.state") is None}

    def _apply_tiering_abort(self, r: dict) -> dict:
        """Roll an uncommitted migration back to hot; queues any pending
        blob for reaping. Refuses past the commit point (the hot extents
        are already on the freelist — the caller finishes instead)."""
        inode = self.inodes.get(r["ino"])
        if inode is None:
            return {"ok": True}
        xa = inode["xattr"]
        if xa.get("tiering.state") == "COMMITTED":
            return {"ok": False, "reason": "committed"}
        pending = xa.pop("tiering.pending", None)
        if pending:
            self._defer_blob_free(r["ino"], pending, r.get("ts", 0.0))
        self._clear_tiering(inode)
        return {"ok": True}

    def _apply_untier_commit(self, r: dict) -> dict:
        """Re-heat: attach freshly-written (unregistered) hot extents
        and release the blob copy, in one atomic apply. Fenced on gen
        like the forward path; a fence failure reclaims the extents the
        engine just wrote (they were never visible)."""
        ts = r.get("ts", 0.0)
        inode = self.inodes.get(r["ino"])
        if inode is None:
            self._defer_free(r["ino"], r["extents"], ts)
            return {"ok": False, "reason": "unlinked"}
        xa = inode["xattr"]
        cold = xa.get("cold.location")
        if (cold is None or inode.get("gen", 0) != r["gen"]
                or inode["extents"]):
            self._defer_free(r["ino"], r["extents"], ts)
            return {"ok": False, "reason": "fenced"}
        inode["extents"] = list(r["extents"])
        inode["gen"] = inode.get("gen", 0) + 1
        xa.pop("cold.location")
        self._defer_blob_free(
            r["ino"], json.loads(cold) if isinstance(cold, str) else cold,
            ts)
        return {"ok": True}

    def _apply_blob_free_done(self, r: dict) -> dict:
        self.blob_freelist.pop(r["key"], None)
        return {}

    def _apply_blob_reconcile_enqueue(self, r: dict) -> dict:
        """Inventory reconciliation found a blob-plane object no inode
        references (the put->blob_written crash window): queue it on the
        freelist so the existing reaper deletes it. Keyed by apply_id
        via _defer_blob_free (ino 0 = no owner), so replicas agree."""
        self._defer_blob_free(0, r["location"], r.get("ts", 0.0))
        return {"ok": True}

    def blob_freelist_entries(self) -> list[tuple[str, dict]]:
        with self._lock:
            return [(k, dict(v)) for k, v in self.blob_freelist.items()]

    # ---------------- reads (no apply) ----------------
    def inode_get(self, ino: int) -> dict:
        with self._lock:
            inode = self.inodes.get(ino)
            if inode is None:
                raise MetaError(ENOENT, f"inode {ino}")
            return dict(inode)

    def lookup(self, parent: int, name: str) -> int:
        with self._lock:
            d = self.dentries.get(parent)
            if d is None or name not in d:
                raise MetaError(ENOENT, f"{name!r} not in {parent}")
            return d[name]

    def readdir(self, parent: int) -> dict[str, int]:
        with self._lock:
            d = self.dentries.get(parent)
            if d is None:
                raise MetaError(ENOTDIR, f"{parent} is not a dir here")
            return dict(d)

    def dentry_count(self, parent: int) -> int:
        with self._lock:
            return len(self.dentries.get(parent, {}))

    def usage_report(self) -> dict:
        """Per-partition usage: total file bytes/count plus per-quota-id
        sums — recomputed from the inode table (deterministic, no delta
        bookkeeping to drift). The master's quota sweep aggregates these
        across partitions."""
        with self._lock:
            total_b = total_f = 0
            per_quota: dict[str, dict] = {}
            for inode in self.inodes.values():
                if inode["type"] != FILE:
                    continue
                total_b += inode["size"]
                total_f += 1
                for qid in inode.get("quota_ids") or []:
                    u = per_quota.setdefault(str(qid), {"bytes": 0, "files": 0})
                    u["bytes"] += inode["size"]
                    u["files"] += 1
            return {"bytes": total_b, "files": total_f,
                    "per_quota": per_quota}

    def check_limits(self, record: dict) -> None:
        """Leader-side submit-door gate (never in apply — replicas must
        stay deterministic): reject writes that exceed pushed limits."""
        op = record.get("op")
        with self._lock:
            enf = self.enforce
            if op in ("mk_inode", "mknod") and record.get("type") == FILE:
                if any(int(q) in enf["exceeded"]
                       for q in record.get("quota_ids") or []):
                    raise MetaError(EDQUOT, "dir quota exceeded")
            elif op in ("append_extents", "truncate"):
                inode = self.inodes.get(record.get("ino"))
                grows = inode is not None and (
                    record.get("size", 0) > inode["size"])
                if not grows:
                    return
                if enf["vol_full"]:
                    raise MetaError(ENOSPC, "volume is full")
                if inode and any(int(q) in enf["exceeded"]
                                 for q in inode.get("quota_ids") or []):
                    raise MetaError(EDQUOT, "dir quota exceeded")


class _SubmitWaiter:
    """One rpc_submit call parked in a partition's submit coalescer."""

    __slots__ = ("record", "result", "exc", "done", "event", "ref")

    def __init__(self, record: dict):
        self.record = record
        self.result = None
        self.exc: BaseException | None = None
        self.done = False
        self.event = threading.Event()
        # span handoff across the coalescer's first-caller-drains
        # boundary (same contract as the raft _ProposeWaiter)
        self.ref = tracelib.capture()

    def finish(self, result, exc: BaseException | None) -> None:
        self.result = result
        self.exc = exc
        self.done = True
        self.event.set()


class _SubmitBatcher:
    """Per-partition group commit for the RPC submit plane: while one
    propose is in flight (the whole replicate→fsync→apply round),
    concurrent mutations for the same partition queue here and the next
    drain carries them ALL as one `__batch__` raft entry — one
    replication round for N requests, per-op results/errors fanned back
    to their callers. A drain of one coalesces nothing: it proposes the
    record unwrapped, so the uncontended path is the pre-batcher
    behavior. Batch width tracks contention — no timers, no added idle
    latency (same first-caller-drains discipline as the raft batcher
    and `_wal_sync` underneath)."""

    def __init__(self, node: "MetaNode", pid: int):
        self.node = node
        self.pid = pid
        self._mu = lockwitness.make_lock("_SubmitBatcher._mu")
        self._queue: list[_SubmitWaiter] = []
        self._busy = False

    def submit(self, record: dict, timeout: float = 30.0):
        w = _SubmitWaiter(record)
        with self._mu:
            self._queue.append(w)
            drain = not self._busy
            if drain:
                self._busy = True
        if drain:
            self._drain()
        if not w.event.wait(timeout) and not w.done:
            raise rpc.RpcError(503, f"submit to partition {self.pid} "
                                    f"timed out awaiting group commit")
        if w.exc is not None:
            raise w.exc
        return w.result

    def _drain(self) -> None:
        while True:
            with self._mu:
                batch = self._queue
                if not batch:
                    self._busy = False
                    return
                self._queue = []
            self._land(batch)

    def _land(self, batch: list[_SubmitWaiter]) -> None:
        from ..utils import metrics

        raft_node = self.node.rafts.get(self.pid)
        span = tracelib.start_span(
            "stage:submit_coalesce",
            links=[w.ref for w in batch if w.ref is not None])
        span.set_tag("stage", "submit_coalesce").set_tag("pid", self.pid)
        span.set_tag("ops", len(batch))
        t0 = time.perf_counter()
        try:
            with span:
                try:
                    if raft_node is None:
                        raise rpc.RpcError(
                            404, f"meta partition {self.pid} no longer "
                                 f"replicated on node {self.node.node_id}")
                    metrics.meta_ops_per_batch.observe(len(batch),
                                                       pid=self.pid)
                    if len(batch) == 1:
                        batch[0].finish(
                            raft_node.propose(batch[0].record), None)
                        return
                    outs = raft_node.propose(
                        {"op": "__batch__",
                         "records": [w.record for w in batch]})
                    metrics.meta_batch_entries.inc(pid=self.pid)
                    metrics.meta_batched_ops.inc(len(batch), pid=self.pid)
                    for w, (result, err) in zip(batch, outs):
                        if err is not None:
                            w.finish(None, MetaError(err[0], err[1]))
                        else:
                            w.finish(result, None)
                except BaseException as e:
                    # batch-level failure (NotLeaderError, timeout,
                    # apply bug): every still-unresolved waiter
                    # observes the same outcome
                    for w in batch:
                        if not w.done:
                            w.finish(None, e)
        finally:
            # the early return above still lands here: the coalesce
            # stage is observed for every drained batch
            tracelib.observe_stage("submit_coalesce",
                                   span.path or "meta.write",
                                   time.perf_counter() - t0)


class MetaNode:
    """Hosts many MetaPartitions; RPC surface for the meta SDK.

    With peers configured, each partition is a raft group member
    (multi-raft: one RaftNode per partition, handlers mounted on this
    node's live route table) — mutations commit through raft before
    applying, so any majority of metanode replicas preserves the trees.
    """

    REDIRECT = 421  # "not leader; retry at meta['leader']"

    TX_SCAN_INTERVAL = 5.0

    def __init__(self, node_id: int, data_dir: str | None = None,
                 addr: str | None = None, node_pool=None):
        self.node_id = node_id
        self.data_dir = data_dir
        self.addr = addr
        self.pool = node_pool
        self.partitions: dict[int, MetaPartition] = {}
        self.rafts: dict[int, object] = {}  # pid -> RaftNode
        self._batchers: dict[int, _SubmitBatcher] = {}  # pid -> coalescer
        self._coalesce = os.environ.get("CUBEFS_META_COALESCE", "1") != "0"
        self.dp_view_fn = None  # set_dp_view: enables the free scan
        self._wires: dict[str, object] = {}  # packet addr -> WireClient
        self.extra_routes: dict = {}  # live raft handlers (rpc.resolve_route)
        self._lock = lockwitness.make_rlock("MetaNode._lock")
        self._stop = threading.Event()
        # native read plane (runtime/src/metaserve.cc): the C++ tree
        # mirror + GIL-free packet server for the hot read ops. Falls
        # back to Python-only when the toolchain is absent.
        self._native_lib = None
        self._native_h = None
        self.native_addr: str | None = None
        if os.environ.get("CUBEFS_NATIVE_META", "1") != "0":
            try:
                from ..runtime import build as rt_build

                self._native_lib = rt_build.load()
                self._native_h = self._native_lib.ms_create()
            except Exception:
                self._native_lib = None
                self._native_h = None
        self._tx_scanner = threading.Thread(target=self._tx_scan_loop,
                                            daemon=True)
        self._tx_scanner.start()

    def serve_native(self, host: str = "127.0.0.1", port: int = 0):
        """Start the C++ read-plane server; returns its addr (None when
        the native runtime is unavailable)."""
        if self._native_h is None:
            return None
        p = self._native_lib.ms_serve(self._native_h, host.encode(), port)
        if p < 0:
            return None
        self.native_addr = f"{host}:{p}"
        return self.native_addr

    def create_partition(self, pid: int, start: int, end: int,
                         peers: list[str] | None = None) -> MetaPartition:
        with self._lock:
            if pid not in self.partitions:
                # creation-time bounds are part of replay determinism:
                # every bounds-checked apply in the wal ran against
                # them, so a restart must replay against them too — NOT
                # against a post-migration (shrunk/grown) table row.
                # The wal's own range_load/range_drop applies re-evolve
                # the bounds in order during replay.
                if self.data_dir:
                    rngf = os.path.join(self.data_dir,
                                        f"mp_{pid}.range.json")
                    try:
                        if os.path.exists(rngf):
                            with open(rngf) as f:
                                rec = json.load(f)
                            start, end = int(rec["start"]), int(rec["end"])
                        else:
                            os.makedirs(self.data_dir, exist_ok=True)
                            tmp = rngf + ".tmp"
                            with open(tmp, "w") as f:
                                json.dump({"start": start, "end": end}, f)
                            os.replace(tmp, rngf)
                    except (OSError, ValueError, KeyError):
                        pass
                replicated = bool(peers and len(peers) > 1)
                # replicated partitions persist via the raft wal (replayed
                # into apply on restart) — a second mp-level oplog would
                # double-apply; standalone partitions keep their own oplog
                pdir = (os.path.join(self.data_dir, f"mp_{pid}")
                        if self.data_dir and not replicated else None)
                mp = MetaPartition(pid, start, end, pdir)
                self.partitions[pid] = mp
                if self._native_h is not None:
                    # lint: allow[CFL003] one-time partition registration (cold path); the pid serves nothing until this returns, so nobody is blocked
                    self._native_lib.ms_add_partition(
                        self._native_h, pid, start, end)
                    mp.attach_mirror(self._native_lib, self._native_h)
                    if not replicated:
                        # standalone partitions always leader-serve
                        # lint: allow[CFL003] same cold registration path — flips serving before any reader knows the pid exists
                        self._native_lib.ms_set_serving(
                            self._native_h, pid, 1, b"")
                if replicated:
                    if not self.addr or self.pool is None:
                        raise rpc.RpcError(
                            500,
                            f"metanode {self.node_id} got replicated partition "
                            f"{pid} but has no addr/node_pool configured",
                        )
                    from ..parallel import raft as raftlib

                    node = raftlib.RaftNode(
                        f"mp{pid}", self.addr, peers, mp.apply, self.pool,
                        data_dir=os.path.join(self.data_dir, f"mp_{pid}_raft")
                        if self.data_dir else None,
                        snapshot_fn=mp.state_bytes,
                        restore_fn=mp.restore_state,
                    )
                    raftlib.register_routes(self.extra_routes, node)
                    if self._native_h is not None:
                        # serving flag flips synchronously with every
                        # role transition — the native plane redirects
                        # (421 leader=...) exactly when Python would
                        lib, h = self._native_lib, self._native_h

                        def _on_role(role, leader, _pid=pid):
                            lib.ms_set_serving(
                                h, _pid, 1 if role == "leader" else 0,
                                (leader or "").encode())

                        node.role_listener = _on_role
                    self.rafts[pid] = node.start()
            return self.partitions[pid]

    def _mp(self, pid: int) -> MetaPartition:
        mp = self.partitions.get(pid)
        if mp is None:
            raise rpc.RpcError(404, f"meta partition {pid} not on node {self.node_id}")
        return mp

    def _batcher(self, pid: int) -> _SubmitBatcher:
        with self._lock:
            b = self._batchers.get(pid)
            if b is None:
                b = self._batchers[pid] = _SubmitBatcher(self, pid)
            return b

    def _mp_leader(self, pid: int) -> MetaPartition:
        """Leader-routed access: replicated partitions serve reads and
        ino allocation from the raft leader only (followers apply
        asynchronously; serving them would allow stale reads right after
        a committed write)."""
        mp = self._mp(pid)
        node = self.rafts.get(pid)
        if node is not None:
            st = node.status()
            if st["role"] != "leader":
                raise rpc.RpcError(self.REDIRECT, f"leader={st['leader'] or ''}")
        return mp

    def _range_gate(self, pid: int, inos) -> None:
        """Donor-side routing fence for live range migration: every
        mutation door (rpc_submit / rpc_submit_batch / rpc_alloc_ino —
        lint CFE002 pins this reachability) bounces ops aimed at a
        frozen or handed-off sub-range with the 453 range-moved code and
        a "pid=<target>" message the sdk follows. Fast path: partitions
        with no migration in flight pay one falsy check."""
        mp = self.partitions.get(pid)
        if mp is None or not (mp.frozen or mp.moved):
            return
        for ino in inos:
            if isinstance(ino, int):
                t = mp.range_target(ino)
                if t is not None:
                    metrics.meta_range_redirects.inc()
                    raise rpc.RpcError(rpc.RANGE_MOVED, f"pid={t}")

    def _range_gate_read(self, pid: int, inos) -> None:
        """Read-side fence: a frozen range still serves reads from the
        donor (its copy is current while mutations are fenced), but a
        MOVED range must redirect — answering ENOENT from dropped trees
        would lie to a client holding a pre-split partition map."""
        mp = self.partitions.get(pid)
        if mp is None or not mp.moved:
            return
        for ino in inos:
            if isinstance(ino, int):
                t = mp.range_moved_target(ino)
                if t is not None:
                    metrics.meta_range_redirects.inc()
                    raise rpc.RpcError(rpc.RANGE_MOVED, f"pid={t}")

    def stop(self) -> None:
        self._stop.set()
        for r in self.rafts.values():
            r.stop()
        with self._lock:
            wires, self._wires = dict(self._wires), {}
        for wc in wires.values():
            try:
                wc.close()
            except Exception:
                pass
        if self._native_h is not None:
            # stop the listener + connections; the store handle is NOT
            # destroyed — partitions still hold mirror references, and a
            # post-stop apply must never write into freed memory
            self._native_lib.ms_stop(self._native_h)

    # ---------------- transaction resolution (the TM scan) --------------
    def _submit_local(self, pid: int, record: dict):
        """Push a record through the partition's commit door (raft if
        replicated, direct submit otherwise)."""
        record.setdefault("ts", time.time())  # proposer-side stamp
        raft_node = self.rafts.get(pid)
        if raft_node is None:
            return self._mp(pid).submit(record)
        return raft_node.propose(record)

    def _coord_status(self, coord: dict, tx_id: str) -> str:
        """Ask the coordinator partition whether tx_id committed.
        Returns committed|pending|unknown; "pending" (= keep waiting) on
        any doubt, so an unreachable coordinator never causes a
        unilateral rollback of a possibly-committed tx."""
        pid = coord["pid"]
        local = self.partitions.get(pid)
        if local is not None:
            node = self.rafts.get(pid)
            if node is None or node.status()["role"] == "leader":
                return local.tx_status(tx_id)
        if self.pool is None:
            return "pending"
        try:
            meta, _ = rpc.call_replicas(
                self.pool, list(coord.get("addrs") or []), "tx_status",
                {"pid": pid, "tx_id": tx_id}, timeout=2.0, deadline=4.0)
            return meta["status"]
        except Exception:
            return "pending"

    def _resolve_expired_txs(self) -> None:
        for pid, mp in list(self.partitions.items()):
            node = self.rafts.get(pid)
            if node is not None and node.status()["role"] != "leader":
                continue
            for tx_id, tx in mp.expired_txs():
                coord = tx.get("coord")
                if coord and coord.get("pid") != pid:
                    st = self._coord_status(coord, tx_id)
                    if st == "pending":
                        continue  # coordinator undecided: keep waiting
                    op = "tx_commit" if st == "committed" else "tx_abort"
                else:
                    # we ARE the coordinator and the client never decided
                    # within the TTL: abort (participants will follow)
                    op = "tx_abort"
                try:
                    self._submit_local(pid, {
                        "op": op, "tx_id": tx_id, "ts": time.time(),
                        "op_id": f"txres-{tx_id}-{op}",
                    })
                except Exception:
                    pass  # retried on the next scan

    def _push_committed_txs(self) -> None:
        """Coordinator side: push the commit decision to any participant
        still pending, and drop the commit record (tx_finish) once every
        participant has provably resolved — the presumed-abort hazard of
        a TTL-based GC never arises."""
        for pid, mp in list(self.partitions.items()):
            node = self.rafts.get(pid)
            if node is not None and node.status()["role"] != "leader":
                continue
            with mp._lock:
                items = [(tx_id, dict(rec))
                         for tx_id, rec in mp.tx_committed.items()
                         if rec.get("parts")]
            for tx_id, rec in items:
                all_resolved = True
                for part in rec["parts"]:
                    st = self._participant_status(part, tx_id)
                    if st == "pending":
                        all_resolved = self._push_commit(part, tx_id) and all_resolved
                    elif st is None:  # unreachable: keep the record
                        all_resolved = False
                if all_resolved:
                    try:
                        self._submit_local(pid, {
                            "op": "tx_finish", "tx_id": tx_id,
                            "op_id": f"txfin-{tx_id}",
                        })
                    except Exception:
                        pass

    def _participant_status(self, part: dict, tx_id: str) -> str | None:
        local = self.partitions.get(part["pid"])
        if local is not None:
            node = self.rafts.get(part["pid"])
            if node is None or node.status()["role"] == "leader":
                return local.tx_status(tx_id)
        if self.pool is None:
            return None
        try:
            meta, _ = rpc.call_replicas(
                self.pool, list(part.get("addrs") or []), "tx_status",
                {"pid": part["pid"], "tx_id": tx_id}, timeout=2.0,
                deadline=4.0)
            return meta["status"]
        except Exception:
            return None

    def _push_commit(self, part: dict, tx_id: str) -> bool:
        record = {"op": "tx_commit", "tx_id": tx_id, "ts": time.time(),
                  "op_id": f"txpush-{tx_id}"}
        local = self.partitions.get(part["pid"])
        if local is not None:
            node = self.rafts.get(part["pid"])
            if node is None or node.status()["role"] == "leader":
                try:
                    self._submit_local(part["pid"], record)
                    return True
                except Exception:
                    return False
        if self.pool is None:
            return False
        try:
            rpc.call_replicas(  # lint: allow[CFR001] record carries op_id "txpush-<tx_id>" (built above) — retries dedup in MetaPartition.apply
                self.pool, list(part.get("addrs") or []), "submit",
                {"pid": part["pid"], "record": record}, timeout=5.0,
                deadline=6.0)
            return True
        except Exception:
            return False

    def _tx_scan_loop(self) -> None:
        while not self._stop.wait(self.TX_SCAN_INTERVAL):
            try:
                self._resolve_expired_txs()
                self._push_committed_txs()
            except Exception:
                pass
            try:
                self._free_scan()
            except Exception:
                pass

    # ---------------- deferred extent deletion (the free scan) ----------
    # partition_free_list.go analog: the leader of each partition walks
    # its freelist, deletes the extents from every replica of their data
    # partitions, and retires the entry through the commit door (so all
    # replicas drop it). Failures leave the entry for the next sweep —
    # that IS the retry policy; a datanode that stays down parks the
    # entry until the master rebuilds/decommissions the partition.
    def set_dp_view(self, fn) -> None:
        """fn: () -> {dp_id: {"dp_id", "replicas": [...]}}. Deployments
        wire this to the master's client_view; tests inject a direct
        map. Without it the scan is inert (standalone metanodes)."""
        self.dp_view_fn = fn

    def _free_scan(self) -> None:
        view = None
        for pid in list(self.partitions):
            mp = self.partitions.get(pid)
            if mp is None:
                continue
            node = self.rafts.get(pid)
            if node is not None and node.status()["role"] != "leader":
                continue
            entries = mp.freelist_entries()
            if not entries:
                continue
            if view is None:
                fn = getattr(self, "dp_view_fn", None)
                if fn is None:
                    return
                view = fn() or {}
            for key, ent in entries:
                if self._stop.is_set():
                    return
                done = True
                seen: set[tuple[int, int]] = set()
                for ek in ent["extents"]:
                    ekey = (ek["dp_id"], ek["extent_id"])
                    if ekey in seen:
                        continue
                    seen.add(ekey)
                    dp = view.get(ek["dp_id"])
                    if dp is None:
                        done = False  # dp not in view (rebuild in flight)
                        continue
                    for addr in dp["replicas"]:
                        try:
                            self.pool.get(addr).call(
                                "delete_extent",
                                {"dp_id": ek["dp_id"],
                                 "extent_id": ek["extent_id"]},
                                timeout=10.0)
                        except Exception:
                            done = False  # replica down: retry next sweep
                if done:
                    try:
                        self._submit_local(
                            pid, {"op": "free_done", "key": key,
                                  "op_id": uuid.uuid4().hex})
                    except Exception:
                        pass  # resubmitted next sweep (idempotent pop)

    # ---------------- RPC surface ----------------
    def rpc_create_partition(self, args, body):
        self.create_partition(args["pid"], args["start"], args["end"],
                              args.get("peers"))
        return {}

    def rpc_submit(self, args, body):
        pid = args["pid"]
        raft_node = self.rafts.get(pid)
        # ts is stamped at THIS door — before the record enters raft —
        # so every replica (and every WAL replay) applies the same
        # timestamp; apply handlers never read the clock (CFM001)
        args["record"].setdefault("ts", time.time())
        self._range_gate(pid, _record_inos(args["record"]))
        try:
            self._mp(pid).check_limits(args["record"])
            if raft_node is None:
                res = self._mp(pid).submit(args["record"])
            else:
                from ..parallel.raft import NotLeaderError

                try:
                    # submit-plane group commit: while one propose is in
                    # flight, concurrent mutations for this partition
                    # coalesce into ONE __batch__ raft entry — one
                    # replication round carries them all, and the raft
                    # batcher amortizes lock/WAL/fsync underneath.
                    # CUBEFS_META_COALESCE=0 keeps per-op proposes (A/B)
                    if self._coalesce:
                        res = self._batcher(pid).submit(args["record"])
                    else:
                        res = raft_node.propose(args["record"])
                except NotLeaderError as e:
                    raise rpc.RpcError(self.REDIRECT,
                                       f"leader={e.leader or ''}") from None
        except MetaError as e:
            raise _rpc_err(e) from None
        return {"result": res}

    def rpc_submit_batch(self, args, body):
        """Server half of the client-side cross-partition fan-out: one
        RPC lands a whole batch of mutations for ONE partition as a
        single __batch__ raft entry — the batch was already coalesced
        client-side, so re-splitting it through per-record batcher
        waiters would only add N events per call; one propose carries
        the lot (and the raft proposal batcher still merges it with any
        concurrent rpc_submit traffic into one WAL write/replication
        round). Per-record outcomes fan back as [result, null] | [null,
        [code, msg]] — a per-record MetaError fails exactly that
        record, while batch-level outcomes (leader redirect, partition
        gone) fail the call so client-side retry/redirect covers every
        record at once. Records carry their own op_ids: a retried batch
        replays cached results instead of re-applying."""
        pid = args["pid"]
        records = list(args["records"])
        now = time.time()  # one proposer-side stamp for the whole batch
        for rec in records:
            rec.setdefault("ts", now)
        # batch-level range fence: a single 453 fails the whole call and
        # the client fan-out re-routes record by record (same contract
        # as the leader redirect below)
        for rec in records:
            self._range_gate(pid, _record_inos(rec))
        raft_node = self.rafts.get(pid)
        mp = self._mp(pid)
        outs: list = [None] * len(records)
        todo: list[tuple[int, dict]] = []
        for i, rec in enumerate(records):
            try:
                mp.check_limits(rec)
            except MetaError as e:
                outs[i] = [None, [e.code, str(e)]]
                continue
            todo.append((i, rec))
        if todo:
            from ..parallel.raft import NotLeaderError
            from ..utils import metrics

            try:
                if raft_node is None:
                    for i, rec in todo:
                        try:
                            outs[i] = [mp.submit(rec), None]
                        except MetaError as e:
                            outs[i] = [None, [e.code, str(e)]]
                elif len(todo) == 1:
                    i, rec = todo[0]
                    try:
                        outs[i] = [raft_node.propose(rec), None]
                    except MetaError as e:
                        outs[i] = [None, [e.code, str(e)]]
                else:
                    landed = raft_node.propose(
                        {"op": "__batch__",
                         "records": [rec for _, rec in todo]})
                    metrics.meta_batch_entries.inc(pid=pid)
                    metrics.meta_batched_ops.inc(len(todo), pid=pid)
                    for (i, _), out in zip(todo, landed):
                        outs[i] = out
            except NotLeaderError as e:
                raise rpc.RpcError(self.REDIRECT,
                                   f"leader={e.leader or ''}") from None
        return {"results": outs}

    def rpc_alloc_ino(self, args, body):
        mp = self._mp_leader(args["pid"])
        # advisory redirect when the cursor sits in a migrating range —
        # routes fresh creates straight at the target; the deterministic
        # errno-28 fence inside alloc_ino stays authoritative
        self._range_gate(args["pid"], (mp._next_ino,))
        try:
            return {"ino": mp.alloc_ino(op_id=args.get("op_id"))}
        except MetaError as e:
            raise _rpc_err(e) from None

    def _local_leader_for_ino(self, ino: int):
        """The partition owning `ino` IF hosted here and leader-served;
        None otherwise (the walk hands back to the client)."""
        with self._lock:
            for pid, mp in self.partitions.items():
                if mp.start <= ino < mp.end:
                    node = self.rafts.get(pid)
                    if node is not None and \
                            node.status()["role"] != "leader":
                        return None
                    return mp
        return None

    def rpc_walk(self, args, body):
        """Server-side path walk (the round-trip killer behind
        stat/resolve: one request replaces one lookup per component).
        Consumes `names` from `ino` while this node leader-serves the
        partitions on the chain; returns the final ino (+ inode when
        `stat` and locally owned) or a partial {ino, remaining} the
        client resumes elsewhere — the cross-partition contract of
        distributed path walking."""
        ino = args["ino"]
        names = list(args["names"])
        try:
            while names:
                mp = self._local_leader_for_ino(ino)
                if mp is None or mp.range_moved_target(ino) is not None:
                    # a moved range hands back a partial: the client
                    # resumes via its (refreshed) partition map instead
                    # of walking a dropped tree
                    break
                ino = mp.lookup(ino, names[0])
                names.pop(0)
            out = {"ino": ino, "remaining": names}
            if not names and args.get("stat"):
                mp = self._local_leader_for_ino(ino)
                if mp is not None:
                    out["inode"] = mp.inode_get(ino)
            return out
        except MetaError as e:
            raise _rpc_err(e) from None


    def rpc_inode_get(self, args, body):
        self._range_gate_read(args["pid"], (args["ino"],))
        try:
            return {"inode": self._mp_leader(args["pid"]).inode_get(args["ino"])}
        except MetaError as e:
            raise _rpc_err(e) from None

    def rpc_lookup(self, args, body):
        self._range_gate_read(args["pid"], (args["parent"],))
        try:
            return {"ino": self._mp_leader(args["pid"]).lookup(args["parent"], args["name"])}
        except MetaError as e:
            raise _rpc_err(e) from None

    def rpc_readdir(self, args, body):
        self._range_gate_read(args["pid"], (args["parent"],))
        try:
            return {"entries": self._mp_leader(args["pid"]).readdir(args["parent"])}
        except MetaError as e:
            raise _rpc_err(e) from None

    def rpc_dentry_count(self, args, body):
        self._range_gate_read(args["pid"], (args["parent"],))
        return {"count": self._mp_leader(args["pid"]).dentry_count(args["parent"])}

    def rpc_tx_status(self, args, body):
        return {"status": self._mp_leader(args["pid"]).tx_status(args["tx_id"])}

    def rpc_usage_report(self, args, body):
        return self._mp_leader(args["pid"]).usage_report()

    def rpc_freelist(self, args, body):
        """Pending deferred deletions (fsck reads this so
        freed-but-not-yet-deleted extents don't count as orphans)."""
        mp = self._mp_leader(args["pid"])
        with mp._lock:
            return {"freelist": {k: v for k, v in mp.freelist.items()}}

    def rpc_blob_freelist(self, args, body):
        """Pending deferred blob deletions (the tiering orphan reaper
        drains this; fsck counts these as referenced, not leaked)."""
        mp = self._mp_leader(args["pid"])
        with mp._lock:
            return {"blob_freelist":
                    {k: v for k, v in mp.blob_freelist.items()}}

    def rpc_list_inos(self, args, body):
        """All inode ids held by the partition (fsck's orphan-inode pass
        compares these against the dentry-reachable set)."""
        mp = self._mp_leader(args["pid"])
        with mp._lock:
            return {"inos": sorted(mp.inodes)}

    def rpc_stat(self, args, body):
        """Node-level stats (console/CLI): partitions, raft roles, and
        the native read plane's serve counter."""
        with self._lock:
            parts = {pid: {"start": mp.start, "end": mp.end,
                           "role": (self.rafts[pid].status()["role"]
                                    if pid in self.rafts else "standalone")}
                     for pid, mp in self.partitions.items()}
        native_ops = (self._native_lib.ms_op_count(self._native_h)
                      if self._native_h is not None else 0)
        return {"node_id": self.node_id, "partitions": parts,
                "native_read_ops": native_ops,
                "native_read_addr": self.native_addr}

    def rpc_mp_fill(self, args, body):
        mp = self._mp_leader(args["pid"])
        with mp._lock:
            return {"next_ino": mp._next_ino, "start": mp.start,
                    "end": mp.end}

    def rpc_drop_partition(self, args, body):
        """Remove a partition (failed-split rollback): stops its raft
        member and forgets the in-RAM trees."""
        with self._lock:
            pid = args["pid"]
            raft_node = self.rafts.pop(pid, None)
            if raft_node is not None:
                raft_node.stop()
            self.partitions.pop(pid, None)
            if self.data_dir:
                try:  # dropped pids never come back: retire the bounds
                    os.remove(os.path.join(self.data_dir,
                                           f"mp_{pid}.range.json"))
                except OSError:
                    pass
            if self._native_h is not None:
                # lint: allow[CFL003] teardown must drain native readers BEFORE the trees free — intentionally atomic with the partition removal
                self._native_lib.ms_drop_partition(self._native_h, pid)
        return {}

    def rpc_set_enforcement(self, args, body):
        # advisory flags from the master's quota sweep; pushed to every
        # replica so the gate survives leader changes
        mp = self._mp(args["pid"])
        with mp._lock:
            mp.enforce = {"vol_full": bool(args.get("vol_full")),
                          "exceeded": set(args.get("exceeded") or [])}
        return {}

    def rpc_snapshot(self, args, body):
        self._mp(args["pid"]).snapshot()
        return {}

    def rpc_export_state(self, args, body):
        """Point-in-time FSM state for the snapshot tool (leader-routed,
        CRC'd so transit corruption is detected). apply_id comes out of
        the serialized state itself, so it always matches the payload."""
        mp = self._mp_leader(args["pid"])
        state, apply_id = mp.export_state()
        return {"crc": zlib.crc32(state), "apply_id": apply_id}, state

    # ---------------- live range migration rpcs (fs/split.py) ----------
    def _propose_door(self, pid: int, record: dict):
        """Range-migration commit door: push one migration apply through
        the partition's normal replication path, mapping raft/Meta
        errors exactly like rpc_submit."""
        from ..parallel.raft import NotLeaderError

        try:
            return self._submit_local(pid, record)
        except NotLeaderError as e:
            raise rpc.RpcError(self.REDIRECT,
                               f"leader={e.leader or ''}") from None
        except MetaError as e:
            raise _rpc_err(e) from None

    def _wire(self, addr: str):
        with self._lock:
            wc = self._wires.get(addr)
            if wc is None:
                from ..sdk.clients import WireClient

                wc = WireClient(addr)
                self._wires[addr] = wc
            return wc

    def rpc_range_export(self, args, body):
        """Donor leader: scoped [lo, hi) snapshot + delta-tap
        registration in one locked capture. The payload is CRC-framed
        per record AND summarized by a whole-payload CRC in the meta;
        over the packet plane it rides FLAG_MORE chunk trains."""
        mp = self._mp_leader(args["pid"])
        try:
            payload, aid = mp.range_export(
                args["lo"], args["hi"], args["split_id"])
        except MetaError as e:
            raise _rpc_err(e) from None
        return {"crc": zlib.crc32(payload), "apply_id": aid}, payload

    def rpc_range_fetch(self, args, body):
        """Target-side bootstrap (the geo `_pull_snapshot` idiom): pull
        the donor leader's range snapshot over the packet mux — HTTP
        fallback when no packet addr is known — verify both CRC layers,
        then propose range_load through THIS partition's own commit door
        so every replica loads identical bytes."""
        from ..utils import fsm as fsmlib
        from ..utils import packet

        pid, lo, hi = args["pid"], args["lo"], args["hi"]
        sid = args["split_id"]
        donor = args["donor"]
        meta = payload = None
        last: Exception | None = None
        for addr in donor.get("addrs") or [None]:
            pk = (donor.get("packet_addrs") or {}).get(addr)
            try:
                if pk:
                    # the mux hands back a memoryview over its receive
                    # buffer — materialize before the buffer recycles
                    meta, payload = self._wire(pk).call(
                        packet.OP_META_RANGE_EXPORT,
                        args={"pid": donor["pid"], "lo": lo, "hi": hi,
                              "split_id": sid})
                    payload = bytes(payload)
                elif addr and self.pool is not None:
                    meta, payload = self.pool.get(addr).call(
                        "range_export",
                        {"pid": donor["pid"], "lo": lo, "hi": hi,
                         "split_id": sid}, timeout=30.0)
                else:
                    continue
                break
            except Exception as e:  # noqa: BLE001 - try the next replica
                last = e
                meta = payload = None
        if meta is None:
            raise rpc.RpcError(
                503, f"range export from donor mp {donor.get('pid')} "
                     f"failed: {last}")
        if zlib.crc32(payload) != meta["crc"]:
            raise rpc.RpcError(
                502, f"range snapshot crc mismatch for split {sid}")
        recs = fsmlib.parse_records(payload)
        state = {"inodes": {}, "dentries": {}, "freelist": {},
                 "blob_freelist": {}, "next_ino": 0}
        for rec in recs:
            k = rec.get("k")
            if k == "head":
                state["next_ino"] = rec.get("next_ino", 0)
            elif k == "inode":
                state["inodes"][str(rec["v"]["ino"])] = rec["v"]
            elif k == "dent":
                state["dentries"][str(rec["parent"])] = rec["entries"]
            elif k == "free":
                state["freelist"][rec["key"]] = rec["v"]
            elif k == "bfree":
                state["blob_freelist"][rec["key"]] = rec["v"]
        self._propose_door(pid, {
            "op": "range_load", "lo": lo, "hi": hi, "state": state,
            "op_id": f"rload-{sid}"})
        return {"inodes": len(state["inodes"]),
                "donor_apply_id": meta["apply_id"]}

    def rpc_range_freeze(self, args, body):
        """Donor leader: fence the migrating sub-range (a replicated
        apply — survives restarts and leader changes) and drain the
        delta tap closed by it. The tap-presence check runs FIRST: a
        leadership change since range_export lost the tap, and freezing
        without it would strand the delta — the engine aborts instead."""
        pid, sid = args["pid"], args["split_id"]
        mp = self._mp_leader(pid)
        if sid not in mp._range_taps:
            raise rpc.RpcError(
                409, f"no delta tap for split {sid} on mp {pid} "
                     f"(donor leadership moved since export?)")
        self._propose_door(pid, {
            "op": "range_freeze", "lo": args["lo"], "hi": args["hi"],
            "target_pid": args["target_pid"], "split_id": sid,
            "op_id": f"rfreeze-{sid}"})
        delta, poisoned = mp.range_drain(sid)
        return {"delta": delta, "poisoned": poisoned}

    def rpc_range_thaw(self, args, body):
        """Abort path: unfreeze the donor sub-range (idempotent)."""
        self._propose_door(args["pid"], {
            "op": "range_thaw", "split_id": args["split_id"],
            "op_id": f"rthaw-{args['split_id']}"})
        return {}

    def rpc_range_replay(self, args, body):
        """Target leader: replay the drained delta through the normal
        commit door. Records carry the donor-side op_ids (or synthesized
        "#i/#d/#r" derivatives), so a retried replay dedups instead of
        double-applying; a record that failed identically at donor apply
        time fails identically here."""
        pid = args["pid"]
        applied = failed = 0
        for rec in args.get("records") or []:
            try:
                self._propose_door(pid, dict(rec))
                applied += 1
            except rpc.RpcError as e:
                if 400 <= e.code < 500 and e.code != self.REDIRECT:
                    failed += 1  # deterministic per-record refusal
                else:
                    raise
        return {"applied": applied, "failed": failed}

    def rpc_range_activate(self, args, body):
        """Target leader: claim [lo, hi) — runs only after the delta
        replay, so a reader routed here never sees a stale copy."""
        self._propose_door(args["pid"], {
            "op": "range_activate", "lo": args["lo"], "hi": args["hi"],
            "op_id": f"ractivate-{args['split_id']}"})
        return {}

    def rpc_range_drop(self, args, body):
        """Donor leader: forget the handed-off sub-range and leave the
        moved marker that keeps redirecting stale clients."""
        self._propose_door(args["pid"], {
            "op": "range_drop", "lo": args["lo"], "hi": args["hi"],
            "target_pid": args["target_pid"],
            "op_id": f"rdrop-{args['split_id']}"})
        return {}

    # ---------------- binary packet plane (manager_op.go analog) --------
    # The reference serves EVERY meta op over the 64-byte binary packet
    # protocol (metanode/manager_op.go:300 opCreateInode et al.), not
    # HTTP. The hot SDK ops ride it here: persistent connections kill
    # the per-call HTTP setup+JSON-envelope tax that dominates
    # mdtest-shape workloads. Handlers delegate to the same rpc_*
    # methods, so both transports share one semantics (leader redirect,
    # errno encoding, idempotent submits).
    def serve_packets(self, host: str = "127.0.0.1",
                      port: int = 0, audit=None,
                      workers: int | None = None) -> "packet.PacketServer":
        from ..utils import packet

        def wrap(rpc_method):
            def handler(hdr, args, payload):
                try:
                    out = rpc_method(args, payload)
                except rpc.RpcError as e:
                    # full rpc status (421 leader redirect, 499 errno=..)
                    # rides the reply args — the SDK maps it exactly like
                    # the HTTP transport would
                    raise packet.PacketError(
                        packet.RESULT_RPC, e.message, code=e.code
                    ) from None
                if isinstance(out, tuple):
                    return out
                return out, b""
            return handler

        srv = packet.PacketServer({
            packet.OP_META_LOOKUP: wrap(self.rpc_lookup),
            packet.OP_META_INODE_GET: wrap(self.rpc_inode_get),
            packet.OP_META_READDIR: wrap(self.rpc_readdir),
            packet.OP_META_SUBMIT: wrap(self.rpc_submit),
            packet.OP_META_SUBMIT_BATCH: wrap(self.rpc_submit_batch),
            packet.OP_META_DENTRY_COUNT: wrap(self.rpc_dentry_count),
            packet.OP_META_ALLOC_INO: wrap(self.rpc_alloc_ino),
            packet.OP_META_WALK: wrap(self.rpc_walk),
            packet.OP_META_RANGE_EXPORT: wrap(self.rpc_range_export),
            packet.OP_PING: lambda hdr, a, p: ({}, b""),
        }, host, port, service="metanode", audit=audit, workers=workers)
        return srv.start()
