"""AuthNode: ticket-based service authentication + user credential store.

Role parity: authnode/ (Kerberos-like ticket service: getTicket at
api_service.go:32, raft-replicated keystore FSM at keystore_fsm.go) and
master's user/AK-SK store (master/user.go). Crypto is stdlib HMAC-SHA256
(key derivation + ticket MACs) rather than a cipher dependency: tickets
are MAC-authenticated claims, and each service verifies with its own
registered key. The keystore replicates through the same apply-door
pattern as the other metadata FSMs (raft-pluggable).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import secrets
import time

from ..utils import lockwitness, rpc


class AuthError(Exception):
    pass


def _mac(key: bytes, payload: bytes) -> bytes:
    return hmac.new(key, payload, hashlib.sha256).digest()


class KeyStore:
    """client/service id -> secret key, with an apply-door for
    replication parity with the other FSMs."""

    def __init__(self, data_dir: str | None = None):
        self._lock = lockwitness.make_rlock("KeyStore._lock")
        self.keys: dict[str, str] = {}  # id -> b64 key
        self.data_dir = data_dir
        self._wal = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            path = os.path.join(data_dir, "keystore.jsonl")
            if os.path.exists(path):
                for line in open(path):
                    line = line.strip()
                    if line:
                        try:
                            self.apply(json.loads(line))
                        except json.JSONDecodeError:
                            break
            self._wal = open(path, "a")

    def submit(self, record: dict):
        with self._lock:
            out = self.apply(record)
            if self._wal is not None:
                self._wal.write(json.dumps(record) + "\n")
                self._wal.flush()
            return out

    def apply(self, record: dict):
        with self._lock:
            op = record["op"]
            if op == "put_key":
                self.keys[record["id"]] = record["key"]
                return {}
            if op == "del_key":
                self.keys.pop(record["id"], None)
                return {}
            raise AuthError(f"unknown keystore op {op!r}")

    def get(self, id_: str) -> bytes:
        with self._lock:
            k = self.keys.get(id_)
            if k is None:
                raise AuthError(f"no key registered for {id_!r}")
            return base64.b64decode(k)


class AuthNode:
    TICKET_TTL = 3600.0

    def __init__(self, data_dir: str | None = None):
        self.store = KeyStore(data_dir)

    # ---------------- registration ----------------
    def register(self, id_: str, key: bytes | None = None) -> bytes:
        key = key or secrets.token_bytes(32)
        self.store.submit({"op": "put_key", "id": id_,
                           "key": base64.b64encode(key).decode()})
        return key

    # ---------------- tickets ----------------
    def get_ticket(self, client_id: str, service_id: str,
                   client_proof: str) -> dict:
        """Issue a ticket for client->service. The client proves key
        possession with HMAC(client_key, client_id|service_id|minute)."""
        ckey = self.store.get(client_id)
        now = int(time.time())
        ok = any(
            hmac.compare_digest(
                client_proof,
                _mac(ckey, f"{client_id}|{service_id}|{now // 60 - d}".encode()).hex(),
            )
            for d in (0, 1)  # allow one minute of clock skew
        )
        if not ok:
            raise AuthError("client proof rejected")
        skey = self.store.get(service_id)
        session_key = secrets.token_bytes(32)
        claims = {
            "client": client_id, "service": service_id,
            "exp": time.time() + self.TICKET_TTL,
            "session": base64.b64encode(session_key).decode(),
        }
        payload = json.dumps(claims, sort_keys=True).encode()
        # MAC appended as a FIXED 32-byte suffix: raw digest bytes may
        # contain any separator byte, so delimiter-splitting is unsafe
        ticket = base64.b64encode(payload + _mac(skey, payload)).decode()
        return {"ticket": ticket,
                "session_key": base64.b64encode(session_key).decode()}

    @staticmethod
    def verify_ticket(ticket: str, service_key: bytes,
                      service_id: str) -> dict:
        """Service-side check: MAC + expiry + audience."""
        try:
            raw = base64.b64decode(ticket)
            if len(raw) <= 32:
                raise ValueError("too short")
            payload, mac = raw[:-32], raw[-32:]
        except Exception:
            raise AuthError("malformed ticket") from None
        if not hmac.compare_digest(mac, _mac(service_key, payload)):
            raise AuthError("ticket MAC invalid")
        claims = json.loads(payload)
        if claims["service"] != service_id:
            raise AuthError("ticket audience mismatch")
        if claims["exp"] < time.time():
            raise AuthError("ticket expired")
        return claims

    @staticmethod
    def client_proof(client_id: str, service_id: str, client_key: bytes) -> str:
        now = int(time.time())
        return _mac(client_key, f"{client_id}|{service_id}|{now // 60}".encode()).hex()

    # ---------------- RPC surface ----------------
    def rpc_register(self, args, body):
        key = self.register(args["id"])
        return {"key": base64.b64encode(key).decode()}

    def rpc_get_ticket(self, args, body):
        try:
            return self.get_ticket(args["client_id"], args["service_id"],
                                   args["proof"])
        except AuthError as e:
            raise rpc.RpcError(403, str(e)) from None


class UserStore:
    """AK/SK user registry with per-volume grants (master/user.go role)."""

    def __init__(self):
        self._lock = lockwitness.make_rlock("UserStore._lock")
        self.users: dict[str, dict] = {}  # ak -> {sk, user_id, policies}

    def create_user(self, user_id: str) -> dict:
        with self._lock:
            ak = secrets.token_hex(8)
            sk = secrets.token_hex(16)
            self.users[ak] = {"user_id": user_id, "sk": sk, "volumes": {}}
            return {"user_id": user_id, "access_key": ak, "secret_key": sk}

    def grant(self, ak: str, volume: str, perm: str = "rw") -> None:
        with self._lock:
            self.users[ak]["volumes"][volume] = perm

    def secret_for(self, ak: str) -> str | None:
        with self._lock:
            u = self.users.get(ak)
            return u["sk"] if u else None

    def allowed(self, ak: str, volume: str, write: bool) -> bool:
        with self._lock:
            u = self.users.get(ak)
            if u is None:
                return False
            perm = u["volumes"].get(volume, "")
            return "w" in perm if write else bool(perm)

    # ---------------- RPC surface ----------------
    def rpc_create_user(self, args, body):
        return self.create_user(args["user_id"])

    def rpc_grant(self, args, body):
        self.grant(args["ak"], args["volume"], args.get("perm", "rw"))
        return {}

    def rpc_secret_for(self, args, body):
        return {"sk": self.secret_for(args["ak"])}
