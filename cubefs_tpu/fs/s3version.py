"""S3 bucket versioning + object lock over the FS volume adapter.

Role parity: objectnode/router.go:244-312 (bucket versioning routes,
ListObjectVersions, versionId subresources) and objectnode/object_lock.go
(retention / legal hold configuration and enforcement).

Storage model (no side database — everything rides the volume):

- The plain object path ``/key`` is ALWAYS the newest version. A
  versioned overwrite or delete first *renames* the current file into
  the archive — no data copy, and the version's xattrs travel with it.
- Archived versions live at ``/.versions/<quoted-key>/<vid>`` where
  <quoted-key> is the key percent-encoded into a single path component
  (so ``a`` and ``a/b`` can both have version histories without the
  directory trees colliding).
- A delete marker is an empty archived file with ``s3.dm=1``.
- Per-version metadata is xattrs: ``s3.vid`` (version id; "null" for
  versions written while suspended or before versioning), ``s3.vts``
  (creation time, ns — the version ordering), ``s3.etag``, and the
  object-lock fields ``s3.ret.mode`` / ``s3.ret.until`` /
  ``s3.legalhold``.

Lock enforcement matches AWS semantics: an unversioned DELETE (which
only adds a marker) is always allowed; permanently deleting or
overwriting a protected *version* is denied — COMPLIANCE
unconditionally, GOVERNANCE unless the caller set
``x-amz-bypass-governance-retention``.
"""

from __future__ import annotations

import json
import secrets
import time
import urllib.parse

from . import metanode as mn
from .client import FileSystem, FsError

VDIR = ".versions"

XA_VERSIONING = "s3.versioning"  # bucket root: "Enabled" | "Suspended"
XA_OBJLOCK = "s3.objectlock"     # bucket root: JSON lock configuration
XA_VID = "s3.vid"
XA_VTS = "s3.vts"
XA_DM = "s3.dm"
XA_ETAG = "s3.etag"
XA_RET_MODE = "s3.ret.mode"      # "GOVERNANCE" | "COMPLIANCE"
XA_RET_UNTIL = "s3.ret.until"    # unix seconds, str
XA_LEGAL_HOLD = "s3.legalhold"   # "ON" | "OFF"

NULL_VID = "null"


class S3VersionError(Exception):
    def __init__(self, http: int, code: str, msg: str):
        super().__init__(msg)
        self.http = http
        self.code = code


class Locked(S3VersionError):
    def __init__(self, why: str):
        super().__init__(403, "AccessDenied", why)


def _now_ns() -> int:
    return time.time_ns()


def new_vid() -> str:
    return secrets.token_hex(16)


def iso8601(unix: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(unix))


def parse_iso8601(s: str) -> float:
    import calendar

    s = s.strip().rstrip("Z")
    if "." in s:
        s = s[: s.index(".")]
    return calendar.timegm(time.strptime(s, "%Y-%m-%dT%H:%M:%S"))


class VersionStore:
    """All version/lock operations for one bucket (= one FileSystem)."""

    def __init__(self, fs: FileSystem):
        self.fs = fs

    # ---- bucket configuration -------------------------------------
    def status(self) -> str | None:
        try:
            return self.fs.getxattr("/", XA_VERSIONING)
        except FsError:
            return None

    def set_status(self, status: str) -> None:
        if status not in ("Enabled", "Suspended"):
            raise S3VersionError(400, "MalformedXML",
                                 f"bad versioning status {status!r}")
        if status == "Suspended" and self.lock_config() is not None:
            # AWS: a bucket with object lock can never suspend versioning
            raise S3VersionError(
                409, "InvalidBucketState",
                "versioning cannot be suspended with object lock enabled")
        self.fs.setxattr("/", XA_VERSIONING, status)

    def lock_config(self) -> dict | None:
        try:
            raw = self.fs.getxattr("/", XA_OBJLOCK)
        except FsError:
            return None
        return json.loads(raw) if raw else None

    def set_lock_config(self, conf: dict) -> None:
        if self.status() != "Enabled":
            raise S3VersionError(
                409, "InvalidBucketState",
                "object lock requires versioning to be enabled")
        self.fs.setxattr("/", XA_OBJLOCK, json.dumps(conf))

    # ---- path helpers ---------------------------------------------
    def _vdir(self, key: str) -> str:
        return f"/{VDIR}/" + urllib.parse.quote(key, safe="")

    def _ensure_vdir(self, key: str) -> str:
        for d in (f"/{VDIR}", self._vdir(key)):
            try:
                self.fs.mkdir(d)
            except FsError as e:
                if e.errno != mn.EEXIST:
                    raise
        return self._vdir(key)

    def _meta(self, path: str) -> dict:
        """Inode + version xattrs for one version file."""
        ino = self.fs.resolve(path)
        inode = self.fs.meta.inode_get(ino)
        xa = inode["xattr"]
        return {
            "path": path,
            "dir": inode["type"] == mn.DIR,
            "size": inode["size"],
            "vid": xa.get(XA_VID) or NULL_VID,
            "vts": int(xa.get(XA_VTS) or 0),
            "dm": xa.get(XA_DM) == "1",
            "etag": xa.get(XA_ETAG) or "",
            "ret_mode": xa.get(XA_RET_MODE),
            "ret_until": float(xa[XA_RET_UNTIL]) if xa.get(XA_RET_UNTIL)
            else None,
            "legal_hold": xa.get(XA_LEGAL_HOLD) == "ON",
        }

    def _archived(self, key: str) -> list[dict]:
        """Archived versions of `key`, newest first."""
        vdir = self._vdir(key)
        try:
            names = self.fs.readdir(vdir)
        except FsError:
            return []
        out = [self._meta(f"{vdir}/{n}") for n in names]
        out.sort(key=lambda m: m["vts"], reverse=True)
        return out

    def _current(self, key: str) -> dict | None:
        try:
            m = self._meta("/" + key)
        except FsError:
            return None
        # a directory is key-prefix structure, never an object version:
        # without this guard a versioned DELETE of "a" would archive the
        # whole /a subtree as one "version"
        return None if m["dir"] else m

    # ---- lock enforcement ------------------------------------------
    def check_unlocked(self, meta: dict, bypass_governance: bool) -> None:
        """Raise Locked if this version may not be destroyed/overwritten."""
        if meta["dm"]:
            return  # markers carry no payload and are never locked
        if meta["legal_hold"]:
            raise Locked(f"version {meta['vid']} is under legal hold")
        until = meta["ret_until"]
        if until is not None and until > time.time():
            mode = meta["ret_mode"] or "GOVERNANCE"
            if mode == "COMPLIANCE":
                raise Locked(
                    f"version {meta['vid']} is locked (COMPLIANCE) "
                    f"until {iso8601(until)}")
            if not bypass_governance:
                raise Locked(
                    f"version {meta['vid']} is locked (GOVERNANCE) "
                    f"until {iso8601(until)}; bypass not requested")

    def _apply_default_retention(self, path: str) -> None:
        conf = self.lock_config()
        rule = (conf or {}).get("default") or None
        if not rule:
            return
        days = rule.get("days") or 0
        years = rule.get("years") or 0
        until = time.time() + days * 86400 + years * 365 * 86400
        self.fs.setxattr(path, XA_RET_MODE, rule["mode"])
        self.fs.setxattr(path, XA_RET_UNTIL, str(until))

    # ---- version lifecycle -----------------------------------------
    def _stamp(self, path: str, vid: str, dm: bool = False,
               etag: str = "") -> None:
        self.fs.setxattr(path, XA_VID, vid)
        self.fs.setxattr(path, XA_VTS, str(_now_ns()))
        if dm:
            self.fs.setxattr(path, XA_DM, "1")
        if etag:
            self.fs.setxattr(path, XA_ETAG, etag)

    def _archive_current(self, key: str) -> None:
        """Move /key (always the newest version) into the archive."""
        cur = self._current(key)
        if cur is None:
            return
        vdir = self._ensure_vdir(key)
        self.fs.rename("/" + key, f"{vdir}/{cur['vid']}")

    def put(self, key: str, write_fn, etag: str,
            bypass_governance: bool = False) -> str:
        """Versioned PutObject. `write_fn()` performs the actual object
        write to /key (the caller owns directory creation etc). Returns
        the new version id."""
        st = self.status()
        if st == "Enabled":
            self._archive_current(key)
            write_fn()
            vid = new_vid()
            self._stamp("/" + key, vid, etag=etag)
            self._apply_default_retention("/" + key)
            return vid
        # Suspended: the write replaces the null version wherever it is;
        # a LOCKED null version must refuse the overwrite (its data
        # would be destroyed)
        cur = self._current(key)
        if cur is not None and cur["vid"] != NULL_VID:
            self._archive_current(key)
        elif cur is not None:
            self.check_unlocked(cur, bypass_governance)
        for m in self._archived(key):
            if m["vid"] == NULL_VID:
                self.check_unlocked(m, bypass_governance)
                self.fs.unlink(m["path"])
        write_fn()
        self._stamp("/" + key, NULL_VID, etag=etag)
        return NULL_VID

    def delete(self, key: str) -> str:
        """Versioned DeleteObject without versionId: archive the current
        version and leave a delete marker as the newest version. Always
        allowed (no data is destroyed). Returns the marker's vid."""
        st = self.status()
        vdir = self._ensure_vdir(key)
        if st == "Enabled":
            self._archive_current(key)
            vid = new_vid()
            self.fs.write_file(f"{vdir}/{vid}", b"")
            self._stamp(f"{vdir}/{vid}", vid, dm=True)
            return vid
        # Suspended: a null delete marker replaces the null version
        cur = self._current(key)
        if cur is not None:
            if cur["vid"] == NULL_VID:
                # replacing a marker is fine; replacing DATA destroys it
                self.check_unlocked(cur, bypass_governance=False)
                self.fs.unlink("/" + key)
            else:
                self._archive_current(key)
        for m in self._archived(key):
            if m["vid"] == NULL_VID:
                self.check_unlocked(m, bypass_governance=False)
                self.fs.unlink(m["path"])
        self.fs.write_file(f"{vdir}/{NULL_VID}", b"")
        self._stamp(f"{vdir}/{NULL_VID}", NULL_VID, dm=True)
        return NULL_VID

    def find(self, key: str, vid: str) -> dict:
        cur = self._current(key)
        if cur is not None and cur["vid"] == vid:
            return cur
        for m in self._archived(key):
            if m["vid"] == vid:
                return m
        raise S3VersionError(404, "NoSuchVersion",
                             f"{key} has no version {vid}")

    def delete_version(self, key: str, vid: str,
                       bypass_governance: bool) -> bool:
        """Permanently delete one version (DELETE ?versionId=...).
        Returns True if the deleted version was a delete marker."""
        meta = self.find(key, vid)
        self.check_unlocked(meta, bypass_governance)
        self.fs.unlink(meta["path"])
        if self._current(key) is None:
            # the newest version went away (the current file, or the
            # marker that was shadowing the archive): newest remaining
            # real version becomes the object again
            self._promote(key)
        self._prune(key)
        return meta["dm"]

    def _ensure_parents(self, key: str) -> None:
        path = ""
        for d in [p for p in key.split("/") if p][:-1]:
            path += "/" + d
            try:
                self.fs.mkdir(path)
            except FsError as e:
                if e.errno != mn.EEXIST:
                    raise

    def _promote(self, key: str) -> None:
        """After the newest version went away with /key absent: if the
        newest remaining version is real data, it becomes /key again
        (rename keeps its vid/lock xattrs). A marker stays archived —
        its presence is what makes GET return 404. The key's parent
        directories were pruned when the object went away, so recreate
        them first."""
        arch = self._archived(key)
        if arch and not arch[0]["dm"]:
            self._ensure_parents(key)
            self.fs.rename(arch[0]["path"], "/" + key)

    def _prune(self, key: str) -> None:
        vdir = self._vdir(key)
        try:
            if not self.fs.readdir(vdir):
                self.fs.unlink(vdir)
        except FsError:
            pass

    # ---- reads ------------------------------------------------------
    def latest_is_marker(self, key: str) -> bool:
        """True when the object's newest version is a delete marker
        (GET must 404 with x-amz-delete-marker: true)."""
        if self._current(key) is not None:
            return False
        arch = self._archived(key)
        return bool(arch) and arch[0]["dm"]

    def read_version(self, key: str, vid: str) -> tuple[bytes, dict]:
        meta = self.find(key, vid)
        if meta["dm"]:
            # AWS: GET with a delete marker's versionId is 405
            raise S3VersionError(405, "MethodNotAllowed",
                                 "the specified version is a delete marker")
        return self.fs.read_file(meta["path"]), meta

    # ---- retention / legal hold -------------------------------------
    def _target(self, key: str, vid: str | None) -> dict:
        # retention/legal hold only mean something on a bucket with
        # object lock configured — without it no delete path enforces
        # them, and claiming WORM protection that nothing enforces is
        # worse than refusing (AWS: 400 InvalidRequest)
        if self.lock_config() is None:
            raise S3VersionError(
                400, "InvalidRequest",
                "bucket has no object lock configuration")
        if vid:
            return self.find(key, vid)
        cur = self._current(key)
        if cur is None:
            raise S3VersionError(404, "NoSuchKey", key)
        return cur

    def get_retention(self, key: str, vid: str | None) -> dict | None:
        m = self._target(key, vid)
        if m["ret_until"] is None:
            return None
        return {"mode": m["ret_mode"] or "GOVERNANCE",
                "until": m["ret_until"]}

    def set_retention(self, key: str, vid: str | None, mode: str,
                      until: float, bypass_governance: bool) -> None:
        if mode not in ("GOVERNANCE", "COMPLIANCE"):
            raise S3VersionError(400, "MalformedXML",
                                 f"bad retention mode {mode!r}")
        m = self._target(key, vid)
        if m["dm"]:
            raise S3VersionError(400, "InvalidRequest",
                                 "cannot set retention on a delete marker")
        old_until = m["ret_until"]
        if old_until is not None and old_until > time.time():
            shortening = until < old_until
            if m["ret_mode"] == "COMPLIANCE" and shortening:
                raise Locked("COMPLIANCE retention cannot be shortened")
            if (m["ret_mode"] or "GOVERNANCE") == "GOVERNANCE" \
                    and shortening and not bypass_governance:
                raise Locked("GOVERNANCE retention shortening requires "
                             "bypass")
        self.fs.setxattr(m["path"], XA_RET_MODE, mode)
        self.fs.setxattr(m["path"], XA_RET_UNTIL, str(until))

    def get_legal_hold(self, key: str, vid: str | None) -> bool:
        return self._target(key, vid)["legal_hold"]

    def set_legal_hold(self, key: str, vid: str | None, on: bool) -> None:
        m = self._target(key, vid)
        if m["dm"]:
            raise S3VersionError(400, "InvalidRequest",
                                 "cannot set legal hold on a delete marker")
        self.fs.setxattr(m["path"], XA_LEGAL_HOLD, "ON" if on else "OFF")

    # ---- ListObjectVersions -----------------------------------------
    def list_versions(self, list_keys_fn, prefix: str,
                      max_keys: int, key_marker: str,
                      vid_marker: str) -> tuple[list[dict], bool, str, str]:
        """All versions of all keys under `prefix`, key order then
        newest-first within a key. `list_keys_fn(prefix)` enumerates
        live keys (the gateway's walker); archived-only keys (latest is
        a marker) are found through the archive directory itself."""
        keys = {t[0] for t in list_keys_fn(prefix)}
        # keys whose only remnants are archived versions/markers
        try:
            for qname in self.fs.readdir(f"/{VDIR}"):
                k = urllib.parse.unquote(qname)
                if k.startswith(prefix):
                    keys.add(k)
        except FsError:
            pass
        entries: list[dict] = []
        for k in sorted(keys):
            versions = []
            cur = self._current(k)
            if cur is not None:
                versions.append(cur)
            versions.extend(self._archived(k))
            for i, m in enumerate(versions):
                entries.append({**m, "key": k, "is_latest": i == 0})
        if key_marker:
            # resume strictly after the marker pair IN LISTED ORDER
            # (vids are random tokens, so comparing them would be
            # meaningless): skip up to and including the marker entry
            start = 0
            for i, e in enumerate(entries):
                if e["key"] > key_marker:
                    break
                start = i + 1
                if (e["key"] == key_marker and vid_marker
                        and e["vid"] == vid_marker):
                    break
            entries = entries[start:]
        truncated = len(entries) > max_keys
        page = entries[:max_keys]
        nk = page[-1]["key"] if truncated else ""
        nv = page[-1]["vid"] if truncated else ""
        return page, truncated, nk, nv
