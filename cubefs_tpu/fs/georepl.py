"""Cross-cluster geo-replication gateway: cluster wiring for the
utils/georepl.py core.

Role parity: the reference runs whole standby regions fed by
asynchronous raft-log shipping with an operator-driven, fenced
promote/failback runbook; here ONE ``GeoGateway`` per cluster owns that
region's side of every replicated partition:

* On the serving side it installs the ``GeoShipper`` tap into each
  host's commit door (``MetaPartition.submit``/``submit_many``,
  ``ReplicatedFsm._commit``/``_commit_many``) and ``pump()`` ships the
  unacked tail to the peer gateway over ordinary RPC, healing sequence
  gaps from the shipper's bounded ring and falling back to a full
  snapshot bootstrap — ``fsm_recover_from_state`` generalized across
  clusters — over the PR 17 packet mux (OP_GEO_SNAPSHOT rides the
  FLAG_MORE chunk train, so a multi-MB partition image streams in
  CRC-checked chunks and a corrupt chunk poisons one transfer, not the
  shared connection).
* On the follower side it is the ONE RPC surface through which shipped
  records reach local FSMs (``rpc_geo_ship`` -> ``GeoApplier.deliver``
  -> host ``geo_apply``; lint CFG001 pins this), flips every host into
  follower mode (mutations bounce with GeoRedirect 452 toward the
  primary region; reads keep serving locally, feeding the follower's
  AZ-local flash tier), and answers resync instructions by pulling the
  primary's snapshot through the sdk ``WireClient`` (the CFX-sanctioned
  packet-plane home).
* Role changes go through ``transition()``: the fenced promote/failback
  state machine (utils/georepl.GeoController) plus the cluster-side
  effects — promote adopts the applier's position into the shipper so
  the partition keeps ONE logical sequence across the swap, demote
  marks every part for bootstrap (an old primary's unshipped divergent
  tail must be DISCARDED, never merged), resume_following folds a
  drained shipper position back into the applier for the graceful
  direction swap.

Raft-replicated hosts are refused: raft is the intra-region replication
plane, geo ships standalone-FSM clusters (one stream per partition, no
second consensus inside a region's group).
"""

from __future__ import annotations

import threading
import zlib

from ..utils import faultinject, lockwitness, metrics, packet, rpc
from ..utils import georepl as geo
from ..utils.retry import MONOTONIC

# states in which this cluster serves mutations and ships its commits
_SERVING = ("PRIMARY", "PROMOTED", "FAILBACK_SYNC")


class _Part:
    """One replicated partition: the host FSM plus its shipper/applier
    pair. Which half is live follows the gateway's controller state."""

    def __init__(self, gw: "GeoGateway", key: str, host, kind: str,
                 tenant: str, primary: str | None):
        self.key = key
        self.host = host
        self.kind = kind  # "mp" | "fsm"
        self.primary = primary  # peer-region addr mutations redirect to
        self.needs_bootstrap = False
        host.geo_part = key  # gate metrics label (cubefs_geo_redirects)
        self.shipper = geo.GeoShipper(
            key, epoch_fn=lambda: gw.controller.epoch, clock=gw.clock,
            tenant=tenant)
        state_path = None
        if gw.data_dir:
            state_path = f"{gw.data_dir}/geo_{key.replace(':', '_')}.json"
        self.applier = geo.GeoApplier(
            key, apply_fn=host.geo_apply, clock=gw.clock, tenant=tenant,
            state_path=state_path)

    def _set_mode(self, mode: str | None) -> None:
        if self.kind == "fsm":
            self.host.geo_set_mode(mode, self.primary)
        else:
            self.host.geo_mode = mode
            self.host.geo_primary = self.primary

    def set_role(self, serving: bool, fenced: bool) -> None:
        if serving:
            self._set_mode(None)
            # tap installed BEFORE activating: commits racing the flip
            # are either pre-tap (recovered via bootstrap) or sequenced
            self.shipper.active = True
            self.host.geo_tap = self.shipper.tap
            self.applier.fenced = False  # epoch armor, not the fence,
            # rejects a healed old primary's stream (the counter test)
        else:
            self.host.geo_tap = None
            self.shipper.active = False
            self._set_mode("follower")
            self.applier.fenced = fenced

    def snapshot_with_seq(self) -> tuple[bytes, int]:
        """(state, ship-seq) captured under the host's COMMIT lock —
        the same lock every tap fires under post-apply, so the pair is
        exactly consistent: a bootstrapped follower resumes the stream
        at seq+1 with no lost or double-applied record around the
        snapshot point."""
        if self.kind == "mp":
            with self.host._lock:
                return self.host.state_bytes(), self.shipper.seq
        with self.host._wal_lock:
            return self.host._state_bytes(), self.shipper.seq

    def restore(self, data: bytes) -> None:
        if self.kind == "mp":
            self.host.restore_state(data)
            if self.host.data_dir:
                self.host.snapshot()  # checkpoint; oplog restarts clean
        else:
            self.host.fsm_recover_from_state(data)


class GeoGateway:
    """Per-cluster geo endpoint: rpc_* surface for the peer region,
    pump loop for the serving side, transition orchestration for the
    operator (cubefs-cli geo)."""

    def __init__(self, cluster: str, pool, addr: str,
                 peer_addr: str | None = None, role: str = "primary",
                 data_dir: str | None = None, clock=MONOTONIC):
        if not geo.enabled():
            raise RuntimeError(
                "geo-replication is behind CUBEFS_GEO (default off)")
        self.cluster = cluster
        self.pool = pool
        self.addr = addr
        self.peer_addr = peer_addr
        self.data_dir = data_dir
        self.clock = clock
        self.controller = geo.GeoController(
            cluster, state="PRIMARY" if role == "primary" else "FOLLOWING")
        self._parts: dict[str, _Part] = {}
        self._wires: dict[str, object] = {}
        self._lock = lockwitness.make_rlock("GeoGateway._lock")
        self._pkt = None
        self.packet_addr: str | None = None
        self._stop_evt: threading.Event | None = None
        self._thread: threading.Thread | None = None
        pool.bind(addr, self)

    # ---------------- wiring ----------------
    def attach_metanode(self, node, primaries: dict | None = None,
                        tenant: str = "fs") -> list[str]:
        """Register every standalone partition of a metanode. `primaries`
        maps pid -> the primary REGION's metanode addr (what redirected
        mutations retry against)."""
        keys = []
        for pid, mp in sorted(node.partitions.items()):
            if pid in node.rafts:
                raise RuntimeError(
                    f"mp {pid} is raft-replicated; geo ships "
                    "standalone-FSM clusters only")
            keys.append(self._attach(
                f"mp:{pid}", mp, "mp", tenant, (primaries or {}).get(pid)))
        return keys

    def attach_fsm(self, name: str, host, primary: str | None = None,
                   tenant: str | None = None) -> str:
        """Register a ReplicatedFsm host (master / clustermgr FSM)."""
        if host.raft is not None:
            raise RuntimeError(
                f"fsm {name!r} is raft-replicated; geo ships "
                "standalone-FSM clusters only")
        return self._attach(f"fsm:{name}", host, "fsm", tenant or name,
                            primary)

    def _attach(self, key: str, host, kind: str, tenant: str,
                primary: str | None) -> str:
        with self._lock:
            part = _Part(self, key, host, kind, tenant, primary)
            self._parts[key] = part
            part.set_role(serving=self.controller.state in _SERVING,
                          fenced=self.controller.state == "FENCED")
        return key

    def _part(self, key: str) -> _Part:
        with self._lock:
            part = self._parts.get(key)
        if part is None:
            raise rpc.RpcError(404, f"unknown geo part {key!r}")
        return part

    # ---------------- role transitions ----------------
    def transition(self, op: str, op_id: str | None = None) -> dict:
        """Controller edge + cluster-side effects, atomically under the
        gateway lock. op_id replays return the recorded outcome WITHOUT
        re-running side effects — a retried promote must not re-adopt
        (the shipper may have advanced past the adoption point)."""
        with self._lock:
            if op == "promote":
                # fence ABOVE every epoch this cluster has ever applied
                for part in self._parts.values():
                    self.controller.observe_epoch(part.applier.epoch)
            out = self.controller.transition(op, op_id=op_id)
            if out.get("replayed"):
                return out
            if op == "promote":
                for part in self._parts.values():
                    # continue the partition's ONE logical sequence from
                    # where this side's applier left it
                    part.applier.adopt(part.applier.applied_seq,
                                       self.controller.epoch)
                    part.shipper.adopt(part.applier.applied_seq)
                    part.needs_bootstrap = False
            elif op == "demote":
                for part in self._parts.values():
                    # an old primary's unshipped tail is DIVERGENT
                    # history: discard via snapshot bootstrap, never
                    # merge it into the new primary's stream
                    part.needs_bootstrap = True
            elif op == "resume_following":
                for part in self._parts.values():
                    # graceful direction swap after a drained fence:
                    # local state == shipped history, so the applier
                    # resumes at this side's own ship position
                    part.applier.adopt(
                        max(part.applier.applied_seq, part.shipper.seq),
                        self.controller.epoch)
            self._sync_roles()
            return out

    def _sync_roles(self) -> None:
        st = self.controller.state
        for part in self._parts.values():
            part.set_role(serving=st in _SERVING, fenced=st == "FENCED")

    # ---------------- serving side: the pump ----------------
    def pump(self, max_records: int = 256,
             backfill_rounds: int = 4) -> dict:
        """Ship every part's unacked tail to the peer gateway; heal
        reported gaps from the ring (bounded rounds) and instruct a
        snapshot resync on a ring miss or an explicit bootstrap ask.
        Returns per-part outcomes (tests/bench drive this directly; the
        background loop in start() just calls it on an interval)."""
        if self.controller.state not in _SERVING or not self.peer_addr:
            return {}
        with self._lock:
            parts = dict(self._parts)
        peer = self.pool.get(self.peer_addr)
        out = {}
        # sender identity keys one-way partition rules (a region that
        # can hear but not be heard keeps receiving acks it can't earn)
        with faultinject.sender(self.addr):
            for key, part in sorted(parts.items()):
                try:
                    out[key] = self._pump_part(
                        peer, key, part, max_records, backfill_rounds)
                except rpc.RpcError as e:
                    out[key] = {"error": f"{e.code}: {e.message}"}
                except OSError as e:
                    out[key] = {"error": str(e)}
        return out

    def _pump_part(self, peer, key: str, part: _Part, max_records: int,
                   rounds: int) -> dict:
        lines = part.shipper.pending(max_records)
        reply, _ = peer.call("geo_ship", {"part": key, "lines": lines})
        for _ in range(rounds):
            if reply.get("fenced"):
                break
            if reply.get("bootstrap"):
                reply = self._instruct_resync(peer, key)
                break
            need = reply.get("need")
            if need is None:
                break
            fill = part.shipper.backfill(int(need))
            if fill is None:  # ring wrapped past the gap: full transfer
                reply = self._instruct_resync(peer, key)
                break
            metrics.geo_backfills.inc(part=key, kind="ring")
            reply, _ = peer.call("geo_ship", {"part": key, "lines": fill})
        acked = part.shipper.ack(int(reply["applied_seq"]))
        return {"applied_seq": int(reply["applied_seq"]), "acked": acked,
                "fenced": bool(reply.get("fenced")),
                "pending_bytes": part.shipper.pending_bytes()}

    def _instruct_resync(self, peer, key: str) -> dict:
        """Tell the follower to pull a full snapshot of `key` from this
        side (packet mux when served, rpc fallback otherwise)."""
        reply, _ = peer.call("geo_resync", {
            "part": key, "packet_addr": self.packet_addr,
            "from": self.addr})
        return {"applied_seq": reply["applied_seq"],
                "epoch": reply["epoch"], "need": None, "fenced": False}

    def start(self, interval: float = 0.05) -> None:
        """Background pump loop (bench/daemon mode; tests pump
        explicitly for deterministic schedules)."""
        if self._thread is not None:
            return
        self._stop_evt = threading.Event()

        def loop():
            while not self._stop_evt.wait(interval):
                try:
                    self.pump()
                except Exception:  # noqa: BLE001 - keep the loop alive
                    pass

        self._thread = threading.Thread(
            target=loop, daemon=True, name=f"geo-pump-{self.cluster}")
        self._thread.start()

    def close(self) -> None:
        if self._stop_evt is not None:
            self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._pkt is not None:
            self._pkt.stop()
            self._pkt = None
        with self._lock:
            wires, self._wires = dict(self._wires), {}
        for wc in wires.values():
            wc.close()

    # ---------------- follower side: rpc surface ----------------
    def rpc_geo_ship(self, args, body):
        """Peer pump -> local applier. The applier is the ONE door into
        the host FSMs (lint CFG001): epoch fencing, duplicate skip and
        gap detection all live behind it."""
        part = self._part(args["part"])
        if part.needs_bootstrap:
            return {"applied_seq": part.applier.applied_seq,
                    "epoch": part.applier.epoch, "need": None,
                    "fenced": False, "bootstrap": True}
        out = part.applier.deliver(args.get("lines") or [])
        self.controller.observe_epoch(part.applier.epoch)
        return out

    def rpc_geo_resync(self, args, body):
        """Primary-instructed full bootstrap: pull the snapshot, adopt
        (state, seq, epoch) in one step. Idempotent by contract — the
        transfer lands the primary's CURRENT image, so replaying it
        converges to the same state."""
        part = self._part(args["part"])
        meta, payload = self._pull_snapshot(part, args.get("packet_addr"))
        if zlib.crc32(payload) != meta["crc"]:
            raise rpc.RpcError(
                502, f"geo snapshot crc mismatch for {part.key}")
        part.applier.bootstrap(payload, meta["seq"], meta["epoch"],
                               part.restore)
        part.needs_bootstrap = False
        self.controller.observe_epoch(int(meta["epoch"]))
        return {"applied_seq": part.applier.applied_seq,
                "epoch": part.applier.epoch}

    def _pull_snapshot(self, part: _Part, packet_addr: str | None):
        if packet_addr:
            # multi-MB partition images ride the mux's FLAG_MORE chunk
            # train: per-chunk CRC, one corrupt chunk poisons this
            # transfer only (PacketError), never the shared connection.
            # The mux hands back a memoryview over its receive buffer —
            # materialize it before the buffer is recycled.
            meta, payload = self._wire(packet_addr).call(
                packet.OP_GEO_SNAPSHOT, args={"part": part.key})
            return meta, bytes(payload)
        if not self.peer_addr:
            raise rpc.RpcError(503, "no peer to bootstrap from")
        return self.pool.get(self.peer_addr).call(
            "geo_snapshot", {"part": part.key})

    def _wire(self, addr: str):
        with self._lock:
            wc = self._wires.get(addr)
            if wc is None:
                from ..sdk.clients import WireClient
                wc = WireClient(addr)
                self._wires[addr] = wc
            return wc

    def rpc_geo_snapshot(self, args, body):
        """RPC fallback for the snapshot pull (tests without a packet
        server); same atomic (state, seq) capture as the mux path."""
        part = self._part(args["part"])
        data, seq = part.snapshot_with_seq()
        return ({"crc": zlib.crc32(data), "seq": seq,
                 "epoch": self.controller.epoch}, data)

    def rpc_geo_status(self, args, body):
        return self.status()

    def rpc_geo_transition(self, args, body):
        return self.transition(args["op"], op_id=args.get("op_id"))

    def status(self) -> dict:
        with self._lock:
            parts = dict(self._parts)
        ps = {}
        for key, part in sorted(parts.items()):
            ps[key] = {
                "ship_seq": part.shipper.seq,
                "applied_seq": part.applier.applied_seq,
                "epoch": part.applier.epoch,
                "pending_bytes": part.shipper.pending_bytes(),
                "needs_bootstrap": part.needs_bootstrap,
            }
        return {"cluster": self.cluster, "state": self.controller.state,
                "epoch": self.controller.epoch, "peer": self.peer_addr,
                "packet_addr": self.packet_addr, "parts": ps}

    # ---------------- packet plane (snapshot/backfill transfers) ------
    def serve_packets(self, host: str = "127.0.0.1", port: int = 0,
                      workers: int = 2):
        """Binary plane for bulk geo transfers. Payloads above the mux
        chunk size stream as FLAG_MORE trains automatically."""

        def wrap(fn):
            def handler(hdr, args, payload):
                try:
                    return fn(hdr, args, payload)
                except rpc.RpcError as e:
                    raise packet.PacketError(
                        packet.RESULT_RPC, e.message, code=e.code) from e
            return handler

        def snap(hdr, args, payload):
            part = self._part(args["part"])
            data, seq = part.snapshot_with_seq()
            return ({"crc": zlib.crc32(data), "seq": seq,
                     "epoch": self.controller.epoch}, data)

        def backfill(hdr, args, payload):
            part = self._part(args["part"])
            lines = part.shipper.backfill(int(args["from_seq"]))
            if lines is None:
                return {"miss": True, "count": 0}, b""
            return ({"miss": False, "count": len(lines)},
                    "".join(lines).encode())

        srv = packet.PacketServer(
            {packet.OP_GEO_SNAPSHOT: wrap(snap),
             packet.OP_GEO_BACKFILL: wrap(backfill)},
            host, port, service="geo", workers=workers)
        self._pkt = srv.start()
        self.packet_addr = self._pkt.addr
        return self._pkt
