"""DataNode: replicated extent storage with chain replication.

Role parity: datanode/ — per-partition extent storage on the native
engine (datanode/storage), leader→followers chain replication with ack
aggregation (repl/repl_protocol.go:311 sendRequestToAllFollowers), CRC
fingerprint diffing for replica repair (data_partition_repair.go:102).

Writes take two paths, like the reference:
  * APPENDS (beyond the extent's written end) ride the chain — leader
    writes locally and fans out to followers, acking when all applied.
  * OVERWRITES of already-written ranges go through a PER-PARTITION
    RAFT group (datanode/partition_raft.go, ApplyRandomWrite at
    partition_op_by_raft.go:224): concurrent overwrites commit in one
    total order on every replica, so a leader change mid-storm cannot
    leave replicas diverged the way racing chain-forwards could.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time

from ..utils import lockwitness, metrics, rpc
from ..utils.diskhealth import DiskHealthTracker
from ..utils.retry import RetryPolicy
from .extent_store import (BlockCrcError, ExtentError, ExtentStore,
                           verified_read)


class DataPartition:
    def __init__(self, dp_id: int, path: str, peers: list[str], leader: str):
        self.dp_id = dp_id
        self.path = path
        self.store = ExtentStore(path)
        self.peers = list(peers)  # all replica addrs incl. leader
        self.leader = leader
        self.raft = None  # per-dp raft group for the random-write path
        self._meta_path = os.path.join(path, "dp_meta.json")
        self._lock = lockwitness.make_lock("DataPartition._lock")
        self.next_extent = 1
        if os.path.exists(self._meta_path):
            meta = json.load(open(self._meta_path))
            self.next_extent = meta.get("next_extent", 1)
            self.peers = meta.get("peers", self.peers)
            self.leader = meta.get("leader", self.leader)
        self._persist()

    def apply_random_write(self, entry: dict) -> dict:
        """Raft apply: serialize one overwrite onto the local store —
        runs identically on every replica at the same log position."""
        self.store.write(entry["extent_id"], entry["offset"],
                         base64.b64decode(entry["data"]))
        return {}

    def _persist(self) -> None:
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"dp_id": self.dp_id, "next_extent": self.next_extent,
                       "peers": self.peers, "leader": self.leader}, f)
        os.replace(tmp, self._meta_path)

    def extent_lock(self, extent_id: int) -> threading.Lock:
        """Per-extent writer lock, held by the DESIGNATED leader across a
        whole write (classify + replicate-everywhere). Both paths ack
        only when every replica applied, so serializing initiation here
        totally orders overlapping writes — a chain append and a raft
        overwrite can never interleave differently on different
        replicas."""
        with self._lock:
            if not hasattr(self, "_ext_locks"):
                self._ext_locks = {}
            return self._ext_locks.setdefault(extent_id, lockwitness.make_lock("DataPartition._ext_lock"))

    def alloc_extent(self, op_id: str | None = None) -> int:
        """Mint the next extent id. A transport retry must get the same
        id back — otherwise the retry mints a second, orphaned extent
        (fsck would report it, but never creating it is better)."""
        with self._lock:
            if not hasattr(self, "_alloc_cache"):
                self._alloc_cache = {}
            if op_id is not None and op_id in self._alloc_cache:
                return self._alloc_cache[op_id]
            eid = self.next_extent
            self.next_extent += 1
            self._persist()
            self.store.create(eid)
            if op_id is not None:
                self._alloc_cache[op_id] = eid
                if len(self._alloc_cache) > 1024:
                    for k in list(self._alloc_cache)[:512]:
                        del self._alloc_cache[k]
            return eid


class DataNode:
    def __init__(self, node_id: int, root_dir: str, addr: str, node_pool,
                 qos=None, disks: list[str] | None = None):
        from ..utils.ratelimit import DiskQos

        self.node_id = node_id
        self.root = root_dir
        # multi-disk model (datanode/space_manager.go + disk.go role):
        # each dp lives on ONE disk; a failed disk takes down its dps
        # only, and the master's disk manager migrates exactly those
        self.disks = [os.path.abspath(d) for d in (disks or [root_dir])]
        self.disk_broken: set[str] = set()  # sticky per-disk health
        self.dp_disk: dict[int, str] = {}  # dp_id -> disk path
        # softer-than-broken quarantine (limping disk): keyed by disk
        # INDEX into self.disks; tests may swap in a FakeClock tracker
        self.health = DiskHealthTracker(addr or str(node_id),
                                        range(len(self.disks)))
        self.addr = addr
        self.nodes = node_pool  # addr -> rpc client (for chain forward)
        # client-facing IO shaping (datanode/limit.go): raft applies and
        # chain replica legs are exempt — throttling consensus/repair
        # traffic would stall recovery, exactly what QoS must not do
        self.qos = qos if isinstance(qos, DiskQos) else DiskQos.from_config(qos)
        self.partitions: dict[int, DataPartition] = {}
        self.extra_routes: dict = {}  # live raft handlers (rpc.resolve_route)
        self._lock = lockwitness.make_rlock("DataNode._lock")
        self._broken = False
        # native C++ read plane (runtime/src/dataserve.cc): serves
        # OP_READ from the same extent-store handles, GIL-free
        self._native_lib = None
        self._native_h = None
        self.native_addr: str | None = None
        if os.environ.get("CUBEFS_NATIVE_DATA", "1") != "0":
            try:
                from ..runtime import build as rt_build

                self._native_lib = rt_build.load()
                self._native_h = self._native_lib.ds_create()
            except Exception:
                self._native_lib = None
                self._native_h = None
        # chain legs that failed mid-append: (dp_id, extent_id) -> peers
        # whose replica diverged in the appended range. Repaired
        # immediately in the background (not left to the next fsck /
        # rebuild sweep — a leader read in that window would serve bytes
        # the client was told failed).
        # (dp_id, extent_id, peer) -> {"gen": int, "running": bool}; a
        # repair thread re-syncs until it completes a pass whose gen is
        # still current, so writes landing mid-repair are never lost
        self.pending_repairs: dict[tuple[int, int, str], dict] = {}
        self._repair_lock = lockwitness.make_lock("DataNode._repair_lock")
        for d in self.disks:
            os.makedirs(d, exist_ok=True)
        # reopen partitions found on every disk (raft rejoins via its
        # wal once the master re-pushes the peer set)
        for disk in self.disks:
            for name in os.listdir(disk):
                if name.startswith("dp_") and os.path.isdir(
                        os.path.join(disk, name)):
                    dp_id = int(name[3:])
                    dp = DataPartition(dp_id, os.path.join(disk, name), [], "")
                    self.partitions[dp_id] = dp
                    self.dp_disk[dp_id] = disk
                    self._native_register(dp)
                    if len(dp.peers) > 1:
                        self._start_dp_raft(dp)

    # Native-plane handle discipline: EVERY ds_* call happens while
    # holding self._lock and re-checks _native_h; stop() nulls the
    # attribute under that lock before destroying, so a concurrent
    # caller (e.g. the heartbeat thread's disk_report) can never use a
    # freed handle.

    @property
    def broken(self) -> bool:
        return self._broken

    @broken.setter
    def broken(self, v: bool) -> None:
        # the native read plane honors the same kill switch (tests and
        # failure simulations set this attribute directly)
        self._broken = v
        with self._lock:
            if self._native_h is not None:
                # lint: allow[CFL003] kill-switch flip must be atomic with _broken so the two planes never disagree; single bounded native store
                self._native_lib.ds_set_down(self._native_h, 1 if v else 0)

    def serve_native(self, host: str = "127.0.0.1", port: int = 0):
        """Start the C++ read plane; returns its addr (None when the
        native runtime is unavailable, or when client-read QoS is
        configured — the native plane does not shape reads, and
        silently bypassing a configured limit would make QoS dead
        config; such deployments keep the Python plane)."""
        if self.qos is not None and getattr(self.qos, "read", None):
            return None
        with self._lock:
            if self._native_h is None:
                return None
            # lint: allow[CFL003] one-time startup: the read plane has no traffic until this returns its port
            p = self._native_lib.ds_serve(self._native_h, host.encode(),
                                          port)
        if p < 0:
            return None
        self.native_addr = f"{host}:{p}"
        return self.native_addr

    def _native_register(self, dp: DataPartition) -> None:
        with self._lock:
            if self._native_h is None:
                return
            disk = self.dp_disk.get(dp.dp_id)
            serving = 0 if disk in self.disk_broken else 1
            # lint: allow[CFL003,CFL101] cold registration: the dp serves nothing until it is added; local native call, no network; lock guards _native_h lifecycle
            self._native_lib.ds_add_partition(
                self._native_h, dp.dp_id, dp.store.handle, serving)

    def _pick_disk(self) -> str:
        """Healthy disk with the fewest partitions (space_manager.go
        placement role). Quarantined disks (limping, not dead) get no
        NEW allocations while any unquarantined disk remains — but a
        fully-quarantined node still allocates rather than 503s, since
        quarantine is a soft signal."""
        healthy = [d for d in self.disks if d not in self.disk_broken]
        if not healthy:
            raise rpc.RpcError(503, f"all disks broken on {self.addr}")
        unquarantined = [d for d in healthy
                         if not self.health.is_quarantined(
                             self.disks.index(d))]
        healthy = unquarantined or healthy
        counts = {d: 0 for d in healthy}
        for disk in self.dp_disk.values():
            if disk in counts:
                counts[disk] += 1
        return min(healthy, key=lambda d: (counts[d], d))

    def create_partition(self, dp_id: int, peers: list[str], leader: str) -> None:
        with self._lock:
            if dp_id not in self.partitions:
                disk = self._pick_disk()
                self.partitions[dp_id] = DataPartition(
                    dp_id, os.path.join(disk, f"dp_{dp_id}"), peers, leader
                )
                self.dp_disk[dp_id] = disk
                self._native_register(self.partitions[dp_id])
            else:
                dp = self.partitions[dp_id]
                dp.peers, dp.leader = list(peers), leader
                dp._persist()
            dp = self.partitions[dp_id]
            if dp.raft is not None:
                current = set(dp.raft.peers) | {self.addr}
                if current != set(dp.peers):
                    # master re-pushed a changed replica set (e.g. dead
                    # replica swapped): restart the group on the new
                    # membership over the same wal (crude but safe
                    # reconfiguration — no joint consensus yet)
                    dp.raft.stop()
                    dp.raft = None
            if dp.raft is None and len(dp.peers) > 1:
                self._start_dp_raft(dp)

    def _start_dp_raft(self, dp: DataPartition) -> None:
        from ..parallel import raft as raftlib

        def apply_guarded(entry, _dp=dp):
            # a store failure inside the raft apply (incl. on replicas,
            # where apply exceptions are swallowed) must still run the
            # disk triage, or a follower's dying disk is never detected
            try:
                return _dp.apply_random_write(entry)
            except (OSError, ExtentError):
                disk = self.dp_disk.get(_dp.dp_id)
                if disk is not None:
                    self._probe_disk(disk)
                raise

        node = raftlib.RaftNode(
            f"dp{dp.dp_id}", self.addr, dp.peers, apply_guarded,
            self.nodes,
            data_dir=os.path.join(dp.path, "raft"),
        )
        raftlib.register_routes(self.extra_routes, node)
        dp.raft = node.start()

    def _dp(self, dp_id: int) -> DataPartition:
        if self.broken:
            raise rpc.RpcError(503, f"datanode {self.addr} is down")
        dp = self.partitions.get(dp_id)
        if dp is None:
            raise rpc.RpcError(404, f"dp {dp_id} not on {self.addr}")
        disk = self.dp_disk.get(dp_id)
        if disk in self.disk_broken:
            raise rpc.RpcError(
                503, f"disk {disk} on {self.addr} is broken")
        return dp

    def mark_disk_broken(self, path: str) -> None:
        """Sticky disk failure (disk.go triggerDiskError role): IO
        errors and operator action land here; the next heartbeat's disk
        report makes the master migrate this disk's partitions. The
        native read plane stops serving the disk's dps immediately."""
        path = os.path.abspath(path)
        with self._lock:  # vs create/drop_partition mutating dp_disk
            self.disk_broken.add(path)
            affected = [dp_id for dp_id, d in self.dp_disk.items()
                        if d == path]
            if self._native_h is not None:
                for dp_id in affected:
                    # lint: allow[CFL003] broken-disk fence must be atomic with disk_broken — releasing the lock first would let a native read slip through on a dead disk
                    self._native_lib.ds_set_serving(self._native_h,
                                                    dp_id, 0)

    def _probe_disk(self, disk: str) -> None:
        """Write+fsync health probe; a failure marks the disk broken
        (sticky). Per-call unique probe name: concurrent probes must
        not race each other's unlink into a false positive. ENOSPC and
        EDQUOT are NOT death — a full disk is healthy, just full, and
        evacuating it would move data for nothing."""
        import errno as errno_mod
        import uuid

        if disk in self.disk_broken:
            return
        probe = os.path.join(disk, f".health_probe.{uuid.uuid4().hex[:8]}")
        try:
            with open(probe, "wb") as f:
                f.write(b"ok")
                f.flush()
                os.fsync(f.fileno())
            os.unlink(probe)
        except OSError as pe:
            if pe.errno in (errno_mod.ENOSPC, errno_mod.EDQUOT):
                try:
                    os.unlink(probe)
                except OSError:
                    pass
                return
            self.mark_disk_broken(disk)  # also stops native serving

    def _disk_io_guard(self, dp_id: int, exc: Exception):
        """Store failure triage (disk.go triggerDiskError role): the
        extent store surfaces every failure as ExtentError, which could
        be a logical error OR a dying disk. Disambiguate with a direct
        write+fsync probe on the disk — a failed probe marks the disk
        broken (sticky) and surfaces 503 so clients fail over and the
        heartbeat report triggers migration; a healthy probe re-raises
        the original error unchanged."""
        disk = self.dp_disk.get(dp_id)
        if disk is not None:
            self._probe_disk(disk)
        if disk in self.disk_broken:
            raise rpc.RpcError(
                503, f"disk {disk} failed on {self.addr}: {exc}") from None
        raise exc

    def drop_partition(self, dp_id: int) -> None:
        """Remove a replica this node no longer owns (the master
        repointed the replica set after a disk migration): stop its
        raft member, close the store and delete the data — a stale
        live replica would keep serving bytes that no longer receive
        writes."""
        import shutil

        with self._lock:
            dp = self.partitions.pop(dp_id, None)
            disk = self.dp_disk.pop(dp_id, None)
        if dp is None:
            return
        with self._lock:
            if self._native_h is not None:
                # drains in-flight native reads BEFORE the store closes
                # lint: allow[CFL003] teardown drains in-flight native reads BEFORE the store closes; intentionally atomic with the drop
                self._native_lib.ds_drop_partition(self._native_h, dp_id)
        if dp.raft is not None:
            dp.raft.stop()
        try:
            dp.store.close()
        except Exception:
            pass
        if disk is not None:
            shutil.rmtree(os.path.join(disk, f"dp_{dp_id}"),
                          ignore_errors=True)

    def disk_report(self) -> dict:
        """Per-disk health + resident dps (heartbeat payload; the
        master's disk manager consumes it). Also drains native-plane
        read failures into the disk triage — a dying disk that only
        serves GIL-free reads must still get probed and migrated."""
        failed_disks = []
        with self._lock:
            if self._native_h is not None:
                import ctypes

                buf = (ctypes.c_uint64 * 64)()
                # lint: allow[CFL003] bounded 64-slot buffer drain, no I/O; lock only guards _native_h against concurrent close
                n = self._native_lib.ds_take_failed(self._native_h, buf, 64)
                failed_disks = [self.dp_disk[int(buf[i])]
                                for i in range(n)
                                if int(buf[i]) in self.dp_disk]
        for disk in failed_disks:
            self._probe_disk(disk)
        # quarantine probe rides the heartbeat cadence (breaker
        # half-open analog): cooldown elapsed -> one real write+fsync
        # decides pass/fail
        for idx, disk in enumerate(self.disks):
            if self.health.probe_due(idx):
                self.health.probe_result(idx, self._io_probe_ok(disk))
        with self._lock:
            out = {}
            for idx, d in enumerate(self.disks):
                out[d] = {"broken": d in self.disk_broken,
                          "quarantined": self.health.is_quarantined(idx),
                          "dps": sorted(i for i, dd in self.dp_disk.items()
                                        if dd == d)}
            return out

    def _io_probe_ok(self, disk: str) -> bool:
        """Quarantine probe: same write+fsync as _probe_disk but scored
        pass/fail instead of sticky-breaking (ENOSPC still passes)."""
        import errno as errno_mod
        import uuid

        probe = os.path.join(disk, f".quarantine_probe.{uuid.uuid4().hex[:8]}")
        try:
            with open(probe, "wb") as f:
                f.write(b"ok")
                f.flush()
                os.fsync(f.fileno())
            os.unlink(probe)
            return True
        except OSError as pe:
            if pe.errno in (errno_mod.ENOSPC, errno_mod.EDQUOT):
                return True
            return False

    # ---------------- write path (chain replication) ----------------
    def write(self, dp_id: int, extent_id: int, offset: int, data: bytes,
              chain: bool = True, hops: int = 2) -> None:
        """Write entry point. Follower legs (chain=False) apply locally.
        Everything else routes to the DESIGNATED leader, which holds the
        per-extent lock across the whole operation and classifies it
        exactly once: appends ride the chain, overwrites of
        already-written ranges divert to the per-dp raft group. Both
        paths ack only when every replica applied, so the lock totally
        orders overlapping writes — no replica can see a chain append
        and a raft overwrite in different orders."""
        dp = self._dp(dp_id)
        if not chain:
            self._timed_store_write(dp, dp_id, extent_id, offset, data)
            return
        if dp.leader and dp.leader != self.addr:
            if hops <= 0:
                raise rpc.RpcError(503, f"dp {dp_id}: leader route loop")
            self.nodes.get(dp.leader).call(
                "write", {"dp_id": dp_id, "extent_id": extent_id,
                          "offset": offset, "hops": hops - 1},
                data, timeout=30.0)
            return
        if self.qos is not None:
            # charge the bucket only where the IO actually happens (the
            # designated leader); a stale-view forwarder must not burn
            # its budget on bytes it never writes
            self.qos.acquire_write(len(data))
        with dp.extent_lock(extent_id):
            if len(dp.peers) > 1 and offset < dp.store.size(extent_id):
                raft = dp.raft
                if raft is None:
                    # membership restart in flight: an overwrite must
                    # NEVER fall back to the chain (it would bypass the
                    # raft log and silently diverge a rejoining replica)
                    raise rpc.RpcError(
                        503, f"dp {dp_id} raft reconfiguring; retry")
                self._random_write(dp, extent_id, offset, data)
                return
            self._timed_store_write(dp, dp_id, extent_id, offset, data)
            self._chain_forward(dp, extent_id, offset, data)

    def _timed_store_write(self, dp: DataPartition, dp_id: int,
                           extent_id: int, offset: int, data: bytes) -> None:
        """Local store write with latency/error fed to the quarantine
        tracker (every local IO is a health sample)."""
        disk_idx = self._disk_index(dp_id)
        t0 = time.monotonic()
        try:
            dp.store.write(extent_id, offset, data)
            self.health.record_io(disk_idx, time.monotonic() - t0)
        except (OSError, ExtentError) as e:
            self.health.record_io(disk_idx, time.monotonic() - t0, ok=False)
            self._disk_io_guard(dp_id, e)

    def _chain_forward(self, dp: DataPartition, extent_id: int, offset: int,
                       data: bytes) -> None:
        errs = []
        followers = [p for p in dp.peers if p != self.addr]
        threads = []

        def fwd(peer):
            try:
                self.nodes.get(peer).call(
                    "write_replica",
                    {"dp_id": dp.dp_id, "extent_id": extent_id,
                     "offset": offset},
                    data, timeout=15.0,
                )
            except Exception as e:
                errs.append((peer, e))

        for p in followers:
            t = threading.Thread(target=fwd, args=(p,))
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if errs:
            # the leader's local bytes already persisted: until the
            # failed legs are re-synced the replicas diverge in this
            # range, so queue an immediate repair instead of waiting for
            # a periodic fingerprint diff
            for peer, _ in errs:
                self._queue_leg_repair(dp.dp_id, extent_id, peer)
            peers = ", ".join(p for p, _ in errs)
            raise rpc.RpcError(500, f"chain write failed on {peers}: {errs[0][1]}")

    def _queue_leg_repair(self, dp_id: int, extent_id: int, peer: str,
                          attempts: int = 5) -> None:
        key = (dp_id, extent_id, peer)
        with self._repair_lock:
            st = self.pending_repairs.get(key)
            if st is not None and st["running"]:
                # a repair thread is mid-sync; bump the generation so it
                # re-syncs before declaring the leg clean (a sync started
                # before this write may have copied pre-write bytes)
                st["gen"] += 1
                return
            gen0 = st["gen"] + 1 if st else 1
            self.pending_repairs[key] = {"gen": gen0, "running": True}

        def run():
            while True:
                with self._repair_lock:
                    gen = self.pending_repairs[key]["gen"]
                ok = False
                # budget-bounded (attempts), not deadline-bounded: the
                # repair thread may legitimately outlive any fixed window
                r = RetryPolicy(base=0.05, cap=2.0, deadline=None,
                                max_retries=attempts - 1).start(
                    op="sync_extent_from")
                while True:
                    try:
                        self.nodes.get(peer).call(
                            "sync_extent_from",
                            {"dp_id": dp_id, "extent_id": extent_id,
                             "src_addr": self.addr}, timeout=30.0)
                        ok = True
                        break
                    except Exception:
                        if not r.tick(reason="leg-repair"):
                            break
                with self._repair_lock:
                    st = self.pending_repairs[key]
                    if ok and st["gen"] == gen:
                        del self.pending_repairs[key]
                        return
                    if not ok and st["gen"] == gen:
                        # attempts exhausted (peer likely down): stop the
                        # thread but keep the entry visible (rpc_stat) and
                        # restartable — the next failed chain leg, or the
                        # master's rebuild sweep, re-arms a fresh thread
                        st["running"] = False
                        return
                    # gen advanced while we were syncing/failing: go again

        threading.Thread(target=run, daemon=True).start()

    def _random_write(self, dp: DataPartition, extent_id: int, offset: int,
                      data: bytes, deadline: float = 8.0) -> None:
        """Commit an overwrite through the dp raft group, forwarding to
        the current raft leader if this replica isn't it (ApplyRandomWrite
        analog: one total order for overwrites across leader changes).

        Retries are deadline-bounded, not count-bounded: an election
        under write-storm load can outlast any fixed small retry count
        (seen on the deployed real-socket cluster), and failing a write
        because the group took 1-2s to elect is wrong."""
        from ..parallel.raft import NotLeaderError

        entry = {"op": "random_write", "extent_id": extent_id,
                 "offset": offset, "data": base64.b64encode(data).decode()}
        last: Exception | None = None
        r = rpc.FAILOVER_POLICY.start(op="random_write", deadline=deadline)
        while True:
            try:
                # wait_all: readers may hit ANY replica right after the
                # ack (k-faster selection), so the overwrite must be
                # applied everywhere before acking — the same contract
                # the chain gives appends
                dp.raft.propose(entry, wait_all=True)
                return
            except NotLeaderError as e:
                last = e
                if not e.leader or e.leader == self.addr:
                    if r.tick(reason="election"):
                        continue
                else:
                    try:
                        # dedicated forward: the raft leader proposes
                        # as-is, never re-classifies (its local extent
                        # size may lag)
                        self.nodes.get(e.leader).call(
                            "random_write_forward",
                            {"dp_id": dp.dp_id, "extent_id": extent_id,
                             "offset": offset}, data, timeout=15.0)
                        return
                    except Exception as fwd_err:
                        last = fwd_err
                        if r.tick(reason="forward-failed"):
                            continue
            except TimeoutError as e:
                last = e
                # propose() already blocked its own timeout; only check
                # the overall deadline, no extra backoff sleep
                if r.tick(reason="commit-timeout", sleep=False):
                    continue
            break
        raise rpc.RpcError(503, f"dp {dp.dp_id} random write failed: {last}")

    def _disk_index(self, dp_id: int) -> int:
        disk = self.dp_disk.get(dp_id)
        return self.disks.index(disk) if disk in self.disks else 0

    @staticmethod
    def _rot_unit(dp_id: int, extent_id: int) -> str:
        """At-rest fault-plan unit key for one replica's extent copy."""
        return f"dp{dp_id}:e{extent_id}"

    def read(self, dp_id: int, extent_id: int, offset: int, length: int,
             internal: bool = False) -> bytes:
        """internal=True (replica repair) bypasses client QoS — throttling
        recovery is exactly the starvation QoS must not cause."""
        dp = self._dp(dp_id)
        if self.qos is not None and not internal:
            self.qos.acquire_read(length)
        disk_idx = self._disk_index(dp_id)
        t0 = time.monotonic()
        try:
            data = verified_read(
                dp.store, extent_id, offset, length,
                node_addr=self.addr or str(self.node_id),
                disk_id=disk_idx, unit=self._rot_unit(dp_id, extent_id),
                source="repair" if internal else "read")
            self.health.record_io(disk_idx, time.monotonic() - t0)
            return data
        except BlockCrcError:
            raise  # data integrity, not disk death: 409 path upstream
        except (OSError, ExtentError) as e:
            self.health.record_io(disk_idx, time.monotonic() - t0, ok=False)
            self._disk_io_guard(dp_id, e)

    # ---------------- repair (CRC fingerprint diff) ----------------
    def extent_fingerprint(self, dp_id: int, extent_id: int) -> tuple[int, int]:
        dp = self._dp(dp_id)
        size = dp.store.size(extent_id)
        if size == 0:  # absent or empty extent: nothing to fingerprint
            return 0, 0
        crc = dp.store.extent_crc(extent_id)
        # a planted at-rest fault must diverge this replica's fingerprint
        # exactly like real rot would, so scrub/fsck replica-compare
        # spots it without the simulation touching native store bytes
        plan = rpc._fault
        if plan is not None:
            kind = plan.at_rest_fault(self.addr or str(self.node_id),
                                      self._disk_index(dp_id),
                                      self._rot_unit(dp_id, extent_id))
            if kind == "torn_write":
                return max(size - 1, 1), crc ^ 0x0F0F0F0F
            if kind is not None:  # bitflip / stale_crc
                return size, crc ^ 0xA5A5A5A5
        return size, crc

    def sync_extent_from(self, dp_id: int, extent_id: int, src_addr: str,
                         source: str = "repair") -> None:
        """Pull a full extent from a healthy replica (streamed in 1MiB
        spans) — the repair executor for CRC/size-diverged replicas AND
        the one in-place rewrite the fs-plane read-repair / scrub / fsck
        healers all route through. ``source`` labels who triggered it
        ("read" | "scrub" | "fsck" | "repair") in the healed metric."""
        dp = self._dp(dp_id)
        meta, _ = self.nodes.get(src_addr).call(
            "extent_fingerprint", {"dp_id": dp_id, "extent_id": extent_id}
        )
        size = meta["size"]
        dp.store.create(extent_id)
        span = 1 << 20
        for off in range(0, size, span):
            _, chunk = self.nodes.get(src_addr).call(
                "read_internal", {"dp_id": dp_id, "extent_id": extent_id,
                                  "offset": off,
                                  "length": min(span, size - off)},
            )
            dp.store.write(extent_id, off, chunk)
        plan = rpc._fault
        if plan is not None and plan.heal_rot(
                self.addr or str(self.node_id), self._disk_index(dp_id),
                self._rot_unit(dp_id, extent_id)):
            # the rewrite replaced a genuinely rotten copy (heal_rot is
            # False for rewrites of clean units — zero false repairs)
            metrics.integrity_corruptions_healed.inc(plane="fs",
                                                     source=source)

    # ---------------- RPC surface ----------------
    def rpc_create_partition(self, args, body):
        self.create_partition(args["dp_id"], args["peers"], args["leader"])
        return {}

    def rpc_alloc_extent(self, args, body):
        return {"extent_id": self._dp(args["dp_id"]).alloc_extent(
            op_id=args.get("op_id"))}

    def rpc_write(self, args, body):
        self.write(args["dp_id"], args["extent_id"], args["offset"], body,
                   hops=args.get("hops", 2))
        return {}

    def rpc_random_write_forward(self, args, body):
        # raft-leader leg of an overwrite classified by the designated
        # leader: propose only, never re-classify
        dp = self._dp(args["dp_id"])
        if dp.raft is None:
            raise rpc.RpcError(500, f"dp {args['dp_id']} has no raft group")
        self._random_write(dp, args["extent_id"], args["offset"], body)
        return {}

    def rpc_write_replica(self, args, body):
        # follower leg: apply locally, never re-forward
        self.write(args["dp_id"], args["extent_id"], args["offset"], body,
                   chain=False)
        return {}

    def rpc_read_internal(self, args, body):
        # repair plane: QoS-exempt (see read())
        try:
            data = self.read(args["dp_id"], args["extent_id"],
                             args["offset"], args["length"], internal=True)
        except BlockCrcError as e:
            raise rpc.RpcError(409, str(e)) from None
        except ExtentError as e:
            raise rpc.RpcError(500, str(e)) from None
        return {}, data

    def rpc_read(self, args, body):
        try:
            data = self.read(args["dp_id"], args["extent_id"], args["offset"],
                             args["length"])
        except BlockCrcError as e:
            raise rpc.RpcError(409, str(e)) from None
        except ExtentError as e:
            raise rpc.RpcError(500, str(e)) from None
        return {}, data

    def rpc_extent_fingerprint(self, args, body):
        size, crc = self.extent_fingerprint(args["dp_id"], args["extent_id"])
        return {"size": size, "crc": crc}

    def rpc_disk_report(self, args, body):
        return {"disks": self.disk_report()}

    def rpc_drop_partition(self, args, body):
        self.drop_partition(args["dp_id"])
        return {}

    def rpc_mark_disk_broken(self, args, body):
        self.mark_disk_broken(args["path"])
        return {}

    def rpc_list_extents(self, args, body):
        store = self._dp(args["dp_id"]).store
        eids = store.list_extents()
        out = {"extents": eids}
        if args.get("want_ages"):
            # one stat(2) per extent — only fsck's orphan pass needs it
            out["ages"] = {str(e): store.extent_age(e) for e in eids}
        return out

    def rpc_delete_extent(self, args, body):
        self._dp(args["dp_id"]).store.delete(args["extent_id"])
        return {}

    def rpc_sync_extent_from(self, args, body):
        self.sync_extent_from(args["dp_id"], args["extent_id"],
                              args["src_addr"],
                              source=args.get("source", "repair"))
        return {}

    def rpc_dp_raft_status(self, args, body):
        """Raft role/leader/term of one dp's overwrite group (ops/debug
        surface; the CLI's datapartition status path)."""
        dp = self._dp(args["dp_id"])
        st = dp.raft.status() if dp.raft is not None else None
        return {"status": st}

    def rpc_stat(self, args, body):
        with self._repair_lock:
            pending = [
                {"dp_id": dp, "extent_id": ext, "peer": peer,
                 "running": st["running"]}
                for (dp, ext, peer), st in self.pending_repairs.items()
            ]
        with self._lock:
            # lint: allow[CFL003] atomic counter read, no I/O; lock only guards _native_h against concurrent close
            native_ops = (self._native_lib.ds_op_count(self._native_h)
                          if self._native_h is not None else 0)
        return {"node_id": self.node_id, "partitions": sorted(self.partitions),
                "pending_repairs": pending,
                "disks": self.disk_report(),
                "native_read_ops": native_ops,
                "native_read_addr": self.native_addr}

    # ---------------- binary packet plane (proto/packet.go analog) -----
    # The HOT data path speaks the 64-byte-header binary protocol over
    # persistent TCP, not HTTP: the packet server maps opcodes straight
    # onto the same write/read/repair logic, so both transports share
    # one consistency story (leader routing, raft overwrites, chain).
    def serve_packets(self, host: str = "127.0.0.1",
                      port: int = 0, audit=None,
                      workers: int | None = None) -> "packet.PacketServer":
        from ..utils import packet

        def op_write(hdr, args, payload):
            self.write(hdr["partition"], hdr["extent"], hdr["offset"],
                       payload, hops=args.get("hops", 2))
            return {}, b""

        def op_write_replica(hdr, args, payload):
            self.write(hdr["partition"], hdr["extent"], hdr["offset"],
                       payload, chain=False)
            return {}, b""

        def op_read(hdr, args, payload):
            try:
                data = self.read(hdr["partition"], hdr["extent"],
                                 hdr["offset"], args["length"])
            except BlockCrcError as e:
                raise packet.PacketError(0xC1, str(e)) from None
            except ExtentError as e:
                raise packet.PacketError(0xC2, str(e)) from None
            return {}, data

        def op_fingerprint(hdr, args, payload):
            size, crc = self.extent_fingerprint(hdr["partition"],
                                                hdr["extent"])
            return {"size": size, "crc": crc}, b""

        def op_alloc(hdr, args, payload):
            return {"extent_id": self._dp(hdr["partition"]).alloc_extent()}, b""

        def op_ping(hdr, args, payload):
            return {"node_id": self.node_id}, b""

        srv = packet.PacketServer({
            packet.OP_WRITE: op_write,
            packet.OP_WRITE_REPLICA: op_write_replica,
            packet.OP_READ: op_read,
            packet.OP_FINGERPRINT: op_fingerprint,
            packet.OP_ALLOC_EXTENT: op_alloc,
            packet.OP_PING: op_ping,
        }, host=host, port=port, service="datanode", audit=audit,
           workers=workers,
           # one client's pipelined piece train must apply in arrival
           # order per extent: write() classifies append-vs-overwrite
           # by the extent's current size, so pool reordering would
           # misread disjoint in-window appends as overlap and divert
           # them through raft (a ~6x write-throughput cliff)
           ordered_ops={packet.OP_WRITE, packet.OP_WRITE_REPLICA}).start()
        self.packet_addr = srv.addr
        self._packet_srv = srv
        return srv

    def stop(self) -> None:
        srv = getattr(self, "_packet_srv", None)
        if srv is not None:
            srv.stop()
        with self._lock:
            # null under the lock: every other ds_* caller holds this
            # lock for its whole call, so once we observe/clear the
            # handle here nobody can be mid-call on it
            h, self._native_h = self._native_h, None
        if h is not None:
            # stop the native plane and drain its reads BEFORE closing
            # stores: a read racing a close would touch freed memory;
            # then free the DataServe (no leak per node lifecycle)
            self._native_lib.ds_stop(h)
            for dp_id in list(self.partitions):
                self._native_lib.ds_drop_partition(h, dp_id)
            self._native_lib.ds_destroy(h)
        for dp in self.partitions.values():
            if dp.raft is not None:
                dp.raft.stop()
            dp.store.close()
        self.partitions.clear()
