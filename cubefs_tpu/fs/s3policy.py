"""S3 authorization surface: ACLs, bucket policy, CORS, object tagging.

Role parity: objectnode/acl.go (canned ACLs + grants), policy.go
(bucket policy statements with Effect/Principal/Action/Resource and
wildcard matching; explicit Deny wins), cors.go, tagging.go. Bucket
configuration documents persist as xattrs on the backing volume's root
inode (replicated through the metanode plane); object tags as xattrs on
the object's inode.

Evaluation order (the reference's policy-check flow):
    1. bucket policy explicit Deny  -> deny
    2. bucket policy Allow          -> allow
    3. ACL grant covers the action  -> allow
    4. user-store volume grant      -> allow (authenticated users only)
    5.                              -> deny
"""

from __future__ import annotations

import fnmatch
import json
import xml.etree.ElementTree as ET
import xml.sax.saxutils as xs

# xattr keys on the volume root / object inode
XA_ACL = "s3.acl"
XA_POLICY = "s3.policy"
XA_CORS = "s3.cors"
XA_TAGS = "s3.tags"
XA_META = "s3.meta"  # {"ct": content-type, "meta": {lower-name: value}}
XA_LIFECYCLE = "s3.lifecycle"

CANNED_ACLS = ("private", "public-read", "public-read-write",
               "authenticated-read")

# HEAD authorizes as s3:GetObject, matching AWS (there is no separate
# HeadObject permission)
READ_ACTIONS = {"s3:GetObject", "s3:ListBucket", "s3:GetObjectTagging",
                "s3:GetBucketVersioning", "s3:ListBucketVersions",
                "s3:GetObjectRetention", "s3:GetObjectLegalHold"}
WRITE_ACTIONS = {"s3:PutObject", "s3:DeleteObject", "s3:PutObjectTagging",
                 "s3:DeleteObjectTagging", "s3:PutObjectRetention",
                 "s3:PutObjectLegalHold"}


class S3ConfigError(Exception):
    pass


# ---------------- ACL ----------------
def acl_allows(acl: str | None, action: str, principal: str | None) -> bool:
    """Canned-ACL evaluation: does this ACL grant `action` to
    `principal` (None = anonymous)?"""
    acl = acl or "private"
    if action in READ_ACTIONS:
        if acl in ("public-read", "public-read-write"):
            return True
        if acl == "authenticated-read" and principal is not None:
            return True
    if action in WRITE_ACTIONS and acl == "public-read-write":
        return True
    return False


def acl_to_xml(acl: str, owner: str) -> bytes:
    grants = [("FULL_CONTROL", owner)]
    if acl in ("public-read", "public-read-write"):
        grants.append(("READ", "AllUsers"))
    if acl == "public-read-write":
        grants.append(("WRITE", "AllUsers"))
    if acl == "authenticated-read":
        grants.append(("READ", "AuthenticatedUsers"))
    body = "".join(
        f"<Grant><Grantee>{xs.escape(who)}</Grantee>"
        f"<Permission>{perm}</Permission></Grant>"
        for perm, who in grants
    )
    return (f"<?xml version='1.0'?><AccessControlPolicy>"
            f"<Owner><ID>{xs.escape(owner)}</ID></Owner>"
            f"<AccessControlList>{body}</AccessControlList>"
            f"</AccessControlPolicy>").encode()


# ---------------- bucket policy ----------------
def parse_policy(doc: bytes) -> dict:
    """Validate a bucket-policy JSON document; returns the parsed dict.
    Statement shape: Effect Allow|Deny, Principal "*"|ak|{"AWS": [...]},
    Action str|[...], Resource str|[...] (arn:aws:s3:::bucket[/key])."""
    try:
        pol = json.loads(doc)
    except ValueError as e:  # JSONDecodeError or non-UTF-8 body
        raise S3ConfigError(f"policy is not valid JSON: {e}") from None
    stmts = pol.get("Statement")
    if not isinstance(stmts, list) or not stmts:
        raise S3ConfigError("policy needs a non-empty Statement list")
    for s in stmts:
        if s.get("Effect") not in ("Allow", "Deny"):
            raise S3ConfigError("statement Effect must be Allow or Deny")
        if "Action" not in s or "Resource" not in s:
            raise S3ConfigError("statement needs Action and Resource")
    return pol


def _as_list(v) -> list:
    if isinstance(v, dict):  # {"AWS": [...]} principal form
        v = v.get("AWS", [])
    return v if isinstance(v, list) else [v]


def _principal_matches(stmt, principal: str | None) -> bool:
    pr = _as_list(stmt.get("Principal", "*"))
    for p in pr:
        if p == "*" or (principal is not None and p == principal):
            return True
    return False


def _glob_any(patterns: list, value: str) -> bool:
    return any(fnmatch.fnmatchcase(value, p) for p in patterns)


def policy_decision(policy: dict | None, action: str, bucket: str,
                    key: str, principal: str | None) -> str | None:
    """Returns "Allow", "Deny", or None (policy silent)."""
    if not policy:
        return None
    resource = f"arn:aws:s3:::{bucket}" + (f"/{key}" if key else "")
    decision = None
    for stmt in policy.get("Statement", []):
        if not _principal_matches(stmt, principal):
            continue
        if not _glob_any(_as_list(stmt["Action"]), action):
            continue
        if not _glob_any(_as_list(stmt["Resource"]), resource):
            continue
        if stmt["Effect"] == "Deny":
            return "Deny"  # explicit deny wins immediately
        decision = "Allow"
    return decision


def authorize(action: str, bucket: str, key: str, principal: str | None,
              acl: str | None, policy: dict | None,
              user_grant_ok: bool) -> bool:
    """The combined authorization decision (see module docstring)."""
    decision = policy_decision(policy, action, bucket, key, principal)
    if decision == "Deny":
        return False
    if decision == "Allow":
        return True
    if acl_allows(acl, action, principal):
        return True
    return principal is not None and user_grant_ok


# ---------------- CORS ----------------
def parse_cors(doc: bytes) -> list[dict]:
    """<CORSConfiguration><CORSRule><AllowedOrigin/><AllowedMethod/>
    <AllowedHeader/><MaxAgeSeconds/></CORSRule>...</CORSConfiguration>"""
    try:
        root = ET.fromstring(doc)
    except ET.ParseError as e:
        raise S3ConfigError(f"bad CORS XML: {e}") from None
    valid_methods = {"GET", "PUT", "POST", "DELETE", "HEAD"}
    rules = []
    for r in root.findall("CORSRule"):
        rule = {
            "origins": [e.text or "" for e in r.findall("AllowedOrigin")],
            "methods": [e.text or "" for e in r.findall("AllowedMethod")],
            "headers": [e.text or "" for e in r.findall("AllowedHeader")],
            "max_age": int(r.findtext("MaxAgeSeconds", "0") or 0),
        }
        if not rule["origins"] or not rule["methods"]:
            raise S3ConfigError("CORSRule needs AllowedOrigin and "
                                "AllowedMethod")
        for m in rule["methods"]:
            if m not in valid_methods:
                raise S3ConfigError(f"unsupported AllowedMethod {m!r}")
        for v in rule["origins"] + rule["headers"]:
            # these values flow into response headers: no control chars
            if any(ord(ch) < 0x20 or ch == "\x7f" for ch in v):
                raise S3ConfigError("control characters in CORS rule")
        rules.append(rule)
    if not rules:
        raise S3ConfigError("CORSConfiguration needs at least one CORSRule")
    return rules


def cors_match(rules: list[dict] | None, origin: str,
               method: str) -> dict | None:
    """First rule matching origin+method, or None."""
    for rule in rules or []:
        if method not in rule["methods"]:
            continue
        if any(fnmatch.fnmatchcase(origin, o) for o in rule["origins"]):
            return rule
    return None


def cors_headers(rule: dict, origin: str) -> dict:
    out = {
        "Access-Control-Allow-Origin": origin,
        "Access-Control-Allow-Methods": ", ".join(rule["methods"]),
    }
    if rule["headers"]:
        out["Access-Control-Allow-Headers"] = ", ".join(rule["headers"])
    if rule["max_age"]:
        out["Access-Control-Max-Age"] = str(rule["max_age"])
    return out


# ---------------- object tagging ----------------
def parse_tagging(doc: bytes) -> dict[str, str]:
    try:
        root = ET.fromstring(doc)
    except ET.ParseError as e:
        raise S3ConfigError(f"bad Tagging XML: {e}") from None
    tags: dict[str, str] = {}
    ts = root.find("TagSet")
    for t in (ts.findall("Tag") if ts is not None else []):
        k = t.findtext("Key")
        if not k:
            raise S3ConfigError("Tag needs a Key")
        tags[k] = t.findtext("Value") or ""
    if len(tags) > 10:  # S3's object-tag limit
        raise S3ConfigError("at most 10 tags per object")
    return tags


def tagging_to_xml(tags: dict[str, str]) -> bytes:
    body = "".join(
        f"<Tag><Key>{xs.escape(k)}</Key><Value>{xs.escape(v)}</Value></Tag>"
        for k, v in sorted(tags.items())
    )
    return (f"<?xml version='1.0'?><Tagging><TagSet>{body}</TagSet>"
            f"</Tagging>").encode()


# ---------------- lifecycle configuration ----------------
def parse_lifecycle(doc: bytes) -> list[dict]:
    """<LifecycleConfiguration><Rule><ID/><Filter><Prefix/></Filter>
    <Status/><Expiration><Days/></Expiration>
    <Transition><Days/></Transition></Rule>... (namespaced or not)."""
    try:
        root = ET.fromstring(doc)
    except ET.ParseError as e:
        raise S3ConfigError(f"bad lifecycle XML: {e}") from None
    def _days(parent, what: str, rule_id: str) -> int:
        """Days is REQUIRED and >= 1 (AWS rule): a missing or zero value
        must never silently become expire-everything-now."""
        raw = parent.findtext("{*}Days")
        try:
            days = int(raw)
        except (TypeError, ValueError):
            raise S3ConfigError(
                f"rule {rule_id!r}: {what} needs an integer Days") from None
        if days < 1:
            raise S3ConfigError(f"rule {rule_id!r}: Days must be >= 1")
        return days

    rules = []
    # "{*}name" matches the element in ANY namespace including none, so
    # one expression covers AWS-SDK documents and bare XML alike
    for r in root.findall("{*}Rule"):
        rule = {
            "id": r.findtext("{*}ID") or f"rule-{len(rules) + 1}",
            "status": r.findtext("{*}Status") or "Enabled",
            "prefix": "",
            "expire_days": None,
            "transition_days": None,
        }
        flt = r.find("{*}Filter")
        if flt is not None:
            rule["prefix"] = flt.findtext("{*}Prefix") or ""
        else:
            # legacy (pre-Filter) format puts Prefix directly on the
            # Rule; ignoring it would silently widen the rule to the
            # WHOLE bucket — the exact expire-everything hazard the
            # Days validation exists to prevent
            rule["prefix"] = r.findtext("{*}Prefix") or ""
        exp = r.find("{*}Expiration")
        if exp is not None:
            rule["expire_days"] = _days(exp, "Expiration", rule["id"])
        tr = r.find("{*}Transition")
        if tr is not None:
            rule["transition_days"] = _days(tr, "Transition", rule["id"])
        if rule["expire_days"] is None and rule["transition_days"] is None:
            raise S3ConfigError(
                f"rule {rule['id']!r} needs Expiration or Transition")
        if rule["status"] not in ("Enabled", "Disabled"):
            raise S3ConfigError(f"bad Status {rule['status']!r}")
        rules.append(rule)
    if not rules:
        raise S3ConfigError("LifecycleConfiguration needs at least one Rule")
    return rules


def lifecycle_to_xml(rules: list[dict]) -> bytes:
    out = []
    for r in rules:
        parts = [f"<ID>{xs.escape(r['id'])}</ID>",
                 f"<Filter><Prefix>{xs.escape(r['prefix'])}</Prefix></Filter>",
                 f"<Status>{r['status']}</Status>"]
        if r.get("expire_days") is not None:
            parts.append(f"<Expiration><Days>{r['expire_days']}</Days>"
                         f"</Expiration>")
        if r.get("transition_days") is not None:
            parts.append(f"<Transition><Days>{r['transition_days']}</Days>"
                         f"<StorageClass>EC_COLD</StorageClass>"
                         f"</Transition>")
        out.append("<Rule>" + "".join(parts) + "</Rule>")
    return (f"<?xml version='1.0'?><LifecycleConfiguration>"
            f"{''.join(out)}</LifecycleConfiguration>").encode()
