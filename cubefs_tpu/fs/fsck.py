"""fsck: filesystem consistency checker.

Role parity: tool/fsck — walks the volume's metadata tree, verifies
every extent key resolves to readable bit-identical replicas (CRC
fingerprint agreement), reports dangling extent keys, orphaned dentries
(pointing to missing inodes), and orphaned extents on datanodes that no
inode references (reclaimable leak candidates).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils import rpc
from . import metanode as mn
from .client import FileSystem, FsError


@dataclass
class FsckReport:
    files: int = 0
    dirs: int = 0
    bytes_checked: int = 0
    dangling_extents: list = field(default_factory=list)  # (path, ek, err)
    replica_mismatches: list = field(default_factory=list)  # (path, ek, fps)
    orphan_dentries: list = field(default_factory=list)  # (parent_path, name)
    orphan_extents: list = field(default_factory=list)  # (dp_id, extent_id)
    orphan_extent_ages: dict = field(default_factory=dict)  # (dp,eid)->sec
    orphan_inodes: list = field(default_factory=list)  # ino (no dentry)
    pending_free: int = 0  # freelist entries awaiting the deletion scan
    reclaimed_extents: int = 0
    reclaimed_inodes: int = 0
    healed_extents: list = field(default_factory=list)  # (dp_id, eid) via --heal
    deduped_mismatches: int = 0  # already healed by the scrubber

    @property
    def clean(self) -> bool:
        return not (self.dangling_extents or self.replica_mismatches
                    or self.orphan_dentries or self.orphan_extents
                    or self.orphan_inodes)

    def summary(self) -> dict:
        return {
            "files": self.files, "dirs": self.dirs,
            "bytes": self.bytes_checked,
            "dangling_extents": len(self.dangling_extents),
            "replica_mismatches": len(self.replica_mismatches),
            "orphan_dentries": len(self.orphan_dentries),
            "orphan_extents": len(self.orphan_extents),
            "orphan_inodes": len(self.orphan_inodes),
            "pending_free": self.pending_free,
            "reclaimed_extents": self.reclaimed_extents,
            "reclaimed_inodes": self.reclaimed_inodes,
            "healed_extents": len(self.healed_extents),
            "deduped_mismatches": self.deduped_mismatches,
            "clean": self.clean,
        }


def fsck(fs: FileSystem, node_pool, check_orphans: bool = True,
         reclaim: bool = False, orphan_grace: float = 3600.0,
         scrubber=None, heal: bool = False) -> FsckReport:
    """Meta-tree coherence plus the meta<->data reachability pass:
    datanode extents referenced by no inode AND no freelist entry are
    orphans (a leak the deferred-deletion design makes impossible for
    crashes after unlink, but disk swaps / partial rebuilds can still
    manufacture). `reclaim` deletes orphan extents from datanodes and
    funnels orphan inodes through rm_inode (whose extents then ride the
    freelist, so reclaim never races the free scan).

    `scrubber` (an fs.scrub.FsScrubber) dedups replica mismatches the
    continuous scrubber already healed — they'd otherwise double-report
    while the heal propagates. `heal=True` routes each remaining
    mismatch through scrub.heal_extent: the SAME sanctioned healer the
    scrubber and client read-repair use, never a second repair path."""
    report = FsckReport()
    referenced: set[tuple[int, int]] = set()
    seen_inos: set[int] = set()
    _walk(fs, node_pool, "/", mn.ROOT_INO, report, referenced, seen_inos)
    if scrubber is not None and report.replica_mismatches:
        healed = getattr(scrubber, "healed", set())
        kept = [m for m in report.replica_mismatches
                if (m[1]["dp_id"], m[1]["extent_id"]) not in healed]
        report.deduped_mismatches = (len(report.replica_mismatches)
                                     - len(kept))
        report.replica_mismatches = kept
    if heal and report.replica_mismatches:
        from .scrub import heal_extent

        still_bad = []
        for cpath, ek, fps in report.replica_mismatches:
            key = (ek["dp_id"], ek["extent_id"])
            try:
                heal_extent(fs, node_pool, key[0], key[1], source="fsck")
                report.healed_extents.append(key)
            except (FsError, rpc.RpcError, OSError):
                still_bad.append((cpath, ek, fps))
        report.replica_mismatches = still_bad
    # freed-but-not-yet-deleted extents are NOT orphans: the metanode
    # free scan owns them
    pending = fs.meta.freelist_all()
    report.pending_free = len(pending)
    for ent in pending.values():
        for ek in ent["extents"]:
            referenced.add((ek["dp_id"], ek["extent_id"]))
    _find_orphan_inodes(fs, seen_inos, referenced, report)
    if check_orphans:
        _find_orphan_extents(fs, node_pool, referenced, report)
    if reclaim:
        _reclaim(fs, node_pool, report, orphan_grace)
    return report


def _find_orphan_inodes(fs, seen_inos, referenced,
                        report: FsckReport) -> None:
    """Inodes no dentry reaches (e.g. a client that crashed between
    dentry_delete and inode_delete). Their extents are still accounted
    to them — marked referenced here so the extent pass doesn't call
    them orphans — but the space only comes back when rm_inode moves
    them to the freelist (reclaim does that)."""
    for ino in sorted(fs.meta.list_inos()):
        if ino != mn.ROOT_INO and ino not in seen_inos:
            report.orphan_inodes.append(ino)
            try:
                for ek in fs.meta.inode_get(ino)["extents"]:
                    referenced.add((ek["dp_id"], ek["extent_id"]))
            except (FsError, rpc.RpcError, OSError):
                pass


def _reclaim(fs, pool, report: FsckReport, orphan_grace: float) -> None:
    import time as _time

    now = _time.time()
    for ino in report.orphan_inodes:
        try:
            inode = fs.meta.inode_get(ino)
            # grace window: a client mid-create (mk_inode committed,
            # dentry_create not yet issued) looks exactly like an orphan;
            # only reclaim inodes old enough that no live create can
            # still be racing us
            if now - inode.get("ctime", 0.0) < orphan_grace:
                continue
            fs.meta.inode_delete(ino)  # extents -> freelist -> free scan
            report.reclaimed_inodes += 1
        except (FsError, rpc.RpcError, OSError):
            pass
    for dp_id, eid in report.orphan_extents:
        # same grace discipline as orphan inodes: an extent a client just
        # wrote but has not yet committed to the metanode (append_extents
        # in flight) looks exactly like an orphan — only reclaim extents
        # old enough that no live write can still be racing us
        if report.orphan_extent_ages.get((dp_id, eid), 0.0) < orphan_grace:
            continue
        try:
            dp = fs.data._dp_by_id(dp_id)
        except FsError:
            continue
        ok = True
        for addr in dp["replicas"]:
            try:
                pool.get(addr).call(
                    "delete_extent", {"dp_id": dp_id, "extent_id": eid})
            except (rpc.RpcError, OSError):
                ok = False
        if ok:
            report.reclaimed_extents += 1


def _walk(fs, pool, path, ino, report: FsckReport,
          referenced: set[tuple[int, int]],
          seen_inos: set[int] | None = None) -> None:
    if seen_inos is not None:
        seen_inos.add(ino)
    try:
        entries = fs.meta.readdir(ino)
    except FsError:
        return
    report.dirs += 1
    for name, child in sorted(entries.items()):
        cpath = f"{path.rstrip('/')}/{name}"
        try:
            inode = fs.meta.inode_get(child)
        except FsError:
            report.orphan_dentries.append((path, name))
            continue
        if seen_inos is not None:
            seen_inos.add(child)
        if inode["type"] == mn.DIR:
            _walk(fs, pool, cpath, child, report, referenced, seen_inos)
            continue
        report.files += 1
        for ek in inode["extents"]:
            referenced.add((ek["dp_id"], ek["extent_id"]))
            try:
                dp = fs.data._dp_by_id(ek["dp_id"])
            except FsError as e:
                report.dangling_extents.append((cpath, ek, str(e)))
                continue
            fps = {}
            for addr in dp["replicas"]:
                try:
                    meta, _ = pool.get(addr).call(
                        "extent_fingerprint",
                        {"dp_id": ek["dp_id"], "extent_id": ek["extent_id"]},
                    )
                    fps[addr] = (meta["size"], meta["crc"])
                except (rpc.RpcError, OSError) as e:
                    fps[addr] = ("unreachable", str(e)[:40])
            values = {v for v in fps.values() if v[0] != "unreachable"}
            if not values:
                report.dangling_extents.append((cpath, ek, "no replica readable"))
            elif len(values) > 1:
                report.replica_mismatches.append((cpath, ek, fps))
            else:
                report.bytes_checked += ek["size"]


def list_referenced_extents(fs) -> list[tuple[int, int]]:
    """Every (dp_id, extent_id) any inode references — the cheap subset
    of the fsck walk (no fingerprinting), reused as the fs-plane
    scrubber's work list."""
    out: list[tuple[int, int]] = []
    seen: set[tuple[int, int]] = set()
    for ino in sorted(fs.meta.list_inos()):
        try:
            inode = fs.meta.inode_get(ino)
        except (FsError, rpc.RpcError, OSError):
            continue
        for ek in inode.get("extents", []):
            key = (ek["dp_id"], ek["extent_id"])
            if key not in seen:
                seen.add(key)
                out.append(key)
    return out


def _find_orphan_extents(fs, pool, referenced, report: FsckReport) -> None:
    seen_dps = set()
    for dp in fs.data.dps:
        if dp["dp_id"] in seen_dps:
            continue
        seen_dps.add(dp["dp_id"])
        for addr in dp["replicas"]:
            try:
                meta, _ = pool.get(addr).call(
                    "list_extents", {"dp_id": dp["dp_id"], "want_ages": True})
            except (rpc.RpcError, OSError):
                continue
            ages = meta.get("ages", {})
            for eid in meta["extents"]:
                if (dp["dp_id"], eid) not in referenced:
                    key = (dp["dp_id"], eid)
                    if key not in report.orphan_extents:
                        report.orphan_extents.append(key)
                        report.orphan_extent_ages[key] = ages.get(
                            str(eid), 0.0)
            break
