"""fsck: filesystem consistency checker.

Role parity: tool/fsck — walks the volume's metadata tree, verifies
every extent key resolves to readable bit-identical replicas (CRC
fingerprint agreement), reports dangling extent keys, orphaned dentries
(pointing to missing inodes), and orphaned extents on datanodes that no
inode references (reclaimable leak candidates).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..utils import rpc
from . import metanode as mn
from .client import FileSystem, FsError


@dataclass
class FsckReport:
    files: int = 0
    dirs: int = 0
    bytes_checked: int = 0
    dangling_extents: list = field(default_factory=list)  # (path, ek, err)
    replica_mismatches: list = field(default_factory=list)  # (path, ek, fps)
    orphan_dentries: list = field(default_factory=list)  # (parent_path, name)
    orphan_extents: list = field(default_factory=list)  # (dp_id, extent_id)

    @property
    def clean(self) -> bool:
        return not (self.dangling_extents or self.replica_mismatches
                    or self.orphan_dentries or self.orphan_extents)

    def summary(self) -> dict:
        return {
            "files": self.files, "dirs": self.dirs,
            "bytes": self.bytes_checked,
            "dangling_extents": len(self.dangling_extents),
            "replica_mismatches": len(self.replica_mismatches),
            "orphan_dentries": len(self.orphan_dentries),
            "orphan_extents": len(self.orphan_extents),
            "clean": self.clean,
        }


def fsck(fs: FileSystem, node_pool, check_orphans: bool = True) -> FsckReport:
    report = FsckReport()
    referenced: set[tuple[int, int]] = set()
    _walk(fs, node_pool, "/", mn.ROOT_INO, report, referenced)
    if check_orphans:
        _find_orphan_extents(fs, node_pool, referenced, report)
    return report


def _walk(fs, pool, path, ino, report: FsckReport,
          referenced: set[tuple[int, int]]) -> None:
    try:
        entries = fs.meta.readdir(ino)
    except FsError:
        return
    report.dirs += 1
    for name, child in sorted(entries.items()):
        cpath = f"{path.rstrip('/')}/{name}"
        try:
            inode = fs.meta.inode_get(child)
        except FsError:
            report.orphan_dentries.append((path, name))
            continue
        if inode["type"] == mn.DIR:
            _walk(fs, pool, cpath, child, report, referenced)
            continue
        report.files += 1
        for ek in inode["extents"]:
            referenced.add((ek["dp_id"], ek["extent_id"]))
            try:
                dp = fs.data._dp_by_id(ek["dp_id"])
            except FsError as e:
                report.dangling_extents.append((cpath, ek, str(e)))
                continue
            fps = {}
            for addr in dp["replicas"]:
                try:
                    meta, _ = pool.get(addr).call(
                        "extent_fingerprint",
                        {"dp_id": ek["dp_id"], "extent_id": ek["extent_id"]},
                    )
                    fps[addr] = (meta["size"], meta["crc"])
                except rpc.RpcError as e:
                    fps[addr] = ("unreachable", str(e)[:40])
            values = {v for v in fps.values() if v[0] != "unreachable"}
            if not values:
                report.dangling_extents.append((cpath, ek, "no replica readable"))
            elif len(values) > 1:
                report.replica_mismatches.append((cpath, ek, fps))
            else:
                report.bytes_checked += ek["size"]


def _find_orphan_extents(fs, pool, referenced, report: FsckReport) -> None:
    seen_dps = set()
    for dp in fs.data.dps:
        if dp["dp_id"] in seen_dps:
            continue
        seen_dps.add(dp["dp_id"])
        for addr in dp["replicas"]:
            try:
                meta, _ = pool.get(addr).call("list_extents", {"dp_id": dp["dp_id"]})
            except rpc.RpcError:
                continue
            for eid in meta["extents"]:
                if (dp["dp_id"], eid) not in referenced:
                    key = (dp["dp_id"], eid)
                    if key not in report.orphan_extents:
                        report.orphan_extents.append(key)
            break
