"""Client SDK: meta routing + streaming extent IO + a filesystem facade.

Role parity: sdk/meta (MetaWrapper partition-range routing, meta/api.go),
sdk/data (ExtentClient/Streamer extent pipeline, stream/extent_client.go
:712 Write), and the FUSE client's VFS semantics (client/fs) as a
Python file API (open/read/write/mkdir/readdir/unlink/rename/stat) —
the gateway layers (FUSE wire protocol, S3) sit on top of this facade.
"""

from __future__ import annotations

import os
import threading
import time

import uuid

from ..utils import lockwitness
from ..utils import metrics as _metrics
from ..utils import packet as pkt
from ..utils import retry as retrylib
from ..utils import rpc
from ..utils import trace as tracelib
from . import metanode as mn


class FsError(Exception):
    def __init__(self, errno_: int, msg: str):
        super().__init__(msg)
        self.errno = errno_


# backoff while chasing a 453 RANGE_MOVED whose commit hasn't published
# a new owner yet: the freeze window is tens of milliseconds, so the
# chase stays well under the cap
_MOVED_BACKOFF = retrylib.RetryPolicy(base=0.03, cap=0.25, jitter=0.5,
                                      deadline=None)


# meta ops served on the binary packet plane (manager_op.go analog);
# everything else stays on HTTP
_META_PACKET_OPS = {"lookup": pkt.OP_META_LOOKUP,
                    "inode_get": pkt.OP_META_INODE_GET,
                    "readdir": pkt.OP_META_READDIR,
                    "submit": pkt.OP_META_SUBMIT,
                    "submit_batch": pkt.OP_META_SUBMIT_BATCH,
                    "dentry_count": pkt.OP_META_DENTRY_COUNT,
                    "alloc_ino": pkt.OP_META_ALLOC_INO,
                    "walk": pkt.OP_META_WALK}

# read ops additionally served by the metanode's native C++ read plane
# (runtime/src/metaserve.cc) when the view advertises meta_read_addrs
_META_READ_OPS = {"lookup", "inode_get", "readdir", "dentry_count", "walk"}


def _op_ids_stamped(method: str, payload: dict) -> bool:
    """Does every mutation in this meta packet call carry the op_id the
    metanode FSM dedups? Gates idempotent=True on the binary transport:
    a reconnect-resend is exactly-once ONLY through that window."""
    if method == "submit":
        return "op_id" in payload.get("record", {})
    if method == "submit_batch":
        return all("op_id" in r for r in payload.get("records") or ())
    if method == "alloc_ino":
        return "op_id" in payload
    return False


def _routing_ino(method: str, payload: dict) -> int | None:
    """The inode a meta call routes by — what a 453/EMOVED range
    redirect re-resolves against the refreshed partition table. None
    for calls with no single routing inode (alloc_ino rotates instead;
    submit_batch falls back to per-record submits in the fanout)."""
    if method == "submit":
        r = payload.get("record") or {}
        for k in ("parent", "src_parent", "ino"):
            v = r.get(k)
            if isinstance(v, int):
                return v
        return None
    if method in ("lookup", "readdir", "dentry_count"):
        v = payload.get("parent")
        return v if isinstance(v, int) else None
    if method in ("inode_get", "walk"):
        v = payload.get("ino")
        return v if isinstance(v, int) else None
    return None


def _moved_exc(e: Exception) -> bool:
    """Is this a live-range-migration routing bounce? Either the
    proposer-side fence (453 RANGE_MOVED) or the deterministic
    apply-side errno (EMOVED rides the 499 errno encoding)."""
    if isinstance(e, rpc.RpcError):
        if e.code == rpc.RANGE_MOVED:
            return True
        return (e.code == 499
                and e.message.startswith(f"errno={mn.EMOVED}:"))
    return isinstance(e, FsError) and e.errno == mn.EMOVED




class _FanoutWaiter:
    """One submit parked in the client's cross-partition coalescer.
    Doubles as the async handle submit_async returns."""

    __slots__ = ("record", "result", "exc", "done", "event", "ref",
                 "enq_t")

    def __init__(self, record: dict):
        self.record = record
        self.result = None
        self.exc: BaseException | None = None
        self.done = False
        self.event = threading.Event()
        # span handoff across the first-caller-drains boundary: the
        # drain span links back to every submitter through this ref
        self.ref = tracelib.capture()
        self.enq_t = time.perf_counter()

    def finish(self, result, exc: BaseException | None) -> None:
        self.result = result
        self.exc = exc
        self.done = True
        self.event.set()

    def wait(self, timeout: float = 30.0):
        if not self.event.wait(timeout) and not self.done:
            raise TimeoutError("fan-out submit not resolved in time")
        if self.exc is not None:
            raise self.exc
        return self.result


class SubmitFanout:
    """Client-side cross-partition submit coalescer (CUBEFS_META_FANOUT
    = K, 0 disables): mutations queue per metapartition, the first
    caller to find a partition idle drains its whole queue as ONE
    submit_batch RPC, and a K-wide gate keeps up to K partitions'
    batches in flight concurrently — the same first-caller-drains shape
    as codec/batcher.py, lifted to the wire. Under a multi-threaded
    workload the per-partition RPC tax amortizes across every queued
    record AND the partitions progress in parallel instead of one
    submit round-trip at a time. submit_async() + the lazy drain pool
    give a single-threaded caller the same K-partition concurrency."""

    def __init__(self, wrapper: "MetaWrapper", k: int):
        self.wrapper = wrapper
        self.k = k
        self._mu = lockwitness.make_lock("SubmitFanout._mu")
        self._queues: dict[int, list[_FanoutWaiter]] = {}
        self._busy: dict[int, int] = {}  # pid -> batches on the wire
        self._scheduled: dict[int, int] = {}  # pid -> drain tasks queued
        # per-partition window: with the mux transport (one shared
        # connection, req_id-demuxed) up to CUBEFS_PKT_WINDOW batches
        # per partition pipeline on that connection; the legacy serial
        # transport keeps the one-batch-per-partition discipline (each
        # extra batch would cost another pooled socket, not a stream)
        self.window = pkt.window_size() if pkt.mux_enabled() else 1
        self._gate = threading.Semaphore(max(k, k * self.window))
        self._pool = None  # lazy; only submit_async needs threads

    def submit(self, mp: dict, record: dict, timeout: float = 30.0):
        w = self._enqueue(mp, record)
        self._drain_if_idle(mp)
        return w.wait(timeout)

    def submit_async(self, mp: dict, record: dict) -> _FanoutWaiter:
        """Queue a mutation and return its handle; a drain-pool worker
        ships the partition's batch so ONE caller thread can keep K
        partitions in flight (call .wait() to collect). One drain task
        per partition burst: the drain re-spins while records keep
        arriving, so scheduling a task per record would only tax the
        pool."""
        pid = mp["pid"]
        with self._mu:
            self._queues.setdefault(pid, []).append(w := _FanoutWaiter(record))
            # one drain task per in-flight slot: up to `window` tasks
            # per partition keep that many batches pipelined on the mux
            # connection (legacy window=1 restores one-task-per-burst)
            cnt = self._scheduled.get(pid, 0)
            schedule = cnt < self.window
            if schedule:
                self._scheduled[pid] = cnt + 1
        if schedule:
            self._ensure_pool().submit(self._drain_scheduled, mp)
        return w

    def _drain_scheduled(self, mp: dict) -> None:
        pid = mp["pid"]
        with self._mu:
            n = self._scheduled.get(pid, 1) - 1
            if n:
                self._scheduled[pid] = n
            else:
                self._scheduled.pop(pid, None)
        self._drain_if_idle(mp)

    def close(self) -> None:
        """Stop the async drain pool (sync submits keep working)."""
        with self._mu:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)

    def _enqueue(self, mp: dict, record: dict) -> _FanoutWaiter:
        w = _FanoutWaiter(record)
        with self._mu:
            self._queues.setdefault(mp["pid"], []).append(w)
        return w

    def _ensure_pool(self):
        with self._mu:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._pool = ThreadPoolExecutor(
                    max_workers=max(self.k, min(32, self.k * self.window)),
                    thread_name_prefix="meta-fanout")
            return self._pool

    def _drain_if_idle(self, mp: dict) -> None:
        pid = mp["pid"]
        while True:
            with self._mu:
                batch = self._queues.get(pid)
                if not batch or self._busy.get(pid, 0) >= self.window:
                    return
                self._busy[pid] = self._busy.get(pid, 0) + 1
                self._queues[pid] = []
                inflight = sum(self._busy.values())
            try:
                _metrics.meta_fanout_inflight.observe(inflight)
                self._land(mp, batch)
            finally:
                with self._mu:
                    n = self._busy.get(pid, 1) - 1
                    if n:
                        self._busy[pid] = n
                    else:
                        self._busy.pop(pid, None)
            # records queued while we were on the wire ride the next
            # spin (unless another caller already claimed the drain)

    def _land(self, mp: dict, batch: list[_FanoutWaiter]) -> None:
        pid = mp["pid"]
        self._gate.acquire()  # at most K partitions' batches in flight
        t0 = time.perf_counter()
        tracelib.observe_stage("fanout_queue_wait", "meta.write",
                               [t0 - w.enq_t for w in batch])
        links = [w.ref for w in batch if w.ref is not None]
        cur = tracelib.current()
        if cur is None and links:
            # async drains run on pool threads with no context: adopt
            # the first submitter as parent so the drain still stitches
            first = links[0]
            span = tracelib.Span(
                "stage:fanout_drain", first.trace_id, first.span_id,
                sampled=first.sampled, path=first.path)
            for ref in links[1:]:
                span.link(ref)
        else:
            span = tracelib.start_span("stage:fanout_drain", links=links)
        span.set_tag("stage", "fanout_drain").set_tag("pid", pid)
        span.set_tag("ops", len(batch))
        with span:
            self._land_wire(mp, batch)
        tracelib.observe_stage("fanout_drain", span.path or "meta.write",
                               time.perf_counter() - t0)

    def _land_wire(self, mp: dict, batch: list[_FanoutWaiter]) -> None:
        pid = mp["pid"]
        try:
            if len(batch) == 1:
                # uncontended fast path: plain submit, no batch envelope
                meta, _ = self.wrapper._call_wire(
                    mp, "submit", {"record": batch[0].record})
                batch[0].finish(meta["result"], None)
                return
            try:
                meta, _ = self.wrapper._call_wire(
                    mp, "submit_batch",
                    {"records": [w.record for w in batch]})
            except (rpc.RpcError, FsError) as e:
                if not _moved_exc(e):
                    raise
                # batch-level range fence: the partition no longer owns
                # every record's inode, so the envelope can't land as
                # one unit — fall back to per-record submits, each
                # re-routed through the 453-chasing single-op path
                self._resubmit_moved(batch)
                return
            _metrics.meta_fanout_batches.inc(pid=pid)
            _metrics.meta_fanout_ops.inc(len(batch), pid=pid)
            for w, (result, err) in zip(batch, meta["results"]):
                if err is not None and err[0] == mn.EMOVED:
                    # apply-side fence caught a record already in the
                    # raft queue when the freeze landed: it bounced
                    # (never applied) — land it on the new owner
                    self._resubmit_moved([w])
                elif err is not None:
                    w.finish(None, FsError(err[0], err[1]))
                else:
                    w.finish(result, None)
        except BaseException as e:
            # batch-level failure (redirect exhausted, transport): every
            # still-unresolved waiter observes the same outcome
            for w in batch:
                if not w.done:
                    w.finish(None, e)
        finally:
            self._gate.release()

    def _resubmit_moved(self, waiters: list[_FanoutWaiter]) -> None:
        """Land records bounced by a live range migration one at a time,
        each routed by its own inode against a fresh partition table.
        The bounced attempt never applied (the fence is checked before
        — or deterministically instead of — the handler), so a fresh
        op_id on the new owner keeps exactly-once intact."""
        for w in waiters:
            try:
                ino = _routing_ino("submit", {"record": w.record})
                if ino is None:
                    raise FsError(
                        mn.EMOVED, "record has no routing inode")
                nmp = self.wrapper._mp_for(ino)
                meta, _ = self.wrapper._call_wire(
                    nmp, "submit", {"record": w.record})
                w.finish(meta["result"], None)
            except BaseException as e:  # noqa: BLE001 - per-record fate
                w.finish(None, e)


class MetaWrapper:
    """Routes inode/dentry ops to the owning meta partition by range."""

    def __init__(self, vol_view: dict, node_pool):
        self.mps = vol_view["mps"]
        self.nodes = node_pool
        self._rr = 0
        self._lock = lockwitness.make_lock("MetaWrapper._lock")
        # range-table watermark: every committed split/merge bumps it
        # exactly once on the master, so staleness is one compare
        self.mp_version = vol_view.get("mp_version", 0)
        # FileSystem wires this to the master's client_view when it
        # knows a master address; a range miss or 453 redirect re-pulls
        # the table through it before giving up
        self._refresh_cb = None
        self._refresh_ts = 0.0
        # binary meta plane (manager_op.go): metanodes that advertise a
        # packet address serve the hot ops over persistent TCP; HTTP
        # stays as the per-address fallback (same negative-cache
        # discipline as the data path)
        self.packet_addrs: dict[str, str] = dict(
            vol_view.get("meta_packet_addrs") or {})
        # native C++ read plane (fastest): read ops try it first, then
        # the Python packet plane, then HTTP — per-plane negative cache
        self.read_addrs: dict[str, str] = dict(
            vol_view.get("meta_read_addrs") or {})
        self._packet_clients: dict[str, object] = {}
        self._packet_down: dict[str, float] = {}  # plane addr -> retry ts
        # cross-partition fan-out coalescer: submits queue per partition
        # and ship as submit_batch RPCs, up to K partitions' batches in
        # flight (CUBEFS_META_FANOUT=0 restores per-op submits — A/B)
        try:
            k = int(os.environ.get("CUBEFS_META_FANOUT", "8") or "0")
        except ValueError:
            k = 8
        self.fanout: SubmitFanout | None = (
            SubmitFanout(self, k) if k > 0 else None)

    def _mp_for(self, ino: int) -> dict:
        for mp in self.mps:
            if mp["start"] <= ino < mp["end"]:
                return mp
        # a miss usually means the table is stale (a split/merge landed
        # since the last view pull): re-fetch ONCE before giving up —
        # a freshly migrated inode must not surface as ENOENT
        if self.refresh_view(force=True):
            for mp in self.mps:
                if mp["start"] <= ino < mp["end"]:
                    return mp
        raise FsError(mn.ENOENT, f"no meta partition owns inode {ino}")

    def refresh_view(self, force: bool = False) -> bool:
        """Re-pull the volume view from the master (when FileSystem
        wired a callback). Throttled so a burst of misses costs one
        master round-trip; returns True when a pull happened."""
        cb = self._refresh_cb
        if cb is None:
            return False
        now = time.monotonic()
        if not force and now - self._refresh_ts < 1.0:
            return False
        self._refresh_ts = now
        try:
            view = cb()
        except Exception:  # noqa: BLE001 - stale table, retried later
            return False
        if (view.get("mp_version", 0) != self.mp_version
                or len(view.get("mps") or []) != len(self.mps)):
            self.update_mps(view["mps"], view.get("mp_version", 0))
        # migrations can land partitions on nodes this client has never
        # talked to: adopt their advertised planes too
        for a, p in (view.get("meta_packet_addrs") or {}).items():
            self.packet_addrs.setdefault(a, p)
        for a, p in (view.get("meta_read_addrs") or {}).items():
            self.read_addrs.setdefault(a, p)
        return True

    REDIRECT = 421  # metanode "not leader" status

    def _call(self, mp: dict, method: str, args: dict):
        """Partition call router: submits detour through the cross-
        partition fan-out coalescer when it's enabled (CUBEFS_META_FANOUT
        > 0) so concurrent mutations against one partition share a
        submit_batch RPC; everything else goes straight to the wire."""
        if method in ("submit", "submit_batch"):
            # client hop of the meta write path: the root span a
            # stitched client -> metanode -> raft trace hangs from
            with tracelib.path_span("meta.write", f"client.{method}") as sp:
                sp.set_tag("svc", "client").set_tag("pid", mp["pid"])
                if method == "submit" and self.fanout is not None:
                    return ({"result": self.fanout.submit(
                        mp, args["record"])}, b"")
                return self._call_wire(mp, method, args)
        return self._call_wire(mp, method, args)

    def _call_wire(self, mp: dict, method: str, args: dict):
        """Call the partition via the shared replica/redirect loop.
        Mutations ("submit"/"submit_batch") carry unique op_ids so a
        retry after a lost response is exactly-once; metanode 4xx codes
        map back to errnos. Hot ops ride the binary packet plane when
        advertised."""
        addrs = list(mp.get("addrs") or [mp["addr"]])
        payload = {"pid": mp["pid"], **args}
        if method == "submit":
            payload["record"] = dict(payload["record"])
            payload["record"].setdefault("op_id", uuid.uuid4().hex)
        elif method == "submit_batch":
            # stamp ids BEFORE the replica loop: a transport retry must
            # re-present the same ids for the dedup window to catch
            payload["records"] = [dict(r) for r in payload["records"]]
            for r in payload["records"]:
                r.setdefault("op_id", uuid.uuid4().hex)
        for attempt in range(self.MOVED_RETRIES + 1):
            try:
                if ((self.packet_addrs or self.read_addrs)
                        and method in _META_PACKET_OPS):
                    # same replica/redirect loop, per-address call
                    # swapped for the packet transport (with per-address
                    # HTTP fallback inside _packet_one)
                    return rpc.call_replicas(
                        self.nodes, addrs, method, payload, deadline=10.0,
                        call_fn=lambda a: (
                            self._packet_one(a, method, payload), b""))
                return rpc.call_replicas(self.nodes, addrs, method,
                                         payload, deadline=10.0)
            except rpc.RpcError as e:
                if _moved_exc(e) and attempt < self.MOVED_RETRIES:
                    nmp = self._moved_reroute(method, payload, attempt)
                    if nmp is not None:
                        mp = nmp
                        addrs = list(mp.get("addrs") or [mp["addr"]])
                        payload["pid"] = mp["pid"]
                        continue
                if _moved_exc(e) and method == "alloc_ino":
                    # no routing inode to chase: surface the standard
                    # range-exhausted errno so inode_create rotates to
                    # the next partition (and picks up the new one on
                    # its next view refresh)
                    raise FsError(
                        28, f"mp {payload['pid']} inode range "
                            f"migrating: {e.message}") from None
                if e.code == 499 and e.message.startswith("errno="):
                    errno_ = int(
                        e.message[len("errno="):].split(":", 1)[0])
                    raise FsError(errno_, e.message) from None
                if (400 <= e.code < 500
                        and e.code not in (404, self.REDIRECT,
                                           rpc.GEO_REDIRECT,
                                           rpc.RANGE_MOVED)):
                    # 452/453 are ROUTING codes like 421, not errnos:
                    # if one still surfaces here the retries above are
                    # exhausted — bubble the transport error instead of
                    # minting a bogus errno-52/53
                    raise FsError(e.code - 400, e.message) from None
                raise

    # bounded chase of a migrating range: the freeze window is the
    # donor's delta drain + target replay + master commit — short, but
    # real; each retry re-pulls the table and backs off a little
    MOVED_RETRIES = 8

    def _moved_reroute(self, method: str, payload: dict,
                       attempt: int) -> dict | None:
        """Resolve a 453/EMOVED bounce against a fresh partition table.
        Returns the partition to retry against, or None when this call
        has no single routing inode (the caller falls back: alloc_ino
        rotates, submit_batch re-lands per record)."""
        ino = _routing_ino(method, payload)
        if ino is None:
            return None
        self.refresh_view(force=True)
        try:
            nmp = self._mp_for(ino)
        except FsError:
            return None
        if nmp["pid"] == payload["pid"]:
            # the commit hasn't published yet: wait out a slice of the
            # freeze window before re-presenting the same op_id
            r = _MOVED_BACKOFF.start(op="meta.moved_chase")
            r.attempt = attempt
            r.tick(reason="range-moved")
        return nmp

    def _packet_one(self, addr: str, method: str, payload: dict) -> dict:
        """One meta call to one node, trying the fastest advertised
        plane first: native C++ read plane (read ops only) -> Python
        packet plane -> HTTP. Packet rpc-status errors are re-raised as
        RpcError so every transport shares one redirect / errno
        semantics; protocol-level failures negative-cache that plane
        only and fall through to the next."""
        planes = []
        if method in _META_READ_OPS and addr in self.read_addrs:
            planes.append(self.read_addrs[addr])
        paddr = self.packet_addrs.get(addr)
        if paddr:
            planes.append(paddr)
        for plane in planes:
            if time.monotonic() < self._packet_down.get(plane, 0.0):
                continue
            cli = self._packet_clients.get(plane)
            if cli is None:
                cli = self._packet_clients[plane] = pkt.PacketClient(
                    plane, timeout=10.0, connect_timeout=2.0)
            op = _META_PACKET_OPS[method]
            idem = op in pkt.IDEMPOTENT_OPS
            if not idem:
                # mutations are retry-safe on this transport only
                # because _call_wire / inode_create stamped op_ids
                # BEFORE the replica loop — assert the contract here,
                # where the idempotent flag is minted
                assert _op_ids_stamped(method, payload), \
                    f"unstamped mutating meta op {method!r}"
                idem = True
            try:
                rargs, _ = cli.call(op, args=payload, idempotent=idem)
                return rargs
            except pkt.PacketError as e:
                if e.code is not None:
                    raise rpc.RpcError(e.code, e.message) from None
                # protocol-level failure (crc, desync): distrust this
                # plane for a while, fall through to the next
                self._packet_down[plane] = time.monotonic() + 30.0
            except (ConnectionError, OSError, TimeoutError):
                self._packet_down[plane] = time.monotonic() + 30.0
        meta, _ = self.nodes.get(addr).call(method, payload)
        return meta

    # ---- inode/dentry API (reference sdk/meta/api.go shapes) ----
    def inode_create(self, typ: str, mode: int = 0o644, target=None,
                     quota_ids: list[int] | None = None) -> dict:
        # rotate across a SNAPSHOT of the partition table from a
        # captured offset, so every partition is tried exactly once even
        # when concurrent creates advance the shared cursor; a
        # range-exhausted mp (ENOSPC from alloc_ino) is skipped — the
        # master's split sweep appends fresh partitions, which a view
        # refresh picks up
        last: FsError | None = None
        for sweep in range(2):
            mps = list(self.mps)
            with self._lock:
                offset = self._rr
                self._rr += 1
            for step in range(len(mps)):
                mp = mps[(offset + step) % len(mps)]
                try:
                    ino = self._call(mp, "alloc_ino",
                                     {"op_id": uuid.uuid4().hex})[0]["ino"]
                except FsError as e:
                    if e.errno == 28:  # inode range exhausted/migrating
                        last = e
                        continue
                    raise
                rec = {"op": "mk_inode", "ino": ino, "type": typ,
                       "mode": mode, "ts": time.time()}
                if target is not None:
                    rec["target"] = target
                if quota_ids:
                    rec["quota_ids"] = list(quota_ids)
                self._call(mp, "submit", {"record": rec})
                return self.inode_get(ino)
            # every partition we KNOW is exhausted — but a split/merge
            # may have republished the table since our last view pull;
            # re-fetch once and re-rotate before giving up
            if sweep or not self.refresh_view(force=True):
                break
        raise last if last else FsError(28, "no meta partition has free inodes")

    def update_mps(self, mps: list[dict],
                   version: int | None = None) -> None:
        """Adopt a refreshed partition table (e.g. after an mp split)."""
        self.mps = mps
        if version is not None:
            self.mp_version = version

    def walk(self, ino: int, names: list[str],
             stat: bool = False) -> tuple[int, dict | None]:
        """Server-side path walk: ONE round trip consumes as many
        components as the target node leader-serves (vs one lookup RT
        per component). Partial results resume at the partition owning
        the returned ino; a no-progress partial (leadership mid-move)
        degrades to a single classic lookup so the loop always
        terminates."""
        names = list(names)
        out: dict = {}
        while names:
            mp = self._mp_for(ino)
            out = self._call(mp, "walk",
                             {"ino": ino, "names": names,
                              "stat": stat})[0]
            remaining = out["remaining"]
            if not remaining:
                ino = out["ino"]
                break
            if out["ino"] == ino and len(remaining) == len(names):
                # no progress (leadership mid-move): one classic lookup
                # guarantees forward motion
                ino = self.lookup(ino, names[0])
                names = names[1:]
            else:
                ino, names = out["ino"], remaining
            out = {}
        inode = out.get("inode")
        if stat and inode is None:
            inode = self.inode_get(ino)
        return ino, inode

    def mknod(self, parent: int, name: str, typ: str, mode: int = 0o644,
              target=None, quota_ids: list[int] | None = None) -> int:
        """Compound create: inode + dentry in ONE commit against the
        parent's partition (locality-preserving placement). Falls back
        to the classic alloc-elsewhere + two commits when the parent's
        inode range is exhausted."""
        rec = {"op": "mknod", "parent": parent, "name": name, "type": typ,
               "mode": mode, "ts": time.time()}
        if target is not None:
            rec["target"] = target
        if quota_ids:
            rec["quota_ids"] = list(quota_ids)
        try:
            mp = self._mp_for(parent)
            return self._call(mp, "submit",
                              {"record": rec})[0]["result"]["ino"]
        except FsError as e:
            if e.errno != 28:
                raise
        inode = self.inode_create(typ, mode, target=target,
                                  quota_ids=quota_ids)
        try:
            self.dentry_create(parent, name, inode["ino"])
        except FsError:
            self.inode_delete(inode["ino"])
            raise
        return inode["ino"]

    def unlink2(self, parent: int, name: str) -> int:
        """Compound unlink (dentry + inode, one commit). Raises
        FsError(18) when the child inode is in another partition — the
        caller runs the classic two-op path."""
        mp = self._mp_for(parent)
        rec = {"op": "unlink2", "parent": parent, "name": name,
               "ts": time.time()}
        return self._call(mp, "submit", {"record": rec})[0]["result"]["ino"]

    def inode_get(self, ino: int) -> dict:
        mp = self._mp_for(ino)
        return self._call(mp, "inode_get", {"ino": ino})[0]["inode"]

    def inode_delete(self, ino: int) -> list:
        mp = self._mp_for(ino)
        res = self._call(mp, "submit", {"record": {
            "op": "rm_inode", "ino": ino, "ts": time.time()}})
        return res[0]["result"].get("extents", [])

    # ---- hardlinks (metanode CreateLink role) ----
    def inc_nlink(self, ino: int) -> int:
        mp = self._mp_for(ino)
        res = self._call(mp, "submit", {"record": {
            "op": "inc_nlink", "ino": ino, "ts": time.time()}})
        return res[0]["result"]["nlink"]

    def dec_nlink(self, ino: int) -> bool:
        """Drop one link; True when the inode was removed (last link)."""
        mp = self._mp_for(ino)
        res = self._call(mp, "submit", {"record": {
            "op": "dec_nlink", "ino": ino, "ts": time.time()}})
        return res[0]["result"]["removed"]

    def dentry_create(self, parent: int, name: str, ino: int) -> None:
        mp = self._mp_for(parent)
        self._call(mp, "submit", {"record": {
            "op": "mk_dentry", "parent": parent, "name": name, "ino": ino}})

    def dentry_delete(self, parent: int, name: str) -> int:
        mp = self._mp_for(parent)
        res = self._call(mp, "submit", {"record": {
            "op": "rm_dentry", "parent": parent, "name": name}})
        return res[0]["result"]["ino"]

    def lookup(self, parent: int, name: str) -> int:
        mp = self._mp_for(parent)
        return self._call(mp, "lookup", {"parent": parent, "name": name})[0]["ino"]

    def readdir(self, parent: int) -> dict[str, int]:
        mp = self._mp_for(parent)
        return self._call(mp, "readdir", {"parent": parent})[0]["entries"]

    def dentry_count(self, parent: int) -> int:
        mp = self._mp_for(parent)
        return self._call(mp, "dentry_count", {"parent": parent})[0]["count"]

    def freelist_all(self) -> dict[str, dict]:
        """Pending deferred deletions across all partitions (fsck input:
        these extents are freed-but-not-yet-deleted, not orphans)."""
        out: dict[str, dict] = {}
        for mp in self.mps:
            try:
                fl = self._call(mp, "freelist", {})[0]["freelist"]
            except (FsError, rpc.RpcError):
                continue
            for k, v in fl.items():
                out[f"{mp['pid']}:{k}"] = v
        return out

    def list_inos(self) -> set[int]:
        """Every inode id the partitions hold (fsck's orphan-inode pass)."""
        inos: set[int] = set()
        for mp in self.mps:
            try:
                inos.update(self._call(mp, "list_inos", {})[0]["inos"])
            except (FsError, rpc.RpcError):
                pass
        return inos

    def append_extents(self, ino: int, extents: list[dict], size: int) -> None:
        mp = self._mp_for(ino)
        self._call(mp, "submit", {"record": {
            "op": "append_extents", "ino": ino, "extents": extents,
            "size": size, "ts": time.time()}})

    def set_attr(self, ino: int, **attrs) -> None:
        mp = self._mp_for(ino)
        self._call(mp, "submit", {"record": {
            "op": "set_attr", "ino": ino, **attrs, "ts": time.time()}})

    def set_xattr(self, ino: int, key: str, value) -> None:
        mp = self._mp_for(ino)
        self._call(mp, "submit", {"record": {
            "op": "set_xattr", "ino": ino, "key": key, "value": value}})

    def truncate(self, ino: int, size: int = 0) -> list:
        mp = self._mp_for(ino)
        res = self._call(mp, "submit", {"record": {
            "op": "truncate", "ino": ino, "size": size, "ts": time.time()}})
        return res[0]["result"].get("extents", [])

    # ---- cold-tier migration FSM (fs/tiering.py is the sole driver;
    # each step is one idempotent op_id-stamped submit, so WAL replay
    # and transport retries land exactly once) ----
    def tiering_prepare(self, ino: int) -> dict:
        res = self._call(self._mp_for(ino), "submit", {"record": {
            "op": "tiering_prepare", "ino": ino, "ts": time.time()}})
        return res[0]["result"]

    def tiering_blob_written(self, ino: int, gen: int, location: dict) -> dict:
        res = self._call(self._mp_for(ino), "submit", {"record": {
            "op": "tiering_blob_written", "ino": ino, "gen": gen,
            "location": location, "ts": time.time()}})
        return res[0]["result"]

    def tiering_commit(self, ino: int, gen: int) -> dict:
        res = self._call(self._mp_for(ino), "submit", {"record": {
            "op": "tiering_commit", "ino": ino, "gen": gen,
            "ts": time.time()}})
        return res[0]["result"]

    def tiering_finish(self, ino: int) -> dict:
        res = self._call(self._mp_for(ino), "submit", {"record": {
            "op": "tiering_finish", "ino": ino, "ts": time.time()}})
        return res[0]["result"]

    def tiering_abort(self, ino: int) -> dict:
        res = self._call(self._mp_for(ino), "submit", {"record": {
            "op": "tiering_abort", "ino": ino, "ts": time.time()}})
        return res[0]["result"]

    def untier_commit(self, ino: int, gen: int, extents: list[dict]) -> dict:
        res = self._call(self._mp_for(ino), "submit", {"record": {
            "op": "untier_commit", "ino": ino, "gen": gen,
            "extents": extents, "ts": time.time()}})
        return res[0]["result"]

    def blob_freelist_all(self) -> dict[str, dict]:
        """Pending deferred blob deletions across all partitions, keyed
        `pid:key` (reaper input; fsck counts these as referenced)."""
        out: dict[str, dict] = {}
        for mp in self.mps:
            try:
                fl = self._call(
                    mp, "blob_freelist", {})[0]["blob_freelist"]
            except (FsError, rpc.RpcError):
                continue
            for k, v in fl.items():
                out[f"{mp['pid']}:{k}"] = v
        return out

    def blob_free_done(self, pid: int, key: str) -> None:
        for mp in self.mps:
            if mp["pid"] == pid:
                self._call(mp, "submit", {"record": {
                    "op": "blob_free_done", "key": key}})
                return
        raise FsError(mn.ENOENT, f"no meta partition {pid}")

    def blob_reconcile_enqueue(self, location: dict) -> None:
        """Inventory-reconciliation sink: a blob-plane location that no
        inode references rides the blob_freelist so the existing reaper
        deletes it (satellite: closes the put->blob_written leak
        window)."""
        self._call(self.mps[0], "submit", {"record": {
            "op": "blob_reconcile_enqueue", "location": location,
            "ts": time.time()}})

    # ---- rename (atomic; metanode/transaction.go analog) ----
    def rename_local(self, src_parent: int, src_name: str,
                     dst_parent: int, dst_name: str, ino: int,
                     victim: int | None = None,
                     noreplace: bool = False) -> int | None:
        """Same-partition atomic rename; `victim` is the dst inode the
        caller validated (re-asserted inside the apply); `noreplace`
        makes an existing target EEXIST atomically. Returns the
        replaced victim inode (or None)."""
        mp = self._mp_for(src_parent)
        res = self._call(mp, "submit", {"record": {
            "op": "rename_local", "src_parent": src_parent,
            "src_name": src_name, "dst_parent": dst_parent,
            "dst_name": dst_name, "ino": ino, "victim": victim,
            "noreplace": noreplace}})
        return res[0]["result"].get("victim")

    def _mp_ref(self, mp: dict) -> dict:
        return {"pid": mp["pid"],
                "addrs": list(mp.get("addrs") or [mp["addr"]])}

    def rename_tx(self, src_parent: int, src_name: str,
                  dst_parent: int, dst_name: str, ino: int,
                  victim: int | None = None,
                  victim_is_dir: bool = False,
                  noreplace: bool = False) -> int | None:
        """Cross-partition rename as a two-phase transaction. The DST
        partition is the coordinator: it is prepared and committed FIRST,
        so its durable commit decision is what an expired participant
        consults (roll forward) — no crash point leaves the file linked
        twice or lost. The coordinator's prepare lists the participants,
        so its scanner pushes the decision and only drops the commit
        record once everyone has resolved. A dir victim gets a
        guard_empty_dir participant on its own partition, locking out
        new children while the tx is in flight. Returns the replaced
        victim inode (or None)."""
        src_mp = self._mp_for(src_parent)
        dst_mp = self._mp_for(dst_parent)
        tx_id = uuid.uuid4().hex
        coord = self._mp_ref(dst_mp)
        ts = time.time()
        # group sub-ops by owning partition (src/dst/guard may coincide)
        by_pid: dict[int, tuple[dict, list[dict]]] = {}

        def add_op(mp_, op_):
            by_pid.setdefault(mp_["pid"], (mp_, []))[1].append(op_)

        add_op(dst_mp, {"kind": "link", "parent": dst_parent,
                        "name": dst_name, "ino": ino, "victim": victim,
                        "noreplace": noreplace})
        add_op(src_mp, {"kind": "rm", "parent": src_parent,
                        "name": src_name, "ino": ino})
        if victim is not None and victim_is_dir:
            # lock the victim dir on ITS partition so no child can appear
            # between the client's emptiness check and the commit
            add_op(self._mp_for(victim),
                   {"kind": "guard_empty_dir", "parent": victim, "name": ""})
        dst_ops = by_pid.pop(dst_mp["pid"])[1]
        part_preps = list(by_pid.values())
        parts = [self._mp_ref(mp_) for mp_, _ in part_preps]
        self._call(dst_mp, "submit", {"record": {
            "op": "tx_prepare", "tx_id": tx_id, "coord": coord,
            "parts": parts, "ts": ts, "ops": dst_ops}})
        prepared: list[dict] = []
        try:
            for mp_, ops_ in part_preps:
                self._call(mp_, "submit", {"record": {
                    "op": "tx_prepare", "tx_id": tx_id, "coord": coord,
                    "ts": ts, "ops": ops_}})
                prepared.append(mp_)
        except FsError:
            for mp_ in [dst_mp] + prepared:
                try:
                    self._call(mp_, "submit", {"record": {
                        "op": "tx_abort", "tx_id": tx_id}})
                except FsError:
                    pass
            raise
        res = self._call(dst_mp, "submit", {"record": {
            "op": "tx_commit", "tx_id": tx_id, "ts": ts}})
        for mp_, _ in part_preps:
            try:
                self._call(mp_, "submit", {"record": {
                    "op": "tx_commit", "tx_id": tx_id, "ts": ts}})
            except (FsError, rpc.RpcError):
                # the coordinator's commit IS the outcome: a transiently
                # unreachable participant gets the decision pushed by the
                # coordinator's scanner; reporting failure here would be
                # wrong (and would skip the victim cleanup)
                pass
        victims = res[0]["result"].get("victims") or []
        return victims[0] if victims else None

    # ---- the cluster-wide dir-rename mutex (s_vfs_rename_mutex analog):
    # cross-directory DIR renames serialize on one named lock on the
    # root-owning partition, so two concurrent dir moves cannot weave a
    # detached cycle past each other's ancestry checks. Held as a
    # prepared tx: a crashed holder is auto-released by TX_TTL expiry.
    def lock_dir_rename(self, timeout: float = 10.0) -> tuple[str, float]:
        """Returns (tx_id, ts): ts is the stamp the TTL counts from —
        holders must derive their work deadline from it, not from the
        (later) moment the grant RPC returned."""
        mp = self._mp_for(1)
        tx_id = uuid.uuid4().hex
        r = rpc.FAILOVER_POLICY.start(op="dir_rename_lock", deadline=timeout)
        while True:
            ts = time.time()
            try:
                self._call(mp, "submit", {"record": {
                    "op": "tx_prepare", "tx_id": tx_id, "ts": ts,
                    "coord": self._mp_ref(mp),
                    "ops": [{"kind": "mutex", "parent": 0,
                             "name": "__dir_rename__"}]}})
                return tx_id, ts
            except FsError as e:
                # EBUSY: another rename holds the mutex; back off within
                # the timeout instead of spinning at a fixed 50 ms
                if e.errno != 16 or not r.tick(reason="mutex-busy"):
                    raise

    def unlock_dir_rename(self, tx_id: str) -> None:
        self._call(self._mp_for(1), "submit", {"record": {
            "op": "tx_abort", "tx_id": tx_id}})


class ExtentClient:
    """Streaming extent IO against data partitions.

    Write: route to a dp leader, allocate/reuse an extent, chain-write,
    then commit the extent key to the metanode (write-then-commit order,
    like the Streamer's flush)."""

    PACKET = 128 << 10  # write packet granularity
    EXTENT_CAP = 128 << 20  # roll to a fresh extent past this (max extent)
    TINY_THRESHOLD = 4 << 10  # small writes pack into shared tiny extents
    TINY_EXTENT_CAP = 8 << 20

    def __init__(self, vol_view: dict, node_pool):
        self.dps = vol_view["dps"]
        self.nodes = node_pool
        # binary packet plane per datanode (proto/packet.go transport):
        # replicas that advertise one serve reads over persistent TCP
        self.packet_addrs: dict[str, str] = dict(
            vol_view.get("packet_addrs") or {})
        # native C++ read plane (dataserve.cc): reads try it first
        self.read_addrs: dict[str, str] = dict(
            vol_view.get("data_read_addrs") or {})
        self._packet_clients: dict[str, object] = {}
        self._packet_down: dict[str, float] = {}  # plane addr -> retry ts
        self._rr = 0
        self._lock = lockwitness.make_lock("ExtentClient._lock")
        # per-inode open extent: ino -> (dp, extent_id, next_offset)
        self._streams: dict[int, tuple[dict, int, int]] = {}
        # shared tiny-extent stream (datanode storage_tinyfile role):
        # many small files append into ONE extent, so a million 1KB files
        # don't cost a million extents. _tiny_lock guards offset
        # RESERVATION only (the stream is shared across inodes); the
        # writes themselves run concurrently on disjoint ranges.
        self._tiny: tuple[dict, int, int] | None = None
        self._tiny_lock = lockwitness.make_lock("ExtentClient._tiny_lock")
        self._latency: dict[str, float] = {}  # addr -> EWMA seconds

    def _pick_dp(self) -> dict:
        with self._lock:
            dp = self.dps[self._rr % len(self.dps)]
            self._rr += 1
            return dp

    def write(self, meta: MetaWrapper, ino: int, file_offset: int,
              data: bytes) -> None:
        """Write through the inode's open extent, rolling to fresh
        extents at the cap — a single huge write spans several extent
        keys, like the streamer's packet pipeline."""
        if not data:
            # empty write: no extent to allocate, but the mtime/gen stamp
            # must still land (an empty overwrite fences a tiering commit
            # like any other data mutation)
            meta.append_extents(ino, [], size=file_offset)
            return
        if len(data) <= self.TINY_THRESHOLD and file_offset == 0:
            self._write_tiny(meta, ino, data)
            return
        extent_keys = self.write_extents(ino, file_offset, data)
        meta.append_extents(ino, extent_keys, size=file_offset + len(data))

    def write_extents(self, ino: int, file_offset: int,
                      data: bytes) -> list[dict]:
        """Write payload bytes to datanode extents WITHOUT registering
        them on the metanode — the caller owns the commit. write() pairs
        this with append_extents; the tiering engine's un-tier path
        instead lands the keys through one fenced untier_commit apply,
        so a racing write can atomically reject the whole re-heat."""
        extent_keys: list[dict] = []
        done = 0
        while done < len(data):
            with self._lock:
                stream = self._streams.get(ino)
            if stream is not None and stream[2] >= self.EXTENT_CAP:
                stream = None  # extent full: roll to a new one
            if stream is None:
                dp = self._pick_dp()
                leader = self.nodes.get(dp["leader"])
                eid = leader.call("alloc_extent",
                                  {"dp_id": dp["dp_id"],
                                   "op_id": uuid.uuid4().hex})[0]["extent_id"]
                ext_off = 0
            else:
                dp, eid, ext_off = stream
                leader = self.nodes.get(dp["leader"])
            seg = min(len(data) - done, self.EXTENT_CAP - ext_off)
            self._write_pieces(dp, eid, ext_off, memoryview(data), done, seg)
            extent_keys.append({
                "dp_id": dp["dp_id"], "extent_id": eid, "ext_offset": ext_off,
                "file_offset": file_offset + done, "size": seg,
            })
            with self._lock:
                self._streams[ino] = (dp, eid, ext_off + seg)
            done += seg
        return extent_keys

    def _write_tiny(self, meta: MetaWrapper, ino: int, data: bytes) -> None:
        """Append a whole small file into the shared tiny extent; the
        extent key is flagged tiny so per-file GC skips it (space comes
        back via scrub-compaction, the punch-hole analog).

        Scope: the tiny stream is per-ExtentClient, so packing pays off
        for long-lived clients (gateway/FUSE/SDK daemons); one-shot CLI
        invocations still get one extent per file. Datanode-side shared
        tiny extents and tiny-extent space compaction (punch-hole) are
        future work — fsck reports wholly-dead tiny extents meanwhile."""
        # reserve the (dp, extent, offset) range under the lock; the
        # networked write + meta commit — AND the alloc_extent RPC when
        # the shared extent rolls — run OUTSIDE it, so one slow datanode
        # round-trip never stalls every concurrent small-file write
        while True:
            with self._tiny_lock:
                tiny = self._tiny
                if (tiny is not None
                        and tiny[2] + len(data) <= self.TINY_EXTENT_CAP):
                    dp, eid, off = tiny
                    self._tiny = (dp, eid, off + len(data))
                    break
            # shared extent absent/full: allocate a replacement without
            # holding the lock, then race to install it. A loser's spare
            # extent stays empty (fsck reports it wholly dead); the
            # loser re-checks and packs into the winner's extent.
            dp = self._pick_dp()
            eid = self.nodes.get(dp["leader"]).call(
                "alloc_extent", {"dp_id": dp["dp_id"],
                                 "op_id": uuid.uuid4().hex})[0]["extent_id"]
            with self._tiny_lock:
                cur = self._tiny
                if cur is None or cur[2] + len(data) > self.TINY_EXTENT_CAP:
                    off = 0
                    self._tiny = (dp, eid, len(data))
                    break
        self._leader_write(dp, eid, off, data)
        meta.append_extents(
            ino,
            [{"dp_id": dp["dp_id"], "extent_id": eid, "ext_offset": off,
              "file_offset": 0, "size": len(data), "tiny": True}],
            size=len(data),
        )

    def close_stream(self, ino: int) -> None:
        with self._lock:
            self._streams.pop(ino, None)

    def _dp_by_id(self, dp_id: int) -> dict:
        for dp in self.dps:
            if dp["dp_id"] == dp_id:
                return dp
        raise FsError(5, f"unknown dp {dp_id}")

    def read(self, inode: dict, offset: int, length: int) -> bytes:
        """Assemble file bytes from the extent list (later keys win)."""
        size = inode["size"]
        if offset >= size:
            return b""
        length = min(length, size - offset)
        out = bytearray(length)
        for ek in inode["extents"]:
            lo = max(offset, ek["file_offset"])
            hi = min(offset + length, ek["file_offset"] + ek["size"])
            if lo >= hi:
                continue
            dp = self._dp_by_id(ek["dp_id"])
            data = self._read_replicated(
                dp, ek["extent_id"], ek["ext_offset"] + (lo - ek["file_offset"]),
                hi - lo,
            )
            out[lo - offset : hi - offset] = data
        return bytes(out)

    def _read_replicated(self, dp: dict, eid: int, off: int, ln: int) -> bytes:
        """Read from the historically-fastest replica first (k-faster
        selector role: an EWMA of per-address latency orders candidates;
        failures and SHORT reads fall through to the next replica).

        Unmeasured replicas get the median of the measured ones as a
        neutral prior (never 0 — a fresh, possibly mid-repair replica
        must not outrank a known-fast one), with the leader as the
        tiebreak."""
        known = sorted(self._latency.get(a) for a in dp["replicas"]
                       if a in self._latency)
        # unmeasured replicas: just under the median — they never outrank
        # a known-fast replica by much, but do outrank a known-slow one
        prior = known[len(known) // 2] * 0.99 if known else 0.0
        order = sorted(
            dp["replicas"],
            key=lambda a: (self._latency.get(a, prior),
                           0 if a == dp["leader"] else 1),
        )
        last_err = None
        crc_failed: list[str] = []  # replicas that served a CRC 409
        for addr in order:
            t0 = time.monotonic()
            try:
                data = self._read_one(addr, dp["dp_id"], eid, off, ln)
                if len(data) != ln:
                    # lagging / mid-repair replica: treat like a failure,
                    # a short read silently corrupts the assembled file
                    raise rpc.RpcError(
                        409, f"short read {len(data)} != {ln} from {addr}"
                    )
            except rpc.RpcError as e:
                last_err = e
                # a 409 that is NOT a short read is a CRC/integrity
                # refusal: remember the replica for read-repair once a
                # healthy copy answers (short reads are laggards, not
                # rot — repairing them would be a false repair)
                if e.code == 409 and "short read" not in str(e):
                    crc_failed.append(addr)
                # heavy penalty so failed replicas sort last for a while
                self._latency[addr] = self._latency.get(addr, 0.0) * 0.7 + 0.3 * 5.0
                continue
            dt = time.monotonic() - t0
            self._latency[addr] = self._latency.get(addr, dt) * 0.7 + 0.3 * dt
            if crc_failed:
                self._read_repair(dp, eid, addr, crc_failed)
            return data
        raise FsError(5, f"all replicas failed for dp {dp['dp_id']}: {last_err}")

    def _read_repair(self, dp: dict, eid: int, healthy_addr: str,
                     bad_addrs: list[str]) -> None:
        """Transparent fs-plane read-repair: the replica that refused a
        read with a CRC 409 gets rewritten in place from the replica
        that just served the bytes, through the ONE sanctioned healer
        (DataNode.sync_extent_from — same path scrub and fsck --heal
        use). Synchronous and best-effort: the client already has good
        bytes, so a repair failure only counts a metric. Door:
        CUBEFS_VERIFY_READS=0 turns repair off (detection still 409s;
        the door is FSM-digest-identical because repairs never write
        FSM records)."""
        if os.environ.get("CUBEFS_VERIFY_READS", "1") == "0":
            return
        for bad in bad_addrs:
            with tracelib.path_span("fs.read", "integrity.read_repair") as sp:
                sp.set_tag("dp_id", dp["dp_id"])
                sp.set_tag("extent_id", eid)
                sp.set_tag("bad", bad)
                try:
                    self.nodes.get(bad).call(
                        "sync_extent_from",
                        {"dp_id": dp["dp_id"], "extent_id": eid,
                         "src_addr": healthy_addr, "source": "read"},
                        timeout=30.0)
                except (rpc.RpcError, OSError):
                    _metrics.integrity_repair_failures.inc(plane="fs")

    def _write_pieces(self, dp: dict, eid: int, ext_off: int,
                      data: memoryview, done: int, seg: int) -> None:
        """Ship one extent segment as PACKET-granularity pieces. On the
        mux transport up to CUBEFS_PKT_WINDOW pieces pipeline in flight
        on the shared connection (the streamer's packet-pipeline shape);
        the legacy/RPC paths keep the serial piece loop. Pieces land at
        disjoint absolute offsets, so in-window reordering is harmless —
        the datanode's per-extent lock orders overlapping writes."""
        cli, paddr = self._write_plane(dp)
        if cli is None or not cli.mux:
            written = 0
            while written < seg:
                piece = data[done + written
                             : done + min(written + self.PACKET, seg)]
                self._leader_write(dp, eid, ext_off + written, piece)
                written += len(piece)
            return
        from ..utils import packet as pkt

        window = pkt.window_size()
        futs: list[tuple] = []  # (future, piece offset)
        written = 0
        try:
            while written < seg:
                piece = data[done + written
                             : done + min(written + self.PACKET, seg)]
                off = ext_off + written
                # absolute bytes at a fixed (extent, offset): a
                # reconnect-resend rewrites the identical range (the
                # rpc_allowlist 'write_replica' justification family)
                fut = cli.call_async(
                    pkt.OP_WRITE, partition=dp["dp_id"], extent=eid,
                    offset=off, payload=piece, idempotent=True)
                futs.append((fut, off))
                written += len(piece)
                if len(futs) >= window:
                    self._collect_write(futs.pop(0), dp, paddr)
            while futs:
                self._collect_write(futs.pop(0), dp, paddr)
        finally:
            # a failed window must not leave unreaped in-flight pieces
            for fut, _ in futs:
                try:
                    fut.result(cli.timeout)
                except Exception:
                    pass

    def _collect_write(self, ent: tuple, dp: dict, paddr: str) -> None:
        """Resolve one pipelined write piece, mapping failures exactly
        like the serial `_leader_write` packet leg."""
        from ..utils import packet as pkt

        fut, off = ent
        addr = dp["leader"]
        try:
            fut.result()
        except pkt.PacketError as e:
            raise rpc.RpcError(500, f"packet write: {e}") from None
        except TimeoutError:
            self._packet_down[paddr] = time.monotonic() + 30.0
            raise rpc.RpcError(
                504, f"packet write to {addr} timed out; "
                     f"possibly still executing") from None
        except (ConnectionError, OSError) as e:
            # unlike the serial leg there is no same-call RPC fallback
            # mid-window (earlier pieces already rode the packet plane);
            # negative-cache the plane and surface — the caller owns
            # the retry, and its next attempt takes the RPC path
            self._packet_down[paddr] = time.monotonic() + 30.0
            raise rpc.RpcError(503, f"packet write: {e}") from None

    def _write_plane(self, dp: dict):
        """The leader's usable packet-plane client, or (None, None) when
        none is advertised / the plane is in negative-cache cooldown."""
        paddr = self.packet_addrs.get(dp["leader"])
        if not paddr or time.monotonic() < self._packet_down.get(paddr, 0.0):
            return None, None
        from ..utils import packet as pkt

        cli = self._packet_clients.get(paddr)
        if cli is None:
            cli = self._packet_clients[paddr] = pkt.PacketClient(
                paddr, timeout=30.0, connect_timeout=2.0)
        return cli, paddr

    def _leader_write(self, dp: dict, eid: int, off: int,
                      data: bytes) -> None:
        """One write leg to the designated leader: the binary packet
        plane when advertised (same negative-cache discipline as reads),
        RPC otherwise. Server-side semantics are identical — both
        transports enter DataNode.write()."""
        addr = dp["leader"]
        paddr = self.packet_addrs.get(addr)
        # keyed by PLANE addr, shared with _read_one: a packet plane a
        # read discovered dead suppresses writes too (and one client
        # pool serves both directions)
        if paddr and time.monotonic() >= self._packet_down.get(paddr, 0.0):
            from ..utils import packet as pkt

            cli = self._packet_clients.get(paddr)
            if cli is None:
                cli = self._packet_clients[paddr] = pkt.PacketClient(
                    paddr, timeout=30.0, connect_timeout=2.0)
            try:
                # absolute bytes at a fixed (extent, offset): replay-safe
                cli.call(pkt.OP_WRITE, partition=dp["dp_id"], extent=eid,
                         offset=off, payload=data, idempotent=True)
                return
            except pkt.PacketError as e:
                raise rpc.RpcError(500, f"packet write: {e}") from None
            except TimeoutError:
                # the write may STILL be executing on a saturated peer:
                # an automatic RPC resend would double its load (and
                # could land behind a newer same-offset write). Surface
                # the timeout; the caller owns the retry decision.
                self._packet_down[paddr] = time.monotonic() + 30.0
                raise rpc.RpcError(
                    504, f"packet write to {addr} timed out; "
                         f"possibly still executing") from None
            except (ConnectionError, OSError):
                self._packet_down[paddr] = time.monotonic() + 30.0
        self.nodes.get(addr).call(
            "write", {"dp_id": dp["dp_id"], "extent_id": eid,
                      "offset": off}, data)

    def _read_one(self, addr: str, dp_id: int, eid: int, off: int,
                  ln: int) -> bytes:
        """One replica read, trying the fastest advertised plane first:
        the native C++ read plane (dataserve.cc), then the Python
        packet plane, then RPC. Transport failures negative-cache that
        plane only; protocol errors surface (the caller fails over to
        another replica)."""
        from ..utils import packet as pkt

        planes = []
        if addr in self.read_addrs:
            planes.append(self.read_addrs[addr])
        if addr in self.packet_addrs:
            planes.append(self.packet_addrs[addr])
        for plane in planes:
            if time.monotonic() < self._packet_down.get(plane, 0.0):
                continue
            cli = self._packet_clients.get(plane)
            if cli is None:
                # short connect timeout: a blackholed packet port must
                # not stall reads before the RPC fallback kicks in
                cli = self._packet_clients[plane] = pkt.PacketClient(
                    plane, timeout=30.0, connect_timeout=2.0)
            try:
                _, data = cli.call(pkt.OP_READ, partition=dp_id, extent=eid,
                                   offset=off, args={"length": ln})
                return data
            except pkt.PacketError as e:
                raise rpc.RpcError(409, f"packet read: {e}") from None
            except TimeoutError:
                # don't stack a second 30s wait on the same node: count
                # it as a replica failure so the read fails over to the
                # NEXT replica immediately
                self._packet_down[plane] = time.monotonic() + 30.0
                raise rpc.RpcError(
                    504, f"packet read from {addr} timed out") from None
            except (ConnectionError, OSError):
                # plane down: remember it and stop paying the connect
                # cost on every read until the cooldown passes
                self._packet_down[plane] = time.monotonic() + 30.0
        _, data = self.nodes.get(addr).call(
            "read", {"dp_id": dp_id, "extent_id": eid,
                     "offset": off, "length": ln},
        )
        return data


class FileSystem:
    """Path-level facade over meta + data clients (the VFS layer)."""

    QUOTA_TTL = 30.0  # seconds between quota-table refreshes

    def __init__(self, vol_view: dict, node_pool, master_addr: str | None = None,
                 *, flash_fgm=None, client_az: str | None = None,
                 blob_client=None):
        self.meta = MetaWrapper(vol_view, node_pool)
        self.data = ExtentClient(vol_view, node_pool)
        self.vol_name = vol_view.get("name")
        self.nodes = node_pool
        self.master_addr = master_addr
        self.client_az = client_az
        if master_addr is not None:
            # lets the meta router chase live range migrations (and
            # satisfy range misses) by re-pulling the view on demand
            self.meta._refresh_cb = self._fetch_view
        # A/B door for the AZ-local hot-read tier: CUBEFS_READ_CACHE=1
        # (plus a flash ring handle) routes reads through CachedReader;
        # off (default) is byte-for-byte the plain ExtentClient path.
        try:
            rc = int(os.environ.get("CUBEFS_READ_CACHE", "0") or "0")
        except ValueError:
            rc = 0
        self.read_cache = None
        if rc > 0 and flash_fgm is not None:
            try:
                hot = int(os.environ.get("CUBEFS_READ_HOT", "2") or "2")
            except ValueError:
                hot = 2
            from .remotecache import CachedReader
            self.read_cache = CachedReader(
                self.data, flash_fgm, node_pool, client_az=client_az,
                hotness_threshold=hot)
        # A/B door for transparent cold-tier read-through:
        # CUBEFS_TIERING=1 (plus a blob client) routes extent-less
        # cold.location inodes to the blob plane; off (default) is
        # byte-for-byte the pre-tiering path.
        self.tiering = None
        try:
            td = int(os.environ.get("CUBEFS_TIERING", "0") or "0")
        except ValueError:
            td = 0
        if td > 0 and blob_client is not None:
            from .tiering import TieringEngine
            self.tiering = TieringEngine(self, blob_client)
        # dir_ino -> [qid]: files created under a quota dir inherit its
        # ids (master_quota_manager.go analog); long-lived clients with a
        # master configured re-pull the table every QUOTA_TTL, so quotas
        # set after mount still take effect (sdk/meta quota-cache analog)
        self.quotas: dict[int, list[int]] = {}
        self._quota_ts = time.time()
        self.update_quotas(vol_view.get("quotas") or {})

    def update_quotas(self, quotas: dict) -> None:
        table: dict[int, list[int]] = {}
        for qid, q in quotas.items():
            table.setdefault(int(q["dir_ino"]), []).append(int(qid))
        self.quotas = table

    def _fetch_view(self) -> dict:
        return self.nodes.get(self.master_addr).call(
            "client_view", {"name": self.vol_name})[0]["volume"]

    def _maybe_refresh_quotas(self) -> None:
        if (self.master_addr is None
                or time.time() - self._quota_ts < self.QUOTA_TTL):
            return
        self._quota_ts = time.time()  # even on failure: don't hammer
        try:
            view = self._fetch_view()
            self.update_quotas(view.get("quotas") or {})
            # mp_version is the single range-table watermark: a merge
            # SHRINKS the table, so a length compare alone would miss it
            if (view.get("mp_version", 0) != self.meta.mp_version
                    or len(view.get("mps") or []) != len(self.meta.mps)):
                self.meta.update_mps(view["mps"],
                                     view.get("mp_version", 0))
        except Exception:
            pass  # stale table; retried after the next TTL

    # ---- path helpers ----
    def resolve(self, path: str) -> int:
        parts = [p for p in path.split("/") if p]
        if not parts:
            return mn.ROOT_INO
        ino, _ = self.meta.walk(mn.ROOT_INO, parts)
        return ino

    def _parent_of(self, path: str) -> tuple[int, str]:
        parent, _, name = self._walk_parent(path)
        return parent, name

    def _walk_parent(self, path: str) -> tuple[int, list[int], str]:
        """Resolve the parent dir, returning (parent_ino, ancestor_inos
        incl. parent, leaf_name) — the ancestor chain feeds quota
        inheritance, so the per-component walk only runs when quotas
        are actually configured; otherwise one server-side walk."""
        parts = [p for p in path.split("/") if p]
        if not parts:
            raise FsError(22, "root has no parent")
        if not self.quotas:
            parent, _ = self.meta.walk(mn.ROOT_INO, parts[:-1])
            return parent, [parent], parts[-1]
        parent = mn.ROOT_INO
        chain = [parent]
        for part in parts[:-1]:
            parent = self.meta.lookup(parent, part)
            chain.append(parent)
        return parent, chain, parts[-1]

    def _inherited_quota_ids(self, ancestors: list[int]) -> list[int]:
        out: list[int] = []
        for ino in ancestors:
            for qid in self.quotas.get(ino, []):
                if qid not in out:
                    out.append(qid)
        return out

    # ---- files & dirs ----
    def mkdir(self, path: str, mode: int = 0o755) -> int:
        parent, name = self._parent_of(path)
        return self.meta.mknod(parent, name, mn.DIR, mode)

    def create(self, path: str, mode: int = 0o644) -> int:
        self._maybe_refresh_quotas()
        parent, ancestors, name = self._walk_parent(path)
        qids = self._inherited_quota_ids(ancestors)
        return self.meta.mknod(parent, name, mn.FILE, mode,
                               quota_ids=qids)

    def write_file(self, path: str, data: bytes, append: bool = False) -> int:
        try:
            ino = self.resolve(path)
        except FsError:
            ino = self.create(path)
        inode = self.meta.inode_get(ino)
        off = inode["size"] if append else 0
        if not append and inode["size"]:
            if self.read_cache is not None:
                # overwrite: evict every flash copy of the old extents
                # BEFORE they leave the inode (write-path invalidation)
                self.read_cache.invalidate(inode.get("extents") or [])
            self.meta.truncate(ino, 0)
            self.data.close_stream(ino)
            # freed extents ride the metanode freelist: the server's
            # free scan deletes them (deferred deletion, crash-safe)
        self.data.write(self.meta, ino, off, data)
        return ino

    def pwrite_file(self, path: str, offset: int, data: bytes) -> int:
        """pwrite(2)-style offset write, creating the file on demand
        (the native C ABI's write leg)."""
        try:
            ino = self.resolve(path)
        except FsError:
            ino = self.create(path)
        if self.read_cache is not None:
            inode = self.meta.inode_get(ino)
            lo, hi = offset, offset + len(data)
            self.read_cache.invalidate(
                [ek for ek in inode.get("extents") or []
                 if ek["file_offset"] < hi
                 and ek["file_offset"] + ek["size"] > lo])
        self.data.write(self.meta, ino, offset, data)
        return ino

    def truncate_file(self, path: str, size: int) -> None:
        ino = self.resolve(path)
        if self.read_cache is not None:
            inode = self.meta.inode_get(ino)
            self.read_cache.invalidate(
                [ek for ek in inode.get("extents") or []
                 if ek["file_offset"] + ek["size"] > size])
        self.meta.truncate(ino, size)
        self.data.close_stream(ino)
        # freed extents are reclaimed server-side via the freelist

    def read_file(self, path: str, offset: int = 0, length: int | None = None) -> bytes:
        inode = self.meta.inode_get(self.resolve(path))
        if inode["type"] == mn.DIR:
            # read(2) of a directory is EISDIR — which also exercises the
            # 499 errno= wire form (421 is a reserved transport code)
            raise FsError(mn.EISDIR, f"{path} is a directory")
        if length is None:
            length = max(0, inode["size"] - offset)
        else:
            # pread(2) semantics: reads at/past EOF return short/empty
            length = max(0, min(length, inode["size"] - offset))
        if (self.tiering is not None and not inode["extents"]
                and inode["xattr"].get("cold.location")):
            # cold tier: extents released, payload lives in the blob
            # plane — read through it (AZ-local degraded reads inside)
            return self.tiering.read_cold(inode, offset, length)
        if self.read_cache is not None:
            return self.read_cache.read(inode, offset, length)
        return self.data.read(inode, offset, length)

    def readdir(self, path: str) -> dict[str, int]:
        return self.meta.readdir(self.resolve(path))

    def stat(self, path: str) -> dict:
        parts = [p for p in path.split("/") if p]
        if not parts:
            return self.meta.inode_get(mn.ROOT_INO)
        _, inode = self.meta.walk(mn.ROOT_INO, parts, stat=True)
        return inode

    def unlink(self, path: str) -> None:
        parent, name = self._parent_of(path)
        if self.read_cache is not None:
            try:
                inode = self.meta.inode_get(self.meta.lookup(parent, name))
                self.read_cache.invalidate(inode.get("extents") or [])
            except FsError:
                pass  # racing unlink: nothing left to invalidate
        try:
            # compound: dentry + inode in one commit (mknod placement
            # puts them in the same partition); errno 18 = foreign inode
            ino = self.meta.unlink2(parent, name)
            self.data.close_stream(ino)
            return
        except FsError as e:
            if e.errno != 18:
                raise
        ino = self.meta.lookup(parent, name)
        inode = self.meta.inode_get(ino)
        if inode["type"] == mn.DIR and self.meta.dentry_count(ino) > 0:
            raise FsError(mn.ENOTEMPTY, f"{path} not empty")
        self.meta.dentry_delete(parent, name)
        # dec_nlink removes the inode only on the LAST link, moving its
        # extents onto the partition's replicated freelist; the
        # metanode's background scan deletes them from the datanodes —
        # a client crash ANYWHERE in this sequence leaks at most an
        # orphan inode, which fsck reclaims (never raw extents)
        if self.meta.dec_nlink(ino):
            self.data.close_stream(ino)

    def link(self, existing: str, new: str) -> int:
        """Hardlink (link(2)): a second dentry to the same inode.
        Directories are EPERM, per POSIX."""
        ino = self.resolve(existing)
        parent, name = self._parent_of(new)
        return self.link_at(ino, parent, name)["ino"]

    def link_at(self, ino: int, new_parent: int, name: str) -> dict:
        """Inode-based link for the FUSE opcode handler; returns the
        post-link inode dict. Bumps nlink FIRST, then installs the
        dentry — a crash in between leaks an overcounted nlink (fsck's
        reachability pass reclaims it), never a dentry pointing at a
        removable inode."""
        inode = self.meta.inode_get(ino)
        if inode["type"] == mn.DIR:
            raise FsError(mn.EPERM,
                          "hardlinks to directories are not allowed")
        inode["nlink"] = self.meta.inc_nlink(ino)
        try:
            self.meta.dentry_create(new_parent, name, ino)
        except FsError:
            # DEFINITE semantic rejection (e.g. EEXIST): safe to
            # compensate. A transport-level RpcError is AMBIGUOUS — the
            # dentry may have committed — so the overcount is left for
            # fsck; compensating there could free a still-linked inode.
            try:
                self.meta.dec_nlink(ino)
            except (FsError, rpc.RpcError):
                pass  # overcount leak at worst; fsck reclaims
            raise
        return inode

    def rename(self, old: str, new: str) -> None:
        old_parent, old_name = self._parent_of(old)
        new_parent, new_name = self._parent_of(new)
        self.rename_at(old_parent, old_name, new_parent, new_name)

    def rename_at(self, old_parent: int, old_name: str,
                  new_parent: int, new_name: str,
                  noreplace: bool = False) -> None:
        """POSIX rename: atomic, replacing an existing target (file over
        file, dir over empty dir). Same-partition renames are ONE fsm
        apply; cross-partition renames run the two-phase transaction —
        either way no crash point leaves the file linked twice or lost.
        Inode-based so the FUSE opcode handler can call it directly."""
        ino = self.meta.lookup(old_parent, old_name)
        try:
            victim_ino = self.meta.lookup(new_parent, new_name)
        except FsError:
            victim_ino = None
        if noreplace and victim_ino is not None:
            raise FsError(mn.EEXIST, f"{new_name!r} exists (NOREPLACE)")
        if victim_ino == ino:
            return  # same file: POSIX says do nothing
        src = self.meta.inode_get(ino)
        victim_is_dir = False
        if victim_ino is not None:
            vic = self.meta.inode_get(victim_ino)
            victim_is_dir = vic["type"] == mn.DIR
            if victim_is_dir:
                if src["type"] != mn.DIR:
                    raise FsError(mn.EISDIR, f"{new_name!r} is a directory")
                if self.meta.dentry_count(victim_ino) > 0:
                    raise FsError(mn.ENOTEMPTY, f"{new_name!r} not empty")
            elif src["type"] == mn.DIR:
                raise FsError(mn.ENOTDIR, f"{new_name!r} is not a directory")
        # cross-directory DIR moves serialize on the cluster-wide rename
        # mutex, then re-run the ancestry check under it: two concurrent
        # dir moves can no longer weave a detached cycle past each
        # other's checks (the kernel does the same with
        # s_vfs_rename_mutex)
        dir_move = src["type"] == mn.DIR and old_parent != new_parent
        mutex_tx, walk_deadline = None, None
        if dir_move:
            mutex_tx, lock_ts = self.meta.lock_dir_rename()
            # the mutex is a prepared tx auto-released at TX_TTL counted
            # from lock_ts (the stamp inside the tx, NOT the moment the
            # grant returned): if the ancestry walk below outlived it, a
            # concurrent dir move could acquire the "held" mutex and both
            # would proceed. The deadline is checked BEFORE each walk RPC,
            # so reserve one full RPC deadline (10s) plus slack for an
            # in-flight call straddling the check.
            walk_deadline = lock_ts + mn.MetaPartition.TX_TTL - 10.0 - 2.0
        try:
            if src["type"] == mn.DIR and self._in_subtree(
                ino, new_parent, deadline=walk_deadline
            ):
                # POSIX: renaming a dir into its own subtree is EINVAL —
                # it would detach the subtree into an unreachable cycle
                raise FsError(22, "cannot move a directory into itself")
            src_mp = self.meta._mp_for(old_parent)
            dst_mp = self.meta._mp_for(new_parent)
            # the single-apply fast path needs every touched structure on
            # ONE partition: both parent dentry maps, and (for a dir
            # victim) the victim's own children map — its emptiness is
            # re-asserted inside the apply, which only sees local state
            local_ok = src_mp["pid"] == dst_mp["pid"] and not (
                victim_is_dir
                and self.meta._mp_for(victim_ino)["pid"] != src_mp["pid"]
            )
            if local_ok:
                victim = self.meta.rename_local(
                    old_parent, old_name, new_parent, new_name, ino,
                    victim=victim_ino, noreplace=noreplace)
            else:
                victim = self.meta.rename_tx(
                    old_parent, old_name, new_parent, new_name, ino,
                    victim=victim_ino, victim_is_dir=victim_is_dir,
                    noreplace=noreplace)
        finally:
            if mutex_tx is not None:
                try:
                    self.meta.unlock_dir_rename(mutex_tx)
                except (FsError, rpc.RpcError):
                    pass  # TX_TTL expiry releases a stranded lock
        if victim is not None:
            # replaced target: drop ONE link (post-commit cleanup; a
            # crash here leaves an unreferenced inode for fsck, never a
            # dangling dentry). Other hardlinks keep the inode alive;
            # the last link's extents ride the server-side freelist.
            if self.meta.dec_nlink(victim):
                self.data.close_stream(victim)

    def _in_subtree(
        self, root_ino: int, target_ino: int, deadline: float | None = None
    ) -> bool:
        """True if target_ino is root_ino or lives anywhere under it
        (walks DOWN from root — inodes carry no parent pointers).

        `deadline`: abort with EBUSY past it — callers holding the
        TTL-bounded dir-rename mutex must not let the walk outlive the
        lock (the cycle-weave protection would silently vanish)."""
        if root_ino == target_ino:
            return True
        def check():
            # called before EVERY walk RPC (readdir and per-child
            # inode_get), so the worst overshoot past the deadline is the
            # single in-flight call the caller's margin reserves for
            if deadline is not None and time.time() > deadline:
                raise FsError(
                    mn.EBUSY,
                    "directory tree too large to safely check under the "
                    "rename mutex; retry",
                )

        queue = [root_ino]
        seen = {root_ino}
        while queue:
            cur = queue.pop()
            check()
            try:
                entries = self.meta.readdir(cur)
            except FsError:
                continue
            for child in entries.values():
                if child == target_ino:
                    return True
                if child not in seen:
                    seen.add(child)
                    check()
                    try:
                        if self.meta.inode_get(child)["type"] == mn.DIR:
                            queue.append(child)
                    except FsError:
                        pass
        return False

    def setxattr(self, path: str, key: str, value: str) -> None:
        self.meta.set_xattr(self.resolve(path), key, value)

    def getxattr(self, path: str, key: str):
        return self.meta.inode_get(self.resolve(path))["xattr"].get(key)
