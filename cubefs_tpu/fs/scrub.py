"""fs-plane continuous scrubber + the ONE sanctioned extent healer.

Role parity: datanode's CRC scrub loop — every extent any inode
references gets its replica CRC fingerprints compared on a rolling
cursor (reusing fsck's walk primitives for the work list), and a
divergent replica is rewritten in place through
``DataNode.sync_extent_from`` — the same executor the client-side
read-repair and ``fsck --heal`` use, so there is exactly one code path
that ever rewrites an extent copy ("one sanctioned healer, not two").

Heal decision: majority vote over the replicas' ``(size, crc)``
fingerprints, leader's fingerprint as the tiebreak — the same diffing
repair has always used (data_partition_repair.go role), now continuous.
Multi-way disagreement with no majority is left for operators (healing
from an arbitrary copy could cement wrong data), mirroring the blob
inspector's unique-culprit rule.

Discipline (rate limit, SCRUB-priority admission, resumable persisted
cursor, CUBEFS_SCRUB door, clock injection) all comes from
``utils.scrub.Scrubber``; healed extents are remembered in ``healed``
so a later fsck run dedups instead of re-reporting them.
"""

from __future__ import annotations

import json
import os

from ..utils import metrics, qos, rpc
from ..utils.retry import MONOTONIC, Clock
from ..utils.scrub import Scrubber


def heal_extent(fs, pool, dp_id: int, extent_id: int,
                source: str = "scrub") -> bool:
    """Compare one extent's replica fingerprints; rewrite every
    divergent copy from a majority-fingerprint replica. Returns True
    when divergence was found (and a heal attempted), False when the
    replicas already agree — so callers can count real repairs without
    ever rewriting a clean extent (zero false repairs)."""
    dp = fs.data._dp_by_id(dp_id)
    fps: dict[str, tuple[int, int]] = {}
    for addr in dp["replicas"]:
        try:
            meta, _ = pool.get(addr).call(
                "extent_fingerprint",
                {"dp_id": dp_id, "extent_id": extent_id})
            fps[addr] = (meta["size"], meta["crc"])
        except (rpc.RpcError, OSError):
            continue  # unreachable replica: the repair sweep's problem
    if len(set(fps.values())) <= 1:
        return False  # consistent (or nothing readable): nothing to heal
    votes: dict[tuple[int, int], int] = {}
    for v in fps.values():
        votes[v] = votes.get(v, 0) + 1
    leader_fp = fps.get(dp.get("leader"))
    best = max(votes, key=lambda v: (votes[v], v == leader_fp))
    top = [v for v in votes if votes[v] == votes[best]]
    if len(top) > 1 and leader_fp not in top:
        # no majority and the leader can't break the tie: healing from
        # an arbitrary copy could cement wrong data — leave for operators
        metrics.integrity_repair_failures.inc(plane="fs")
        return True
    healthy = [a for a, v in fps.items() if v == best]
    src = dp["leader"] if dp.get("leader") in healthy else healthy[0]
    for addr in (a for a, v in fps.items() if v != best):
        try:
            pool.get(addr).call(
                "sync_extent_from",
                {"dp_id": dp_id, "extent_id": extent_id,
                 "src_addr": src, "source": source}, timeout=30.0)
        except (rpc.RpcError, OSError):
            metrics.integrity_repair_failures.inc(plane="fs")
    return True


class FsScrubber:
    """Continuous fs-plane scrub driver over the generic Scrubber."""

    def __init__(self, fs, pool, *, clock: Clock = MONOTONIC,
                 rate: float = 0.0, data_dir: str | None = None):
        self.fs = fs
        self.pool = pool
        # (dp_id, extent_id) this scrubber healed — fsck dedups on it
        self.healed: set[tuple[int, int]] = set()
        cursor_load = cursor_save = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            path = os.path.join(data_dir, "fs_scrub_cursor.json")

            def cursor_load():
                if os.path.exists(path):
                    return json.load(open(path)).get("cursor")
                return None

            def cursor_save(cursor):
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"cursor": cursor}, f)
                os.replace(tmp, path)

        self.scrubber = Scrubber("fs", self._list_units, self._scrub_unit,
                                 clock=clock, rate=rate,
                                 cursor_load=cursor_load,
                                 cursor_save=cursor_save)

    def _list_units(self) -> list[str]:
        from .fsck import list_referenced_extents

        # same unit-key shape the at-rest fault plan uses (dpX:eY)
        return [f"dp{d}:e{e}" for d, e in list_referenced_extents(self.fs)]

    def _scrub_unit(self, unit: str) -> str:
        dp_part, e_part = unit.split(":")
        dp_id, eid = int(dp_part[2:]), int(e_part[1:])
        try:
            with qos.admit("fs.scrub", priority=qos.SCRUB, svc="fsck"):
                diverged = heal_extent(self.fs, self.pool, dp_id, eid,
                                       source="scrub")
        except qos.QosRejected:
            return "skipped"  # brownout: give way to foreground
        except (rpc.RpcError, OSError):
            return "skipped"
        if diverged:
            self.healed.add((dp_id, eid))
            return "corrupt"
        return "clean"

    # thin delegation so callers (cli, tests) treat both planes alike
    def run_once(self, max_units: int | None = None) -> dict:
        return self.scrubber.run_once(max_units=max_units)

    def run_full_pass(self) -> dict:
        return self.scrubber.run_full_pass()

    def start(self, interval: float = 1.0, units_per_tick: int = 8) -> None:
        self.scrubber.start(interval, units_per_tick)

    def stop(self) -> None:
        self.scrubber.stop()

    def status(self) -> dict:
        st = self.scrubber.status()
        st["healed"] = len(self.healed)
        return st
