"""FS-plane failure-domain topology: the placement scorer for the master.

Role parity: master/topology.go + master/node_selector.go — the fs-plane
twin of blob/topology.py. The master's registries label every node with
AZ (``zone``) > rack; this module is the single authority for turning
those labels plus a load view into replica placements:

  * ``select_hosts``  — dp/mp replica spread: one-per-AZ when enough AZs
    exist, even spread when fewer, one nodeset otherwise
  * ``pick_destination`` — rebuild/migration target with an explicit AZ
    preference ladder (failed replica's AZ > un-colocated AZ > fresh
    rack > least load)
  * ``pick_leader``   — write-leadership rotation inside a replica set
  * ``misplacement``  — the colocation score the rate-limited sweep
    drives to zero (gauge contract: 0 == every dp honors one-per-AZ up
    to the cluster's labeled AZ count)

Everything here is a pure function over registry info dicts
(``addr -> {"zone": ..., "rack": ..., "hb": ...}``); the master owns the
locks and the commit door. The only sanctioned load sorts in the fs
control plane live in this file (lint: CFZ002).
"""

from __future__ import annotations

DEFAULT_ZONE = "default"


def az_of(info: dict) -> str:
    return info.get("zone") or DEFAULT_ZONE


def rack_of(info: dict) -> str:
    # an unlabeled rack is its own host: rack-spread degrades to
    # host-spread instead of treating all unlabeled nodes as colocated
    return info.get("rack") or info.get("addr", "")


def zones_of(reg: dict, addrs: list[str]) -> dict[str, list[str]]:
    zones: dict[str, list[str]] = {}
    for a in addrs:
        zones.setdefault(az_of(reg.get(a) or {}), []).append(a)
    return zones


def labeled_azs(reg: dict) -> list[str]:
    """Every AZ the registry knows about, including ones with no live
    node — a dark AZ still bounds the fair share the sweep scores
    against (same contract as blob cluster_misplacement)."""
    return sorted({az_of(i) for i in reg.values()})


def nodesets(members: list[str], size: int) -> list[list[str]]:
    """Chunk a zone's nodes into nodesets (failure domains),
    deterministically by address order."""
    members = sorted(members)
    return [members[i:i + size] for i in range(0, len(members), size)]


def order_by_load(addrs: list[str], load: dict) -> list[str]:
    """The only sanctioned load sort outside this module's selectors."""
    return sorted(addrs, key=lambda a: (load.get(a, 0), a))


# ---------------- pluggable node selectors (node_selector.go) ----------
def _select_least_load(cands: list[str], k: int, load: dict,
                       state: dict) -> list[str]:
    return order_by_load(cands, load)[:k]


def _select_round_robin(cands: list[str], k: int, load: dict,
                        state: dict) -> list[str]:
    cands = sorted(cands)
    start = state.get("rr", 0) % len(cands)
    state["rr"] = start + k
    return [cands[(start + i) % len(cands)] for i in range(k)]


def _select_carry_weight(cands: list[str], k: int, load: dict,
                         state: dict) -> list[str]:
    """CarryWeightNodeSelector analog: each node accumulates carry
    proportional to its headroom; the k highest carries win and pay 1."""
    carry = state.setdefault("carry", {})
    for a in cands:
        carry[a] = carry.get(a, 0.0) + 1.0 / (1.0 + load.get(a, 0))
    picks = sorted(cands, key=lambda a: (-carry.get(a, 0.0), a))[:k]
    for a in picks:
        carry[a] -= 1.0
    return picks


SELECTORS = {
    "least_load": _select_least_load,
    "round_robin": _select_round_robin,
    "carry_weight": _select_carry_weight,
}


# ---------------- replica-set placement ----------------
def select_hosts(reg: dict, live: list[str], k: int, load: dict,
                 pick, nodeset_size: int = 3) -> list[str]:
    """Topology-aware placement: one replica per zone when k zones
    exist (cross-AZ volumes); otherwise all replicas from one nodeset
    of the least-loaded zone (the reference keeps a partition's
    replicas inside one failure domain). ``pick`` is the master's
    pluggable selector (cands, k, load) -> picks."""
    zones = zones_of(reg, live)
    if len(zones) >= k > 1:
        zone_load = {z: sum(load.get(a, 0) for a in m)
                     for z, m in zones.items()}
        picked_zones = sorted(zones, key=lambda z: (zone_load[z], z))[:k]
        return [pick(zones[z], 1, load)[0] for z in picked_zones]
    if len(zones) > 1:
        # fewer zones than replicas: spread as evenly as possible —
        # an explicit colocation degrade, scored by misplacement below
        out: list[str] = []
        ordered = sorted(zones, key=lambda z: (-len(zones[z]), z))
        zi = 0
        while len(out) < k:
            z = ordered[zi % len(ordered)]
            remaining = [a for a in zones[z] if a not in out]
            if remaining:
                out.append(pick(remaining, 1, load)[0])
            zi += 1
            if zi > 4 * k:
                break
        return out
    members = next(iter(zones.values()))
    full = [ns for ns in nodesets(members, nodeset_size) if len(ns) >= k]
    if full:
        ns = min(full, key=lambda s: (sum(load.get(a, 0) for a in s), s[0]))
        return pick(ns, k, load)
    return pick(members, k, load)  # no full nodeset: whole zone


def pick_leader(picks: list[str], intra_load: dict | None) -> str:
    """Rotate write leadership: the replica carrying the fewest
    leaderships placed so far in this planning pass wins."""
    return min(picks, key=lambda a: (intra_load or {}).get(a, 0))


def pick_destination(reg: dict, cands: list[str], survivors: list[str],
                     *, prefer_az: str | None = None,
                     load: dict | None = None) -> str:
    """Rebuild/migration target selection (blob pick_destination's
    ladder, fs-shaped): among candidate addrs not already in the
    replica set, prefer — in order —

      1. the failed replica's AZ (``prefer_az``), keeping the dp's
         AZ footprint intact through a rebuild
      2. an AZ not already occupied by a surviving replica (colocation
         comes last, never first)
      3. a rack no survivor lives on
      4. least placement load, then address (deterministic)

    ``survivors`` are the replica addrs that remain after the failure.
    """
    if not cands:
        raise ValueError("no candidate destinations")
    load = load or {}
    surv_az_count: dict[str, int] = {}
    surv_racks = set()
    for a in survivors:
        info = reg.get(a) or {"addr": a}
        surv_az_count[az_of(info)] = surv_az_count.get(az_of(info), 0) + 1
        surv_racks.add(rack_of(info))

    def key(a: str):
        info = reg.get(a) or {"addr": a}
        az = az_of(info)
        return (0 if (prefer_az is not None and az == prefer_az) else 1,
                surv_az_count.get(az, 0),
                1 if rack_of(info) in surv_racks else 0,
                load.get(a, 0), a)

    return min(cands, key=key)


# ---------------- misplacement scoring (sweep contract) ----------------
def fair_share(k: int, az_count: int) -> int:
    """Ceil fair share of k replicas across az_count AZs."""
    return -(-k // max(az_count, 1))


def replica_misplacement(reg: dict, replicas: list[str],
                         cluster_azs: list[str] | None = None) -> list[str]:
    """Replicas colocated in an AZ beyond the cluster's fair share —
    the addrs the sweep should move, deterministically chosen (the
    lexically-first replica in each over-full AZ stays). An unlabeled
    (single-AZ) cluster has fair share == k and never misplaces."""
    azs = cluster_azs if cluster_azs is not None else labeled_azs(reg)
    fair = fair_share(len(replicas), len(azs))
    by_az: dict[str, list[str]] = {}
    for a in replicas:
        by_az.setdefault(az_of(reg.get(a) or {}), []).append(a)
    out: list[str] = []
    for members in by_az.values():
        if len(members) > fair:
            out.extend(sorted(members)[fair:])
    return sorted(out)


def cluster_misplacement(reg: dict, volumes: dict) -> dict:
    """Score every volume's dps against the one-per-AZ contract.
    Returns {"misplaced": total, "dps": [(vol, dp_id, [excess addrs])]}
    — the work list the rate-limited sweep consumes and the value the
    ``cubefs_fs_placement_misplaced`` gauge reports."""
    azs = labeled_azs(reg)
    total = 0
    work: list[tuple[str, int, list[str]]] = []
    for vname, vol in sorted(volumes.items()):
        for dp in vol["dps"]:
            excess = replica_misplacement(reg, dp["replicas"], azs)
            if excess:
                total += len(excess)
                work.append((vname, dp["dp_id"], excess))
    return {"misplaced": total, "dps": work}


# ---------------- operator views ----------------
def topology_tree(reg: dict, live: set, decommissioned: set) -> dict:
    """az -> rack -> {addr: {live, decommissioned}} for one node kind
    (`cubefs-cli topology tree` renders this next to the blob map)."""
    tree: dict[str, dict] = {}
    for a, info in sorted(reg.items()):
        az = tree.setdefault(az_of(info), {})
        az.setdefault(rack_of(info), {})[a] = {
            "live": a in live, "decommissioned": a in decommissioned}
    return tree
