"""Elastic metadata plane: metapartition auto-split/merge with live
inode-range migration (master/meta_partition_manager.go role).

The reference master splits a hot meta partition by APPENDING a fresh
partition for the next inode range (``Master.split_meta_partition``) —
existing inodes stay put, so a partition that went hot stays hot.  This
engine moves the load itself: a fenced three-phase state machine hands
the USED upper half of a hot partition's inode range to a brand-new
partition, live, without stopping writes to the rest of the donor.

Phases (every durable step is an idempotent, op_id-fenced FSM apply —
the PR-12 discipline — so any crash boundary replays clean):

  PREPARE             master commits ``split_prepare``: the split plan
                      and the target pid reservation land in the
                      replicated ``Master.splits`` ledger BEFORE any
                      metanode sees an RPC.  A crash here can neither
                      mint a duplicate pid nor orphan an untracked
                      half-built partition — recovery reads the ledger.
  FROZEN-RANGE-COPIED target partition is created empty; the donor
                      leader streams a CRC-framed range snapshot over
                      the packet mux (FLAG_MORE chunk trains, geo
                      bootstrap idiom) while a leader-local delta tap
                      records every racing mutation; then the donor
                      freezes ONLY the migrating sub-range (replicated
                      apply), the tap drains, and the target replays
                      the delta through its own commit door.  Racing
                      mutations therefore always either win on the
                      donor (tapped + replayed) or bounce with a
                      453/EMOVED routing code the SDK follows to the
                      new owner.  Writes to the REST of the donor's
                      range never stop.
  COMMITTED           master commits the range-table change as ONE
                      ``split_commit`` apply: donor end shrinks, the
                      target row appears, and the volume's
                      ``mp_version`` watermark bumps exactly once —
                      clients re-route atomically on their next view
                      refresh.  The donor then drops the moved trees
                      and keeps a tombstone that redirects stale
                      clients.

Merge is the inverse: a cold partition's range is migrated into its
left-adjacent neighbour with the same machinery, then the donor row is
removed (``merge_commit``) and its raft group is dropped.

The rate-limited balance sweep (the ``sweep_misplaced`` idiom) drives
the ``cubefs_meta_partition_imbalance`` gauge to zero: each call aborts
any in-flight migration left by a crashed leader, then performs at most
``max_moves`` migrations.  The automatic sweep hides behind the
``CUBEFS_META_SPLIT`` door (default OFF — digest-identical to a build
without this file); explicit operator ``split``/``merge`` calls work
regardless of the door.
"""
from __future__ import annotations

import os

from ..utils import lockwitness, metrics, rpc, slo


def door_open() -> bool:
    """CUBEFS_META_SPLIT gates only the AUTOMATIC balance sweep."""
    return os.environ.get("CUBEFS_META_SPLIT", "0").lower() \
        not in ("0", "", "false", "no")


# a hot meta.write SLO (burn >= 1 means the error budget is being spent
# faster than it accrues) halves the fill threshold: partitions split
# EARLY while the plane is under pressure, late when it is idle
HOT_BURN_RATE = 1.0

# partitions narrower than this never auto-split: each split halves the
# donor's span, so without a floor a persistently-full donor would be
# shaved into confetti by successive sweeps
MIN_SPLIT_SPAN = 4096


class SplitEngine:
    """Master-driven three-phase metapartition migrator.

    Lives on the master leader (``Master.split_engine()``); every
    durable step goes through the master's replicated FSM, so a deposed
    or restarted leader recovers from the ``splits`` ledger alone.
    ``fault_hook`` (tests only) is called at each phase boundary with
    ``(stage, split_id)`` — raising from it abandons the drive exactly
    where a crash would.
    """

    def __init__(self, master):
        self.m = master
        self.fault_hook = None  # tests: fn(stage, split_id) at boundaries
        self._last_imbalance = 0
        # one migration at a time, TRY-acquired: a long-running admin
        # operation must fail fast for contenders, not queue a proposer
        # thread behind seconds of metanode RPCs. Never held inside the
        # master's locks — the drive deliberately spans the phase RPCs.
        self._busy = lockwitness.make_lock(
            "SplitEngine._busy",
            allow_block="migration mutex spans the three-phase drive "
                        "by design; contenders try-acquire and bounce")

    # ---------------- plumbing ----------------
    def _fault(self, stage: str, sid: str) -> None:
        hook = self.fault_hook
        if hook is not None:
            hook(stage, sid)

    def _call(self, addrs: list[str], method: str, args: dict) -> dict:
        """Leader-following call into a metanode replica set."""
        meta, _ = rpc.call_replicas(self.m.nodes, addrs, method, args,
                                    deadline=10.0)
        return meta

    def _packet_addrs(self, addrs: list[str]) -> dict[str, str]:
        with self.m._lock:
            return {a: i["packet_addr"]
                    for a, i in self.m.metanodes.items()
                    if a in addrs and i.get("packet_addr")}

    def _mp_of(self, name: str, pid: int) -> dict:
        from .master import MasterError
        with self.m._lock:
            vol = self.m.volumes.get(name)
            if vol is None:
                raise MasterError(f"no volume {name!r}")
            mp = next((m for m in vol["mps"] if m["pid"] == pid), None)
            if mp is None:
                raise MasterError(f"no mp {pid} in volume {name!r}")
            return dict(mp)

    def _fill(self, mp: dict) -> dict | None:
        """Donor-leader usage report; None when every replica is down."""
        try:
            return self._call(mp.get("addrs") or [mp["addr"]],
                              "mp_fill", {"pid": mp["pid"]})
        except Exception:  # noqa: BLE001 - retried on the next sweep
            return None

    # ---------------- split ----------------
    def split(self, name: str, pid: int | None = None,
              split_ino: int | None = None) -> dict:
        """Split one partition: hand its used upper half to a fresh
        partition, live. Explicit pid/split_ino pin the plan (CLI);
        otherwise the fullest partition splits at the midpoint of its
        USED range."""
        from .master import MasterError
        if not self._busy.acquire(False):
            raise MasterError(
                "a metapartition migration is already in flight")
        try:
            plan = self._plan_split(name, pid, split_ino)
            return self._drive_split(plan)
        finally:
            self._busy.release()

    def _plan_split(self, name: str, pid: int | None,
                    split_ino: int | None) -> dict:
        from .master import MasterError
        m = self.m
        with m._lock:
            vol = m.volumes.get(name)
            if vol is None:
                raise MasterError(f"no volume {name!r}")
            mps = [dict(p) for p in vol["mps"]]
            live = m._live(m.metanodes)
            if not live:
                raise MasterError("no live metanodes")
        if not mps:
            raise MasterError(f"volume {name!r} has no meta partitions")
        if pid is None:
            # fullest USED fraction wins; unreachable partitions skipped
            best = None
            for mp in mps:
                f = self._fill(mp)
                span = mp["end"] - mp["start"]
                if f is None or span <= 0:
                    continue
                frac = (min(f["next_ino"], mp["end"])
                        - mp["start"]) / span
                if best is None or frac > best[0]:
                    best = (frac, mp, f)
            if best is None:
                raise MasterError(f"no reachable mp in volume {name!r}")
            _, donor, fill = best
        else:
            donor = next((p for p in mps if p["pid"] == pid), None)
            if donor is None:
                raise MasterError(f"no mp {pid} in volume {name!r}")
            fill = self._fill(donor)
            if fill is None:
                raise MasterError(f"mp {pid} unreachable")
        start, end = donor["start"], donor["end"]
        # a donor that split before can have its alloc cursor beyond its
        # (shrunk) end — its USED range is its whole remaining range
        used = min(fill["next_ino"], end) - start
        if split_ino is None:
            # midpoint of the USED range, clamped strictly inside it:
            # the donor keeps [start, lo), the target takes [lo, end)
            if used < 2:
                raise MasterError(
                    f"mp {donor['pid']} has only {used} used inodes — "
                    f"nothing to split")
            split_ino = start + used // 2
        if not start < split_ino < end:
            raise MasterError(
                f"split point {split_ino} outside mp {donor['pid']} "
                f"range ({start}, {end})")
        donor_addrs = donor.get("addrs") or [donor["addr"]]
        with m._lock:
            # provisional: names the split id; the ACTUAL target pid is
            # assigned inside the split_prepare apply, serial with every
            # other pid source (a volume create can land between here
            # and the prepare commit)
            tpid = m._next_pid
            meta_load = m._meta_load()
            k = min(m.replicas, len(live))
            # prefer hosts that do NOT hold the donor — the point of a
            # split is spreading load, not doubling it on one box
            cands = [a for a in live if a not in donor_addrs]
            if len(cands) < k:
                cands = live
            addrs = m._select_hosts(m.metanodes, cands, k, meta_load)
        sid = f"sp{tpid}-{name}-{donor['pid']}-{split_ino}"
        return {"split_id": sid, "kind": "split", "name": name,
                "donor_pid": donor["pid"], "donor_addrs": donor_addrs,
                "split_ino": split_ino, "hi": end,
                "target_pids": [tpid], "addrs": addrs}

    def _drive_split(self, plan: dict) -> dict:
        from .master import MasterError
        m = self.m
        sid, name = plan["split_id"], plan["name"]
        lo, hi = plan["split_ino"], plan["hi"]
        addrs, donor_addrs = plan["addrs"], plan["donor_addrs"]
        split = {k: v for k, v in plan.items()
                 if k not in ("name", "donor_addrs")}

        # -- PREPARE: plan + pid reservation land durably first --------
        # the apply assigns the authoritative target pid (and a replayed
        # prepare returns the original assignment via op_id dedup)
        split = m._commit({"op": "split_prepare", "name": name,
                           "split": split, "op_id": f"{sid}#prep"})
        tpid = split["target_pids"][0]
        self._fault("prepared", sid)

        created = []
        try:
            for a in addrs:
                # empty range [lo, lo): range_activate claims [lo, hi)
                # only after the copy + delta replay land
                m.nodes.get(a).call(
                    "create_partition",
                    {"pid": tpid, "start": lo, "end": lo, "peers": addrs})
                created.append(a)
        except Exception as e:  # noqa: BLE001 - roll the prepare back
            self._abort(split, name, f"target create failed: {e}",
                        drop_pids=created and [tpid] or [],
                        drop_addrs=created, thaw=False)
            raise MasterError(
                f"split {sid}: target create failed: {e}") from e
        self._fault("created", sid)

        donor_info = {"pid": plan["donor_pid"], "addrs": donor_addrs,
                      "packet_addrs": self._packet_addrs(donor_addrs)}
        try:
            # -- FROZEN-RANGE-COPIED: snapshot, fence, drain, replay ---
            fetched = self._call(addrs, "range_fetch",
                                 {"pid": tpid, "lo": lo, "hi": hi,
                                  "split_id": sid, "donor": donor_info})
            self._fault("copied", sid)
            frozen = self._call(donor_addrs, "range_freeze",
                                {"pid": plan["donor_pid"], "lo": lo,
                                 "hi": hi, "target_pid": tpid,
                                 "split_id": sid})
            if frozen.get("poisoned"):
                raise _Poisoned(frozen["poisoned"])
            replayed = self._call(addrs, "range_replay",
                                  {"pid": tpid, "split_id": sid,
                                   "records": frozen["delta"]})
            self._fault("frozen", sid)
            self._call(addrs, "range_activate",
                       {"pid": tpid, "lo": lo, "hi": hi,
                        "split_id": sid})
            self._fault("activated", sid)
        except _Poisoned as e:
            self._abort(split, name, f"delta tap poisoned: {e}",
                        drop_pids=[tpid], drop_addrs=addrs)
            raise MasterError(
                f"split {sid} aborted: delta tap poisoned ({e}) — "
                f"retry when the racing transaction settles") from None
        except rpc.RpcError as e:
            self._abort(split, name, f"phase rpc failed: {e}",
                        drop_pids=[tpid], drop_addrs=addrs)
            raise MasterError(f"split {sid} failed: {e}") from e

        # -- COMMITTED: ONE apply rewrites the range table -------------
        m._commit({"op": "split_commit", "split_id": sid, "name": name,
                   "op_id": f"{sid}#commit"})
        self._fault("committed", sid)

        # post-commit cleanup is best-effort: a dangling frozen marker
        # on the donor still redirects (453) to the committed owner, so
        # a failed drop costs memory, not correctness
        drop_ok = True
        try:
            self._call(donor_addrs, "range_drop",
                       {"pid": plan["donor_pid"], "lo": lo, "hi": hi,
                        "target_pid": tpid, "split_id": sid})
        except Exception:  # noqa: BLE001
            drop_ok = False
        metrics.meta_range_migrations.inc(kind="split")
        return {"split_id": sid, "donor_pid": plan["donor_pid"],
                "target_pid": tpid, "split_ino": lo, "hi": hi,
                "addrs": addrs, "copied_inodes": fetched.get("inodes"),
                "delta_applied": replayed.get("applied"),
                "delta_failed": replayed.get("failed"),
                "donor_dropped": drop_ok}

    # ---------------- merge ----------------
    def merge(self, name: str, donor_pid: int | None = None,
              absorber_pid: int | None = None) -> dict:
        """Merge a cold partition into its left-adjacent neighbour: the
        same three-phase migration with the absorber as target, then
        ``merge_commit`` removes the donor row and its raft group."""
        from .master import MasterError
        if not self._busy.acquire(False):
            raise MasterError(
                "a metapartition migration is already in flight")
        try:
            plan = self._plan_merge(name, donor_pid, absorber_pid)
            return self._drive_merge(plan)
        finally:
            self._busy.release()

    def _plan_merge(self, name: str, donor_pid: int | None,
                    absorber_pid: int | None) -> dict:
        from .master import MasterError
        with self.m._lock:
            vol = self.m.volumes.get(name)
            if vol is None:
                raise MasterError(f"no volume {name!r}")
            mps = sorted((dict(p) for p in vol["mps"]),
                         key=lambda p: p["start"])
        if len(mps) < 2:
            raise MasterError(f"volume {name!r} has nothing to merge")
        if donor_pid is None:
            cand = self._merge_candidates(mps)
            if not cand:
                raise MasterError(
                    f"no cold mergeable partition in {name!r}")
            donor_pid, absorber_pid = cand[0]
        donor = next((p for p in mps if p["pid"] == donor_pid), None)
        if donor is None:
            raise MasterError(f"no mp {donor_pid} in volume {name!r}")
        if absorber_pid is None:
            left = next((p for p in mps if p["end"] == donor["start"]),
                        None)
            if left is None:
                raise MasterError(
                    f"mp {donor_pid} has no left-adjacent absorber")
            absorber_pid = left["pid"]
        absorber = next((p for p in mps if p["pid"] == absorber_pid),
                        None)
        if absorber is None or absorber["end"] != donor["start"]:
            raise MasterError(
                f"mp {absorber_pid} is not left-adjacent to mp "
                f"{donor_pid} — merge needs absorber.end == donor.start")
        sid = f"mg{donor_pid}-{name}-{absorber_pid}"
        return {"split_id": sid, "kind": "merge", "name": name,
                "donor_pid": donor_pid,
                "donor_addrs": donor.get("addrs") or [donor["addr"]],
                "absorber_pid": absorber_pid,
                "split_ino": donor["start"], "hi": donor["end"],
                "target_pids": [],
                "addrs": absorber.get("addrs") or [absorber["addr"]]}

    def _drive_merge(self, plan: dict) -> dict:
        from .master import MasterError
        m = self.m
        sid, name = plan["split_id"], plan["name"]
        lo, hi = plan["split_ino"], plan["hi"]
        apid = plan["absorber_pid"]
        addrs, donor_addrs = plan["addrs"], plan["donor_addrs"]
        split = {k: v for k, v in plan.items()
                 if k not in ("name", "donor_addrs")}

        split = m._commit({"op": "split_prepare", "name": name,
                           "split": split, "op_id": f"{sid}#prep"})
        self._fault("prepared", sid)

        donor_info = {"pid": plan["donor_pid"], "addrs": donor_addrs,
                      "packet_addrs": self._packet_addrs(donor_addrs)}
        try:
            fetched = self._call(addrs, "range_fetch",
                                 {"pid": apid, "lo": lo, "hi": hi,
                                  "split_id": sid, "donor": donor_info})
            self._fault("copied", sid)
            frozen = self._call(donor_addrs, "range_freeze",
                                {"pid": plan["donor_pid"], "lo": lo,
                                 "hi": hi, "target_pid": apid,
                                 "split_id": sid})
            if frozen.get("poisoned"):
                raise _Poisoned(frozen["poisoned"])
            replayed = self._call(addrs, "range_replay",
                                  {"pid": apid, "split_id": sid,
                                   "records": frozen["delta"]})
            self._fault("frozen", sid)
            self._call(addrs, "range_activate",
                       {"pid": apid, "lo": lo, "hi": hi,
                        "split_id": sid})
            self._fault("activated", sid)
        except _Poisoned as e:
            self._abort(split, name, f"delta tap poisoned: {e}")
            raise MasterError(
                f"merge {sid} aborted: delta tap poisoned ({e})"
            ) from None
        except rpc.RpcError as e:
            self._abort(split, name, f"phase rpc failed: {e}")
            raise MasterError(f"merge {sid} failed: {e}") from e

        m._commit({"op": "merge_commit", "split_id": sid, "name": name,
                   "op_id": f"{sid}#commit"})
        self._fault("committed", sid)

        # the donor row is gone from the table: retire its raft group
        dropped = 0
        for a in donor_addrs:
            try:
                m.nodes.get(a).call("drop_partition",
                                    {"pid": plan["donor_pid"]})
                dropped += 1
            except Exception:  # noqa: BLE001 - orphan costs memory only
                pass
        metrics.meta_range_migrations.inc(kind="merge")
        return {"split_id": sid, "donor_pid": plan["donor_pid"],
                "absorber_pid": apid, "lo": lo, "hi": hi,
                "copied_inodes": fetched.get("inodes"),
                "delta_applied": replayed.get("applied"),
                "delta_failed": replayed.get("failed"),
                "donor_replicas_dropped": dropped}

    # ---------------- abort / recovery ----------------
    def _abort(self, split: dict, name: str, reason: str,
               drop_pids: list[int] | None = None,
               drop_addrs: list[str] | None = None,
               thaw: bool = True) -> None:
        """Unwind a half-done migration: thaw the donor sub-range, drop
        any target partitions (splits only — a merge's absorber is a
        live partition that just holds a redundant, soon-overwritten
        copy), and retire the ledger entry. Every step is idempotent;
        the ledger commit is the only one that must land."""
        sid = split["split_id"]
        if thaw:
            try:
                self._call(split.get("donor_addrs")
                           or self._mp_addrs(name, split["donor_pid"]),
                           "range_thaw",
                           {"pid": split["donor_pid"], "split_id": sid,
                            "lo": split["split_ino"], "hi": split["hi"]})
            except Exception:  # noqa: BLE001
                pass
        pids = drop_pids if drop_pids is not None \
            else split.get("target_pids", [])
        addrs = drop_addrs if drop_addrs is not None \
            else split.get("addrs", [])
        for tp in pids:
            for a in addrs:
                try:
                    self.m.nodes.get(a).call("drop_partition",
                                             {"pid": tp})
                except Exception:  # noqa: BLE001
                    pass
        self.m._commit({"op": "split_abort", "split_id": sid,
                        "name": name, "reason": reason,
                        "op_id": f"{sid}#abort-{reason[:24]}"})
        metrics.meta_range_migration_aborts.inc(
            reason=split.get("kind", "split"))

    def _mp_addrs(self, name: str, pid: int) -> list[str]:
        try:
            mp = self._mp_of(name, pid)
            return mp.get("addrs") or [mp["addr"]]
        except Exception:  # noqa: BLE001
            return []

    def recover(self) -> list[str]:
        """Abort every in-flight migration left by a crashed/deposed
        leader. The replicated ledger is the whole truth: anything in
        it did not commit, so the donor thaws, targets drop, and the
        plan retries from scratch on a later sweep."""
        with self.m._lock:
            pending = {sid: dict(s) for sid, s in self.m.splits.items()}
        for sid, s in pending.items():
            self._abort(s, s.get("name", ""), "leader recovery")
        return sorted(pending)

    # ---------------- detection / balance sweep ----------------
    def _merge_candidates(self, mps: list[dict]) -> list[tuple[int, int]]:
        """(donor_pid, absorber_pid) pairs: a donor that never allocated
        an inode merges left. Conservative on purpose — empty is the
        one coldness signal that cannot misfire under sampling."""
        out = []
        for left, right in zip(mps, mps[1:]):
            if left["end"] != right["start"]:
                continue
            f = self._fill(right)
            if f is not None and f["next_ino"] == right["start"]:
                out.append((right["pid"], left["pid"]))
        return out

    def detect(self) -> list[dict]:
        """Scan every volume for actionable imbalance and publish the
        ``cubefs_meta_partition_imbalance`` gauge (0 == balanced)."""
        m = self.m
        with m._lock:
            vols = {n: sorted((dict(p) for p in v["mps"]),
                              key=lambda p: p["start"])
                    for n, v in m.volumes.items()}
        burn = (slo.DEFAULT_TRACKER.snapshot()
                .get("meta.write", {}).get("burn_rate", 0.0))
        threshold = m.MP_SPLIT_THRESHOLD
        if burn >= HOT_BURN_RATE:
            # the write plane is burning SLO budget: split sooner
            threshold /= 2
        actions = []
        for name, mps in vols.items():
            for mp in mps:
                span = mp["end"] - mp["start"]
                f = self._fill(mp)
                if f is None or span < MIN_SPLIT_SPAN:
                    continue
                frac = (min(f["next_ino"], mp["end"])
                        - mp["start"]) / span
                if frac >= threshold:
                    actions.append({"kind": "split", "name": name,
                                    "pid": mp["pid"],
                                    "fill": round(frac, 4)})
            for donor_pid, absorber_pid in self._merge_candidates(mps):
                actions.append({"kind": "merge", "name": name,
                                "pid": donor_pid,
                                "absorber_pid": absorber_pid})
        self._last_imbalance = len(actions)
        metrics.meta_partition_imbalance.set(len(actions))
        return actions

    def balance(self, max_moves: int = 1, auto: bool = False) -> dict:
        """Rate-limited sweep (the ``sweep_misplaced`` idiom): recover
        abandoned migrations, then perform at most ``max_moves`` of the
        detected actions. ``auto=True`` is the periodic/automatic entry
        and respects the CUBEFS_META_SPLIT door; operator calls do not."""
        if auto and not door_open():
            return {"skipped": "CUBEFS_META_SPLIT door is off",
                    "actions": [], "imbalance": self._last_imbalance}
        recovered = self.recover()
        work = self.detect()
        done, failed = [], []
        for act in work[:max(0, int(max_moves))]:
            try:
                if act["kind"] == "split":
                    res = self.split(act["name"], pid=act["pid"])
                else:
                    res = self.merge(act["name"], donor_pid=act["pid"],
                                     absorber_pid=act["absorber_pid"])
                done.append(dict(act, result=res))
            except Exception as e:  # noqa: BLE001 - sweep must not die
                failed.append(dict(act, error=str(e)))
        remaining = len(work) - len(done)
        self._last_imbalance = remaining
        metrics.meta_partition_imbalance.set(remaining)
        return {"actions": done, "failed": failed,
                "recovered": recovered, "imbalance": remaining}

    def status(self, name: str | None = None) -> dict:
        """Operator view: in-flight ledger + range table + door state."""
        with self.m._lock:
            splits = {sid: dict(s) for sid, s in self.m.splits.items()
                      if name is None or s.get("name") == name}
            vols = {n: {"mp_version": v.get("mp_version", 0),
                        "mps": [{"pid": p["pid"], "start": p["start"],
                                 "end": p["end"]}
                                for p in sorted(v["mps"],
                                                key=lambda p: p["start"])]}
                    for n, v in self.m.volumes.items()
                    if name is None or n == name}
        return {"door": door_open(), "in_flight": splits,
                "volumes": vols, "imbalance": self._last_imbalance}


class _Poisoned(Exception):
    """Delta tap overflowed or saw an un-normalizable record (straddling
    rename, range-touching transaction): the snapshot+delta pair no
    longer reconstructs the donor state, so the migration must abort."""
