"""LcNode: S3 lifecycle rule executor.

Role parity: lcnode/ — scans volume metadata against lifecycle rules
(lc_scanner.go) and applies expiration actions; the reference also
transitions storage classes (lc_transition.go), which here delegates to
fs/tiering.py's TieringEngine: a crash-safe two-phase migration state
machine instead of the old read->put->truncate sequence (which could
lose bytes if the node died between the put and the truncate, and
rescanned empty files forever).

A scan pass now does four jobs:
  1. resume any migration a previous (crashed) run left mid-flight
     (tiering.state xattr present) — roll forward or roll back,
  2. start new transitions / expirations per the rules,
  3. promote re-heated cold files back to hot extents,
  4. reap orphaned blobs off the metanode's deferred blob freelist.

Time is injected (utils/retry.py Clock protocol) so lifecycle aging is
testable on a FakeClock without sleeping; the default is wall time
because rule age math compares against inode mtimes, which are epoch
stamps.
"""

from __future__ import annotations

import fnmatch  # noqa: F401  (rule prefixes may grow glob support)
import logging
import threading
import time
from dataclasses import dataclass, field

from ..utils import faultinject, metrics
from ..utils.retry import Clock
from . import metanode as mn
from .client import FileSystem, FsError
from .tiering import TieringEngine

log = logging.getLogger("cubefs.lcnode")


class _WallClock(Clock):
    """Epoch-time clock: lifecycle ages are computed against inode
    mtimes (time.time() stamps), so the scheduler clock must share
    their origin — unlike utils.retry.MONOTONIC."""

    def now(self) -> float:
        return time.time()


WALL = _WallClock()


@dataclass
class LifecycleRule:
    rule_id: str
    prefix: str = ""  # path prefix, e.g. "/logs/"
    expire_after_s: float | None = None  # delete when mtime older
    transition_after_s: float | None = None  # move payload to blob plane
    enabled: bool = True


@dataclass
class ScanReport:
    scanned: int = 0
    expired: int = 0
    transitioned: int = 0
    resumed: int = 0  # half-done migrations rolled forward/back
    untiered: int = 0  # cold files promoted back to hot
    reaped: int = 0  # orphan blobs deleted off the deferred freelist
    errors: list = field(default_factory=list)


class LcNode:
    def __init__(self, fs: FileSystem, blob_access=None, *,
                 clock: Clock | None = None,
                 engine: TieringEngine | None = None,
                 codemode: int | None = None):
        self.fs = fs
        self.clock = clock or WALL
        if engine is None and blob_access is not None:
            engine = TieringEngine(fs, blob_access, codemode=codemode)
        self.engine = engine
        self.rules: list[LifecycleRule] = []
        self._stop = threading.Event()

    def set_rules(self, rules: list[LifecycleRule]) -> None:
        self.rules = list(rules)

    def load_rules_from_bucket(self) -> int:
        """Adopt the bucket's S3 LifecycleConfiguration (stored by the
        gateway as the s3.lifecycle xattr on the volume root) — the
        master/lifecycle_manager.go -> lcnode task flow, compacted:
        the executor reads the volume's own config. Returns rule count."""
        import json

        from . import s3policy

        try:
            raw = self.fs.getxattr("/", s3policy.XA_LIFECYCLE)
        except FsError:
            raw = None
        if not raw:
            self.rules = []
            return 0
        day = 86400.0
        rules = []
        for r in json.loads(raw):
            rules.append(LifecycleRule(
                rule_id=r["id"],
                prefix="/" + r.get("prefix", "").lstrip("/"),
                expire_after_s=(r["expire_days"] * day
                                if r.get("expire_days") is not None else None),
                transition_after_s=(r["transition_days"] * day
                                    if r.get("transition_days") is not None
                                    else None),
                enabled=r.get("status", "Enabled") == "Enabled",
            ))
        self.rules = rules
        return len(rules)

    def scan_once(self) -> ScanReport:
        report = ScanReport()
        now = self.clock.now()
        self._walk("/", mn.ROOT_INO, now, report)
        if self.engine is not None:
            for ino in self.engine.hot_candidates():
                try:
                    if self.engine.untier(ino) == "promoted":
                        report.untiered += 1
                except FsError as e:
                    report.errors.append((f"ino:{ino}", str(e)))
            report.reaped = self.engine.reap_orphans()
        return report

    def _walk(self, path: str, ino: int, now: float,
              report: ScanReport) -> None:
        try:
            entries = self.fs.meta.readdir(ino)
        except FsError:
            return
        for name, child in sorted(entries.items()):
            cpath = f"{path.rstrip('/')}/{name}"
            try:
                inode = self.fs.meta.inode_get(child)
            except FsError:
                continue
            if inode["type"] == mn.DIR:
                self._walk(cpath, child, now, report)
                continue
            report.scanned += 1
            self._apply_rules(cpath, child, inode, now, report)
        return

    def _apply_rules(self, cpath: str, child: int, inode: dict,
                     now: float, report: ScanReport) -> None:
        if (self.engine is not None
                and inode["xattr"].get("tiering.state") is not None):
            # a previous run died mid-migration: recover FIRST,
            # regardless of rule matching or age
            try:
                out = self.engine.migrate(child)
            except FsError as e:
                report.errors.append((cpath, str(e)))
            else:
                report.resumed += 1
                if out == "resumed":
                    report.transitioned += 1
            return
        for rule in self.rules:
            if not rule.enabled or not cpath.startswith(rule.prefix):
                continue
            age = now - inode["mtime"]
            try:
                if (rule.expire_after_s is not None
                        and age > rule.expire_after_s):
                    self.fs.unlink(cpath)
                    report.expired += 1
                    break
                if (rule.transition_after_s is not None
                        and age > rule.transition_after_s
                        and self.engine is not None
                        and not inode["xattr"].get("cold.location")):
                    if self.engine.migrate(child) == "migrated":
                        report.transitioned += 1
                    break
            except FsError as e:
                report.errors.append((cpath, str(e)))

    def read_through(self, path: str) -> bytes:
        """Read a possibly-cold file: hot extents if present, else fetch
        from the blob plane via the pinned location."""
        inode = self.fs.meta.inode_get(self.fs.resolve(path))
        if inode["extents"]:
            return self.fs.data.read(inode, 0, inode["size"])
        if (self.engine is not None
                and inode["xattr"].get("cold.location")):
            return self.engine.read_cold(inode, 0, inode["size"])
        return b""

    def start(self, interval: float = 60.0) -> None:
        def loop():
            while not self._stop.wait(interval):
                try:
                    self.scan_once()
                except faultinject.InjectedCrash:
                    raise  # a drill kill takes the whole node down
                except Exception:
                    # a broken scan must not silently kill the
                    # lifecycle loop: count it, log it, keep scanning
                    metrics.lc_scan_errors.inc()
                    log.exception("lifecycle scan failed; will retry")

        threading.Thread(target=loop, daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
