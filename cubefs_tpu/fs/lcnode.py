"""LcNode: S3 lifecycle rule executor.

Role parity: lcnode/ — scans volume metadata against lifecycle rules
(lc_scanner.go) and applies expiration actions; the reference also
transitions storage classes (lc_transition.go), which here maps to
re-writing a file's payload into the EC blob plane (cold tier) and
recording the blob location in an xattr.
"""

from __future__ import annotations

import fnmatch
import threading
import time
from dataclasses import dataclass, field

from . import metanode as mn
from .client import FileSystem, FsError


@dataclass
class LifecycleRule:
    rule_id: str
    prefix: str = ""  # path prefix, e.g. "/logs/"
    expire_after_s: float | None = None  # delete when mtime older
    transition_after_s: float | None = None  # move payload to blob plane
    enabled: bool = True


@dataclass
class ScanReport:
    scanned: int = 0
    expired: int = 0
    transitioned: int = 0
    errors: list = field(default_factory=list)


class LcNode:
    def __init__(self, fs: FileSystem, blob_access=None):
        self.fs = fs
        self.blob = blob_access  # AccessHandler-compatible (cold tier)
        self.rules: list[LifecycleRule] = []
        self._stop = threading.Event()

    def set_rules(self, rules: list[LifecycleRule]) -> None:
        self.rules = list(rules)

    def load_rules_from_bucket(self) -> int:
        """Adopt the bucket's S3 LifecycleConfiguration (stored by the
        gateway as the s3.lifecycle xattr on the volume root) — the
        master/lifecycle_manager.go -> lcnode task flow, compacted:
        the executor reads the volume's own config. Returns rule count."""
        import json

        from . import s3policy

        try:
            raw = self.fs.getxattr("/", s3policy.XA_LIFECYCLE)
        except FsError:
            raw = None
        if not raw:
            self.rules = []
            return 0
        day = 86400.0
        rules = []
        for r in json.loads(raw):
            rules.append(LifecycleRule(
                rule_id=r["id"],
                prefix="/" + r.get("prefix", "").lstrip("/"),
                expire_after_s=(r["expire_days"] * day
                                if r.get("expire_days") is not None else None),
                transition_after_s=(r["transition_days"] * day
                                    if r.get("transition_days") is not None
                                    else None),
                enabled=r.get("status", "Enabled") == "Enabled",
            ))
        self.rules = rules
        return len(rules)

    def scan_once(self) -> ScanReport:
        report = ScanReport()
        now = time.time()
        self._walk("/", mn.ROOT_INO, now, report)
        return report

    def _walk(self, path: str, ino: int, now: float, report: ScanReport) -> None:
        try:
            entries = self.fs.meta.readdir(ino)
        except FsError:
            return
        for name, child in sorted(entries.items()):
            cpath = f"{path.rstrip('/')}/{name}"
            try:
                inode = self.fs.meta.inode_get(child)
            except FsError:
                continue
            if inode["type"] == mn.DIR:
                self._walk(cpath, child, now, report)
                continue
            report.scanned += 1
            for rule in self.rules:
                if not rule.enabled or not cpath.startswith(rule.prefix):
                    continue
                age = now - inode["mtime"]
                try:
                    if rule.expire_after_s is not None and age > rule.expire_after_s:
                        self.fs.unlink(cpath)
                        report.expired += 1
                        break
                    if (rule.transition_after_s is not None
                            and age > rule.transition_after_s
                            and self.blob is not None
                            and not inode["xattr"].get("cold.location")):
                        self._transition(cpath, inode, report)
                        break
                except FsError as e:
                    report.errors.append((cpath, str(e)))
        return

    def _transition(self, path: str, inode: dict, report: ScanReport) -> None:
        """Cold-tier transition: payload moves to the EC blob plane; the
        hot-tier extents are released and the location pinned in xattr
        (the FS<->blob bridge, sdk/data/blobstore writer role)."""
        data = self.fs.read_file(path)
        loc = self.blob.put(data) if data else None
        if loc is not None:
            self.fs.meta.set_xattr(inode["ino"], "cold.location",
                                   __import__("json").dumps(loc.to_dict()))
            self.fs.meta.truncate(inode["ino"], 0)
            self.fs.meta.set_attr(inode["ino"], size=len(data))
            # hot extents ride the metanode freelist (deferred deletion)
            report.transitioned += 1

    def read_through(self, path: str) -> bytes:
        """Read a possibly-cold file: hot extents if present, else fetch
        from the blob plane via the pinned location."""
        inode = self.fs.meta.inode_get(self.fs.resolve(path))
        if inode["extents"]:
            return self.fs.data.read(inode, 0, inode["size"])
        cold = inode["xattr"].get("cold.location")
        if cold:
            from ..blob.types import Location

            return self.blob.get(Location.from_dict(__import__("json").loads(cold)))
        return b""

    def start(self, interval: float = 60.0) -> None:
        def loop():
            while not self._stop.wait(interval):
                try:
                    self.scan_once()
                except Exception:
                    pass

        threading.Thread(target=loop, daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
