"""S3 access-audit sinks: webhook and durable-queue fan-out.

Role parity: objectnode/audit_webhook.go (async batched HTTP POST of
audit entries to an operator endpoint) and audit_kafka.go (audit events
onto the message bus). The queue sink rides the framework's durable
jsonl MessageQueue — the same Kafka-replacement the blob plane's
repair/delete events use — so downstream consumers get at-least-once
delivery with offsets.

Sinks are fire-and-forget from the request path: the gateway never
blocks on (or fails because of) an audit destination; overflow is
counted and dropped, mirroring the reference's bounded async channel.
"""

from __future__ import annotations

import json
import queue
import threading
import urllib.request

from ..utils import metrics

audit_events = metrics.DEFAULT.counter(
    "cubefs_s3_audit_events_total", "S3 audit events emitted", ("sink",))
audit_dropped = metrics.DEFAULT.counter(
    "cubefs_s3_audit_dropped_total", "S3 audit events dropped", ("sink",))


class WebhookAuditSink:
    """Async batched POST of audit events to an HTTP endpoint
    (audit_webhook.go): a background worker drains a bounded queue and
    ships JSON-array batches; a slow/dead endpoint drops events (with a
    counter), never backpressures the gateway."""

    def __init__(self, url: str, max_queue: int = 4096,
                 batch_size: int = 64, timeout: float = 5.0):
        self.url = url
        self.batch_size = batch_size
        self.timeout = timeout
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def emit(self, event: dict) -> None:
        try:
            self._q.put_nowait(event)
            audit_events.inc(sink="webhook")
        except queue.Full:
            audit_dropped.inc(sink="webhook")

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            batch = [first]
            while len(batch) < self.batch_size:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
            self._post(batch)

    def _post(self, batch: list[dict]) -> None:
        try:
            req = urllib.request.Request(
                self.url, data=json.dumps(batch).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            urllib.request.urlopen(req, timeout=self.timeout).close()
        except Exception:
            audit_dropped.inc(sink="webhook", value=len(batch))

    def close(self) -> None:
        """Graceful shutdown: flush buffered events (one final batch
        round) before stopping — a clean stop must not silently lose
        audit records."""
        self._stop.set()
        self._thread.join(timeout=2)
        pending = []
        while True:
            try:
                pending.append(self._q.get_nowait())
            except queue.Empty:
                break
        for i in range(0, len(pending), self.batch_size):
            self._post(pending[i:i + self.batch_size])


class QueueAuditSink:
    """Audit events onto a durable MessageQueue topic (audit_kafka.go
    analog): consumers poll/ack with at-least-once semantics."""

    def __init__(self, mq):
        self.mq = mq

    def emit(self, event: dict) -> None:
        try:
            self.mq.put(event)
            audit_events.inc(sink="queue")
        except Exception:
            audit_dropped.inc(sink="queue")

    def close(self) -> None:
        pass
