"""S3 interop edges: streaming chunked signatures, POST policy uploads,
and a minimal STS surface.

Role parity: objectnode/auth_signature_chunk.go (aws-chunked payload
signing — real AWS SDKs send `STREAMING-AWS4-HMAC-SHA256-PAYLOAD` on
large PUTs), objectnode/post_policy.go (browser form uploads) and
objectnode/sts.go (temporary credentials). Everything is stdlib crypto;
the SigV4 key chain comes from s3auth.signing_key.
"""

from __future__ import annotations

import base64
import calendar
import hashlib
import hmac
import json
import secrets
import time

from . import s3auth

STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"
EMPTY_SHA = hashlib.sha256(b"").hexdigest()


# ---------------- aws-chunked payload signing -------------------------

def _chunk_string_to_sign(amz_date: str, scope: str, prev_sig: str,
                          data: bytes) -> str:
    return "\n".join([
        "AWS4-HMAC-SHA256-PAYLOAD", amz_date, scope, prev_sig,
        EMPTY_SHA, hashlib.sha256(data).hexdigest(),
    ])


def _iter_chunks(body: bytes):
    """THE aws-chunked framing parser (one parser, two consumers): yield
    (data, signature) per chunk including the final empty one; raise
    ValueError on malformed framing."""
    pos = 0
    while True:
        nl = body.find(b"\r\n", pos)
        if nl < 0:
            raise ValueError("truncated chunk header")
        head = body[pos:nl].decode("ascii", "replace")
        size_s, _, ext = head.partition(";")
        try:
            size = int(size_s, 16)
        except ValueError:
            raise ValueError(f"bad chunk size {size_s!r}") from None
        sig = (ext[len("chunk-signature="):]
               if ext.startswith("chunk-signature=") else "")
        data = body[nl + 2:nl + 2 + size]
        if len(data) != size:
            raise ValueError("truncated chunk data")
        yield data, sig
        pos = nl + 2 + size
        if size == 0:
            return  # final chunk; anything after is optional trailers
        if body[pos:pos + 2] != b"\r\n":
            raise ValueError("missing chunk CRLF")
        pos += 2


def verify_aws_chunked(body: bytes, seed_sig: str, key: bytes,
                       amz_date: str, scope: str) -> tuple[bool, bytes | str]:
    """Decode aws-chunked framing (`<hex-size>;chunk-signature=<sig>\\r\\n
    <data>\\r\\n` … `0;chunk-signature=<sig>\\r\\n\\r\\n`), verifying each
    chunk's signature chains from the previous (seed = the Authorization
    header's signature). Returns (True, decoded_payload) or
    (False, reason) — a single forged/reordered/substituted chunk breaks
    the chain."""
    out = bytearray()
    prev = seed_sig
    try:
        for data, sig in _iter_chunks(body):
            if not sig:
                return False, "missing chunk-signature"
            expect = hmac.new(
                key,
                _chunk_string_to_sign(amz_date, scope, prev, data).encode(),
                hashlib.sha256).hexdigest()
            if not hmac.compare_digest(expect, sig):
                return False, "chunk signature mismatch"
            prev = expect
            out.extend(data)
    except ValueError as e:
        return False, str(e)
    return True, bytes(out)


def strip_aws_chunked(body: bytes) -> bytes:
    """Framing removal WITHOUT verification — for gateways running with
    no authenticator, where there is no key to verify against.
    Best-effort: malformed framing yields the chunks parsed so far."""
    out = bytearray()
    try:
        for data, _sig in _iter_chunks(body):
            out.extend(data)
    except ValueError:
        pass
    return bytes(out)


def build_aws_chunked(payload: bytes, chunk_size: int, seed_sig: str,
                      key: bytes, amz_date: str, scope: str) -> bytes:
    """Client-side encoder (tests/CLI): produce the exact on-the-wire
    body an AWS SDK sends for a streaming-signed PUT."""
    out = bytearray()
    prev = seed_sig
    chunks = [payload[i:i + chunk_size]
              for i in range(0, len(payload), chunk_size)] + [b""]
    for data in chunks:
        sig = hmac.new(
            key, _chunk_string_to_sign(amz_date, scope, prev, data).encode(),
            hashlib.sha256).hexdigest()
        out.extend(f"{len(data):x};chunk-signature={sig}\r\n".encode())
        out.extend(data)
        if data:
            out.extend(b"\r\n")
        prev = sig
    out.extend(b"\r\n")
    return bytes(out)


# ---------------- POST policy uploads ---------------------------------

def parse_multipart(body: bytes, content_type: str) -> dict[str, bytes]:
    """Minimal multipart/form-data parser: field name -> raw value (the
    `file` part keeps its bytes)."""
    b_idx = content_type.find("boundary=")
    if b_idx < 0:
        return {}
    boundary = content_type[b_idx + 9:].split(";")[0].strip().strip('"')
    delim = b"--" + boundary.encode()
    fields: dict[str, bytes] = {}
    # split()[1:] skips the preamble; a part is "\r\n<headers>\r\n\r\n
    # <value>\r\n" — strip EXACTLY the framing CRLFs, never the value's
    # own trailing newline bytes (an unbounded strip would silently
    # truncate uploads ending in \r or \n)
    for part in body.split(delim)[1:]:
        if part.startswith(b"--"):
            break  # closing boundary
        if part.startswith(b"\r\n"):
            part = part[2:]
        if part.endswith(b"\r\n"):
            part = part[:-2]
        head, _, value = part.partition(b"\r\n\r\n")
        name = filename = None
        for line in head.split(b"\r\n"):
            lo = line.decode("latin1")
            if lo.lower().startswith("content-disposition:"):
                for item in lo.split(";"):
                    item = item.strip()
                    if item.startswith("name="):
                        name = item[5:].strip('"')
                    elif item.startswith("filename="):
                        filename = item[9:].strip('"')
        if name:
            fields[name] = value
            if filename is not None:
                # reserved dotted key: S3's ${filename} substitution
                # needs the upload part's client-supplied filename
                fields[f".filename.{name}"] = filename.encode()
    return fields


def _parse_iso8601(s: str) -> float | None:
    for fmt in ("%Y-%m-%dT%H:%M:%S.%fZ", "%Y-%m-%dT%H:%M:%SZ"):
        try:
            return calendar.timegm(time.strptime(s, fmt))
        except ValueError:
            continue
    return None


def verify_post_policy(fields: dict[str, bytes], secret_for,
                       now: float | None = None,
                       implicit: dict[str, str] | None = None
                       ) -> tuple[bool, str]:
    """Verify a browser POST upload form (post_policy.go): the signature
    is the SigV4 chain applied to the base64 policy document; the policy
    must be unexpired and every condition must hold against the form.
    `implicit` supplies request-derived values that are not form fields
    (S3's `bucket` condition matches the URL's bucket). Returns
    (True, access_key) or (False, reason)."""
    try:
        policy_b64 = fields["policy"].decode()
        cred = fields["x-amz-credential"].decode()
        amz_date = fields["x-amz-date"].decode()
        sig = fields["x-amz-signature"].decode()
        algo = fields.get("x-amz-algorithm", b"").decode()
    except KeyError as e:
        return False, f"missing form field {e}"
    if algo != "AWS4-HMAC-SHA256":
        return False, "unsupported x-amz-algorithm"
    try:
        ak, date, region, service, _term = cred.split("/", 4)
    except ValueError:
        return False, "malformed x-amz-credential"
    sk = secret_for(ak)
    if sk is None:
        return False, f"unknown access key {ak}"
    key = s3auth.signing_key(sk, date, region, service)
    expect = hmac.new(key, policy_b64.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(expect, sig):
        return False, "policy signature mismatch"
    try:
        policy = json.loads(base64.b64decode(policy_b64))
    except (ValueError, json.JSONDecodeError):
        return False, "malformed policy document"
    exp = _parse_iso8601(policy.get("expiration", ""))
    if exp is None:
        return False, "policy has no valid expiration"
    if (time.time() if now is None else now) > exp:
        return False, "policy expired"
    try:
        for cond in policy.get("conditions", []):
            if isinstance(cond, dict):
                items = [("eq", k, v) for k, v in cond.items()]
            elif isinstance(cond, list) and len(cond) == 3:
                items = [tuple(cond)]
            else:
                return False, f"malformed condition {cond!r}"
            for op, k, v in items:
                if op == "content-length-range":
                    n = len(fields.get("file", b""))
                    if not (int(k) <= n <= int(v)):
                        return False, "content-length-range violated"
                    continue
                name = str(k).lstrip("$").lower()
                if implicit and name in implicit:
                    got = implicit[name]
                else:
                    got = fields.get(name, b"").decode("utf-8", "replace")
                if op == "eq":
                    if got != v:
                        return False, f"condition eq failed for {name}"
                elif op == "starts-with":
                    if not got.startswith(v):
                        return False, f"condition starts-with failed for {name}"
                else:
                    return False, f"unsupported condition op {op!r}"
    except (TypeError, ValueError):
        # a correctly-signed but malformed policy (non-numeric range
        # bounds, non-string values) is a REJECTION, not a crashed
        # handler thread
        return False, "malformed condition value"
    return True, ak


# ---------------- STS (temporary credentials) -------------------------

class Sts:
    """Stateless temporary-credential issuer (sts.go role): the session
    token IS the state — a MAC'd claim of (parent key, temp key, expiry)
    — and the temp secret is derived from the server key, so any gateway
    holding the same Sts key can validate without shared storage."""

    MAX_DURATION = 12 * 3600

    def __init__(self, key: bytes | None = None):
        self.key = key or secrets.token_bytes(32)

    def _temp_sk(self, tak: str, exp: int) -> str:
        return hmac.new(self.key, f"sk|{tak}|{exp}".encode(),
                        hashlib.sha256).hexdigest()[:40]

    def issue(self, parent_ak: str, duration: int = 3600,
              now: float | None = None) -> dict:
        exp = int((time.time() if now is None else now)
                  + max(900, min(duration, self.MAX_DURATION)))
        tak = "ASIA" + secrets.token_hex(8).upper()
        payload = json.dumps({"pak": parent_ak, "tak": tak, "exp": exp},
                             sort_keys=True).encode()
        mac = hmac.new(self.key, payload, hashlib.sha256).digest()
        return {
            "access_key": tak,
            "secret_key": self._temp_sk(tak, exp),
            "session_token": base64.b64encode(payload + mac).decode(),
            "expiration": exp,
        }

    def resolve(self, token: str, now: float | None = None) -> dict | None:
        """Validate a session token; returns {"pak","tak","sk","exp"} or
        None (invalid/expired)."""
        try:
            raw = base64.b64decode(token)
            payload, mac = raw[:-32], raw[-32:]
        except (ValueError, IndexError):
            return None
        if len(raw) <= 32 or not hmac.compare_digest(
                mac, hmac.new(self.key, payload, hashlib.sha256).digest()):
            return None
        try:
            claims = json.loads(payload)
        except json.JSONDecodeError:
            return None
        if claims.get("exp", 0) < (time.time() if now is None else now):
            return None
        return {"pak": claims["pak"], "tak": claims["tak"],
                "sk": self._temp_sk(claims["tak"], claims["exp"]),
                "exp": claims["exp"]}
