"""FUSE client: POSIX access via the kernel, speaking the /dev/fuse ABI.

Role parity: client/ (the cfs-client FUSE daemon: mount at
client/fuse.go:885 via a forked bazil/fuse, VFS impl under client/fs/).
The reference leans on a vendored Go FUSE library; here the kernel wire
protocol (FUSE_INIT handshake + request/reply framing + the core opcode
set) is implemented directly on the raw device fd — no libfuse — and
dispatches into the FileSystem facade (cubefs_tpu/fs/client.py), so
`ls`, `cat`, `cp`, `mkdir` on the mountpoint hit metanode/datanode like
any other client.

Requires root (direct mount(2) via ctypes) or fusermount. Linux only.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import os
import stat as stat_mod
import struct
import threading

from ..utils import lockwitness
import time

from . import metanode as mn
from .client import FileSystem, FsError

# ---- fuse kernel ABI constants ----
FUSE_KERNEL_VERSION = 7
FUSE_KERNEL_MINOR = 31

(FUSE_LOOKUP, FUSE_FORGET, FUSE_GETATTR, FUSE_SETATTR) = (1, 2, 3, 4)
FUSE_READLINK, FUSE_SYMLINK = 5, 6
FUSE_MKDIR, FUSE_UNLINK, FUSE_RMDIR, FUSE_RENAME = 9, 10, 11, 12
FUSE_LINK = 13
FUSE_OPEN, FUSE_READ, FUSE_WRITE, FUSE_STATFS, FUSE_RELEASE = 14, 15, 16, 17, 18
FUSE_FSYNC, FUSE_SETXATTR, FUSE_GETXATTR, FUSE_FLUSH = 20, 21, 22, 25
FUSE_LISTXATTR, FUSE_REMOVEXATTR = 23, 24
FUSE_RENAME2 = 45
RENAME_NOREPLACE = 1  # renameat2(2) flag
FUSE_INIT, FUSE_OPENDIR, FUSE_READDIR, FUSE_RELEASEDIR = 26, 27, 28, 29
FUSE_ACCESS, FUSE_CREATE = 34, 35
FUSE_DESTROY = 38

_IN_HDR = struct.Struct("<IIQQIIII")  # len opcode unique nodeid uid gid pid pad
_OUT_HDR = struct.Struct("<IiQ")  # len error unique
_ATTR = struct.Struct("<QQQQQQIIIIIIIIII")  # ino size blocks a/m/ctime + nsec*3 mode nlink uid gid rdev blksize flags
_ENTRY_OUT = struct.Struct("<QQQQII")  # nodeid generation entry_valid attr_valid nsecs


def _attr_bytes(inode: dict) -> bytes:
    mode = inode["mode"]
    if inode["type"] == mn.DIR:
        mode |= stat_mod.S_IFDIR
    elif inode["type"] == mn.SYMLINK:
        mode |= stat_mod.S_IFLNK
    else:
        mode |= stat_mod.S_IFREG
    size = inode["size"]
    t = lambda x: int(x)
    return _ATTR.pack(
        inode["ino"], size, (size + 511) // 512,
        t(inode["atime"]), t(inode["mtime"]), t(inode["ctime"]),
        0, 0, 0, mode, inode["nlink"], inode["uid"], inode["gid"], 0, 4096, 0,
    )


class FuseMount:
    """One mounted volume; a daemon thread serves kernel requests."""

    def __init__(self, fs: FileSystem, mountpoint: str):
        self.fs = fs
        self.mnt = os.path.abspath(mountpoint)
        self.fd = -1
        self._thread: threading.Thread | None = None
        self._write_buffers: dict[int, int] = {}  # fh -> ino (open handles)
        self._next_fh = 1
        self._lock = lockwitness.make_lock("FuseMount._lock")

    # ---------------- mount / unmount ----------------
    def mount(self) -> "FuseMount":
        os.makedirs(self.mnt, exist_ok=True)
        self.fd = os.open("/dev/fuse", os.O_RDWR)
        opts = (f"fd={self.fd},rootmode=40755,user_id=0,group_id=0,"
                f"allow_other,default_permissions")
        libc = ctypes.CDLL(ctypes.util.find_library("c"), use_errno=True)
        rc = libc.mount(b"cubefs-tpu", self.mnt.encode(), b"fuse.cubefs-tpu",
                        0, opts.encode())
        if rc != 0:
            e = ctypes.get_errno()
            os.close(self.fd)
            raise OSError(e, f"mount(2) failed: {os.strerror(e)}")
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def unmount(self) -> None:
        libc = ctypes.CDLL(ctypes.util.find_library("c"), use_errno=True)
        libc.umount2(self.mnt.encode(), 2)  # MNT_DETACH
        try:
            os.close(self.fd)
        except OSError:
            pass

    MAX_WRITE = 1 << 20
    # the kernel EINVALs reads whose buffer is smaller than max_write
    # plus the request headers — pad generously
    READ_BUF = MAX_WRITE + (1 << 16)

    # ---------------- serve loop ----------------
    def _serve(self) -> None:
        while True:
            try:
                req = os.read(self.fd, self.READ_BUF)
            except OSError:
                return  # unmounted
            if not req:
                return
            try:
                self._dispatch(req)
            except Exception:
                hdr = _IN_HDR.unpack_from(req)
                self._reply_err(hdr[2], errno.EIO)

    def _reply(self, unique: int, payload: bytes = b"") -> None:
        out = _OUT_HDR.pack(_OUT_HDR.size + len(payload), 0, unique) + payload
        try:
            os.write(self.fd, out)
        except OSError:
            pass

    def _reply_err(self, unique: int, err: int) -> None:
        try:
            os.write(self.fd, _OUT_HDR.pack(_OUT_HDR.size, -err, unique))
        except OSError:
            pass

    def _entry_reply(self, unique: int, inode: dict) -> None:
        payload = _ENTRY_OUT.pack(inode["ino"], 0, 1, 1, 0, 0) + _attr_bytes(inode)
        self._reply(unique, payload)

    # ---------------- dispatch ----------------
    def _dispatch(self, req: bytes) -> None:
        (length, opcode, unique, nodeid, uid, gid, pid, _) = _IN_HDR.unpack_from(req)
        body = req[_IN_HDR.size : length]
        fs = self.fs

        if opcode == FUSE_INIT:
            major, minor = struct.unpack_from("<II", body)
            minor = min(minor, FUSE_KERNEL_MINOR)
            # fuse_init_out (7.23+ layout, zero-extended)
            out = struct.pack(
                "<IIIIHHIIHHI28x",
                FUSE_KERNEL_VERSION, minor,
                0,  # max_readahead
                0,  # flags
                0, 0,  # max_background, congestion_threshold
                self.MAX_WRITE,  # max_write
                1,  # time_gran
                256, 0, 0,  # max_pages, map_alignment, flags2
            )
            self._reply(unique, out)
            return

        if opcode in (FUSE_FORGET, FUSE_DESTROY):
            return  # no reply

        if opcode == FUSE_STATFS:
            # fuse_kstatfs: blocks bfree bavail files ffree bsize namelen frsize pad
            out = struct.pack("<QQQQQIIII4x", 1 << 30, 1 << 29, 1 << 29,
                              1 << 20, 1 << 19, 4096, 255, 4096, 0)
            self._reply(unique, out)
            return

        try:
            self._dispatch_fs(opcode, unique, nodeid, body, fs)
        except FsError as e:
            self._reply_err(unique, e.errno if 0 < e.errno < 130 else errno.EIO)

    def _dispatch_fs(self, opcode, unique, nodeid, body, fs: FileSystem) -> None:
        if opcode == FUSE_LOOKUP:
            name = body.split(b"\x00", 1)[0].decode()
            ino = fs.meta.lookup(nodeid, name)
            self._entry_reply(unique, fs.meta.inode_get(ino))

        elif opcode == FUSE_GETATTR:
            inode = fs.meta.inode_get(nodeid)
            payload = struct.pack("<QII", 1, 0, 0) + _attr_bytes(inode)
            self._reply(unique, payload)

        elif opcode == FUSE_SETATTR:
            # fuse_setattr_in: valid, pad, fh, size, lock_owner,
            # a/m/ctime (+nsecs), then mode at offset 68
            valid, _pad, fh, size = struct.unpack_from("<IIQQ", body)
            attrs = {}
            if valid & (1 << 3):  # FATTR_SIZE
                # EVERY size change rides the real truncate op: a bare
                # size attr leaves stale extents, and a later extend
                # resurrects pre-truncate bytes instead of zeros (POSIX
                # violation caught by tests/conformance/test_posix_ltp)
                fs.meta.truncate(nodeid, size)
                fs.data.close_stream(nodeid)
                # freed extents ride the metanode freelist
            if valid & (1 << 0):  # FATTR_MODE
                mode = struct.unpack_from("<I", body, 68)[0]
                attrs["mode"] = mode & 0o7777
            if attrs:
                fs.meta.set_attr(nodeid, **attrs)
            inode = fs.meta.inode_get(nodeid)
            self._reply(unique, struct.pack("<QII", 1, 0, 0) + _attr_bytes(inode))

        elif opcode in (FUSE_OPEN, FUSE_OPENDIR):
            with self._lock:
                fh = self._next_fh
                self._next_fh += 1
            self._reply(unique, struct.pack("<QII", fh, 0, 0))

        elif opcode in (FUSE_RELEASE, FUSE_RELEASEDIR, FUSE_FLUSH, FUSE_FSYNC,
                        FUSE_ACCESS):
            if opcode == FUSE_RELEASE:
                fs.data.close_stream(nodeid)
            self._reply(unique)

        elif opcode == FUSE_READDIR:
            fh, offset, size, *_ = struct.unpack_from("<QQI", body)
            entries = sorted(fs.meta.readdir(nodeid).items())
            listing = [(".", nodeid, stat_mod.S_IFDIR), ("..", nodeid, stat_mod.S_IFDIR)]
            for name, ino in entries:
                typ = fs.meta.inode_get(ino)["type"]
                mode = stat_mod.S_IFDIR if typ == mn.DIR else stat_mod.S_IFREG
                listing.append((name, ino, mode))
            out = bytearray()
            for i, (name, ino, mode) in enumerate(listing):
                if i < offset:
                    continue
                nb = name.encode()
                ent = struct.pack("<QQII", ino, i + 1, len(nb), mode >> 12) + nb
                ent += b"\x00" * ((8 - len(ent) % 8) % 8)
                if len(out) + len(ent) > size:
                    break
                out += ent
            self._reply(unique, bytes(out))

        elif opcode == FUSE_READ:
            fh, offset, size, *_ = struct.unpack_from("<QQI", body)
            inode = fs.meta.inode_get(nodeid)
            self._reply(unique, fs.data.read(inode, offset, size))

        elif opcode == FUSE_WRITE:
            fh, offset, size, flags = struct.unpack_from("<QQII", body)
            # write payload follows the fuse_write_in struct (40 bytes)
            data = body[40 : 40 + size]
            fs.data.write(fs.meta, nodeid, offset, data)
            self._reply(unique, struct.pack("<II", len(data), 0))

        elif opcode == FUSE_CREATE:
            flags, mode, umask, _pad = struct.unpack_from("<IIII", body)
            name = body[16:].split(b"\x00", 1)[0].decode()
            inode = fs.meta.inode_create(mn.FILE, mode & 0o7777)
            try:
                fs.meta.dentry_create(nodeid, name, inode["ino"])
            except FsError:
                fs.meta.inode_delete(inode["ino"])
                raise
            with self._lock:
                fh = self._next_fh
                self._next_fh += 1
            payload = (_ENTRY_OUT.pack(inode["ino"], 0, 1, 1, 0, 0)
                       + _attr_bytes(inode)
                       + struct.pack("<QII", fh, 0, 0))
            self._reply(unique, payload)

        elif opcode == FUSE_MKDIR:
            mode, umask = struct.unpack_from("<II", body)
            name = body[8:].split(b"\x00", 1)[0].decode()
            inode = fs.meta.inode_create(mn.DIR, mode & 0o7777)
            try:
                fs.meta.dentry_create(nodeid, name, inode["ino"])
            except FsError:
                fs.meta.inode_delete(inode["ino"])
                raise
            self._entry_reply(unique, inode)

        elif opcode == FUSE_SYMLINK:
            name, target = body.split(b"\x00")[:2]
            inode = fs.meta.inode_create(mn.SYMLINK, 0o777,
                                         target=target.decode())
            try:
                fs.meta.dentry_create(nodeid, name.decode(), inode["ino"])
            except FsError:
                fs.meta.inode_delete(inode["ino"])
                raise
            self._entry_reply(unique, inode)

        elif opcode == FUSE_READLINK:
            inode = fs.meta.inode_get(nodeid)
            if inode["type"] != mn.SYMLINK or not inode.get("target"):
                self._reply_err(unique, errno.EINVAL)
            else:
                self._reply(unique, inode["target"].encode())

        elif opcode in (FUSE_UNLINK, FUSE_RMDIR):
            name = body.split(b"\x00", 1)[0].decode()
            ino = fs.meta.lookup(nodeid, name)
            inode = fs.meta.inode_get(ino)
            if opcode == FUSE_RMDIR and fs.meta.dentry_count(ino) > 0:
                raise FsError(mn.ENOTEMPTY, "directory not empty")
            fs.meta.dentry_delete(nodeid, name)
            # last link removes the inode (extents ride the freelist);
            # other hardlinks keep it alive
            if fs.meta.dec_nlink(ino):
                fs.data.close_stream(ino)
            self._reply(unique)

        elif opcode == FUSE_LINK:
            (old_ino,) = struct.unpack_from("<Q", body)
            name = body[8:].split(b"\x00", 1)[0].decode()
            # link_at returns the post-link inode: no extra round trip
            self._entry_reply(unique, fs.link_at(old_ino, nodeid, name))

        elif opcode == FUSE_RENAME:
            newdir = struct.unpack_from("<Q", body)[0]
            names = body[8:].split(b"\x00")
            old_name, new_name = names[0].decode(), names[1].decode()
            # atomic rename(2) semantics (replace-existing) via the
            # client's single-apply / two-phase-tx path
            fs.rename_at(nodeid, old_name, newdir, new_name)
            self._reply(unique)

        elif opcode == FUSE_GETXATTR:
            size, _pad = struct.unpack_from("<II", body)
            name = body[8:].split(b"\x00", 1)[0].decode()
            value = fs.meta.inode_get(nodeid)["xattr"].get(name)
            if value is None:
                self._reply_err(unique, 61)  # ENODATA
                return
            raw = str(value).encode()
            if size == 0:
                self._reply(unique, struct.pack("<II", len(raw), 0))
            elif size < len(raw):
                self._reply_err(unique, errno.ERANGE)
            else:
                self._reply(unique, raw)

        elif opcode == FUSE_SETXATTR:
            size, flags = struct.unpack_from("<II", body)
            rest = body[8:]
            name, value = rest.split(b"\x00", 1)[0], None
            value = rest[len(name) + 1 : len(name) + 1 + size]
            fs.meta.set_xattr(nodeid, name.decode(), value.decode("utf-8", "replace"))
            self._reply(unique)

        elif opcode == FUSE_LISTXATTR:
            size, _pad = struct.unpack_from("<II", body)
            names = sorted(fs.meta.inode_get(nodeid)["xattr"])
            raw = b"".join(n.encode() + b"\x00" for n in names)
            if size == 0:
                self._reply(unique, struct.pack("<II", len(raw), 0))
            elif size < len(raw):
                self._reply_err(unique, errno.ERANGE)
            else:
                self._reply(unique, raw)

        elif opcode == FUSE_REMOVEXATTR:
            name = body.split(b"\x00", 1)[0].decode()
            if name not in fs.meta.inode_get(nodeid)["xattr"]:
                self._reply_err(unique, 61)  # ENODATA
                return
            fs.meta.set_xattr(nodeid, name, None)
            self._reply(unique)

        elif opcode == FUSE_RENAME2:
            newdir, flags, _pad = struct.unpack_from("<QII", body)
            names = body[16:].split(b"\x00")
            old_name, new_name = names[0].decode(), names[1].decode()
            if flags & ~RENAME_NOREPLACE:
                # EXCHANGE/WHITEOUT are unsupported: rejecting beats a
                # silent destructive replace where the kernel contract
                # promises a lossless swap
                self._reply_err(unique, errno.EINVAL)
                return
            # NOREPLACE is enforced atomically inside the rename apply
            fs.rename_at(nodeid, old_name, newdir, new_name,
                         noreplace=bool(flags & RENAME_NOREPLACE))
            self._reply(unique)

        else:
            self._reply_err(unique, errno.ENOSYS)


def mount(fs: FileSystem, mountpoint: str) -> FuseMount:
    return FuseMount(fs, mountpoint).mount()
