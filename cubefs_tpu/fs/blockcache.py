"""Block cache: client-local read cache (bcache daemon role).

Role parity: client/blockcache — a local SSD LRU keyed by extent block
that accelerates repeated reads (bcache/manage.go LRU management). Here
a process-local tier in front of ExtentClient reads, optionally spilling
to a local directory.
"""

from __future__ import annotations

import concurrent.futures
import hashlib
import os

from ..utils import lockwitness
from collections import OrderedDict


class BlockCache:
    def __init__(self, capacity_bytes: int = 128 << 20,
                 spill_dir: str | None = None):
        self.capacity = capacity_bytes
        self.spill_dir = spill_dir
        self._lock = lockwitness.make_lock("BlockCache._lock")
        self._lru: OrderedDict[str, bytes | None] = OrderedDict()
        # spilled entries hold None in the LRU; their payload size is
        # tracked here so the capacity budget covers the spill dir too
        # (a laptop-local cache dir must not grow without bound)
        self._sizes: dict[str, int] = {}
        self._used = 0
        self.hits = 0
        self.misses = 0
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)

    def _path(self, key: str) -> str:
        h = hashlib.sha1(key.encode()).hexdigest()
        return os.path.join(self.spill_dir, h)

    def get(self, key: str) -> bytes | None:
        with self._lock:
            if key in self._lru:
                data = self._lru[key]
                self._lru.move_to_end(key)
                if data is None and self.spill_dir:  # spilled entry
                    data = self._load_spilled(key)
                    if data is None:
                        self.misses += 1
                        return None
                self.hits += 1
                return data
            self.misses += 1
            return None

    def _load_spilled(self, key: str) -> bytes | None:
        """Read a spill file back, verifying the stored digest — a
        truncated or bit-flipped file is dropped and reads as a miss,
        never served as data. Caller holds the lock."""
        try:
            raw = open(self._path(key), "rb").read()
            digest, data = raw[:20], raw[20:]
            if hashlib.sha1(data).digest() != digest:
                raise OSError("spill checksum mismatch")
            return data
        except OSError:
            del self._lru[key]
            self._used -= self._sizes.pop(key, 0)
            try:
                os.unlink(self._path(key))
            except OSError:
                pass
            return None

    def put(self, key: str, data: bytes) -> None:
        with self._lock:
            old = self._lru.pop(key, None)
            if old:
                self._used -= len(old)
            elif key in self._sizes:
                self._used -= self._sizes.pop(key)
            if self.spill_dir:
                with open(self._path(key), "wb") as f:
                    f.write(hashlib.sha1(data).digest())
                    f.write(data)
                self._lru[key] = None  # present on disk
                self._sizes[key] = len(data)
                self._used += len(data)
            else:
                self._lru[key] = data
                self._used += len(data)
            while self._used > self.capacity and self._lru:
                k, evicted = self._lru.popitem(last=False)
                if evicted:
                    self._used -= len(evicted)
                elif self.spill_dir:
                    self._used -= self._sizes.pop(k, 0)
                    try:
                        os.unlink(self._path(k))
                    except OSError:
                        pass

    def stats(self) -> dict:
        with self._lock:
            return {"items": len(self._lru), "bytes": self._used,
                    "hits": self.hits, "misses": self.misses}


class CachingExtentClient:
    """ExtentClient wrapper adding the local block cache on the read
    path (write path invalidates touched extents) plus sequential
    read-ahead (the streamer's stream_aheadread role): a cache miss on
    block k prefetches block k+1 in the background so streaming reads
    hide the fetch latency."""

    BLOCK = 128 << 10
    READAHEAD = 1  # blocks prefetched past a miss

    def __init__(self, inner, cache: BlockCache | None = None,
                 readahead: bool = True):
        self.inner = inner
        self.cache = cache or BlockCache()
        self.readahead = readahead
        self._prefetch_pool = concurrent.futures.ThreadPoolExecutor(2)
        # block -> in-flight Future so demand reads JOIN a running fetch
        # instead of re-issuing it; per-inode generation counters make a
        # racing fetch's put a no-op after a write invalidation
        self._inflight: dict[str, concurrent.futures.Future] = {}
        self._gen: dict[int, int] = {}
        self._pf_lock = lockwitness.make_lock("CachingExtentClient._pf_lock")

    def write(self, meta, ino: int, file_offset: int, data: bytes) -> None:
        self.inner.write(meta, ino, file_offset, data)
        # conservative invalidation: drop all cached blocks of this inode
        # and bump its generation so in-flight fetches can't repopulate
        # the cache with pre-write bytes
        with self._pf_lock:
            self._gen[ino] = self._gen.get(ino, 0) + 1
        with self.cache._lock:
            stale = [k for k in self.cache._lru if k.startswith(f"{ino}/")]
            for k in stale:
                v = self.cache._lru.pop(k)
                if v:
                    self.cache._used -= len(v)
                else:
                    self.cache._used -= self.cache._sizes.pop(k, 0)
                    if self.cache.spill_dir:
                        try:
                            os.unlink(self.cache._path(k))
                        except OSError:
                            pass

    def close_stream(self, ino: int) -> None:
        self.inner.close_stream(ino)

    def _dp_by_id(self, dp_id):
        return self.inner._dp_by_id(dp_id)

    def read(self, inode: dict, offset: int, length: int) -> bytes:
        size = inode["size"]
        if offset >= size:
            return b""
        length = min(length, size - offset)
        out = bytearray(length)
        pos = offset
        while pos < offset + length:
            block = pos // self.BLOCK
            in_block = pos % self.BLOCK
            take = min(offset + length - pos, self.BLOCK - in_block)
            key = f"{inode['ino']}/{block}"
            blk = self.cache.get(key)
            if blk is None:
                with self._pf_lock:
                    fut = self._inflight.get(key)
                if fut is not None:
                    try:  # join the running prefetch instead of re-reading
                        blk = fut.result()
                    except Exception:
                        blk = None
                if blk is None:
                    blk = self._fetch_block(inode, block, size)
                if self.readahead:
                    self._prefetch(inode, block + 1, size)
            out[pos - offset : pos - offset + take] = blk[in_block : in_block + take]
            pos += take
        return bytes(out)

    def _fetch_block(self, inode: dict, b: int, size: int) -> bytes:
        ino = inode["ino"]
        with self._pf_lock:
            gen = self._gen.get(ino, 0)
        data = self.inner.read(
            inode, b * self.BLOCK, min(self.BLOCK, size - b * self.BLOCK)
        )
        with self._pf_lock:
            fresh = self._gen.get(ino, 0) == gen
        if fresh:  # a write during the fetch means these bytes are stale
            self.cache.put(f"{ino}/{b}", data)
        return data

    def _prefetch(self, inode: dict, block: int, size: int) -> None:
        for b in range(block, block + self.READAHEAD):
            if b * self.BLOCK >= size:
                return
            key = f"{inode['ino']}/{b}"
            with self._pf_lock:
                if key in self._inflight or self.cache.get(key) is not None:
                    continue
                fut = concurrent.futures.Future()
                self._inflight[key] = fut

            def fetch(b=b, key=key, fut=fut):
                try:
                    fut.set_result(self._fetch_block(inode, b, size))
                except Exception as e:  # prefetch is best-effort
                    fut.set_exception(e)
                finally:
                    with self._pf_lock:
                        self._inflight.pop(key, None)

            self._prefetch_pool.submit(fetch)
