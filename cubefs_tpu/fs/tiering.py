"""TieringEngine: the crash-safe fs->blob cold-data bridge.

Role parity: sdk/data/blobstore (the reference's BlobStoreClient
wrapping access.API — the ONE place the fs plane stores payload bytes
through the blob plane) plus lcnode/lc_transition.go's storage-class
transition, rebuilt as a two-phase state machine that survives a kill
at any point.

This module is the SOLE sanctioned blob-plane caller in the fs plane
(lint family CFD, tool/lint/checkers/tiering_discipline.py): every
blob put/get/delete the filesystem ever issues goes through here, so
the fencing, verification, and deferred-deletion invariants cannot be
bypassed by a second code path.

Migration protocol (state persisted in inode xattrs, every step an
idempotent op_id-carrying metanode apply — see fs/metanode.py
`_apply_tiering_*`):

    hot --prepare--> PREPARE --blob put + CRC verify-->
    --blob_written--> BLOB_WRITTEN --commit--> COMMITTED
    --finish--> cold (cold.location pinned, extents on the freelist)

Crash/race matrix (the chaos drill in tests/test_tiering.py kills the
engine at every phase boundary via faultinject.gate and races
writes/renames/unlinks):

  * killed after PREPARE          -> rescan aborts; file stays hot
  * killed after BLOB_WRITTEN     -> rescan re-verifies and rolls
                                     FORWARD (gen unchanged) or aborts
                                     + queues the blob (gen bumped)
  * killed after COMMITTED        -> rescan finishes (bookkeeping only)
  * write/rename racing any phase -> gen bump fences the commit; the
                                     write wins, the blob is queued for
                                     the orphan reaper
  * unlink racing any phase       -> rm_inode queues cold.location AND
                                     tiering.pending; nothing leaks
  * residual window: a crash BETWEEN the blob put landing and the
    blob_written record landing strands one blob until bucket-level
    inventory reconciliation (documented in README); every OTHER crash
    point is covered by the deferred blob freelist.

The hot copy is released only at COMMITTED — and only after the blob
copy was read back and byte-compared against the hot extents — so no
crash or fault can lose bytes.
"""

from __future__ import annotations

import json
import os
import zlib
from collections import OrderedDict

from ..blob.access import AccessHandler
from ..blob.types import Location
from ..utils import faultinject, lockwitness, metrics, qos
from ..utils import trace as tracelib


class _AccessAdapter:
    """Dict-location shim so the engine drives a bare AccessHandler
    exactly like the embedded BlobClient (blob/sdk.py) — tests wire the
    handler straight in, deployments pass the SDK client."""

    def __init__(self, handler: AccessHandler):
        self._h = handler

    def put(self, data: bytes, codemode: int | None = None,
            priority: int | None = None) -> dict:
        return self._h.put(data, codemode, priority=priority).to_dict()

    def get(self, location: dict, priority: int | None = None) -> bytes:
        return self._h.get(Location.from_dict(location), priority=priority)

    def delete(self, location: dict, priority: int | None = None) -> None:
        self._h.delete(Location.from_dict(location), priority=priority)


def _loc_of(cold) -> dict:
    return json.loads(cold) if isinstance(cold, str) else cold


class TieringEngine:
    """Drives migrations, read-through, re-heat, and orphan reaping for
    one FileSystem against one blob client."""

    HEAT_TRACK = 4096  # per-inode cold-read counters kept (LRU-bounded)

    def __init__(self, fs, blob, *, codemode: int | None = None,
                 untier_threshold: int | None = None):
        self.fs = fs
        if isinstance(blob, AccessHandler):
            blob = _AccessAdapter(blob)
        self.blob = blob
        self.codemode = codemode
        if untier_threshold is None:
            try:
                untier_threshold = int(
                    os.environ.get("CUBEFS_UNTIER_HOT", "3") or "3")
            except ValueError:
                untier_threshold = 3
        self.untier_threshold = max(1, untier_threshold)
        self._lock = lockwitness.make_lock("TieringEngine._lock")
        # cold-read hotness, same discipline as CachedReader._heat: an
        # LRU-bounded counter per inode; crossing the threshold marks
        # the inode a re-heat candidate the lifecycle scan promotes
        self._heat: OrderedDict[int, int] = OrderedDict()
        self._hot: set[int] = set()

    # ------------------------------------------------------- migration
    def migrate(self, ino: int) -> str:
        """Run (or resume) one cold-tier migration; returns the outcome
        tag recorded in cubefs_tiering_transitions_total."""
        with tracelib.path_span("tiering.migrate", "tiering.migrate") as sp:
            sp.set_tag("svc", "lcnode").set_tag("ino", ino)
            try:
                out = self._migrate(ino)
            except faultinject.InjectedCrash:
                metrics.tiering_transitions.inc(outcome="error")
                raise
            sp.set_tag("outcome", out)
            metrics.tiering_transitions.inc(outcome=out)
            return out

    def _migrate(self, ino: int) -> str:
        inode = self.fs.meta.inode_get(ino)
        if inode["xattr"].get("tiering.state") is not None:
            return self.resume(ino, inode)
        if inode["xattr"].get("cold.location"):
            return "already_cold"
        if qos.scrub_suppressed():
            # brownout: skip BEFORE reading payload bytes — the gate
            # would shed the SCRUB-class blob put anyway
            return "deferred"
        prep = self.fs.meta.tiering_prepare(ino)
        gen, size = prep["gen"], prep["size"]
        faultinject.gate("lcnode", "phase:prepared")
        data = b""
        crc = 0
        if size == 0:
            # empty files ride the same FSM with a sentinel location,
            # so they are migrated ONCE instead of rescanned forever
            location = {"empty": True, "size": 0}
        else:
            inode = self.fs.meta.inode_get(ino)
            with tracelib.stage("hot_read", path="tiering.migrate"):
                data = self.fs.data.read(inode, 0, size)
            crc = zlib.crc32(data)
            try:
                with tracelib.stage("blob_put", path="tiering.migrate"):
                    location = self.blob.put(
                        data, self.codemode, priority=qos.SCRUB)
            except qos.QosRejected:
                self.fs.meta.tiering_abort(ino)
                return "deferred"
        res = self.fs.meta.tiering_blob_written(ino, gen, location)
        if not res.get("ok"):
            return "fenced"
        faultinject.gate("lcnode", "phase:blob_written")
        if size:
            # byte-verify the blob copy BEFORE the hot extents can be
            # released: read it back and compare against what we stored
            with tracelib.stage("verify", path="tiering.migrate"):
                copy = self.blob.get(location, priority=qos.SCRUB)
            if zlib.crc32(copy) != crc or copy != data:
                self.fs.meta.tiering_abort(ino)  # queues the bad blob
                return "verify_failed"
        return self._commit(ino, gen, inode, size)

    def _commit(self, ino: int, gen: int, inode: dict | None,
                size: int) -> str:
        res = self.fs.meta.tiering_commit(ino, gen)
        if not res.get("ok"):
            return "fenced"
        faultinject.gate("lcnode", "phase:committed")
        if self.fs.read_cache is not None and inode is not None:
            # the released extents may be mirrored in the flash tier
            self.fs.read_cache.invalidate(inode.get("extents") or [])
        self.fs.data.close_stream(ino)
        self.fs.meta.tiering_finish(ino)
        metrics.tiering_bytes.inc(size, direction="cold")
        return "migrated"

    def resume(self, ino: int, inode: dict | None = None) -> str:
        """Recovery entry point: a rescan found tiering.state set (the
        previous run died mid-migration). Roll forward past the commit
        point, roll back before it."""
        if inode is None:
            inode = self.fs.meta.inode_get(ino)
        xa = inode["xattr"]
        st = xa.get("tiering.state")
        if st is None:
            return "noop"
        if st == "PREPARE":
            # no blob location recorded: nothing durable to salvage
            self.fs.meta.tiering_abort(ino)
            return "aborted"
        if st == "BLOB_WRITTEN":
            gen = xa.get("tiering.gen")
            if inode.get("gen", 0) != gen:
                self.fs.meta.tiering_abort(ino)  # write won the race
                return "aborted"
            pending = xa.get("tiering.pending") or {}
            size = inode["size"]
            if size and not pending.get("empty"):
                copy = self.blob.get(pending, priority=qos.SCRUB)
                hot = self.fs.data.read(inode, 0, size)
                if copy != hot:
                    self.fs.meta.tiering_abort(ino)
                    return "verify_failed"
            out = self._commit(ino, gen, inode, size)
            return "resumed" if out == "migrated" else out
        # COMMITTED: the blob is the source of truth; just tidy up
        self.fs.meta.tiering_finish(ino)
        return "resumed"

    # ---------------------------------------------------- read-through
    def read_cold(self, inode: dict, offset: int, length: int) -> bytes:
        """Serve a cold file's bytes from the blob plane (AZ-local
        degraded reads happen inside the access GET path). Feeds the
        re-heat counters; length is already EOF-clamped by the caller."""
        ino = inode["ino"]
        metrics.tiering_cold_reads.inc()
        if self._heat_up(ino) >= self.untier_threshold:
            with self._lock:
                self._hot.add(ino)
        location = _loc_of(inode["xattr"]["cold.location"])
        if location.get("empty") or length <= 0:
            return b""
        with tracelib.stage("cold_read", path="fs.read"):
            data = self.blob.get(location)
        metrics.tiering_bytes.inc(length, direction="read")
        return data[offset:offset + length]

    def _heat_up(self, ino: int) -> int:
        with self._lock:
            n = self._heat.pop(ino, 0) + 1
            self._heat[ino] = n
            while len(self._heat) > self.HEAT_TRACK:
                self._heat.popitem(last=False)
            return n

    def hot_candidates(self) -> list[int]:
        """Inodes whose cold-read count crossed the un-tier threshold;
        the lifecycle scan promotes them back to datanode extents."""
        with self._lock:
            return sorted(self._hot)

    # --------------------------------------------------------- re-heat
    def untier(self, ino: int) -> str:
        """Promote a cold file back to hot extents: blob GET, write the
        payload to datanode extents WITHOUT registering them, then land
        the whole promotion through ONE fenced untier_commit apply — a
        racing write atomically rejects it and the orphan extents are
        reclaimed via the freelist."""
        inode = self.fs.meta.inode_get(ino)
        cold = inode["xattr"].get("cold.location")
        if cold is None or inode["extents"]:
            self._forget(ino)
            return "noop"
        location = _loc_of(cold)
        gen = inode.get("gen", 0)
        size = inode["size"]
        extents: list[dict] = []
        if size and not location.get("empty"):
            data = self.blob.get(location, priority=qos.SCRUB)
            extents = self.fs.data.write_extents(ino, 0, data)
        res = self.fs.meta.untier_commit(ino, gen, extents)
        self.fs.data.close_stream(ino)
        self._forget(ino)
        if res.get("ok"):
            metrics.tiering_untiered.inc(outcome="promoted")
            metrics.tiering_bytes.inc(size, direction="hot")
            return "promoted"
        metrics.tiering_untiered.inc(outcome="fenced")
        return "fenced"

    def _forget(self, ino: int) -> None:
        with self._lock:
            self._hot.discard(ino)
            self._heat.pop(ino, None)

    # -------------------------------------------------- orphan reaping
    def reap_orphans(self) -> int:
        """Drain the metanode blob freelist: delete each queued blob
        from the blob plane, then retire the entry via the idempotent
        blob_free_done apply. Any failure leaves the entry for the next
        sweep — deletion is at-least-once, which mark-delete absorbs."""
        entries = self.fs.meta.blob_freelist_all()
        reaped = 0
        for full_key, ent in entries.items():
            pid_s, key = full_key.split(":", 1)
            try:
                self.blob.delete(ent["location"], priority=qos.SCRUB)
            except Exception:
                continue  # blob plane unavailable/shed: retry next sweep
            try:
                self.fs.meta.blob_free_done(int(pid_s), key)
            except Exception:
                continue  # retried next sweep (idempotent pop)
            reaped += 1
        if reaped:
            metrics.tiering_orphans_reaped.inc(reaped)
        metrics.tiering_blob_freelist.set(len(entries) - reaped)
        return reaped

    # ------------------------------------- inventory reconciliation
    # Closes the residual put->blob_written leak window documented in
    # the module docstring: a blob whose PUT landed but whose
    # blob_written record never did is referenced by NOTHING — no
    # xattr, no freelist entry — and only a bucket-level cross-check of
    # blob-plane listings against metadata can find it.

    def _referenced_bids(self) -> set[tuple[int, int]]:
        """Every (vid, bid) the metadata plane can still reach: inode
        cold.location, mid-migration tiering.pending, and blob_freelist
        entries awaiting the reaper."""
        refs: set[tuple[int, int]] = set()

        def add_location(loc) -> None:
            loc = _loc_of(loc)
            if not loc or loc.get("empty"):
                return
            for sl in loc.get("slices", []):
                for k in range(sl["count"]):
                    refs.add((sl["vid"], sl["min_bid"] + k))

        for ino in self.fs.meta.list_inos():
            try:
                xa = self.fs.meta.inode_get(ino).get("xattr") or {}
            except Exception:
                continue
            if xa.get("cold.location"):
                add_location(xa["cold.location"])
            if xa.get("tiering.pending"):
                add_location(xa["tiering.pending"])
        for ent in self.fs.meta.blob_freelist_all().values():
            add_location(ent.get("location"))
        return refs

    def reconcile_inventory(self, listing: dict) -> int:
        """One reconciliation sweep against a blob-plane listing (see
        blob_plane_listing). A bid must show up leaked in TWO
        consecutive sweeps before it is enqueued: a PUT that landed
        between the metadata snapshot and the listing looks exactly
        like a leak for one sweep, and deleting it would eat live data.
        Confirmed leaks are grouped into per-volume synthetic locations
        and enqueued through blob_reconcile_enqueue, so they ride the
        SAME blob_freelist reaper as every other orphan. Returns the
        number of bids enqueued this sweep."""
        refs = self._referenced_bids()
        leaked: set[tuple[int, int]] = set()
        sizes: dict[tuple[int, int], int] = {}
        for vid, info in listing.items():
            for bid, size in info["bids"].items():
                key = (int(vid), int(bid))
                if key not in refs:
                    leaked.add(key)
                    sizes[key] = size
        pending = getattr(self, "_reconcile_pending", set())
        confirmed = leaked & pending
        self._reconcile_pending = leaked - confirmed
        if not confirmed:
            return 0
        # group confirmed bids into contiguous runs per volume — one
        # synthetic Location per run keeps the freelist compact
        by_vid: dict[int, list[int]] = {}
        for vid, bid in confirmed:
            by_vid.setdefault(vid, []).append(bid)
        enqueued = 0
        for vid, bids in sorted(by_vid.items()):
            mode = listing[vid]["codemode"]
            bids.sort()
            run_start = prev = bids[0]
            runs = []
            for b in bids[1:]:
                if b == prev + 1:
                    prev = b
                    continue
                runs.append((run_start, prev))
                run_start = prev = b
            runs.append((run_start, prev))
            for lo, hi in runs:
                count = hi - lo + 1
                blob_size = max(sizes.get((vid, b), 1) for b in
                                range(lo, hi + 1))
                self.fs.meta.blob_reconcile_enqueue({
                    "cluster_id": 1, "codemode": mode,
                    "size": sum(sizes.get((vid, b), 0)
                                for b in range(lo, hi + 1)),
                    "slices": [{"min_bid": lo, "vid": vid, "count": count,
                                "blob_size": max(blob_size, 1)}],
                    "crc": 0})
                enqueued += count
        metrics.tiering_orphans_reconciled.inc(enqueued)
        return enqueued


def blob_plane_listing(cm, node_pool) -> dict:
    """Bucket-level inventory of the blob plane: {vid: {"codemode",
    "bids": {bid: shard_size}}}, from each volume's first listable
    unit (every unit of a volume holds a shard for every bid, so one
    healthy listing per volume is a complete bid census)."""
    out: dict[int, dict] = {}
    for vid in sorted(cm.volumes):
        vol = cm.get_volume(vid)
        bids: dict[int, int] = {}
        for u in vol.units:
            try:
                meta, _ = node_pool.get(u.node_addr).call(
                    "list_chunk",
                    {"disk_id": u.disk_id, "chunk_id": u.chunk_id})
            except Exception:
                continue  # unreachable unit: try the next replica column
            bids = {int(b): int(s) for b, s, _ in meta["shards"]}
            break
        out[vid] = {"codemode": int(vol.codemode), "bids": bids}
    return out
