"""Master: the FS-plane resource manager.

Role parity: master/ — volume lifecycle (meta-partition inode ranges +
data-partition replica sets, cluster.go:3992 vol create / :1901 dp
create), node registries with heartbeat health checks (cluster.go:
851-902), and replica-repair orchestration on node death (decommission
machinery, cluster.go:2525).

Topology (master/topology.go): nodes belong to ZONES; within a zone
they chunk into NODESETS (failure domains). Placement spreads a
partition's replicas across zones when several exist (one per zone),
and keeps them inside one nodeset otherwise — via a PLUGGABLE node
selector (master/node_selector.go: carry-weight, round-robin,
least-load). Meta partitions SPLIT when their inode range fills
(docs/source/design/master.md:23-34): the maintenance sweep appends a
fresh mp for the next range, with zero interruption to existing ones.
"""

from __future__ import annotations

import json
import os
import threading
import time

from . import topology
from ..utils import lockwitness, metrics, rpc
from ..utils.fsm import ReplicatedFsm
from .topology import SELECTORS  # noqa: F401  (public selector registry)

INO_RANGE = 1 << 24  # inodes per meta partition


class MasterError(Exception):
    pass


class Master(ReplicatedFsm):
    HEARTBEAT_TIMEOUT = 10.0
    INO_RANGE = INO_RANGE  # inodes per meta partition (tests shrink it)
    MP_SPLIT_THRESHOLD = 0.8  # fill fraction that triggers an mp split
    NODESET_SIZE = 3

    def __init__(self, node_pool, replicas: int = 3, allow_single_node: bool = False,
                 data_dir: str | None = None, me: str | None = None,
                 peers: list[str] | None = None, selector: str = "least_load"):
        self.nodes = node_pool
        self.replicas = replicas
        self.allow_single_node = allow_single_node
        if selector not in SELECTORS:
            raise MasterError(f"unknown selector {selector!r}; "
                              f"have {sorted(SELECTORS)}")
        self.selector = selector
        self._selector_state: dict = {}
        self._lock = lockwitness.make_rlock("Master._lock")
        self.datanodes: dict[str, dict] = {}  # addr -> info (heartbeat-local)
        self.metanodes: dict[str, dict] = {}
        self.volumes: dict[str, dict] = {}
        # soft usage view from the latest quota sweep — NOT part of the
        # replicated FSM (a new leader re-learns it on its first sweep)
        self.vol_usage: dict[str, int] = {}
        # operator drains ARE replicated state: a restart or failover
        # must not re-place partitions on a drained node
        self.decommissioned: set[str] = set()
        # AK/SK user registry with per-volume grants (master/user.go):
        # replicated FSM state, served to gateways for authentication
        self.users: dict[str, dict] = {}  # ak -> {user_id, sk, volumes}
        # in-flight metapartition range migrations (fs/split.py):
        # REPLICATED state — split_prepare lands the fence (and the
        # target pid reservation) durably BEFORE any metanode RPC, so a
        # crash mid-PREPARE can neither mint a duplicate pid nor lose
        # track of a half-built target partition
        self.splits: dict[str, dict] = {}  # split_id -> plan
        self._split_engine = None  # lazy SplitEngine (fs/split.py)
        self._next_pid = 1
        self._next_dp = 1
        self.data_dir = data_dir
        self._init_fsm("master", data_dir, me, peers, node_pool)

    def _state_dict(self) -> dict:
        return {"volumes": self.volumes,
                "next": [self._next_pid, self._next_dp],
                "decommissioned": sorted(self.decommissioned),
                "users": self.users,
                "splits": self.splits}

    def _load_state_dict(self, state: dict) -> None:
        self.volumes = state["volumes"]
        self._next_pid, self._next_dp = state["next"]
        self.decommissioned = set(state.get("decommissioned", []))
        self.users = state.get("users", {})
        self.splits = state.get("splits", {})
        self._recalc_next_pid()

    def _recalc_next_pid(self) -> None:
        """Re-derive the pid high-water mark from every committed source.

        Committed volume mps are not enough: a split that crashed between
        split_prepare and split_commit has reserved a target pid that
        lives only in ``self.splits``.  Recovery (or a follower catching
        up from a snapshot) must scan those too, or the next volume
        create could mint a duplicate pid and two partitions would fight
        over one raft group directory.
        """
        hi = self._next_pid
        for vol in self.volumes.values():
            for m in vol["mps"]:
                hi = max(hi, m["pid"] + 1)
        for s in self.splits.values():
            for tp in s.get("target_pids", []):
                hi = max(hi, tp + 1)
        self._next_pid = hi

    def _state_bytes(self) -> bytes:
        with self._lock:
            return json.dumps(self._state_dict()).encode()

    def _restore_bytes(self, data: bytes) -> None:
        with self._lock:
            self._load_state_dict(json.loads(data))

    # ---- incremental snapshot segments (metadata_snapshot.go role:
    # snapshot cost is O(touched volumes/users), not O(cluster)) ----
    _SEG_OPS = {  # op -> (prefix, record key) for per-entity segments
        "put_volume": ("vol", "name"),
        "add_mp": ("vol", "name"),
        "update_dp": ("vol", "name"),
        "set_vol_capacity": ("vol", "name"),
        "set_quota": ("vol", "name"),
        "delete_quota": ("vol", "name"),
        "put_user": ("user", "ak"),
        "delete_user": ("user", "ak"),
        "set_grant": ("user", "ak"),
        "split_commit": ("vol", "name"),
        "merge_commit": ("vol", "name"),
    }

    def _segments_of(self, rec: dict) -> list[str]:
        op = rec["op"]
        segs = []
        ent = self._SEG_OPS.get(op)
        if ent is not None:
            segs.append(f"{ent[0]}:{rec[ent[1]]}")
        if op in ("put_volume", "add_mp", "decommission", "split_prepare",
                  "split_commit", "split_abort", "merge_commit"):
            segs.append("meta")  # id counters / drain set / splits moved
        return segs or ["meta"]  # unknown future op: at least the meta

    def _segment_state(self, seg: str):
        kind, _, key = seg.partition(":")
        with self._lock:
            if kind == "vol":
                return self.volumes.get(key)
            if kind == "user":
                return self.users.get(key)
            return {"next": [self._next_pid, self._next_dp],
                    "decommissioned": sorted(self.decommissioned),
                    "splits": self.splits}

    def _load_segment_state(self, seg: str, value) -> None:
        kind, _, key = seg.partition(":")
        if kind == "vol":
            self.volumes[key] = value
        elif kind == "user":
            self.users[key] = value
        else:
            self._next_pid, self._next_dp = value["next"]
            self.decommissioned = set(value["decommissioned"])
            self.splits = value.get("splits", {})
            self._recalc_next_pid()

    def _all_segments(self) -> list[str]:
        with self._lock:
            return (["meta"]
                    + [f"vol:{n}" for n in self.volumes]
                    + [f"user:{a}" for a in self.users])

    def _apply(self, rec: dict):
        rec = dict(rec)
        op = rec.pop("op")
        with self._lock:
            return getattr(self, f"_apply_{op}")(**rec)

    def _apply_put_volume(self, name: str, vol: dict) -> None:
        self.volumes[name] = vol
        # scan in-flight splits too: a crash mid-PREPARE has reserved
        # target pids in self.splits that no committed mp lists yet
        self._recalc_next_pid()
        self._next_dp = max([self._next_dp]
                            + [d["dp_id"] + 1 for d in vol["dps"]])

    # ------- elastic metadata plane (fs/split.py drives these) ---------
    # The three-phase migration commits its routing change as ONE master
    # FSM apply (split_commit / merge_commit): clients observe either the
    # old range table or the new one, never a torn intermediate, and
    # re-route on a single mp_version watermark bump.

    def _apply_split_prepare(self, name: str, split: dict) -> dict:
        """Reserve the split plan in the replicated ledger. Target pids
        are (re)assigned HERE, inside the apply: the engine plans
        without holding the proposal door, so a volume create can mint
        pids between plan and prepare — the apply is the one place the
        assignment is serial with every other pid source, and it is
        deterministic (same FSM state on every replica). Returns the
        stored record; the engine drives with the assigned pids."""
        sid = split["split_id"]
        split = dict(split, name=name)
        if split.get("target_pids"):
            split["target_pids"] = [self._next_pid]
            self._next_pid += 1
        self.splits[sid] = split
        return dict(split)

    def _apply_split_commit(self, split_id: str, name: str = "") -> None:
        s = self.splits.pop(split_id, None)
        if s is None:  # replayed / already aborted: nothing to do
            return
        vol = self.volumes.get(s["name"])
        if vol is None:
            return
        mps = vol["mps"]
        if s.get("kind") == "merge":
            # absorber extends over the donor's range; donor mp vanishes
            donor = next(m for m in mps if m["pid"] == s["donor_pid"])
            absorber = next(m for m in mps
                            if m["pid"] == s["absorber_pid"])
            absorber["end"] = max(absorber["end"], donor["end"])
            mps[:] = [m for m in mps if m["pid"] != s["donor_pid"]]
        else:
            donor = next(m for m in mps if m["pid"] == s["donor_pid"])
            hi = donor["end"]
            donor["end"] = s["split_ino"]
            mps.append({"pid": s["target_pids"][0],
                        "start": s["split_ino"], "end": hi,
                        "addr": s["addrs"][0], "addrs": s["addrs"]})
            mps.sort(key=lambda m: (m["start"], m["pid"]))
        vol["mp_version"] = vol.get("mp_version", 0) + 1

    def _apply_split_abort(self, split_id: str, name: str = "",
                           reason: str = "") -> None:
        self.splits.pop(split_id, None)

    # merge rides the same splits ledger; a distinct commit op keeps the
    # WAL legible and lets _SEG_OPS tag the volume segment it touches
    def _apply_merge_commit(self, split_id: str, name: str = "") -> None:
        self._apply_split_commit(split_id, name)

    # ---------------- users (master/user.go analog) --------------------
    def _apply_put_user(self, ak: str, user: dict) -> None:
        self.users[ak] = user

    def _apply_delete_user(self, ak: str) -> None:
        self.users.pop(ak, None)

    def _apply_set_grant(self, ak: str, volume: str,
                         perm: str | None) -> None:
        u = self.users.get(ak)
        if u is None:
            return
        if perm is None:
            u["volumes"].pop(volume, None)
        else:
            u["volumes"][volume] = perm

    def create_user(self, user_id: str) -> dict:
        import secrets as _secrets

        ak = _secrets.token_hex(8)
        sk = _secrets.token_hex(16)
        self._commit({"op": "put_user", "ak": ak, "user": {
            "user_id": user_id, "sk": sk, "volumes": {}}})
        return {"user_id": user_id, "access_key": ak, "secret_key": sk}

    def delete_user(self, ak: str) -> None:
        with self._lock:
            if ak not in self.users:
                raise MasterError(f"unknown access key {ak!r}")
        self._commit({"op": "delete_user", "ak": ak})

    def grant(self, ak: str, volume: str, perm: str = "rw") -> None:
        if perm not in ("r", "rw"):
            raise MasterError(f"bad perm {perm!r}")
        with self._lock:
            if ak not in self.users:
                raise MasterError(f"unknown access key {ak!r}")
        self._commit({"op": "set_grant", "ak": ak, "volume": volume,
                      "perm": perm})

    def revoke(self, ak: str, volume: str) -> None:
        self._commit({"op": "set_grant", "ak": ak, "volume": volume,
                      "perm": None})

    def secret_for(self, ak: str) -> str | None:
        with self._lock:
            u = self.users.get(ak)
            return u["sk"] if u else None

    def allowed(self, ak: str, volume: str, write: bool) -> bool:
        with self._lock:
            u = self.users.get(ak)
            if u is None:
                return False
            perm = u["volumes"].get(volume, "")
            return "w" in perm if write else bool(perm)

    def rpc_create_user(self, args, body):
        self._leader_gate()
        return self.create_user(args["user_id"])

    def rpc_delete_user(self, args, body):
        self._leader_gate()
        try:
            self.delete_user(args["ak"])
        except MasterError as e:
            raise rpc.RpcError(404, str(e)) from None
        return {}

    def rpc_grant(self, args, body):
        self._leader_gate()
        try:
            self.grant(args["ak"], args["volume"], args.get("perm", "rw"))
        except MasterError as e:
            raise rpc.RpcError(400, str(e)) from None
        return {}

    def rpc_revoke(self, args, body):
        self._leader_gate()
        self.revoke(args["ak"], args["volume"])
        return {}

    def rpc_list_users(self, args, body):
        with self._lock:
            # admin listing: secrets redacted
            return {"users": {ak: {"user_id": u["user_id"],
                                   "volumes": dict(u["volumes"])}
                              for ak, u in self.users.items()}}

    def rpc_user_auth_info(self, args, body):
        """Gateway authentication lookup: sk + grants for one access
        key (the objectnode's user-store backend)."""
        with self._lock:
            u = self.users.get(args["ak"])
            if u is None:
                raise rpc.RpcError(404, f"unknown access key")
            return {"sk": u["sk"], "user_id": u.get("user_id", ""),
                    "volumes": dict(u["volumes"])}

    # ---------------- quotas (master_quota_manager.go analog) ----------
    def _apply_set_vol_capacity(self, name: str, capacity: int) -> None:
        self.volumes[name]["capacity"] = capacity

    def _apply_set_quota(self, name: str, quota: dict) -> None:
        vol = self.volumes[name]
        vol.setdefault("quotas", {})[str(quota["qid"])] = quota

    def _apply_delete_quota(self, name: str, qid: int) -> None:
        self.volumes[name].get("quotas", {}).pop(str(qid), None)

    def set_vol_capacity(self, name: str, capacity: int) -> None:
        with self._lock:
            if name not in self.volumes:
                raise MasterError(f"no volume {name!r}")
        self._commit({"op": "set_vol_capacity", "name": name,
                      "capacity": capacity})

    def set_quota(self, name: str, dir_ino: int, max_bytes: int = 0,
                  max_files: int = 0) -> int:
        """Register a dir quota; files created under the dir inherit its
        quota id and metanodes enforce the limits. Returns the quota id
        (master_quota_manager.go setQuota analog)."""
        with self._propose_lock:
            with self._lock:
                if name not in self.volumes:
                    raise MasterError(f"no volume {name!r}")
                quotas = self.volumes[name].get("quotas", {})
                qid = 1 + max([int(k) for k in quotas], default=0)
            self._commit({"op": "set_quota", "name": name, "quota": {
                "qid": qid, "dir_ino": dir_ino, "max_bytes": max_bytes,
                "max_files": max_files}})
            return qid

    def delete_quota(self, name: str, qid: int) -> None:
        with self._lock:
            if name not in self.volumes:
                raise MasterError(f"no volume {name!r}")
            if str(qid) not in self.volumes[name].get("quotas", {}):
                raise MasterError(f"no quota {qid} on volume {name!r}")
        self._commit({"op": "delete_quota", "name": name, "qid": qid})

    def list_quotas(self, name: str) -> dict:
        with self._lock:
            vol = self.volumes.get(name)
            if vol is None:
                raise MasterError(f"no volume {name!r}")
            return dict(vol.get("quotas", {}))

    def enforce_quotas(self) -> dict:
        """Aggregation sweep (the reference's quota report/enforce loop):
        pull per-partition usage from metanode leaders, sum per volume
        and per quota id, then push vol-full + exceeded-quota flags to
        every partition replica. Enforcement is advisory-pushed (one
        sweep of lag), exactly like the reference. Returns the usage
        summary per volume."""
        with self._lock:
            vols = {n: ({"mps": [dict(m) for m in v["mps"]],
                         "capacity": v.get("capacity", 0),
                         "quotas": dict(v.get("quotas", {}))})
                    for n, v in self.volumes.items()}
        summary = {}
        for name, v in vols.items():
            used_bytes = used_files = 0
            per_quota: dict[str, dict] = {}
            for mp in v["mps"]:
                try:
                    meta, _ = rpc.call_replicas(
                        self.nodes, mp.get("addrs") or [mp["addr"]],
                        "usage_report", {"pid": mp["pid"]}, deadline=5.0)
                except Exception:
                    continue  # partition unreachable: retried next sweep
                used_bytes += meta["bytes"]
                used_files += meta["files"]
                for qid, u in meta.get("per_quota", {}).items():
                    agg = per_quota.setdefault(qid, {"bytes": 0, "files": 0})
                    agg["bytes"] += u["bytes"]
                    agg["files"] += u["files"]
            vol_full = bool(v["capacity"]) and used_bytes >= v["capacity"]
            exceeded = []
            for qid, q in v["quotas"].items():
                u = per_quota.get(qid, {"bytes": 0, "files": 0})
                if ((q["max_bytes"] and u["bytes"] >= q["max_bytes"])
                        or (q["max_files"] and u["files"] >= q["max_files"])):
                    exceeded.append(int(qid))
            for mp in v["mps"]:
                for addr in mp.get("addrs") or [mp["addr"]]:
                    try:
                        self.nodes.get(addr).call("set_enforcement", {
                            "pid": mp["pid"], "vol_full": vol_full,
                            "exceeded": exceeded})
                    except Exception:
                        pass
            with self._lock:
                self.vol_usage[name] = used_bytes
            summary[name] = {"used_bytes": used_bytes,
                             "used_files": used_files,
                             "vol_full": vol_full, "exceeded": exceeded,
                             "per_quota": per_quota}
        return summary

    def start_quota_sweeper(self, interval: float) -> None:
        """Run enforce_quotas on a fixed cadence (the reference's
        scheduleTask quota loop, master/cluster.go:492). The interval IS
        the enforcement-lag bound: a burst can overshoot a quota by at
        most interval x write-rate before the flags land at the
        metanodes (proved by tests/test_quota.py's overshoot test)."""
        self.stop_quota_sweeper()
        self._sweep_interval = interval
        self._sweep_stop = threading.Event()

        def loop():
            import sys

            from ..utils import metrics

            errs = metrics.DEFAULT.counter(
                "cubefs_quota_sweep_errors_total",
                "quota enforcement sweep failures")
            last_warned = 0.0
            while not self._sweep_stop.wait(interval):
                try:
                    self.enforce_quotas()
                except Exception as e:
                    # a persistently-failing sweep silently disables
                    # enforcement — count it and warn (rate-limited)
                    errs.inc()
                    now = time.time()
                    if now - last_warned > 60:
                        last_warned = now
                        print(f"quota sweep failed: {type(e).__name__}: {e}",
                              file=sys.stderr)

        self._sweep_thread = threading.Thread(target=loop, daemon=True)
        self._sweep_thread.start()

    def stop_quota_sweeper(self) -> None:
        ev = getattr(self, "_sweep_stop", None)
        if ev is not None:
            ev.set()
            self._sweep_thread.join(timeout=5)
            self._sweep_stop = None

    def _apply_update_dp(self, name: str, dp_id: int, replicas: list[str],
                         leader: str) -> None:
        for dp in self.volumes[name]["dps"]:
            if dp["dp_id"] == dp_id:
                dp["replicas"] = replicas
                dp["leader"] = leader

    # ---------------- registries ----------------
    def register_datanode(self, addr: str, zone: str = "default",
                          packet_addr: str | None = None,
                          disks: dict | None = None,
                          read_addr: str | None = None,
                          rack: str | None = None) -> None:
        with self._lock:
            info = self.datanodes.setdefault(addr, {"addr": addr})
            info["hb"] = time.time()
            info["zone"] = zone
            if rack:
                info["rack"] = rack
            if packet_addr:
                info["packet_addr"] = packet_addr
            if read_addr:
                info["read_addr"] = read_addr
            if disks is not None:
                info["disks"] = disks

    def register_metanode(self, addr: str, zone: str = "default",
                          packet_addr: str | None = None,
                          read_addr: str | None = None,
                          rack: str | None = None) -> None:
        with self._lock:
            info = self.metanodes.setdefault(addr, {"addr": addr})
            info["hb"] = time.time()
            info["zone"] = zone
            if rack:
                info["rack"] = rack
            if packet_addr:
                info["packet_addr"] = packet_addr
            if read_addr:
                info["read_addr"] = read_addr

    def heartbeat(self, addr: str, kind: str, zone: str | None = None,
                  packet_addr: str | None = None,
                  read_addr: str | None = None,
                  disks: dict | None = None,
                  rack: str | None = None) -> None:
        with self._lock:
            reg = self.datanodes if kind == "data" else self.metanodes
            # unknown addr re-registers: a restarted master recovers its
            # registries from the heartbeat stream — INCLUDING the packet
            # plane address, or a master restart would silently degrade
            # every client to HTTP
            info = reg.setdefault(addr, {"addr": addr})
            info["hb"] = time.time()
            if zone or "zone" not in info:
                info["zone"] = zone or "default"
            if rack:
                info["rack"] = rack
            if packet_addr:
                info["packet_addr"] = packet_addr
            if read_addr:
                info["read_addr"] = read_addr
            if disks is not None:
                # the disk report feeds the disk manager: a disk flagged
                # broken here gets its partitions migrated by the next
                # check_replicas sweep (master/disk_manager.go role)
                info["disks"] = disks

    def _live(self, reg: dict) -> list[str]:
        now = time.time()
        return [a for a, i in reg.items()
                if now - i["hb"] <= self.HEARTBEAT_TIMEOUT
                and a not in self.decommissioned]

    def _apply_decommission(self, addr: str) -> None:
        self.decommissioned.add(addr)

    def decommission_datanode(self, addr: str) -> list:
        """Operator-driven drain (cluster.go:2525 decommission analog):
        exclude the node from placement — committed through the
        replicated FSM, so restarts/failovers keep the drain — then
        rebuild every dp replica it holds onto live nodes. Returns the
        rebuild actions."""
        with self._lock:
            if addr not in self.datanodes:
                raise MasterError(f"unknown datanode {addr!r}")
        self._commit({"op": "decommission", "addr": addr})
        # the node no longer counts as live: the standard repair sweep
        # moves its replicas exactly as if it had died
        return self.check_replicas()

    def node_list(self) -> dict:
        with self._lock:
            now = time.time()

            def view(reg):
                return {
                    a: {"zone": i.get("zone", "default"),
                        "live": now - i["hb"] <= self.HEARTBEAT_TIMEOUT,
                        "decommissioned": a in self.decommissioned}
                    for a, i in reg.items()
                }

            return {"datanodes": view(self.datanodes),
                    "metanodes": view(self.metanodes)}

    # ---------------- topology (zones / nodesets) ----------------
    def _zones_of(self, reg: dict, live: list[str]) -> dict[str, list[str]]:
        return topology.zones_of(reg, live)

    def _nodesets(self, members: list[str]) -> list[list[str]]:
        """Chunk a zone's nodes into nodesets (failure domains) of
        NODESET_SIZE, deterministically by address order."""
        return topology.nodesets(members, self.NODESET_SIZE)

    def topology_view(self) -> dict:
        """Zone -> nodeset -> node tree for both node kinds, including
        dead/decommissioned nodes (flagged) so operators see the whole
        failure-domain picture (`cubefs-cli topology fs`)."""
        with self._lock:
            out = {}
            for kind, reg in (("datanodes", self.datanodes),
                              ("metanodes", self.metanodes)):
                live = set(self._live(reg))
                zones = self._zones_of(reg, list(reg))
                out[kind] = {
                    z: {
                        "nodesets": self._nodesets(members),
                        "nodes": {
                            a: {"live": a in live,
                                "decommissioned": a in self.decommissioned}
                            for a in sorted(members)
                        },
                    }
                    for z, members in sorted(zones.items())
                }
            return out

    def rpc_topology_view(self, args, body):
        return self.topology_view()

    def topology_tree(self) -> dict:
        """az -> rack -> node map for both node kinds (`cubefs-cli
        topology tree` renders this beside the blob-plane zone map)."""
        with self._lock:
            out = {}
            for kind, reg in (("datanodes", self.datanodes),
                              ("metanodes", self.metanodes)):
                out[kind] = topology.topology_tree(
                    reg, set(self._live(reg)), self.decommissioned)
            return out

    def rpc_topology_tree(self, args, body):
        return self.topology_tree()

    def _pick(self, cands: list[str], k: int, load: dict) -> list[str]:
        fn = SELECTORS[self.selector]
        return fn(cands, k, load, self._selector_state)

    def _select_hosts(self, reg: dict, live: list[str], k: int,
                      load: dict) -> list[str]:
        """Replica spread lives in the fs topology scorer (one-per-AZ
        when enough AZs, even spread, one nodeset otherwise); the
        master only supplies its pluggable selector."""
        return topology.select_hosts(reg, live, k, load, self._pick,
                                     self.NODESET_SIZE)

    # ---------------- volume lifecycle ----------------
    def create_volume(self, name: str, mp_count: int = 3, dp_count: int = 4) -> dict:
        # _propose_lock makes the duplicate-name check atomic with the
        # commit: without it two concurrent creates both pass the check
        # and the second silently clobbers the first's partition tables
        with self._propose_lock:
            return self._create_volume_locked(name, mp_count, dp_count)

    def _create_volume_locked(self, name: str, mp_count: int, dp_count: int) -> dict:
        if mp_count < 1 or dp_count < 1:
            raise MasterError("mp_count and dp_count must be >= 1")
        # Phase 1 — plan under the hot lock: dup-check, liveness, host
        # selection, id allocation. NO RPC in here: heartbeats contend
        # on _lock, so a slow node round-trip under it stalls liveness
        # tracking for the whole cluster. _propose_lock (held by our
        # caller) keeps the plan valid until commit.
        with self._lock:
            if name in self.volumes:
                raise MasterError(f"volume {name!r} exists")
            live_meta = self._live(self.metanodes)
            live_data = self._live(self.datanodes)
            if not live_meta or not live_data:
                raise MasterError("need live metanodes and datanodes")
            if len(live_data) < self.replicas and not self.allow_single_node:
                raise MasterError(
                    f"{len(live_data)} datanodes < {self.replicas} replicas"
                )

            mps = []
            meta_replicas = min(self.replicas, len(live_meta))
            meta_load = self._meta_load()
            for i in range(mp_count):
                pid = self._next_pid
                self._next_pid += 1
                start = 1 if i == 0 else i * self.INO_RANGE
                end = (i + 1) * self.INO_RANGE
                addrs = self._select_hosts(self.metanodes, live_meta,
                                           meta_replicas, meta_load)
                for a in addrs:
                    meta_load[a] = meta_load.get(a, 0) + 1
                mps.append({"pid": pid, "start": start, "end": end,
                            "addr": addrs[0], "addrs": addrs})

            dps = []
            intra_load: dict[str, int] = {}
            for i in range(dp_count):
                dps.append(self._plan_dp(live_data, intra_load))
            vol = {"name": name, "mps": mps, "dps": dps, "status": "active"}
        # Phase 2 — issue the partition creates lock-free (safe to
        # retry: nodes treat a duplicate create of a known pid/dp_id as
        # get-or-refresh). A failure aborts before commit, leaving only
        # idempotently re-creatable partitions behind.
        for m in mps:
            for a in m["addrs"]:
                # lint: allow[CFL101] _propose_lock (never _lock) deliberately spans these creates: the dup-name check must stay atomic with the commit, and only concurrent volume creates queue on it
                self.nodes.get(a).call(
                    "create_partition",
                    {"pid": m["pid"], "start": m["start"], "end": m["end"],
                     "peers": m["addrs"]},
                )
        for d in dps:
            for addr in d["replicas"]:
                self.nodes.get(addr).call(
                    "create_partition",
                    {"dp_id": d["dp_id"], "peers": d["replicas"],
                     "leader": d["leader"]},
                )
        # commit the volume table through the FSM door (wal or raft)
        self._commit({"op": "put_volume", "name": name, "vol": vol})
        return self.client_view(name)

    def _plan_dp(self, live_data: list[str], intra_load: dict | None = None) -> dict:
        """Place one dp — pure planning, caller holds _lock; the
        create_partition RPCs go out after the lock is released."""
        dp_id = self._next_dp
        self._next_dp += 1
        k = min(self.replicas, len(live_data))
        # load counts dps per node, INCLUDING ones placed earlier in
        # this same create_volume call (intra_load); topology-aware
        # selection spreads across zones / keeps inside a nodeset, and
        # leadership rotates so one node is not every dp's write leader
        load = {a: 0 for a in live_data}
        for v in self.volumes.values():
            for dp in v["dps"]:
                for r in dp["replicas"]:
                    if r in load:
                        load[r] += 1
        for a, n in (intra_load or {}).items():
            if a in load:
                load[a] += n
        picks = self._select_hosts(self.datanodes, live_data, k, load)
        leader = topology.pick_leader(picks, intra_load)
        if intra_load is not None:
            for a in picks:
                intra_load[a] = intra_load.get(a, 0) + 1
            intra_load[leader] = intra_load.get(leader, 0) + 1
        return {"dp_id": dp_id, "replicas": picks, "leader": leader}

    def client_view(self, name: str) -> dict:
        with self._lock:
            vol = self.volumes.get(name)
            if vol is None:
                raise MasterError(f"no volume {name!r}")
            # packet-plane discovery: every replica's binary-protocol
            # address (when the node registered one) rides the view
            packet_addrs = {a: i["packet_addr"]
                            for a, i in self.datanodes.items()
                            if i.get("packet_addr")}
            meta_packet_addrs = {a: i["packet_addr"]
                                 for a, i in self.metanodes.items()
                                 if i.get("packet_addr")}
            meta_read_addrs = {a: i["read_addr"]
                               for a, i in self.metanodes.items()
                               if i.get("read_addr")}
            data_read_addrs = {a: i["read_addr"]
                               for a, i in self.datanodes.items()
                               if i.get("read_addr")}
            return {"name": name, "mps": [dict(m) for m in vol["mps"]],
                    "dps": [dict(d) for d in vol["dps"]],
                    # single watermark: every committed range-table
                    # change (split/merge) bumps it exactly once, so
                    # clients refresh on compare instead of len()
                    "mp_version": vol.get("mp_version", 0),
                    "quotas": dict(vol.get("quotas", {})),
                    "packet_addrs": packet_addrs,
                    "meta_packet_addrs": meta_packet_addrs,
                    "meta_read_addrs": meta_read_addrs,
                    "data_read_addrs": data_read_addrs}

    def _meta_load(self) -> dict[str, int]:
        """Replica count per metanode across all volumes (placement load)."""
        load: dict[str, int] = {}
        for v in self.volumes.values():
            for mp in v["mps"]:
                for a in mp.get("addrs") or [mp["addr"]]:
                    load[a] = load.get(a, 0) + 1
        return load

    def _dp_load(self) -> dict[str, int]:
        """dp replica count per datanode (placement load; caller holds
        _lock)."""
        load: dict[str, int] = {}
        for v in self.volumes.values():
            for dp in v["dps"]:
                for r in dp["replicas"]:
                    load[r] = load.get(r, 0) + 1
        return load

    # ---------------- meta-partition split ----------------
    def _apply_add_mp(self, name: str, mp: dict) -> None:
        self.volumes[name]["mps"].append(mp)
        self._next_pid = max(self._next_pid, mp["pid"] + 1)

    def check_meta_partitions(self) -> list[tuple[str, int]]:
        """Split sweep (docs/source/design/master.md:23-34): when a
        volume's LAST meta partition passes the fill threshold, append a
        fresh partition for the next inode range. Existing partitions
        and in-flight IO are untouched — clients pick up the new one on
        their next view refresh. Returns (volume, new_pid) actions."""
        with self._lock:
            vols = {n: [dict(m) for m in v["mps"]]
                    for n, v in self.volumes.items()}
        actions = []
        for name, mps in vols.items():
            if not mps:
                continue
            last = max(mps, key=lambda m: m["end"])
            try:
                meta, _ = rpc.call_replicas(
                    self.nodes, last.get("addrs") or [last["addr"]],
                    "mp_fill", {"pid": last["pid"]}, deadline=5.0)
            except Exception:
                continue  # retried next sweep
            span = last["end"] - last["start"]
            if span <= 0 or (meta["next_ino"] - last["start"]) / span \
                    < self.MP_SPLIT_THRESHOLD:
                continue
            try:
                # after_end pins the observed state: a concurrent sweep
                # that already split makes this a no-op, not a second
                # redundant partition
                new_pid = self.split_meta_partition(name,
                                                    after_end=last["end"])
            except (MasterError, rpc.RpcError):
                continue  # one volume's failure must not end the sweep
            if new_pid is not None:
                actions.append((name, new_pid))
        return actions

    def split_meta_partition(self, name: str,
                             after_end: int | None = None) -> int | None:
        with self._propose_lock:
            with self._lock:
                vol = self.volumes.get(name)
                if vol is None:
                    raise MasterError(f"no volume {name!r}")
                if not vol["mps"]:
                    return None
                live_meta = self._live(self.metanodes)
                if not live_meta:
                    return None
                start = max(m["end"] for m in vol["mps"])
                if after_end is not None and start != after_end:
                    return None  # someone already split past our snapshot
                end = start + self.INO_RANGE
                pid = self._next_pid
                self._next_pid += 1
                meta_load = self._meta_load()
                k = min(self.replicas, len(live_meta))
                addrs = self._select_hosts(self.metanodes, live_meta, k,
                                           meta_load)
            created = []
            try:
                for a in addrs:
                    # lint: allow[CFL002] _propose_lock is the cold proposal door, not the hot _lock (released above) — holding it keeps the split's after_end snapshot valid; only other proposers wait
                    self.nodes.get(a).call(
                        "create_partition",
                        {"pid": pid, "start": start, "end": end,
                         "peers": addrs})
                    created.append(a)
            except Exception as e:
                # roll back best-effort so failed splits don't leak
                # orphan partitions on the nodes that did succeed
                for a in created:
                    try:
                        # lint: allow[CFL002] same cold proposal door as the create above — rollback must finish before another proposer reuses the range
                        self.nodes.get(a).call("drop_partition",
                                               {"pid": pid})
                    except Exception:
                        pass
                raise MasterError(f"split of {name!r} failed: {e}") from e
            self._commit({"op": "add_mp", "name": name, "mp": {
                "pid": pid, "start": start, "end": end,
                "addr": addrs[0], "addrs": addrs}})
            return pid

    # ---------------- failure handling ----------------
    def check_replicas(self) -> list[tuple[int, str, str]]:
        """Decommission dead datanodes: for every dp with a dead replica,
        pick a live substitute, resync its extents from a healthy peer,
        and repoint the replica set. Returns (dp_id, dead, new) actions.

        The (slow) extent copy runs OUTSIDE the master lock — heartbeats
        must keep landing while a rebuild streams data, or healthy nodes
        would go stale and cascade."""
        with self._lock:
            live = set(self._live(self.datanodes))
            load = self._dp_load()
            plans = []
            for vname, vol in self.volumes.items():
                for dp in vol["dps"]:
                    dead = [a for a in dp["replicas"] if a not in live]
                    for dead_addr in dead:
                        healthy = [a for a in dp["replicas"] if a in live]
                        cands = [a for a in live
                                 if a not in dp["replicas"]] or (
                                     list(live) if self.allow_single_node else []
                                 )
                        if not healthy or not cands:
                            continue
                        # rebuild into the dead replica's AZ when it has
                        # capacity, so a node loss doesn't erode the
                        # dp's one-per-AZ footprint
                        new = topology.pick_destination(
                            self.datanodes, cands, healthy,
                            prefer_az=topology.az_of(
                                self.datanodes.get(dead_addr) or {}),
                            load=load)
                        load[new] = load.get(new, 0) + 1
                        plans.append((vname, dict(dp), dead_addr, new,
                                      healthy[0]))
        # one sweep covers BOTH failure domains: dead nodes above,
        # broken disks below — existing periodic check_replicas callers
        # must pick up the disk manager without new wiring
        return self._execute_rebuilds(plans) + self.check_broken_disks()

    # ---------------- disk manager (master/disk_manager.go role) --------
    def offline_disk(self, addr: str, path: str) -> list:
        """Migrate every dp whose replica on `addr` lives on `path` to
        other nodes — the node itself stays in service for its healthy
        disks. Driven by the operator (disk offline) or the sweep when
        a heartbeat disk report flags the disk broken."""
        with self._lock:
            info = self.datanodes.get(addr)
            if info is None:
                raise MasterError(f"unknown datanode {addr}")
            cached = (info.get("disks") or {}).get(path)
        # prefer a LIVE disk report: partitions placed since the last
        # heartbeat must not be silently left behind on the dying disk
        report = cached
        try:
            live = self.nodes.get(addr).call("disk_report", {})[0]["disks"]
            report = live.get(path, cached)
        except rpc.RpcError:
            pass  # unreachable node: the cached report is the best view
        if report is None:
            raise MasterError(f"{addr} reports no disk {path}")
        dp_ids = set(report.get("dps") or [])
        # mark the disk on the NODE first: placement must stop preferring
        # the freshly emptied disk, and the next heartbeat's report keeps
        # the broken flag authoritative across master restarts
        try:
            self.nodes.get(addr).call("mark_disk_broken", {"path": path})
        except rpc.RpcError:
            pass  # node unreachable: migration below still proceeds
        return self._migrate_dps_off(addr, dp_ids)

    def _migrate_dps_off(self, addr: str, dp_ids: set) -> list:
        """Rebuild the `addr` replica of each dp in dp_ids onto another
        live node (the per-dp half of decommission; same resync path).
        The node is ALIVE here, so the superseded replica is dropped
        from it — a stale live replica would keep serving bytes that no
        longer receive writes."""
        with self._lock:
            live = set(self._live(self.datanodes))
            load = self._dp_load()
            plans = []
            for vname, vol in self.volumes.items():
                for dp in vol["dps"]:
                    if dp["dp_id"] not in dp_ids or addr not in dp["replicas"]:
                        continue
                    healthy = [a for a in dp["replicas"]
                               if a != addr and a in live]
                    cands = [a for a in live
                             if a not in dp["replicas"]] or (
                                 [a for a in live if a != addr]
                                 if self.allow_single_node else [])
                    if not healthy or not cands:
                        continue
                    # the drained node stays in its AZ: prefer keeping
                    # the migrated replica in that same AZ
                    new = topology.pick_destination(
                        self.datanodes, cands, healthy,
                        prefer_az=topology.az_of(
                            self.datanodes.get(addr) or {}),
                        load=load)
                    load[new] = load.get(new, 0) + 1
                    plans.append((vname, dict(dp), addr, new,
                                  healthy[0]))
        actions = self._execute_rebuilds(plans)
        for dp_id, dead, _new in actions:
            try:
                self.nodes.get(dead).call("drop_partition", {"dp_id": dp_id})
            except rpc.RpcError:
                pass  # node went away mid-migration: nothing to drop
        return actions

    def _execute_rebuilds(self, plans: list) -> list:
        """Shared rebuild driver (check_replicas + the disk manager):
        re-checks each plan against the LIVE dp entry — an earlier
        rebuild in the same sweep may have repointed it, and working
        from the planning snapshot would commit a stale replica list."""
        actions = []
        for vname, dp_snapshot, dead_addr, new_addr, src in plans:
            with self._lock:
                dp = next((d for d in self.volumes[vname]["dps"]
                           if d["dp_id"] == dp_snapshot["dp_id"]), None)
                if dp is None or dead_addr not in dp["replicas"]:
                    continue  # already handled
                dp = dict(dp)
            try:
                self._rebuild_replica(vname, dp, dead_addr, new_addr, src)
                actions.append((dp["dp_id"], dead_addr, new_addr))
            except rpc.RpcError:
                continue  # retried on the next sweep
        return actions

    def check_broken_disks(self) -> list:
        """Sweep half of the disk manager: every disk a heartbeat
        report marked broken gets its partitions migrated."""
        with self._lock:
            broken = [(addr, path, set(rep.get("dps") or []))
                      for addr, info in self.datanodes.items()
                      for path, rep in (info.get("disks") or {}).items()
                      if rep.get("broken")]
        actions = []
        for addr, path, dp_ids in broken:
            actions += self._migrate_dps_off(addr, dp_ids)
        return actions

    # ---------------- misplaced-replica sweep ----------------
    def misplacement_view(self) -> dict:
        """Score every dp against the one-per-AZ contract and publish
        the `cubefs_fs_placement_misplaced` gauge (0 == clean)."""
        with self._lock:
            view = topology.cluster_misplacement(self.datanodes,
                                                 self.volumes)
        metrics.fs_placement_misplaced.set(view["misplaced"])
        return view

    def sweep_misplaced(self, max_moves: int = 1) -> list:
        """Rate-limited sweep: migrate at most `max_moves` colocated dp
        replicas per call toward one-per-AZ. Rebuilds ride the standard
        resync path; the superseded replica (its node is ALIVE — this
        is a placement fix, not a failure) is dropped afterwards.
        Returns (dp_id, old, new) actions."""
        with self._lock:
            live = set(self._live(self.datanodes))
            load = self._dp_load()
            work = topology.cluster_misplacement(self.datanodes,
                                                 self.volumes)["dps"]
            plans = []
            moved = 0
            for vname, dp_id, excess in work:
                if moved >= max_moves:
                    break
                dp = next((d for d in self.volumes[vname]["dps"]
                           if d["dp_id"] == dp_id), None)
                if dp is None:
                    continue
                for old in excess:
                    if moved >= max_moves or old not in dp["replicas"]:
                        break
                    survivors = [a for a in dp["replicas"] if a != old]
                    healthy = [a for a in survivors if a in live]
                    cands = [a for a in live if a not in dp["replicas"]]
                    if not healthy or not cands:
                        continue
                    new = topology.pick_destination(
                        self.datanodes, cands, survivors, load=load)
                    moved_to = [new if a == old else a
                                for a in dp["replicas"]]
                    # only move when the destination actually improves
                    # the AZ spread — a full cluster can't, so the sweep
                    # must not churn replicas for nothing
                    if len(topology.replica_misplacement(
                            self.datanodes, moved_to)) >= len(excess):
                        continue
                    load[new] = load.get(new, 0) + 1
                    plans.append((vname, dict(dp), old, new, healthy[0]))
                    moved += 1
        actions = self._execute_rebuilds(plans)
        for dp_id, old, _new in actions:
            try:
                self.nodes.get(old).call("drop_partition", {"dp_id": dp_id})
            except rpc.RpcError:
                pass  # stale replica cleaned up on a later sweep
        self.misplacement_view()  # refresh the gauge post-move
        return actions

    def _rebuild_replica(self, vname: str, dp: dict, dead: str, new: str,
                         src: str) -> None:
        peers = [new if a == dead else a for a in dp["replicas"]]
        leader = new if dp["leader"] == dead else dp["leader"]
        self.nodes.get(new).call(
            "create_partition", {"dp_id": dp["dp_id"], "peers": peers,
                                 "leader": leader}
        )
        # copy every extent the healthy source actually has
        src_client = self.nodes.get(src)
        extents = src_client.call("list_extents", {"dp_id": dp["dp_id"]})[0]["extents"]
        for eid in extents:
            self.nodes.get(new).call(
                "sync_extent_from",
                {"dp_id": dp["dp_id"], "extent_id": eid, "src_addr": src},
            )
        # repoint every live replica's peer set, then install under lock
        for addr in peers:
            try:
                self.nodes.get(addr).call(
                    "create_partition",
                    {"dp_id": dp["dp_id"], "peers": peers, "leader": leader},
                )
            except rpc.RpcError:
                pass
        self._commit({"op": "update_dp", "name": vname, "dp_id": dp["dp_id"],
                      "replicas": peers, "leader": leader})

    # ---------------- RPC surface ----------------
    def rpc_register(self, args, body):
        zone = args.get("zone", "default")
        if args["kind"] == "data":
            self.register_datanode(args["addr"], zone,
                                   packet_addr=args.get("packet_addr"),
                                   disks=args.get("disks"),
                                   read_addr=args.get("read_addr"),
                                   rack=args.get("rack"))
        else:
            self.register_metanode(args["addr"], zone,
                                   packet_addr=args.get("packet_addr"),
                                   read_addr=args.get("read_addr"),
                                   rack=args.get("rack"))
        return {}

    def rpc_heartbeat(self, args, body):
        self.heartbeat(args["addr"], args["kind"], args.get("zone"),
                       packet_addr=args.get("packet_addr"),
                       read_addr=args.get("read_addr"),
                       disks=args.get("disks"),
                       rack=args.get("rack"))
        return {}

    def rpc_offline_disk(self, args, body):
        self._leader_gate()
        try:
            actions = self.offline_disk(args["addr"], args["path"])
        except MasterError as e:
            raise rpc.RpcError(404, str(e)) from None
        return {"actions": actions}

    def rpc_check_broken_disks(self, args, body):
        self._leader_gate()
        return {"actions": self.check_broken_disks()}

    def rpc_misplacement(self, args, body):
        view = self.misplacement_view()
        return {"misplaced": view["misplaced"],
                "dps": [list(t) for t in view["dps"]]}

    def rpc_sweep_misplaced(self, args, body):
        self._leader_gate()
        actions = self.sweep_misplaced(int(args.get("max_moves", 1)))
        return {"actions": actions,
                "misplaced": self.misplacement_view()["misplaced"]}

    def rpc_node_list(self, args, body):
        return self.node_list()

    def rpc_decommission_datanode(self, args, body):
        self._leader_gate()
        try:
            actions = self.decommission_datanode(args["addr"])
        except MasterError as e:
            raise rpc.RpcError(404, str(e)) from None
        return {"actions": actions}

    def rpc_check_meta_partitions(self, args, body):
        self._leader_gate()
        return {"actions": self.check_meta_partitions()}

    def rpc_split_meta_partition(self, args, body):
        self._leader_gate()
        try:
            return {"pid": self.split_meta_partition(args["name"])}
        except MasterError as e:
            raise rpc.RpcError(404, str(e)) from None

    # ------------- elastic metadata plane (fs/split.py) -------------
    def split_engine(self):
        """Lazy SplitEngine: masters that never migrate pay nothing,
        and tests reach the same instance the RPCs drive."""
        with self._lock:
            if self._split_engine is None:
                from .split import SplitEngine
                self._split_engine = SplitEngine(self)
            return self._split_engine

    def rpc_meta_split(self, args, body):
        self._leader_gate()
        try:
            return self.split_engine().split(
                args["name"], pid=args.get("pid"),
                split_ino=args.get("split_ino"))
        except MasterError as e:
            raise rpc.RpcError(404, str(e)) from None

    def rpc_meta_merge(self, args, body):
        self._leader_gate()
        try:
            return self.split_engine().merge(
                args["name"], donor_pid=args.get("donor_pid"),
                absorber_pid=args.get("absorber_pid"))
        except MasterError as e:
            raise rpc.RpcError(404, str(e)) from None

    def rpc_meta_balance(self, args, body):
        self._leader_gate()
        return self.split_engine().balance(
            int(args.get("max_moves", 1)),
            auto=bool(args.get("auto", False)))

    def rpc_meta_status(self, args, body):
        self._leader_gate()
        return self.split_engine().status(args.get("name"))

    def rpc_create_volume(self, args, body):
        self._leader_gate()
        return {"volume": self.create_volume(
            args["name"], args.get("mp_count", 3), args.get("dp_count", 4)
        )}

    def rpc_client_view(self, args, body):
        self._leader_gate()
        try:
            return {"volume": self.client_view(args["name"])}
        except MasterError as e:
            raise rpc.RpcError(404, str(e)) from None

    def rpc_dp_view(self, args, body):
        """Data partitions keyed by dp_id — all volumes by default, or
        one volume when args carries "name" (the CLI's dp view). The
        metanode free scan resolves freed extents' replicas from the
        unfiltered view (server-side deletes, partition_free_list.go)."""
        self._leader_gate()
        name = args.get("name")
        with self._lock:
            if name is not None and name not in self.volumes:
                raise rpc.RpcError(404, f"no volume {name!r}")
            vols = ([self.volumes[name]] if name is not None
                    else self.volumes.values())
            dps = {}
            for v in vols:
                for dp in v["dps"]:
                    dps[str(dp["dp_id"])] = {
                        "dp_id": dp["dp_id"], "replicas": dp["replicas"]}
            return {"dps": dps}

    def rpc_check_replica_health(self, args, body):
        """Alias of check_replicas (which sweeps both failure domains:
        dead nodes AND broken disks)."""
        self._leader_gate()
        return {"actions": self.check_replicas()}

    def rpc_check_replicas(self, args, body):
        # a deposed leader must not run datanode-mutating rebuilds
        self._leader_gate()
        return {"actions": self.check_replicas()}

    def rpc_set_quota(self, args, body):
        self._leader_gate()
        try:
            qid = self.set_quota(args["name"], args["dir_ino"],
                                 args.get("max_bytes", 0),
                                 args.get("max_files", 0))
        except MasterError as e:
            raise rpc.RpcError(404, str(e)) from None
        return {"qid": qid}

    def rpc_delete_quota(self, args, body):
        self._leader_gate()
        try:
            self.delete_quota(args["name"], args["qid"])
        except MasterError as e:
            raise rpc.RpcError(404, str(e)) from None
        return {}

    def rpc_list_quotas(self, args, body):
        try:
            return {"quotas": self.list_quotas(args["name"])}
        except MasterError as e:
            raise rpc.RpcError(404, str(e)) from None

    def rpc_set_vol_capacity(self, args, body):
        self._leader_gate()
        try:
            self.set_vol_capacity(args["name"], args["capacity"])
        except MasterError as e:
            raise rpc.RpcError(404, str(e)) from None
        return {}

    def rpc_enforce_quotas(self, args, body):
        self._leader_gate()
        return {"summary": self.enforce_quotas()}

    def rpc_stat(self, args, body):
        with self._lock:
            return {"datanodes": len(self.datanodes),
                    "metanodes": len(self.metanodes),
                    "volumes": sorted(self.volumes)}
