"""AWS Signature V4 verification for the S3 gateway.

Role parity: objectnode/auth_signature_v4.go — canonical request,
string-to-sign, and the AWS4-HMAC-SHA256 signing-key chain, verified
against the user store's secret keys. Header-auth flow (the one real
SDKs use); presigned URLs can layer on the same primitives.
"""

from __future__ import annotations

import base64
import calendar
import hashlib
import hmac

from ..utils import lockwitness
import time
import urllib.parse

MAX_CLOCK_SKEW = 15 * 60.0  # seconds, AWS's +/-15min request-time window


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str, service: str) -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_request(method: str, path: str, query: str,
                      headers: dict[str, str], signed_headers: list[str],
                      payload_hash: str) -> str:
    # S3 rule: the canonical URI is the raw request path exactly as sent
    # (single-encoded by the client). Re-encoding via quote(unquote(..))
    # would collapse client escapes like %2F inside a key and diverge
    # from what AWS SDKs sign.
    canon_uri = path or "/"
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    canon_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(pairs)
    )
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n" for h in signed_headers
    )
    return "\n".join([
        method, canon_uri, canon_query, canon_headers,
        ";".join(signed_headers), payload_hash,
    ])


def parse_v4_auth(headers: dict[str, str]) -> dict | None:
    """Split an AWS4-HMAC-SHA256 Authorization header into its parts
    (Credential fields, SignedHeaders, Signature) — shared by header
    verification and the streaming-chunk seed extraction."""
    auth = headers.get("authorization", "")
    if not auth.startswith("AWS4-HMAC-SHA256 "):
        return None
    parts = {}
    for item in auth[len("AWS4-HMAC-SHA256 "):].split(","):
        k, _, v = item.strip().partition("=")
        parts[k] = v
    try:
        ak, date, region, service, _term = parts["Credential"].split("/", 4)
        return {
            "ak": ak, "date": date, "region": region, "service": service,
            "signed_headers": parts["SignedHeaders"].split(";"),
            "signature": parts["Signature"],
        }
    except (KeyError, ValueError):
        return None


def verify_v4(method: str, path: str, query: str, headers: dict[str, str],
              payload: bytes, secret_for,
              now: float | None = None,
              payload_override: str | None = None) -> tuple[bool, str]:
    """Returns (ok, access_key_or_reason). headers keys must be
    lower-cased. secret_for(ak) -> sk | None. payload_override replaces
    the body-hash check with a literal canonical payload hash — used for
    STREAMING-AWS4-HMAC-SHA256-PAYLOAD where the body is authenticated
    by the per-chunk signature chain instead (the CALLER must then run
    that chain check)."""
    parsed = parse_v4_auth(headers)
    if parsed is None:
        return False, "missing/malformed AWS4-HMAC-SHA256 authorization"
    ak, date, region, service = (parsed["ak"], parsed["date"],
                                 parsed["region"], parsed["service"])
    signed_headers = parsed["signed_headers"]
    signature = parsed["signature"]
    sk = secret_for(ak)
    if sk is None:
        return False, f"unknown access key {ak}"
    # host and x-amz-date must be covered by the signature, or an
    # attacker could replay the request against another host/time
    if "host" not in signed_headers or "x-amz-date" not in signed_headers:
        return False, "host and x-amz-date must be signed"
    amz_date = headers.get("x-amz-date", "")
    if not amz_date.startswith(date):
        return False, "x-amz-date does not match credential scope date"
    try:
        req_time = calendar.timegm(time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
    except ValueError:
        return False, "malformed x-amz-date"
    skew = abs((time.time() if now is None else now) - req_time)
    if skew > MAX_CLOCK_SKEW:
        return False, "request time too skewed (replay window exceeded)"
    if payload_override is not None:
        payload_hash = payload_override
    elif "x-amz-content-sha256" in signed_headers:
        payload_hash = headers.get("x-amz-content-sha256", "")
        if (payload_hash != "UNSIGNED-PAYLOAD"
                and hashlib.sha256(payload).hexdigest() != payload_hash):
            return False, "payload hash mismatch"
    else:
        # the header is not covered by the signature, so its value proves
        # nothing: bind the signature to the actual body instead (blocks
        # body substitution via an attacker-supplied UNSIGNED-PAYLOAD)
        payload_hash = hashlib.sha256(payload).hexdigest()
    creq = canonical_request(method, path, query, headers, signed_headers,
                             payload_hash)
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(creq.encode()).hexdigest(),
    ])
    key = signing_key(sk, date, region, service)
    expect = hmac.new(key, string_to_sign.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(expect, signature):
        return False, "signature mismatch"
    return True, ak


def sign_v4(method: str, path: str, query: str, headers: dict[str, str],
            payload: bytes, ak: str, sk: str, amz_date: str,
            region: str = "us-east-1", service: str = "s3",
            payload_override: str | None = None) -> str:
    """Client-side signer (for tests and the CLI): returns the
    Authorization header value. headers must already include host and
    x-amz-date (lower-case keys). payload_override stands in for the
    body hash (streaming-signed PUTs sign the literal
    STREAMING-AWS4-HMAC-SHA256-PAYLOAD marker)."""
    date = amz_date[:8]
    payload_hash = payload_override or hashlib.sha256(payload).hexdigest()
    headers = dict(headers)
    headers.setdefault("x-amz-content-sha256", payload_hash)
    signed_headers = sorted(headers)
    creq = canonical_request(method, path, query, headers, signed_headers,
                             payload_hash)
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    sig = hmac.new(signing_key(sk, date, region, service), sts.encode(),
                   hashlib.sha256).hexdigest()
    return (f"AWS4-HMAC-SHA256 Credential={ak}/{scope}, "
            f"SignedHeaders={';'.join(signed_headers)}, Signature={sig}")


def _canonical_query(pairs: list[tuple[str, str]]) -> str:
    return "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(pairs)
    )


def presign_v4(method: str, path: str, host: str, ak: str, sk: str,
               amz_date: str, expires: int = 3600,
               region: str = "us-east-1", service: str = "s3",
               extra_query: list[tuple[str, str]] | None = None) -> str:
    """Build a presigned-URL query string (SigV4 query auth): the
    signature covers the query itself (minus X-Amz-Signature) and the
    host header; the payload is UNSIGNED-PAYLOAD."""
    date = amz_date[:8]
    scope = f"{date}/{region}/{service}/aws4_request"
    q = [
        ("X-Amz-Algorithm", "AWS4-HMAC-SHA256"),
        ("X-Amz-Credential", f"{ak}/{scope}"),
        ("X-Amz-Date", amz_date),
        ("X-Amz-Expires", str(expires)),
        ("X-Amz-SignedHeaders", "host"),
        *(extra_query or []),
    ]
    creq = "\n".join([
        method, path or "/", _canonical_query(q),
        f"host:{host}\n", "host", "UNSIGNED-PAYLOAD",
    ])
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    sig = hmac.new(signing_key(sk, date, region, service), sts.encode(),
                   hashlib.sha256).hexdigest()
    return _canonical_query(q) + "&X-Amz-Signature=" + sig


def verify_presigned_v4(method: str, path: str, query: str,
                        host: str, secret_for,
                        now: float | None = None) -> tuple[bool, str]:
    """Verify SigV4 query-string auth. Returns (ok, ak_or_reason)."""
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    params = dict(pairs)
    if params.get("X-Amz-Algorithm") != "AWS4-HMAC-SHA256":
        return False, "unsupported algorithm"
    try:
        cred = params["X-Amz-Credential"]
        amz_date = params["X-Amz-Date"]
        expires = int(params["X-Amz-Expires"])
        signed_headers = params["X-Amz-SignedHeaders"].split(";")
        signature = params["X-Amz-Signature"]
        ak, date, region, service, _term = cred.split("/", 4)
    except (KeyError, ValueError):
        return False, "malformed presigned query"
    sk = secret_for(ak)
    if sk is None:
        return False, f"unknown access key {ak}"
    if "host" not in signed_headers:
        return False, "host must be signed"
    if not amz_date.startswith(date):
        return False, "X-Amz-Date does not match credential scope date"
    try:
        t0 = calendar.timegm(time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
    except ValueError:
        return False, "malformed X-Amz-Date"
    t = time.time() if now is None else now
    if t > t0 + min(expires, 7 * 86400):
        return False, "presigned URL expired"
    if t < t0 - MAX_CLOCK_SKEW:
        return False, "presigned URL not yet valid"
    unsigned = [(k, v) for k, v in pairs if k != "X-Amz-Signature"]
    creq = "\n".join([
        method, path or "/", _canonical_query(unsigned),
        "".join(f"{h}:{host if h == 'host' else ''}\n"
                for h in signed_headers),
        ";".join(signed_headers), "UNSIGNED-PAYLOAD",
    ])
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    expect = hmac.new(signing_key(sk, date, region, service), sts.encode(),
                      hashlib.sha256).hexdigest()
    if not hmac.compare_digest(expect, signature):
        return False, "signature mismatch"
    return True, ak


# ---------------- Signature V2 (objectnode/auth_signature_v2.go) -------
_V2_SUBRESOURCES = ("acl", "policy", "cors", "tagging", "uploads",
                    "uploadId", "partNumber")


def _v2_string_to_sign(method: str, path: str, query: str,
                       headers: dict[str, str]) -> str:
    amz = sorted(
        (k.lower(), " ".join(v.split()))
        for k, v in headers.items() if k.lower().startswith("x-amz-")
    )
    canon_amz = "".join(f"{k}:{v}\n" for k, v in amz)
    sub = [(k, v) for k, v in
           urllib.parse.parse_qsl(query, keep_blank_values=True)
           if k in _V2_SUBRESOURCES]
    resource = path or "/"
    if sub:
        resource += "?" + "&".join(
            k if not v else f"{k}={v}" for k, v in sorted(sub))
    date = "" if "x-amz-date" in {k for k, _ in amz} else headers.get("date", "")
    return "\n".join([
        method,
        headers.get("content-md5", ""),
        headers.get("content-type", ""),
        date,
    ]) + "\n" + canon_amz + resource


def sign_v2(method: str, path: str, query: str, headers: dict[str, str],
            ak: str, sk: str) -> str:
    sts = _v2_string_to_sign(method, path, query, headers)
    sig = base64.b64encode(
        hmac.new(sk.encode(), sts.encode(), hashlib.sha1).digest()
    ).decode()
    return f"AWS {ak}:{sig}"


def verify_v2(method: str, path: str, query: str, headers: dict[str, str],
              secret_for, now: float | None = None) -> tuple[bool, str]:
    auth = headers.get("authorization", "")
    if not auth.startswith("AWS ") or ":" not in auth:
        return False, "missing AWS v2 authorization"
    ak, _, sig = auth[4:].rpartition(":")
    sk = secret_for(ak)
    if sk is None:
        return False, f"unknown access key {ak}"
    # replay window on Date / x-amz-date
    date_hdr = headers.get("x-amz-date") or headers.get("date", "")
    req_time = None
    for fmt in ("%a, %d %b %Y %H:%M:%S GMT", "%Y%m%dT%H%M%SZ"):
        try:
            req_time = calendar.timegm(time.strptime(date_hdr, fmt))
            break
        except ValueError:
            continue
    if req_time is None:
        return False, "missing/malformed Date"
    if abs((time.time() if now is None else now) - req_time) > MAX_CLOCK_SKEW:
        return False, "request time too skewed (replay window exceeded)"
    sts = _v2_string_to_sign(method, path, query, headers)
    expect = base64.b64encode(
        hmac.new(sk.encode(), sts.encode(), hashlib.sha1).digest()
    ).decode()
    if not hmac.compare_digest(expect, sig):
        return False, "signature mismatch"
    return True, ak


class MasterUserStore:
    """UserStore backend served by the master's replicated user table
    (master/user.go flow: the gateway fetches AK/SK + grants from the
    resource manager, with a short TTL cache so authentication does not
    hit the master on every request)."""

    TTL = 30.0
    MAX_CACHE = 10_000

    def __init__(self, master_client):
        self._c = master_client
        self._cache: dict[str, tuple[float, dict | None]] = {}
        self._lock = lockwitness.make_lock("MasterUserStore._lock")

    def _info(self, ak: str) -> dict | None:
        from ..utils import rpc as _rpc

        now = time.time()
        with self._lock:
            hit = self._cache.get(ak)
            if hit and now - hit[0] < self.TTL:
                return hit[1]
        try:
            info = self._c.call("user_auth_info", {"ak": ak})[0]
        except _rpc.RpcError as e:
            if not (400 <= e.code < 500):
                # transient master failure: serve the stale cached value
                # if any, and do NOT cache the outage as "unknown key"
                return hit[1] if hit else None
            info = None  # definitive: key does not exist
        except Exception:
            return hit[1] if hit else None
        with self._lock:
            if len(self._cache) >= self.MAX_CACHE:
                # evict NEGATIVE (unknown-key) entries first — spray
                # garbage is negative by definition — then oldest, so an
                # attacker can never push out legitimate keys
                victims = sorted(
                    self._cache.items(),
                    key=lambda kv: (kv[1][1] is not None, kv[1][0]))
                for k, _ in victims[: self.MAX_CACHE // 2]:
                    del self._cache[k]
            self._cache[ak] = (now, info)
        return info

    def secret_for(self, ak: str) -> str | None:
        info = self._info(ak)
        return info["sk"] if info else None

    def allowed(self, ak: str, volume: str, write: bool) -> bool:
        info = self._info(ak)
        if info is None:
            return False
        perm = info["volumes"].get(volume, "")
        return "w" in perm if write else bool(perm)


class S3V4Authenticator:
    """Pluggable objectnode authenticator backed by a UserStore.

    `authenticate` establishes WHO the caller is — V4 header auth, V4
    presigned query auth, or V2 header auth; a request with no
    credentials at all is the anonymous principal (None), left for the
    authorization layer (ACL/policy) to judge. `__call__` keeps the
    legacy boolean authn+grant contract."""

    def __init__(self, user_store, bucket_volume: dict[str, str] | None = None,
                 sts=None):
        self.users = user_store
        self.bucket_volume = bucket_volume or {}
        self.sts = sts  # s3ext.Sts issuer for temporary credentials

    def authenticate(self, handler) -> tuple[bool, str | None, str]:
        """Returns (ok, principal, reason). ok=False means credentials
        were presented but are INVALID (reject 403); principal None with
        ok=True means anonymous."""
        from . import s3ext

        n = int(handler.headers.get("Content-Length") or 0)
        # read + stash the body so the verb handler can reuse it
        body = handler.rfile.read(n) if n else b""
        handler._stashed_body = body
        parsed = urllib.parse.urlsplit(handler.path)
        headers = {k.lower(): v for k, v in handler.headers.items()}
        auth_hdr = headers.get("authorization", "")

        # parse the Authorization header ONCE; every V4 leg below (token
        # check, header verification, chunk-seed extraction) reuses it
        v4 = (parse_v4_auth(headers)
              if auth_hdr.startswith("AWS4-HMAC-SHA256 ") else None)

        # temporary credentials: the session token resolves to a derived
        # temp secret; the principal is the PARENT key (grants follow it)
        secret_for = self.users.secret_for
        principal_map = None
        token = headers.get("x-amz-security-token")
        if token is not None and v4 is not None:
            if self.sts is None:
                return False, None, "session tokens not enabled"
            claims = self.sts.resolve(token)
            if claims is None:
                return False, None, "invalid/expired session token"
            if "x-amz-security-token" not in v4["signed_headers"]:
                # an unsigned token header proves nothing: reject
                return False, None, "x-amz-security-token must be signed"
            tak, tsk = claims["tak"], claims["sk"]
            secret_for = lambda ak: tsk if ak == tak else None  # noqa: E731
            principal_map = {tak: claims["pak"]}
            handler._via_token = True  # STS endpoint refuses chaining

        if auth_hdr.startswith("AWS4-HMAC-SHA256 "):
            streaming = (headers.get("x-amz-content-sha256")
                         == s3ext.STREAMING_PAYLOAD)
            ok, who = verify_v4(
                handler.command, parsed.path, parsed.query, headers, body,
                secret_for,
                payload_override=s3ext.STREAMING_PAYLOAD if streaming else None)
            if ok and streaming:
                # header signature only covers the headers; the body is
                # authenticated chunk-by-chunk against the seed signature
                want = headers.get("x-amz-decoded-content-length")
                try:
                    want_n = None if want is None else int(want)
                except ValueError:
                    return False, None, "malformed x-amz-decoded-content-length"
                sk = secret_for(v4["ak"])
                key = signing_key(sk, v4["date"], v4["region"], v4["service"])
                scope = (f"{v4['date']}/{v4['region']}/{v4['service']}"
                         f"/aws4_request")
                cok, out = s3ext.verify_aws_chunked(
                    body, v4["signature"], key,
                    headers.get("x-amz-date", ""), scope)
                if not cok:
                    return False, None, str(out)
                if want_n is not None and want_n != len(out):
                    return False, None, "decoded length mismatch"
                handler._stashed_body = out
            if ok and principal_map is not None:
                who = principal_map.get(who, who)
            return (ok, who if ok else None, "" if ok else who)
        if headers.get("x-amz-content-sha256") == s3ext.STREAMING_PAYLOAD:
            # aws-chunked bodies are only defined for SigV4 header auth
            # (the chunk chain needs a seed signature); admitting a V2 /
            # presigned / anonymous streaming PUT would store the raw
            # framing — chunk headers and signatures — as object bytes
            return False, None, "streaming payload requires SigV4 header auth"
        if auth_hdr.startswith("AWS "):
            ok, who = verify_v2(handler.command, parsed.path, parsed.query,
                                headers, self.users.secret_for)
            return (ok, who if ok else None, "" if ok else who)
        if "X-Amz-Signature" in parsed.query:
            ok, who = verify_presigned_v4(
                handler.command, parsed.path, parsed.query,
                headers.get("host", ""), self.users.secret_for)
            return (ok, who if ok else None, "" if ok else who)
        if auth_hdr:
            # an unrecognized/malformed Authorization scheme must be
            # rejected, never silently downgraded to anonymous
            return False, None, "unsupported authorization scheme"
        return True, None, ""  # anonymous

    def grant_ok(self, principal: str | None, bucket: str,
                 write: bool) -> bool:
        if principal is None:
            return False
        volume = self.bucket_volume.get(bucket, bucket)
        return self.users.allowed(principal, volume, write)

    def __call__(self, handler) -> bool:
        ok, who, _ = self.authenticate(handler)
        if not ok or who is None:
            return False
        parsed = urllib.parse.urlsplit(handler.path)
        bucket = parsed.path.lstrip("/").split("/", 1)[0]
        write = handler.command in ("PUT", "POST", "DELETE")
        return self.grant_ok(who, bucket, write)
