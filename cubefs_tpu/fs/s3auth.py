"""AWS Signature V4 verification for the S3 gateway.

Role parity: objectnode/auth_signature_v4.go — canonical request,
string-to-sign, and the AWS4-HMAC-SHA256 signing-key chain, verified
against the user store's secret keys. Header-auth flow (the one real
SDKs use); presigned URLs can layer on the same primitives.
"""

from __future__ import annotations

import calendar
import hashlib
import hmac
import time
import urllib.parse

MAX_CLOCK_SKEW = 15 * 60.0  # seconds, AWS's +/-15min request-time window


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def signing_key(secret: str, date: str, region: str, service: str) -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    return _hmac(k, "aws4_request")


def canonical_request(method: str, path: str, query: str,
                      headers: dict[str, str], signed_headers: list[str],
                      payload_hash: str) -> str:
    # S3 rule: the canonical URI is the raw request path exactly as sent
    # (single-encoded by the client). Re-encoding via quote(unquote(..))
    # would collapse client escapes like %2F inside a key and diverge
    # from what AWS SDKs sign.
    canon_uri = path or "/"
    pairs = urllib.parse.parse_qsl(query, keep_blank_values=True)
    canon_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(pairs)
    )
    canon_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n" for h in signed_headers
    )
    return "\n".join([
        method, canon_uri, canon_query, canon_headers,
        ";".join(signed_headers), payload_hash,
    ])


def verify_v4(method: str, path: str, query: str, headers: dict[str, str],
              payload: bytes, secret_for,
              now: float | None = None) -> tuple[bool, str]:
    """Returns (ok, access_key_or_reason). headers keys must be
    lower-cased. secret_for(ak) -> sk | None."""
    auth = headers.get("authorization", "")
    if not auth.startswith("AWS4-HMAC-SHA256 "):
        return False, "missing AWS4-HMAC-SHA256 authorization"
    parts = {}
    for item in auth[len("AWS4-HMAC-SHA256 "):].split(","):
        k, _, v = item.strip().partition("=")
        parts[k] = v
    try:
        cred = parts["Credential"]
        signed_headers = parts["SignedHeaders"].split(";")
        signature = parts["Signature"]
        ak, date, region, service, scope_term = cred.split("/", 4)
    except (KeyError, ValueError):
        return False, "malformed authorization header"
    sk = secret_for(ak)
    if sk is None:
        return False, f"unknown access key {ak}"
    # host and x-amz-date must be covered by the signature, or an
    # attacker could replay the request against another host/time
    if "host" not in signed_headers or "x-amz-date" not in signed_headers:
        return False, "host and x-amz-date must be signed"
    amz_date = headers.get("x-amz-date", "")
    if not amz_date.startswith(date):
        return False, "x-amz-date does not match credential scope date"
    try:
        req_time = calendar.timegm(time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
    except ValueError:
        return False, "malformed x-amz-date"
    skew = abs((time.time() if now is None else now) - req_time)
    if skew > MAX_CLOCK_SKEW:
        return False, "request time too skewed (replay window exceeded)"
    if "x-amz-content-sha256" in signed_headers:
        payload_hash = headers.get("x-amz-content-sha256", "")
        if (payload_hash != "UNSIGNED-PAYLOAD"
                and hashlib.sha256(payload).hexdigest() != payload_hash):
            return False, "payload hash mismatch"
    else:
        # the header is not covered by the signature, so its value proves
        # nothing: bind the signature to the actual body instead (blocks
        # body substitution via an attacker-supplied UNSIGNED-PAYLOAD)
        payload_hash = hashlib.sha256(payload).hexdigest()
    creq = canonical_request(method, path, query, headers, signed_headers,
                             payload_hash)
    scope = f"{date}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(creq.encode()).hexdigest(),
    ])
    key = signing_key(sk, date, region, service)
    expect = hmac.new(key, string_to_sign.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(expect, signature):
        return False, "signature mismatch"
    return True, ak


def sign_v4(method: str, path: str, query: str, headers: dict[str, str],
            payload: bytes, ak: str, sk: str, amz_date: str,
            region: str = "us-east-1", service: str = "s3") -> str:
    """Client-side signer (for tests and the CLI): returns the
    Authorization header value. headers must already include host and
    x-amz-date (lower-case keys)."""
    date = amz_date[:8]
    payload_hash = hashlib.sha256(payload).hexdigest()
    headers = dict(headers)
    headers.setdefault("x-amz-content-sha256", payload_hash)
    signed_headers = sorted(headers)
    creq = canonical_request(method, path, query, headers, signed_headers,
                             payload_hash)
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    sig = hmac.new(signing_key(sk, date, region, service), sts.encode(),
                   hashlib.sha256).hexdigest()
    return (f"AWS4-HMAC-SHA256 Credential={ak}/{scope}, "
            f"SignedHeaders={';'.join(signed_headers)}, Signature={sig}")


class S3V4Authenticator:
    """Pluggable objectnode authenticator backed by a UserStore: verifies
    the signature AND the key's grant on the target bucket/volume."""

    def __init__(self, user_store, bucket_volume: dict[str, str] | None = None):
        self.users = user_store
        self.bucket_volume = bucket_volume or {}

    def __call__(self, handler) -> bool:
        n = int(handler.headers.get("Content-Length") or 0)
        # read + stash the body so the verb handler can reuse it
        body = handler.rfile.read(n) if n else b""
        handler._stashed_body = body
        parsed = urllib.parse.urlsplit(handler.path)
        headers = {k.lower(): v for k, v in handler.headers.items()}
        ok, who = verify_v4(handler.command, parsed.path, parsed.query,
                            headers, body, self.users.secret_for)
        if not ok:
            return False
        bucket = parsed.path.lstrip("/").split("/", 1)[0]
        volume = self.bucket_volume.get(bucket, bucket)
        write = handler.command in ("PUT", "POST", "DELETE")
        return self.users.allowed(who, volume, write)
