"""Pythonic facade over the native ordered-KV engine (ctypes).

The RocksDB choke point of the reference (blobstore/common/kvstorev2/
rocksdb.go, raftstore/raftstore_db/store_rocksdb.go) as a C++ runtime
component: crash-safe mutations (CRC-framed WAL + snapshot compaction)
and ordered range scans. Used by the shardnode's durable shards and as
the segment store for incremental control-plane snapshots.
"""

from __future__ import annotations

import ctypes
import os

from . import build as rt


class KvError(Exception):
    pass


class KvStore:
    def __init__(self, directory: str):
        os.makedirs(directory, exist_ok=True)
        self._lib = rt.load()
        # lint: allow[CFL101] local-disk open, no network; callers' locks guard the handle's lifecycle, which is exactly why open runs under them
        self._h = self._lib.kv_open(directory.encode())
        if not self._h:
            raise KvError(f"cannot open kv store at {directory}")
        self.directory = directory

    def close(self) -> None:
        if self._h:
            self._lib.kv_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- mutations ----
    def put(self, key: bytes | str, value: bytes) -> None:
        k = key.encode() if isinstance(key, str) else key
        if self._lib.kv_put(self._h, k, len(k), value, len(value)) != 0:
            raise KvError(f"put {k!r} failed (WAL write error)")

    def delete(self, key: bytes | str) -> None:
        k = key.encode() if isinstance(key, str) else key
        r = self._lib.kv_del(self._h, k, len(k))
        if r == -1:
            raise KeyError(k)
        if r != 0:
            raise KvError(f"delete {k!r} failed (WAL write error)")

    # ---- reads ----
    def get(self, key: bytes | str) -> bytes:
        k = key.encode() if isinstance(key, str) else key
        cap = 4096
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.kv_get(self._h, k, len(k), buf, cap)
            if n < 0:
                raise KeyError(k)
            if n <= cap:
                return buf.raw[:n]
            cap = int(n)  # value longer than the buffer: retry exact

    def __contains__(self, key: bytes | str) -> bool:
        try:
            self.get(key)
            return True
        except KeyError:
            return False

    def count(self) -> int:
        return int(self._lib.kv_count(self._h))

    def apply_batch(self, ops) -> None:
        """Atomically applies [(op, key, value)] — op "put"/"delete" —
        with a single WAL append + fsync (splits move ranges this way
        instead of paying a sync per key)."""
        blob = bytearray()
        for op, key, value in ops:
            k = key.encode() if isinstance(key, str) else key
            v = value or b""
            blob.append(1 if op == "put" else 2)
            blob += len(k).to_bytes(4, "little")
            blob += len(v).to_bytes(4, "little")
            blob += k
            blob += v
        if not blob:
            return
        # lint: allow[CFL101] local-disk WAL append, bounded, no network; holding the owning shard/segment lock is the batch's atomicity guard
        n = self._lib.kv_batch(self._h, bytes(blob), len(blob))
        if n != len(ops):
            raise KvError(f"batch applied {n}/{len(ops)}")

    def scan(self, start: bytes = b"", end: bytes = b"",
             max_items: int = 1 << 30):
        """Yields (key, value) over [start, end) in key order, paging
        through the native boundary in bounded chunks. The page buffer
        grows when a single record exceeds it (a fat value must never
        silently truncate the scan — range moves and snapshots rely on
        completeness)."""
        remaining = max_items
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        n_out = ctypes.c_uint32()
        more = ctypes.c_uint32()
        while remaining > 0:
            # lint: allow[CFL101] in-memory/local-disk ordered read, no network; callers hold the shard lock so the scan sees one consistent version
            used = self._lib.kv_scan(
                self._h, start, len(start), end, len(end),
                min(remaining, 10_000), buf, cap,
                ctypes.byref(n_out), ctypes.byref(more))
            if used < 0:
                raise KvError("scan failed")
            if n_out.value == 0 and more.value:
                # first record alone exceeds the buffer: grow and retry
                if cap >= 1 << 31:
                    raise KvError("record exceeds 2 GiB scan buffer")
                cap *= 4
                buf = ctypes.create_string_buffer(cap)
                continue
            off = 0
            raw = buf.raw
            last_key = None
            for _ in range(n_out.value):
                klen = int.from_bytes(raw[off:off + 4], "little")
                vlen = int.from_bytes(raw[off + 4:off + 8], "little")
                off += 8
                key = raw[off:off + klen]
                off += klen
                val = raw[off:off + vlen]
                off += vlen
                last_key = key
                yield key, val
                remaining -= 1
            if not more.value or last_key is None:
                return
            start = last_key + b"\x00"

    def median_key(self, start: bytes = b"", end: bytes = b"") -> bytes | None:
        cap = 1 << 16
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.kv_median(self._h, start, len(start), end, len(end),
                                buf, cap)
        return None if n < 0 else buf.raw[:n]

    # ---- maintenance ----
    def compact(self) -> None:
        if self._lib.kv_compact(self._h) != 0:
            raise KvError("compact failed")

    def clear(self) -> None:
        if self._lib.kv_clear(self._h) != 0:
            raise KvError("clear failed")

    def wal_bytes(self) -> int:
        return int(self._lib.kv_wal_bytes(self._h))

    def snap_bytes(self) -> int:
        return int(self._lib.kv_snap_bytes(self._h))
