// Native chunk-store runtime: the blobnode disk engine.
//
// Role parity: reference blobstore/blobnode/core (chunk data files with
// crc32block framing at core/storage/datafile.go:304-379 + RocksDB shard
// meta). This implementation is TPU-framework-native: a C++ engine with a
// C ABI consumed via ctypes (no cgo), storing
//   <dir>/chunk_<id>.data   — append-only shard payloads
//   <dir>/chunk_<id>.idx    — append-only fixed-width index records
// Shard lookup state is rebuilt from the index log at open (last record
// wins, delete records tombstone). CRC32 (IEEE, slicing-by-8) is computed
// on write and verified on read — this is also the CPU baseline the TPU
// CRC kernel is compared against.
//
// Build: g++ -O3 -shared -fPIC -o libcubefs_rt.so chunkstore.cc

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cerrno>
#include <string>
#include <unordered_map>
#include <map>
#include <mutex>
#include <vector>
#include <cstdlib>
#include <dirent.h>
#include <fcntl.h>
#include <unistd.h>
#include <sys/stat.h>

#include "bufpool.h"

namespace {

// ---------------- CRC32 (IEEE reflected), slicing-by-8 ----------------
// CRC32 delegates to the shared native kernel (crc32cpu.cc): CLMUL
// folding at ~13 GB/s with a table fallback, bit-identical with zlib.
extern "C" uint32_t rt_crc32(uint32_t crc, const uint8_t* p, size_t n);

uint32_t crc32_ieee(uint32_t crc, const uint8_t* p, size_t n) {
  return rt_crc32(crc, p, n);
}

// ---------------- index format ----------------
// v2 idx files start with a header carrying the DATA FILE GENERATION:
// compaction writes a new generation data file and commits it with ONE
// atomic idx rename — there is never a moment where a live idx points at
// half-swapped data. Legacy headerless files read as generation 0.
struct __attribute__((packed)) IdxHdr {
  uint64_t magic;  // kIdxMagic
  uint64_t gen;
};
constexpr uint64_t kIdxMagic = 0xCFC17A6Eull;

struct __attribute__((packed)) IdxRec {
  uint64_t bid;      // blob id
  uint64_t offset;   // offset in .data file
  uint32_t size;     // payload bytes
  uint32_t crc;      // payload crc32
  uint32_t flags;    // 1 = delete tombstone
  uint32_t rec_crc;  // crc of the preceding fields
};

struct ShardLoc {
  uint64_t offset;
  uint32_t size;
  uint32_t crc;
};

struct Chunk {
  int data_fd = -1;
  int idx_fd = -1;
  uint64_t data_end = 0;
  uint64_t gen = 0;  // data file generation (committed via the idx)
  std::map<uint64_t, ShardLoc> shards;  // ordered for list-scans
  std::mutex mu;
};

struct Store {
  std::string dir;
  std::unordered_map<uint64_t, Chunk*> chunks;
  std::mutex mu;
  char err[256] = {0};
};

thread_local char g_err[256];

void set_err(Store* s, const char* msg) {
  snprintf(s ? s->err : g_err, 256, "%s (errno=%d %s)", msg, errno,
           errno ? strerror(errno) : "");
}

std::string chunk_path(Store* s, uint64_t id, const char* ext) {
  char buf[64];
  snprintf(buf, sizeof buf, "/chunk_%016llx.%s", (unsigned long long)id, ext);
  return s->dir + buf;
}

std::string data_path(Store* s, uint64_t id, uint64_t gen) {
  if (gen == 0) return chunk_path(s, id, "data");  // legacy name
  char buf[80];
  snprintf(buf, sizeof buf, "/chunk_%016llx.g%llu.data",
           (unsigned long long)id, (unsigned long long)gen);
  return s->dir + buf;
}

void fsync_dir(Store* s) {
  int fd = ::open(s->dir.c_str(), O_RDONLY);
  if (fd >= 0) {
    fsync(fd);
    close(fd);
  }
}

// Remove every data file of this chunk whose generation is not the
// committed one: a crash between the compaction commit rename and the
// old-file unlink leaves gen N-1 behind; a crash before the rename
// leaves gen N+1 — scan rather than guess, so nothing leaks.
void gc_stale_generations(Store* s, uint64_t id, uint64_t live_gen) {
  DIR* d = opendir(s->dir.c_str());
  if (!d) return;
  char prefix[64];
  snprintf(prefix, sizeof prefix, "chunk_%016llx.g", (unsigned long long)id);
  size_t plen = strlen(prefix);
  struct dirent* e;
  while ((e = readdir(d)) != nullptr) {
    if (strncmp(e->d_name, prefix, plen) != 0) continue;
    char* end = nullptr;
    unsigned long long g = strtoull(e->d_name + plen, &end, 10);
    if (end == e->d_name + plen || strcmp(end, ".data") != 0) continue;
    if (g != live_gen) unlink((s->dir + "/" + e->d_name).c_str());
  }
  closedir(d);
  if (live_gen != 0) unlink(chunk_path(s, id, "data").c_str());  // legacy g0
}

bool load_chunk(Store* s, uint64_t id, Chunk* c) {
  std::string ip = chunk_path(s, id, "idx");
  c->idx_fd = ::open(ip.c_str(), O_RDWR | O_CREAT | O_APPEND, 0644);
  if (c->idx_fd < 0) {
    set_err(s, "open idx file");
    return false;
  }
  // the idx header names the data generation (single commit point)
  IdxHdr hdr;
  off_t pos = 0;
  c->gen = 0;
  if (pread(c->idx_fd, &hdr, sizeof hdr, 0) == (ssize_t)sizeof hdr &&
      hdr.magic == kIdxMagic) {
    c->gen = hdr.gen;
    pos = sizeof hdr;
  }
  std::string dp = data_path(s, id, c->gen);
  c->data_fd = ::open(dp.c_str(), O_RDWR | O_CREAT, 0644);
  if (c->data_fd < 0) {
    set_err(s, "open data file");
    return false;
  }
  struct stat st;
  fstat(c->data_fd, &st);
  c->data_end = (uint64_t)st.st_size;
  // replay index log; torn/corrupt tail records are ignored (crash safety)
  IdxRec r;
  while (pread(c->idx_fd, &r, sizeof r, pos) == (ssize_t)sizeof r) {
    uint32_t expect = crc32_ieee(0, (const uint8_t*)&r, sizeof r - 4);
    if (r.rec_crc != expect) break;
    if (r.flags & 1)
      c->shards.erase(r.bid);
    else
      c->shards[r.bid] = ShardLoc{r.offset, r.size, r.crc};
    pos += sizeof r;
  }
  // crashes around compaction can leave stray data files of any other
  // generation (uncommitted gen+1, or the replaced gen-1 if the crash
  // hit between commit rename and unlink): sweep them all
  gc_stale_generations(s, id, c->gen);
  return true;
}

bool append_idx(Store* s, Chunk* c, const IdxRec& rec) {
  IdxRec r = rec;
  r.rec_crc = crc32_ieee(0, (const uint8_t*)&r, sizeof r - 4);
  if (write(c->idx_fd, &r, sizeof r) != (ssize_t)sizeof r) {
    set_err(s, "append idx");
    return false;
  }
  return true;
}

Chunk* get_chunk(Store* s, uint64_t id, bool create) {
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->chunks.find(id);
  if (it != s->chunks.end()) return it->second;
  if (!create) {
    // lazily open if the chunk exists on disk; the idx is the one file
    // every generation keeps (the data filename changes on compaction)
    std::string ip = chunk_path(s, id, "idx");
    if (access(ip.c_str(), F_OK) != 0) {
      set_err(s, "no such chunk");
      return nullptr;
    }
  }
  Chunk* c = new Chunk();
  if (!load_chunk(s, id, c)) {
    delete c;
    return nullptr;
  }
  s->chunks[id] = c;
  return c;
}

}  // namespace

extern "C" {

void* cs_open(const char* dir) {
  Store* s = new Store();
  s->dir = dir;
  ::mkdir(dir, 0755);
  struct stat st;
  if (stat(dir, &st) != 0 || !S_ISDIR(st.st_mode)) {
    set_err(nullptr, "store dir unusable");
    delete s;
    return nullptr;
  }
  return s;
}

void cs_close(void* h) {
  Store* s = (Store*)h;
  if (!s) return;
  for (auto& kv : s->chunks) {
    if (kv.second->data_fd >= 0) ::close(kv.second->data_fd);
    if (kv.second->idx_fd >= 0) ::close(kv.second->idx_fd);
    delete kv.second;
  }
  delete s;
}

const char* cs_last_error(void* h) { return h ? ((Store*)h)->err : g_err; }

int cs_create_chunk(void* h, uint64_t chunk_id) {
  Store* s = (Store*)h;
  return get_chunk(s, chunk_id, true) ? 0 : -1;
}

// Write a shard; returns 0 and fills out_crc. Overwrite of an existing
// bid appends new data and repoints the index (last-wins), matching the
// append-only chunk file + meta-update model.
int cs_put_shard(void* h, uint64_t chunk_id, uint64_t bid, const uint8_t* buf,
                 uint32_t len, uint32_t* out_crc) {
  Store* s = (Store*)h;
  Chunk* c = get_chunk(s, chunk_id, true);
  if (!c) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  uint32_t crc = crc32_ieee(0, buf, len);
  uint64_t off = c->data_end;
  ssize_t wr = pwrite(c->data_fd, buf, len, (off_t)off);
  if (wr != (ssize_t)len) {
    set_err(s, "pwrite shard");
    return -1;
  }
  c->data_end += len;
  IdxRec rec{bid, off, len, crc, 0, 0};
  if (!append_idx(s, c, rec)) return -1;
  c->shards[bid] = ShardLoc{off, len, crc};
  if (out_crc) *out_crc = crc;
  return 0;
}

// Returns shard size, or -1 (missing) / -2 (crc mismatch) / -3 (short buf).
int64_t cs_get_shard(void* h, uint64_t chunk_id, uint64_t bid, uint8_t* buf,
                     uint32_t buf_len, uint32_t* out_crc) {
  Store* s = (Store*)h;
  Chunk* c = get_chunk(s, chunk_id, false);
  if (!c) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->shards.find(bid);
  if (it == c->shards.end()) {
    set_err(s, "shard not found");
    return -1;
  }
  const ShardLoc& loc = it->second;
  if (buf_len < loc.size) {
    set_err(s, "buffer too small");
    return -3;
  }
  if (pread(c->data_fd, buf, loc.size, (off_t)loc.offset) != (ssize_t)loc.size) {
    set_err(s, "pread shard");
    return -1;
  }
  uint32_t crc = crc32_ieee(0, buf, loc.size);
  if (out_crc) *out_crc = crc;
  if (crc != loc.crc) {
    set_err(s, "crc mismatch");
    return -2;
  }
  return (int64_t)loc.size;
}

int cs_delete_shard(void* h, uint64_t chunk_id, uint64_t bid) {
  Store* s = (Store*)h;
  Chunk* c = get_chunk(s, chunk_id, false);
  if (!c) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->shards.find(bid);
  if (it == c->shards.end()) {
    set_err(s, "shard not found");
    return -1;
  }
  IdxRec rec{bid, 0, 0, 0, 1, 0};
  if (!append_idx(s, c, rec)) return -1;
  c->shards.erase(it);
  return 0;
}

// Fill up to cap entries with (bid, size, crc) triples; returns count.
int64_t cs_list_shards(void* h, uint64_t chunk_id, uint64_t* bids,
                       uint32_t* sizes, uint32_t* crcs, int64_t cap) {
  Store* s = (Store*)h;
  Chunk* c = get_chunk(s, chunk_id, false);
  if (!c) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  int64_t i = 0;
  for (auto& kv : c->shards) {
    if (i >= cap) break;
    bids[i] = kv.first;
    sizes[i] = kv.second.size;
    crcs[i] = kv.second.crc;
    i++;
  }
  return i;
}

int64_t cs_shard_count(void* h, uint64_t chunk_id) {
  Store* s = (Store*)h;
  Chunk* c = get_chunk(s, chunk_id, false);
  if (!c) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  return (int64_t)c->shards.size();
}

int cs_sync(void* h, uint64_t chunk_id) {
  Store* s = (Store*)h;
  Chunk* c = get_chunk(s, chunk_id, false);
  if (!c) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  if (fsync(c->data_fd) != 0 || fsync(c->idx_fd) != 0) {
    set_err(s, "fsync");
    return -1;
  }
  return 0;
}

// Compaction: rewrite only the LIVE shards into fresh data+idx files and
// atomically swap them in (role parity: blobnode chunk compaction,
// core/chunk/compact.go) — append-only writes + tombstones otherwise
// grow files forever. Returns bytes reclaimed, or -1.
int64_t cs_compact_chunk(void* h, uint64_t chunk_id) {
  Store* s = (Store*)h;
  Chunk* c = get_chunk(s, chunk_id, false);
  if (!c) return -1;
  std::lock_guard<std::mutex> g(c->mu);
  uint64_t new_gen = c->gen + 1;
  std::string ip = chunk_path(s, chunk_id, "idx");
  std::string ndp = data_path(s, chunk_id, new_gen);
  std::string itmp = ip + ".compact";
  int dfd = ::open(ndp.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0644);
  int ifd = ::open(itmp.c_str(), O_RDWR | O_CREAT | O_TRUNC | O_APPEND, 0644);
  auto fail = [&](const char* msg, int64_t code) {
    set_err(s, msg);
    if (dfd >= 0) close(dfd);
    if (ifd >= 0) close(ifd);
    unlink(ndp.c_str());
    unlink(itmp.c_str());
    return code;
  };
  if (dfd < 0 || ifd < 0) return fail("open compact files", -1);
  IdxHdr hdr{kIdxMagic, new_gen};
  if (write(ifd, &hdr, sizeof hdr) != (ssize_t)sizeof hdr)
    return fail("compact hdr write", -1);
  uint64_t new_end = 0;
  std::map<uint64_t, ShardLoc> new_shards;
  // ONE pooled scratch for the whole pass, sized to the largest shard —
  // per-iteration allocation (pooled or not) would be pure churn, and
  // shards can exceed the pool's largest class
  uint64_t max_size = 0;
  for (auto& kv : c->shards)
    max_size = std::max(max_size, (uint64_t)kv.second.size);
  PoolBuf buf(max_size ? max_size : 1);
  for (auto& kv : c->shards) {
    const ShardLoc& loc = kv.second;
    if (pread(c->data_fd, buf.data, loc.size, (off_t)loc.offset) !=
        (ssize_t)loc.size)
      return fail("compact pread", -1);
    if (crc32_ieee(0, buf.data, loc.size) != loc.crc)
      return fail("compact crc mismatch (refusing to carry corruption)", -2);
    if (pwrite(dfd, buf.data, loc.size, (off_t)new_end) != (ssize_t)loc.size)
      return fail("compact pwrite", -1);
    IdxRec rec{kv.first, new_end, loc.size, loc.crc, 0, 0};
    rec.rec_crc = crc32_ieee(0, (const uint8_t*)&rec, sizeof rec - 4);
    if (write(ifd, &rec, sizeof rec) != (ssize_t)sizeof rec)
      return fail("compact idx write", -1);
    new_shards[kv.first] = ShardLoc{new_end, loc.size, loc.crc};
    new_end += loc.size;
  }
  fsync(dfd);
  fsync(ifd);
  int64_t reclaimed = (int64_t)c->data_end - (int64_t)new_end;
  std::string old_dp = data_path(s, chunk_id, c->gen);
  // SINGLE commit point: the idx rename flips both idx records and (via
  // the header) the data generation; a crash before it leaves the old
  // pair fully intact, a crash after it leaves the new pair in effect
  fsync_dir(s);  // make the new-generation data file's dirent durable
  if (rename(itmp.c_str(), ip.c_str()) != 0)
    return fail("compact commit rename", -1);
  fsync_dir(s);  // make the commit rename itself durable
  close(c->data_fd);
  close(c->idx_fd);
  c->data_fd = dfd;
  c->idx_fd = ifd;
  c->data_end = new_end;
  c->gen = new_gen;
  c->shards = std::move(new_shards);
  unlink(old_dp.c_str());  // best-effort; stray cleaned at next open too
  return reclaimed;
}

// CPU CRC baseline entry point (benchmarked against the TPU kernel).
uint32_t cs_crc32(const uint8_t* buf, uint64_t len) {
  return crc32_ieee(0, buf, len);
}

}  // extern "C"
