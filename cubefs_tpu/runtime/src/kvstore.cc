// Durable ordered KV store — the RocksDB choke-point analog.
//
// Role parity: blobstore/common/kvstorev2/rocksdb.go and
// raftstore/raftstore_db/store_rocksdb.go — the reference backs every
// shard / control-plane state machine with an ordered persistent KV.
// This is a deliberately small log-structured engine with the same
// contract (ordered iteration, range scans, crash-safe mutations), not
// an LSM port: the working set lives in one std::map (shards are
// range-split long before memory pressure matters) with
//
//   snapshot file  sorted (op,key,value) records, CRC32-framed
//   WAL            mutations since the snapshot, same framing
//
// open() loads snapshot + replays the WAL, truncating at the first
// torn/corrupt record (an unacknowledged tail write). compact() dumps
// the map to snapshot.tmp, fsyncs, renames, truncates the WAL;
// auto-compaction triggers when the WAL outgrows max(1 MiB, the
// snapshot size) so recovery cost stays bounded by live data.
//
// Record framing (WAL and snapshot):
//   u32 crc32(payload) | u32 paylen | payload
//   payload = u8 op (1=put, 2=del) | u32 klen | key | value
//
// All calls are serialized by a per-store mutex; handles are opaque
// pointers across the ctypes boundary (cubefs_tpu/runtime/kvstore.py).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

uint32_t crc32_of(const uint8_t* data, size_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

void put_u32(std::string& out, uint32_t v) {
  out.push_back(char(v & 0xFF));
  out.push_back(char((v >> 8) & 0xFF));
  out.push_back(char((v >> 16) & 0xFF));
  out.push_back(char((v >> 24) & 0xFF));
}

uint32_t get_u32(const uint8_t* p) {
  return uint32_t(p[0]) | uint32_t(p[1]) << 8 | uint32_t(p[2]) << 16 |
         uint32_t(p[3]) << 24;
}

std::string frame(uint8_t op, const std::string& key, const std::string& val) {
  std::string payload;
  payload.push_back(char(op));
  put_u32(payload, uint32_t(key.size()));
  payload += key;
  payload += val;
  std::string rec;
  put_u32(rec, crc32_of(reinterpret_cast<const uint8_t*>(payload.data()),
                        payload.size()));
  put_u32(rec, uint32_t(payload.size()));
  rec += payload;
  return rec;
}

struct Store {
  std::mutex mu;
  std::string dir;
  std::map<std::string, std::string> mem;
  int wal_fd = -1;
  uint64_t wal_bytes = 0;
  uint64_t snap_bytes = 0;

  std::string wal_path() const { return dir + "/kv.wal"; }
  std::string snap_path() const { return dir + "/kv.snap"; }

  // Applies records from `path` into mem; stops cleanly at a torn or
  // corrupt tail (the record was never acknowledged). Returns bytes of
  // the valid prefix.
  uint64_t load_file(const std::string& path) {
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) return 0;
    uint64_t good = 0;
    std::vector<uint8_t> buf;
    for (;;) {
      uint8_t hdr[8];
      if (fread(hdr, 1, 8, f) != 8) break;
      uint32_t crc = get_u32(hdr), n = get_u32(hdr + 4);
      if (n > (1u << 30)) break;  // insane length = corruption
      buf.resize(n);
      if (fread(buf.data(), 1, n, f) != n) break;
      if (crc32_of(buf.data(), n) != crc) break;
      if (n < 5) break;
      uint8_t op = buf[0];
      uint32_t klen = get_u32(buf.data() + 1);
      if (5 + klen > n) break;
      std::string key(reinterpret_cast<char*>(buf.data() + 5), klen);
      if (op == 1) {
        mem[key].assign(reinterpret_cast<char*>(buf.data() + 5 + klen),
                        n - 5 - klen);
      } else if (op == 2) {
        mem.erase(key);
      } else {
        break;
      }
      good += 8 + n;
    }
    fclose(f);
    return good;
  }

  bool open() {
    struct stat st{};
    if (stat(dir.c_str(), &st) != 0 || !S_ISDIR(st.st_mode)) return false;
    if (stat(snap_path().c_str(), &st) == 0) snap_bytes = uint64_t(st.st_size);
    load_file(snap_path());
    uint64_t good = load_file(wal_path());
    // drop any torn tail so new appends start at a valid boundary
    wal_fd = ::open(wal_path().c_str(), O_RDWR | O_CREAT, 0644);
    if (wal_fd < 0) return false;
    if (ftruncate(wal_fd, off_t(good)) != 0) return false;
    if (lseek(wal_fd, 0, SEEK_END) < 0) return false;
    wal_bytes = good;
    return true;
  }

  bool append_wal(const std::string& rec) {
    const char* p = rec.data();
    size_t left = rec.size();
    while (left > 0) {
      ssize_t w = write(wal_fd, p, left);
      if (w <= 0) return false;
      p += w;
      left -= size_t(w);
    }
    if (fdatasync(wal_fd) != 0) return false;
    wal_bytes += rec.size();
    return true;
  }

  bool compact_locked() {
    std::string tmp = snap_path() + ".tmp";
    int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return false;
    uint64_t total = 0;
    for (const auto& [k, v] : mem) {
      std::string rec = frame(1, k, v);
      const char* p = rec.data();
      size_t left = rec.size();
      while (left > 0) {
        ssize_t w = write(fd, p, left);
        if (w <= 0) {
          close(fd);
          unlink(tmp.c_str());
          return false;
        }
        p += w;
        left -= size_t(w);
      }
      total += rec.size();
    }
    if (fdatasync(fd) != 0 || close(fd) != 0) {
      unlink(tmp.c_str());
      return false;
    }
    if (rename(tmp.c_str(), snap_path().c_str()) != 0) return false;
    // WAL contents are now covered by the snapshot
    if (ftruncate(wal_fd, 0) != 0) return false;
    if (lseek(wal_fd, 0, SEEK_SET) < 0) return false;
    wal_bytes = 0;
    snap_bytes = total;
    return true;
  }

  void maybe_autocompact() {
    uint64_t threshold = snap_bytes > (1u << 20) ? snap_bytes : (1u << 20);
    if (wal_bytes > threshold) compact_locked();
  }
};

}  // namespace

extern "C" {

void* kv_open(const char* dir) {
  Store* s = new Store();
  s->dir = dir;
  if (!s->open()) {
    delete s;
    return nullptr;
  }
  return s;
}

void kv_close(void* h) {
  Store* s = static_cast<Store*>(h);
  if (s->wal_fd >= 0) close(s->wal_fd);
  delete s;
}

int kv_put(void* h, const char* key, uint32_t klen, const char* val,
           uint32_t vlen) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string k(key, klen), v(val, vlen);
  if (!s->append_wal(frame(1, k, v))) return -1;
  s->mem[k] = std::move(v);
  s->maybe_autocompact();
  return 0;
}

// -1: not found; 0: deleted
int kv_del(void* h, const char* key, uint32_t klen) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string k(key, klen);
  auto it = s->mem.find(k);
  if (it == s->mem.end()) return -1;
  if (!s->append_wal(frame(2, k, ""))) return -2;
  s->mem.erase(it);
  s->maybe_autocompact();
  return 0;
}

// Returns the value length, copying min(vlen, cap) bytes into out.
// -1: not found. Caller retries with a bigger buffer if vlen > cap.
int64_t kv_get(void* h, const char* key, uint32_t klen, char* out,
               uint32_t cap) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->mem.find(std::string(key, klen));
  if (it == s->mem.end()) return -1;
  uint32_t n = uint32_t(it->second.size());
  memcpy(out, it->second.data(), n < cap ? n : cap);
  return int64_t(n);
}

uint64_t kv_count(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return s->mem.size();
}

// Scans [start, end) (empty end = unbounded), at most `max_items`
// records, serialized as (u32 klen | u32 vlen | key | value)* into out.
// Returns bytes written; *n_out = records written; *more_out = 1 if
// items remained (resume with start = last key + '\0'). Items that
// would overflow `cap` also set *more_out.
int64_t kv_scan(void* h, const char* start, uint32_t slen, const char* end,
                uint32_t elen, uint32_t max_items, char* out, uint32_t cap,
                uint32_t* n_out, uint32_t* more_out) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string lo(start, slen), hi(end, elen);
  uint64_t used = 0;
  uint32_t n = 0;
  *more_out = 0;
  for (auto it = s->mem.lower_bound(lo); it != s->mem.end(); ++it) {
    if (!hi.empty() && it->first >= hi) break;
    if (n >= max_items) {
      *more_out = 1;
      break;
    }
    uint64_t need = 8 + it->first.size() + it->second.size();
    if (used + need > cap) {
      *more_out = 1;
      break;
    }
    std::string rec;
    put_u32(rec, uint32_t(it->first.size()));
    put_u32(rec, uint32_t(it->second.size()));
    rec += it->first;
    rec += it->second;
    memcpy(out + used, rec.data(), rec.size());
    used += rec.size();
    n++;
  }
  *n_out = n;
  return int64_t(used);
}

// Median key of [start, end) for range splits. Returns klen (copied up
// to cap) or -1 when the range holds < 2 keys.
int64_t kv_median(void* h, const char* start, uint32_t slen, const char* end,
                  uint32_t elen, char* out, uint32_t cap) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  std::string lo(start, slen), hi(end, elen);
  auto it = s->mem.lower_bound(lo);
  uint64_t n = 0;
  for (auto j = it; j != s->mem.end() && (hi.empty() || j->first < hi); ++j)
    n++;
  if (n < 2) return -1;
  for (uint64_t i = 0; i < n / 2; i++) ++it;
  uint32_t klen = uint32_t(it->first.size());
  memcpy(out, it->first.data(), klen < cap ? klen : cap);
  return int64_t(klen);
}

// Atomically applies a batch of mutations with ONE WAL append + ONE
// fdatasync (range moves during shard splits would otherwise pay a
// sync per key). `data` = sequence of records:
//   u8 op (1=put, 2=del) | u32 klen | u32 vlen | key | value
// Returns the number of records applied, or -1 on malformed input /
// write failure (nothing is applied on failure).
int64_t kv_batch(void* h, const char* data, uint64_t len) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  // parse + frame first: reject malformed input before touching disk
  std::string wal;
  std::vector<std::pair<uint8_t, std::pair<std::string, std::string>>> ops;
  uint64_t off = 0;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(data);
  while (off < len) {
    if (off + 9 > len) return -1;
    uint8_t op = p[off];
    uint32_t klen = get_u32(p + off + 1), vlen = get_u32(p + off + 5);
    off += 9;
    if (off + klen + vlen > len || (op != 1 && op != 2)) return -1;
    std::string key(reinterpret_cast<const char*>(p + off), klen);
    std::string val(reinterpret_cast<const char*>(p + off + klen), vlen);
    off += klen + vlen;
    wal += frame(op, key, val);
    ops.emplace_back(op, std::make_pair(std::move(key), std::move(val)));
  }
  if (!s->append_wal(wal)) return -1;
  for (auto& [op, kvp] : ops) {
    if (op == 1)
      s->mem[kvp.first] = std::move(kvp.second);
    else
      s->mem.erase(kvp.first);
  }
  s->maybe_autocompact();
  return int64_t(ops.size());
}

int kv_compact(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return s->compact_locked() ? 0 : -1;
}

int kv_clear(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  s->mem.clear();
  return s->compact_locked() ? 0 : -1;
}

uint64_t kv_wal_bytes(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return s->wal_bytes;
}

uint64_t kv_snap_bytes(void* h) {
  Store* s = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return s->snap_bytes;
}

}  // extern "C"
