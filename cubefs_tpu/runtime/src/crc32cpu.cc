// Shared native CRC32 (IEEE, reflected 0xEDB88320) — the hot-loop CRC
// for the extent/chunk stores and the C ABI.
//
// Role parity: Go stdlib hash/crc32's CLMUL assembly (used by
// datanode/storage/extent.go:626 and blobstore's crc32block framing) —
// the reference's CPU write path checksums at >10 GB/s via PCLMULQDQ
// folding. This is an original implementation of that standard
// technique (Gopal et al., "Fast CRC Computation for Generic
// Polynomials Using PCLMULQDQ", Intel whitepaper 2009; the published
// folding constants for the IEEE polynomial are public domain and used
// verbatim by zlib variants and the Linux kernel). Verified
// bit-identical against zlib across lengths, alignments and seeds in
// tests/test_crc32cpu.py.
//
// Contract (matches the stores' crc32_ieee): `crc` is a FINALIZED crc
// (as returned to callers); un-finalized internally.

#include <cstddef>
#include <cstdint>

#if defined(__x86_64__)
#include <immintrin.h>
#define CRC_X86 1
#endif

namespace {

// ---------------- table fallback (slicing-by-8) ----------------
struct CrcTables {
  uint32_t t[8][256];
  CrcTables() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++)
      for (int s = 1; s < 8; s++)
        t[s][i] = t[0][t[s - 1][i] & 0xFF] ^ (t[s - 1][i] >> 8);
  }
};
const CrcTables kT;

uint32_t crc32_slice8(uint32_t crc, const uint8_t* p, size_t n) {
  crc = ~crc;
  while (n >= 8) {
    crc ^= (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
           ((uint32_t)p[3] << 24);
    crc = kT.t[7][crc & 0xFF] ^ kT.t[6][(crc >> 8) & 0xFF] ^
          kT.t[5][(crc >> 16) & 0xFF] ^ kT.t[4][crc >> 24] ^
          kT.t[3][p[4]] ^ kT.t[2][p[5]] ^ kT.t[1][p[6]] ^ kT.t[0][p[7]];
    p += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ kT.t[0][(crc ^ *p++) & 0xFF];
  return ~crc;
}

#ifdef CRC_X86
// Published folding constants for the reflected IEEE polynomial
// (Intel whitepaper §4; identical values appear in Chromium zlib's
// crc32_simd.c and the Linux kernel's crc32-pclmul):
//   k1 = x^(4*128+32) mod P, k2 = x^(4*128-32) mod P   (64-byte fold)
//   k3 = x^(128+32)   mod P, k4 = x^(128-32)   mod P   (16-byte fold)
//   k5 = x^(64+32)    mod P                            (128 -> 64)
//   poly = P'<<1 | 1, mu = floor(x^64 / P')            (Barrett)
// Preconditions: n >= 64 and n % 16 == 0 (rt_crc32 slices the tail off).
__attribute__((target("pclmul,sse4.1")))
uint32_t crc32_clmul(uint32_t crc0, const uint8_t* p, size_t n) {
  const __m128i k1k2 = _mm_set_epi64x(0x00000001c6e41596, 0x0000000154442bd4);
  const __m128i k3k4 = _mm_set_epi64x(0x00000000ccaa009e, 0x00000001751997d0);

  __m128i x1 = _mm_loadu_si128((const __m128i*)(p + 0));
  __m128i x2 = _mm_loadu_si128((const __m128i*)(p + 16));
  __m128i x3 = _mm_loadu_si128((const __m128i*)(p + 32));
  __m128i x4 = _mm_loadu_si128((const __m128i*)(p + 48));
  x1 = _mm_xor_si128(x1, _mm_cvtsi32_si128((int)~crc0));
  p += 64;
  n -= 64;

  while (n >= 64) {
    __m128i t;
    t = _mm_clmulepi64_si128(x1, k1k2, 0x00);
    x1 = _mm_clmulepi64_si128(x1, k1k2, 0x11);
    x1 = _mm_xor_si128(_mm_xor_si128(x1, t),
                       _mm_loadu_si128((const __m128i*)(p + 0)));
    t = _mm_clmulepi64_si128(x2, k1k2, 0x00);
    x2 = _mm_clmulepi64_si128(x2, k1k2, 0x11);
    x2 = _mm_xor_si128(_mm_xor_si128(x2, t),
                       _mm_loadu_si128((const __m128i*)(p + 16)));
    t = _mm_clmulepi64_si128(x3, k1k2, 0x00);
    x3 = _mm_clmulepi64_si128(x3, k1k2, 0x11);
    x3 = _mm_xor_si128(_mm_xor_si128(x3, t),
                       _mm_loadu_si128((const __m128i*)(p + 32)));
    t = _mm_clmulepi64_si128(x4, k1k2, 0x00);
    x4 = _mm_clmulepi64_si128(x4, k1k2, 0x11);
    x4 = _mm_xor_si128(_mm_xor_si128(x4, t),
                       _mm_loadu_si128((const __m128i*)(p + 48)));
    p += 64;
    n -= 64;
  }

  // fold 4 lanes into one (16-byte folds)
  __m128i t;
  t = _mm_clmulepi64_si128(x1, k3k4, 0x00);
  x1 = _mm_clmulepi64_si128(x1, k3k4, 0x11);
  x2 = _mm_xor_si128(x2, _mm_xor_si128(x1, t));
  t = _mm_clmulepi64_si128(x2, k3k4, 0x00);
  x2 = _mm_clmulepi64_si128(x2, k3k4, 0x11);
  x3 = _mm_xor_si128(x3, _mm_xor_si128(x2, t));
  t = _mm_clmulepi64_si128(x3, k3k4, 0x00);
  x3 = _mm_clmulepi64_si128(x3, k3k4, 0x11);
  x4 = _mm_xor_si128(x4, _mm_xor_si128(x3, t));

  while (n >= 16) {
    t = _mm_clmulepi64_si128(x4, k3k4, 0x00);
    x4 = _mm_clmulepi64_si128(x4, k3k4, 0x11);
    x4 = _mm_xor_si128(_mm_xor_si128(x4, t),
                       _mm_loadu_si128((const __m128i*)p));
    p += 16;
    n -= 16;
  }

  // Final reduction: the folded accumulator IS a 16-byte virtual
  // message with the same CRC residue as the whole input (verified
  // bit-identical against zlib in the derivation model), so a table
  // pass over its bytes replaces the fiddly Barrett sequence at
  // negligible cost.
  uint8_t tail[16];
  _mm_storeu_si128((__m128i*)tail, x4);
  uint32_t state = 0;  // raw state: the init-xor was folded in via x1
  for (int i = 0; i < 16; i++)
    state = kT.t[0][(state ^ tail[i]) & 0xFF] ^ (state >> 8);
  return ~state;
}
#endif

}  // namespace

extern "C" {

// Shared CRC entry for every native component (and ctypes callers).
uint32_t rt_crc32(uint32_t crc, const uint8_t* p, size_t n) {
#ifdef CRC_X86
  static const bool has_clmul =
      __builtin_cpu_supports("pclmul") && __builtin_cpu_supports("sse4.1");
  if (has_clmul && n >= 64) {
    size_t head = n & ~(size_t)15;  // clmul wants whole 16B blocks
    crc = crc32_clmul(crc, p, head);
    p += head;
    n -= head;
  }
#endif
  return crc32_slice8(crc, p, n);
}

int rt_crc32_level() {
#ifdef CRC_X86
  if (__builtin_cpu_supports("pclmul") && __builtin_cpu_supports("sse4.1"))
    return 1;
#endif
  return 0;
}

}  // extern "C"
