// Native client library: the libcfs-analog C ABI.
//
// Role parity: client/libsdk (cgo libcfs.so with //export cfs_* symbols
// consumed by the Java SDK, libsdk.go:289-840) and the cgo/gRPC sidecar
// boundary named in BASELINE.json. This is a dependency-free C++
// HTTP/1.1 client for the framework's RPC wire shape (POST /method,
// JSON args in X-Rpc-Args, binary body), exposing:
//   cfs_blob_put / cfs_blob_get / cfs_blob_delete  — access gateway
//   cfs_codec_encode / cfs_codec_crc32             — codec sidecar
//   cfs_mount + cfs_open/read/write/lseek/close, cfs_stat_path,
//   cfs_mkdirs, cfs_readdir, cfs_unlink, cfs_rename, cfs_truncate
//     — the POSIX file surface over an FsGateway daemon (the
//       reference embeds the SDK via cgo; this framework's native
//       boundary is a local daemon instead, the bcache pattern)
// so Go/Java/C++ consumers can drive the TPU codec, the blob plane and
// the file plane without a Python runtime.
//
// Build: part of libcubefs_rt.so (see runtime/build.py).

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

thread_local std::string g_nc_err;
thread_local std::string g_nc_meta;  // last response's X-Rpc-Resp JSON
thread_local int g_nc_errno = 0;     // POSIX errno of the last failure

void nc_set_err(const std::string& e) { g_nc_err = e; }

// Decode the gateway's errno-on-the-wire scheme (fsgateway._err /
// metanode._rpc_err): HTTP 400+errno for small errnos, except 404 and
// 421 which are reserved transport codes; 499 carries "errno=NN: ..."
// in the error message for large/colliding errnos. Everything else
// (transport failure, 5xx) is EIO.
int status_to_errno(int status) {
  if (status >= 401 && status <= 498 && status != 404 && status != 421)
    return status - 400;
  if (status == 499) {
    size_t p = g_nc_meta.find("errno=");
    if (p != std::string::npos) return atoi(g_nc_meta.c_str() + p + 6);
  }
  return EIO;
}

// negative-errno return for the POSIX surface (libsdk.go returns
// -errno throughout; so do we)
int nc_fail() { return -(g_nc_errno ? g_nc_errno : EIO); }

int dial(const char* host, int port) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof portstr, "%d", port);
  if (getaddrinfo(host, portstr, &hints, &res) != 0 || !res) {
    nc_set_err("getaddrinfo failed");
    return -1;
  }
  int fd = socket(res->ai_family, res->ai_socktype, 0);
  if (fd < 0 || connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    nc_set_err(std::string("connect failed: ") + strerror(errno));
    if (fd >= 0) close(fd);
    freeaddrinfo(res);
    return -1;
  }
  freeaddrinfo(res);
  return fd;
}

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = (const char*)buf;
  while (n) {
    ssize_t w = ::send(fd, p, n, 0);
    if (w <= 0) return false;
    p += w;
    n -= w;
  }
  return true;
}

// Minimal HTTP/1.1 exchange. Returns status code, fills resp body+meta.
int http_post(const char* host, int port, const std::string& path,
              const std::string& args_json, const uint8_t* body,
              size_t body_len, std::vector<uint8_t>* resp) {
  g_nc_errno = 0;
  int fd = dial(host, port);
  if (fd < 0) {
    g_nc_errno = EIO;
    return -1;
  }
  // heap-built header: args_json (e.g. a multi-slice location) can be
  // arbitrarily long; a fixed buffer would truncate and over-send
  std::string head = "POST /" + path + " HTTP/1.1\r\nHost: " + host +
                     "\r\nX-Rpc-Args: " + args_json +
                     "\r\nContent-Length: " + std::to_string(body_len) +
                     "\r\nConnection: close\r\n\r\n";
  if (!send_all(fd, head.data(), head.size()) ||
      (body_len && !send_all(fd, body, body_len))) {
    nc_set_err("send failed");
    g_nc_errno = EIO;
    close(fd);
    return -1;
  }
  std::string raw;
  char buf[65536];
  ssize_t r;
  while ((r = recv(fd, buf, sizeof buf, 0)) > 0) raw.append(buf, r);
  close(fd);
  size_t hdr_end = raw.find("\r\n\r\n");
  if (hdr_end == std::string::npos) {
    nc_set_err("malformed http response");
    g_nc_errno = EIO;
    return -1;
  }
  int status = 0;
  sscanf(raw.c_str(), "HTTP/1.1 %d", &status);
  // stash X-Rpc-Resp
  g_nc_meta.clear();
  size_t mp = raw.find("X-Rpc-Resp: ");
  if (mp != std::string::npos && mp < hdr_end) {
    size_t e = raw.find("\r\n", mp);
    g_nc_meta = raw.substr(mp + 12, e - mp - 12);
  }
  if (resp) {
    resp->assign(raw.begin() + hdr_end + 4, raw.end());
  }
  if (status != 200) {
    nc_set_err("http status " + std::to_string(status) + ": " + g_nc_meta);
    g_nc_errno = status_to_errno(status);
  }
  return status;
}

std::string json_escape(const char* s) {
  std::string out;
  for (const char* p = s; *p; ++p) {
    unsigned char c = (unsigned char)*p;
    if (c == '"' || c == '\\') {
      out += '\\';
      out += c;
    } else if (c < 0x20) {
      char buf[8];
      snprintf(buf, sizeof buf, "\\u%04x", c);
      out += buf;
    } else {
      out += c;
    }
  }
  return out;
}

// ---------------- POSIX-surface client state ----------------
struct CfsFile {
  std::string path;
  uint64_t offset = 0;
  bool append = false;
};

struct CfsClient {
  std::string host;
  int port = 0;
  std::mutex mu;
  std::map<int, CfsFile> fds;
  int next_fd = 3;
};

// open(2) flag bits (Linux values, the ABI contract)
constexpr int kO_WRONLY = 01;
constexpr int kO_RDWR = 02;
constexpr int kO_CREAT = 0100;
constexpr int kO_EXCL = 0200;
constexpr int kO_TRUNC = 01000;
constexpr int kO_APPEND = 02000;

int fs_call(CfsClient* c, const std::string& method,
            const std::string& args, const uint8_t* body, size_t body_len,
            std::vector<uint8_t>* resp) {
  return http_post(c->host.c_str(), c->port, method, args, body, body_len,
                   resp);
}

// pull an integer field out of the stashed X-Rpc-Resp JSON meta
bool meta_int(const char* key, long long* out) {
  std::string pat = std::string("\"") + key + "\":";
  size_t p = g_nc_meta.find(pat);
  if (p == std::string::npos) return false;
  *out = atoll(g_nc_meta.c_str() + p + pat.size());
  return true;
}

}  // namespace

extern "C" {

const char* cfs_last_error() { return g_nc_err.c_str(); }
const char* cfs_last_meta() { return g_nc_meta.c_str(); }
// POSIX errno of this thread's last failed call (0 after success); the
// cfs_* POSIX surface also returns it as a negative result, matching
// the reference libsdk's -errno contract (libsdk.go:289-840)
int cfs_last_errno() { return g_nc_errno; }

// ---------------- POSIX file surface (libsdk.go:289-840 analog) ------

void* cfs_mount(const char* host, int port) {
  CfsClient* c = new CfsClient();
  c->host = host;
  c->port = port;
  // probe the gateway so a bad address fails at mount, not first IO
  std::vector<uint8_t> resp;
  if (http_post(host, port, "fs_stat", "{\"path\": \"/\"}", nullptr, 0,
                &resp) != 200) {
    delete c;
    return nullptr;
  }
  return c;
}

void cfs_unmount(void* h) { delete (CfsClient*)h; }

int cfs_open(void* h, const char* path, int flags, int mode) {
  CfsClient* c = (CfsClient*)h;
  std::string p = json_escape(path);
  std::vector<uint8_t> resp;
  int st = fs_call(c, "fs_stat", "{\"path\": \"" + p + "\"}", nullptr, 0,
                   &resp);
  uint64_t size = 0;
  if (st == 200 && resp.size() >= 8) {
    if ((flags & kO_CREAT) && (flags & kO_EXCL)) {
      // atomic create-if-absent contract: the file exists, so fail
      nc_set_err("O_EXCL: file exists");
      g_nc_errno = EEXIST;
      return nc_fail();
    }
    memcpy(&size, resp.data(), 8);
    if (flags & kO_TRUNC) {
      if (fs_call(c, "fs_truncate",
                  "{\"path\": \"" + p + "\", \"size\": 0}", nullptr, 0,
                  nullptr) != 200)
        return nc_fail();
      size = 0;
    }
  } else if (flags & kO_CREAT) {
    char args[4352];
    snprintf(args, sizeof args, "{\"path\": \"%s\", \"mode\": %d}",
             p.c_str(), mode);
    int cst = fs_call(c, "fs_create", args, nullptr, 0, nullptr);
    if (cst == 417 && (flags & kO_EXCL)) {
      // lost the create race while O_EXCL was set: must fail
      g_nc_errno = EEXIST;
      return nc_fail();
    }
    if (cst == 417) {
      // lost the create race (EEXIST): O_CREAT without O_EXCL must open
      // the existing file, honoring O_TRUNC
      if (flags & kO_TRUNC) {
        if (fs_call(c, "fs_truncate",
                    "{\"path\": \"" + p + "\", \"size\": 0}", nullptr,
                    0, nullptr) != 200)
          return nc_fail();
      } else if (fs_call(c, "fs_stat", "{\"path\": \"" + p + "\"}",
                         nullptr, 0, &resp) == 200 && resp.size() >= 8) {
        memcpy(&size, resp.data(), 8);
      }
    } else if (cst != 200) {
      return nc_fail();
    }
  } else {
    return nc_fail();  // -ENOENT; detail in cfs_last_error()
  }
  std::lock_guard<std::mutex> g(c->mu);
  int fd = c->next_fd++;
  CfsFile f;
  f.path = path;
  f.append = (flags & kO_APPEND) != 0;
  f.offset = f.append ? size : 0;
  c->fds[fd] = f;
  return fd;
}

int cfs_close(void* h, int fd) {
  CfsClient* c = (CfsClient*)h;
  std::lock_guard<std::mutex> g(c->mu);
  if (!c->fds.erase(fd)) {
    g_nc_errno = EBADF;
    return nc_fail();
  }
  g_nc_errno = 0;  // success with no HTTP round-trip: clear stale errno
  return 0;
}

int64_t cfs_pread(void* h, int fd, void* buf, uint64_t n, uint64_t off) {
  CfsClient* c = (CfsClient*)h;
  std::string path;
  {
    std::lock_guard<std::mutex> g(c->mu);
    auto it = c->fds.find(fd);
    if (it == c->fds.end()) {
      nc_set_err("bad fd");
      g_nc_errno = EBADF;
      return nc_fail();
    }
    path = it->second.path;
  }
  char args[4352];
  snprintf(args, sizeof args,
           "{\"path\": \"%s\", \"offset\": %llu, \"length\": %llu}",
           json_escape(path.c_str()).c_str(), (unsigned long long)off,
           (unsigned long long)n);
  std::vector<uint8_t> resp;
  if (fs_call(c, "fs_read", args, nullptr, 0, &resp) != 200)
    return nc_fail();
  if (resp.size() > n) {
    nc_set_err("gateway returned more than requested");
    g_nc_errno = EIO;
    return nc_fail();
  }
  memcpy(buf, resp.data(), resp.size());
  return (int64_t)resp.size();
}

int64_t cfs_read(void* h, int fd, void* buf, uint64_t n) {
  CfsClient* c = (CfsClient*)h;
  uint64_t off;
  {
    std::lock_guard<std::mutex> g(c->mu);
    auto it = c->fds.find(fd);
    if (it == c->fds.end()) {
      nc_set_err("bad fd");
      g_nc_errno = EBADF;
      return nc_fail();
    }
    off = it->second.offset;
  }
  int64_t got = cfs_pread(h, fd, buf, n, off);
  if (got > 0) {
    std::lock_guard<std::mutex> g(c->mu);
    auto it = c->fds.find(fd);
    if (it != c->fds.end()) it->second.offset = off + got;
  }
  return got;
}

int64_t cfs_pwrite(void* h, int fd, const void* buf, uint64_t n,
                   uint64_t off) {
  CfsClient* c = (CfsClient*)h;
  std::string path;
  {
    std::lock_guard<std::mutex> g(c->mu);
    auto it = c->fds.find(fd);
    if (it == c->fds.end()) {
      nc_set_err("bad fd");
      g_nc_errno = EBADF;
      return nc_fail();
    }
    path = it->second.path;
  }
  char args[4352];
  snprintf(args, sizeof args, "{\"path\": \"%s\", \"offset\": %llu}",
           json_escape(path.c_str()).c_str(), (unsigned long long)off);
  if (fs_call(c, "fs_write", args, (const uint8_t*)buf, n, nullptr) != 200)
    return nc_fail();
  return (int64_t)n;
}

int64_t cfs_write(void* h, int fd, const void* buf, uint64_t n) {
  CfsClient* c = (CfsClient*)h;
  uint64_t off;
  bool append;
  std::string path;
  {
    std::lock_guard<std::mutex> g(c->mu);
    auto it = c->fds.find(fd);
    if (it == c->fds.end()) {
      nc_set_err("bad fd");
      g_nc_errno = EBADF;
      return nc_fail();
    }
    off = it->second.offset;
    append = it->second.append;
    path = it->second.path;
  }
  if (append) {
    // O_APPEND: every write lands at the CURRENT end of file; a failed
    // size probe must fail the write (a stale cached offset would
    // silently overwrite existing bytes)
    std::vector<uint8_t> resp;
    if (fs_call(c, "fs_stat",
                "{\"path\": \"" + json_escape(path.c_str()) + "\"}",
                nullptr, 0, &resp) != 200 || resp.size() < 8) {
      nc_set_err("O_APPEND size probe failed: " + g_nc_err);
      return nc_fail();
    }
    memcpy(&off, resp.data(), 8);
  }
  int64_t put = cfs_pwrite(h, fd, buf, n, off);
  if (put > 0) {
    std::lock_guard<std::mutex> g(c->mu);
    auto it = c->fds.find(fd);
    if (it != c->fds.end()) it->second.offset = off + put;
  }
  return put;
}

int64_t cfs_lseek(void* h, int fd, int64_t off, int whence) {
  CfsClient* c = (CfsClient*)h;
  uint64_t size = 0;
  std::string path;
  {
    std::lock_guard<std::mutex> g(c->mu);
    auto it = c->fds.find(fd);
    if (it == c->fds.end()) {
      nc_set_err("bad fd");
      g_nc_errno = EBADF;
      return nc_fail();
    }
    path = it->second.path;
  }
  if (whence == 2) {  // SEEK_END
    std::vector<uint8_t> resp;
    if (fs_call(c, "fs_stat",
                "{\"path\": \"" + json_escape(path.c_str()) + "\"}",
                nullptr, 0, &resp) != 200 || resp.size() < 8)
      return nc_fail();
    memcpy(&size, resp.data(), 8);
  }
  std::lock_guard<std::mutex> g(c->mu);
  auto it = c->fds.find(fd);
  if (it == c->fds.end()) {
    g_nc_errno = EBADF;
    return nc_fail();
  }
  int64_t base = whence == 0 ? 0
                 : whence == 1 ? (int64_t)it->second.offset
                               : (int64_t)size;
  int64_t pos = base + off;
  if (pos < 0) {
    nc_set_err("negative seek");
    g_nc_errno = EINVAL;
    return nc_fail();
  }
  it->second.offset = (uint64_t)pos;
  g_nc_errno = 0;  // SEEK_SET/CUR succeed locally: clear stale errno
  return pos;
}

// out: size (u64), mode (u32), type (u32: 0 file / 1 dir / 2 symlink),
// mtime seconds (u64) — the gateway's fixed-layout stat record
int cfs_stat_path(void* h, const char* path, uint64_t* size, uint32_t* mode,
                  uint32_t* type, uint64_t* mtime) {
  CfsClient* c = (CfsClient*)h;
  std::vector<uint8_t> resp;
  if (fs_call(c, "fs_stat",
              "{\"path\": \"" + json_escape(path) + "\"}", nullptr, 0,
              &resp) != 200 || resp.size() < 24)
    return nc_fail();
  if (size) memcpy(size, resp.data(), 8);
  if (mode) memcpy(mode, resp.data() + 8, 4);
  if (type) memcpy(type, resp.data() + 12, 4);
  if (mtime) memcpy(mtime, resp.data() + 16, 8);
  return 0;
}

int cfs_mkdirs(void* h, const char* path) {
  CfsClient* c = (CfsClient*)h;
  std::string acc;
  std::string p(path);
  size_t i = 0;
  while (i < p.size()) {
    size_t j = p.find('/', i + 1);
    if (j == std::string::npos) j = p.size();
    acc = p.substr(0, j);
    if (!acc.empty() && acc != "/") {
      int st = fs_call(c, "fs_mkdir",
                       "{\"path\": \"" + json_escape(acc.c_str()) + "\"}",
                       nullptr, 0, nullptr);
      if (st != 200 && st != 417) return nc_fail();  // 417 = EEXIST: fine
    }
    i = j;
  }
  return 0;
}

// newline-joined names into out; returns entry count or -1
int64_t cfs_readdir(void* h, const char* path, char* out, uint64_t cap) {
  CfsClient* c = (CfsClient*)h;
  std::vector<uint8_t> resp;
  if (fs_call(c, "fs_readdir",
              "{\"path\": \"" + json_escape(path) + "\"}", nullptr, 0,
              &resp) != 200)
    return nc_fail();
  if (resp.size() + 1 > cap) {
    nc_set_err("readdir buffer too small");
    g_nc_errno = ERANGE;
    return nc_fail();
  }
  memcpy(out, resp.data(), resp.size());
  out[resp.size()] = 0;
  long long n = 0;
  meta_int("count", &n);
  return n;
}

int cfs_unlink(void* h, const char* path) {
  CfsClient* c = (CfsClient*)h;
  return fs_call(c, "fs_unlink",
                 "{\"path\": \"" + json_escape(path) + "\"}", nullptr, 0,
                 nullptr) == 200
             ? 0
             : nc_fail();
}

int cfs_rmdir(void* h, const char* path) { return cfs_unlink(h, path); }

int cfs_rename(void* h, const char* oldp, const char* newp) {
  CfsClient* c = (CfsClient*)h;
  return fs_call(c, "fs_rename",
                 "{\"old\": \"" + json_escape(oldp) + "\", \"new\": \"" +
                     json_escape(newp) + "\"}",
                 nullptr, 0, nullptr) == 200
             ? 0
             : nc_fail();
}

int cfs_truncate(void* h, const char* path, uint64_t size) {
  CfsClient* c = (CfsClient*)h;
  char args[4352];
  snprintf(args, sizeof args, "{\"path\": \"%s\", \"size\": %llu}",
           json_escape(path).c_str(), (unsigned long long)size);
  return fs_call(c, "fs_truncate", args, nullptr, 0, nullptr) == 200 ? 0
                                                                      : nc_fail();
}

int cfs_flush(void* h, int fd) {
  (void)h;
  (void)fd;
  g_nc_errno = 0;
  return 0;  // writes are synchronous through the gateway
}

// PUT via access; returns 0 and writes the location JSON into loc_out.
int cfs_blob_put(const char* host, int port, const uint8_t* data,
                 uint64_t len, char* loc_out, uint64_t loc_cap) {
  std::vector<uint8_t> resp;
  int st = http_post(host, port, "put", "{}", data, len, &resp);
  if (st != 200) return -1;
  // location JSON is inside the meta header
  if (g_nc_meta.size() + 1 > loc_cap) {
    nc_set_err("location buffer too small");
    return -1;
  }
  memcpy(loc_out, g_nc_meta.c_str(), g_nc_meta.size() + 1);
  return 0;
}

// GET via access; loc_json = {"location": {...}} args payload.
int64_t cfs_blob_get(const char* host, int port, const char* args_json,
                     uint8_t* out, uint64_t cap) {
  std::vector<uint8_t> resp;
  int st = http_post(host, port, "get", args_json, nullptr, 0, &resp);
  if (st != 200) return -1;
  if (resp.size() > cap) {
    nc_set_err("output buffer too small");
    return -2;
  }
  memcpy(out, resp.data(), resp.size());
  return (int64_t)resp.size();
}

int cfs_blob_delete(const char* host, int port, const char* args_json) {
  int st = http_post(host, port, "delete", args_json, nullptr, 0, nullptr);
  return st == 200 ? 0 : -1;
}

// EC encode offload: data = batch*n shards of shard_size bytes; parity
// (batch*m*shard_size) written to out.
// Shared-memory encode for a CO-LOCATED sidecar (codec/service.py
// rpc_encode_shm): shards land in a /dev/shm file, only shapes ride
// HTTP. Measured 6-8x the body-over-HTTP path, whose framing+copies
// cap the boundary at ~0.4 GiB/s (SURVEY §7 hard part 2).
int cfs_codec_encode_shm(const char* host, int port, int n, int m,
                         uint64_t shard_size, int batch,
                         const uint8_t* data, uint8_t* parity_out) {
  size_t in_bytes = (size_t)batch * n * shard_size;
  size_t out_bytes = (size_t)batch * m * shard_size;
  char path[128];
  snprintf(path, sizeof path, "/dev/shm/cubefs-codec-%d-XXXXXX",
           (int)getpid());
  int fd = mkstemp(path);
  if (fd < 0) {
    nc_set_err("mkstemp /dev/shm failed");
    return -1;
  }
  int rc = -1;
  uint8_t* map = nullptr;
  do {
    if (ftruncate(fd, (off_t)(in_bytes + out_bytes)) != 0) {
      nc_set_err("ftruncate shm failed");
      break;
    }
    map = (uint8_t*)mmap(nullptr, in_bytes + out_bytes,
                         PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    if (map == MAP_FAILED) {
      map = nullptr;
      nc_set_err("mmap shm failed");
      break;
    }
    memcpy(map, data, in_bytes);
    char args[256];
    snprintf(args, sizeof args,
             "{\"n\": %d, \"m\": %d, \"shard_size\": %llu, \"batch\": %d, "
             "\"shm\": \"%s\"}",
             n, m, (unsigned long long)shard_size, batch, path);
    std::vector<uint8_t> resp;
    int st = http_post(host, port, "encode_shm", args, nullptr, 0, &resp);
    if (st != 200) break;
    memcpy(parity_out, map + in_bytes, out_bytes);
    rc = 0;
  } while (false);
  if (map) munmap(map, in_bytes + out_bytes);
  close(fd);
  unlink(path);
  return rc;
}

int cfs_codec_encode(const char* host, int port, int n, int m,
                     uint64_t shard_size, int batch, const uint8_t* data,
                     uint8_t* parity_out) {
  char args[256];
  snprintf(args, sizeof args,
           "{\"n\": %d, \"m\": %d, \"shard_size\": %llu, \"batch\": %d}",
           n, m, (unsigned long long)shard_size, batch);
  std::vector<uint8_t> resp;
  int st = http_post(host, port, "encode", args, data,
                     (size_t)batch * n * shard_size, &resp);
  if (st != 200) return -1;
  if (resp.size() != (size_t)batch * m * shard_size) {
    nc_set_err("unexpected parity size");
    return -1;
  }
  memcpy(parity_out, resp.data(), resp.size());
  return 0;
}

// Batched CRC32 offload: blocks of block_len; out = count u32le CRCs.
int cfs_codec_crc32(const char* host, int port, uint64_t block_len,
                    const uint8_t* data, uint64_t data_len, uint32_t* out) {
  char args[128];
  snprintf(args, sizeof args, "{\"block_len\": %llu}",
           (unsigned long long)block_len);
  std::vector<uint8_t> resp;
  int st = http_post(host, port, "crc32", args, data, data_len, &resp);
  if (st != 200) return -1;
  // exact-size check: the caller sized `out` for data_len/block_len CRCs
  size_t expect = (size_t)(data_len / block_len) * 4;
  if (resp.size() != expect) {
    nc_set_err("unexpected crc payload size");
    return -1;
  }
  memcpy(out, resp.data(), resp.size());
  return (int)(resp.size() / 4);
}

}  // extern "C"
