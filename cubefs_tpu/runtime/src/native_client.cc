// Native client library: the libcfs-analog C ABI.
//
// Role parity: client/libsdk (cgo libcfs.so with //export cfs_* symbols
// consumed by the Java SDK) and the cgo/gRPC sidecar boundary named in
// BASELINE.json. This is a dependency-free C++ HTTP/1.1 client for the
// framework's RPC wire shape (POST /method, JSON args in X-Rpc-Args,
// binary body), exposing:
//   cfs_blob_put / cfs_blob_get / cfs_blob_delete  — access gateway
//   cfs_codec_encode / cfs_codec_crc32             — codec sidecar
// so Go/Java/C++ storage nodes can drive the TPU codec and the blob
// plane without a Python runtime.
//
// Build: part of libcubefs_rt.so (see runtime/build.py).

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

thread_local std::string g_nc_err;
thread_local std::string g_nc_meta;  // last response's X-Rpc-Resp JSON

void nc_set_err(const std::string& e) { g_nc_err = e; }

int dial(const char* host, int port) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof portstr, "%d", port);
  if (getaddrinfo(host, portstr, &hints, &res) != 0 || !res) {
    nc_set_err("getaddrinfo failed");
    return -1;
  }
  int fd = socket(res->ai_family, res->ai_socktype, 0);
  if (fd < 0 || connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    nc_set_err(std::string("connect failed: ") + strerror(errno));
    if (fd >= 0) close(fd);
    freeaddrinfo(res);
    return -1;
  }
  freeaddrinfo(res);
  return fd;
}

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = (const char*)buf;
  while (n) {
    ssize_t w = ::send(fd, p, n, 0);
    if (w <= 0) return false;
    p += w;
    n -= w;
  }
  return true;
}

// Minimal HTTP/1.1 exchange. Returns status code, fills resp body+meta.
int http_post(const char* host, int port, const std::string& path,
              const std::string& args_json, const uint8_t* body,
              size_t body_len, std::vector<uint8_t>* resp) {
  int fd = dial(host, port);
  if (fd < 0) return -1;
  // heap-built header: args_json (e.g. a multi-slice location) can be
  // arbitrarily long; a fixed buffer would truncate and over-send
  std::string head = "POST /" + path + " HTTP/1.1\r\nHost: " + host +
                     "\r\nX-Rpc-Args: " + args_json +
                     "\r\nContent-Length: " + std::to_string(body_len) +
                     "\r\nConnection: close\r\n\r\n";
  if (!send_all(fd, head.data(), head.size()) ||
      (body_len && !send_all(fd, body, body_len))) {
    nc_set_err("send failed");
    close(fd);
    return -1;
  }
  std::string raw;
  char buf[65536];
  ssize_t r;
  while ((r = recv(fd, buf, sizeof buf, 0)) > 0) raw.append(buf, r);
  close(fd);
  size_t hdr_end = raw.find("\r\n\r\n");
  if (hdr_end == std::string::npos) {
    nc_set_err("malformed http response");
    return -1;
  }
  int status = 0;
  sscanf(raw.c_str(), "HTTP/1.1 %d", &status);
  // stash X-Rpc-Resp
  g_nc_meta.clear();
  size_t mp = raw.find("X-Rpc-Resp: ");
  if (mp != std::string::npos && mp < hdr_end) {
    size_t e = raw.find("\r\n", mp);
    g_nc_meta = raw.substr(mp + 12, e - mp - 12);
  }
  if (resp) {
    resp->assign(raw.begin() + hdr_end + 4, raw.end());
  }
  if (status != 200) nc_set_err("http status " + std::to_string(status) +
                                ": " + g_nc_meta);
  return status;
}

}  // namespace

extern "C" {

const char* cfs_last_error() { return g_nc_err.c_str(); }
const char* cfs_last_meta() { return g_nc_meta.c_str(); }

// PUT via access; returns 0 and writes the location JSON into loc_out.
int cfs_blob_put(const char* host, int port, const uint8_t* data,
                 uint64_t len, char* loc_out, uint64_t loc_cap) {
  std::vector<uint8_t> resp;
  int st = http_post(host, port, "put", "{}", data, len, &resp);
  if (st != 200) return -1;
  // location JSON is inside the meta header
  if (g_nc_meta.size() + 1 > loc_cap) {
    nc_set_err("location buffer too small");
    return -1;
  }
  memcpy(loc_out, g_nc_meta.c_str(), g_nc_meta.size() + 1);
  return 0;
}

// GET via access; loc_json = {"location": {...}} args payload.
int64_t cfs_blob_get(const char* host, int port, const char* args_json,
                     uint8_t* out, uint64_t cap) {
  std::vector<uint8_t> resp;
  int st = http_post(host, port, "get", args_json, nullptr, 0, &resp);
  if (st != 200) return -1;
  if (resp.size() > cap) {
    nc_set_err("output buffer too small");
    return -2;
  }
  memcpy(out, resp.data(), resp.size());
  return (int64_t)resp.size();
}

int cfs_blob_delete(const char* host, int port, const char* args_json) {
  int st = http_post(host, port, "delete", args_json, nullptr, 0, nullptr);
  return st == 200 ? 0 : -1;
}

// EC encode offload: data = batch*n shards of shard_size bytes; parity
// (batch*m*shard_size) written to out.
int cfs_codec_encode(const char* host, int port, int n, int m,
                     uint64_t shard_size, int batch, const uint8_t* data,
                     uint8_t* parity_out) {
  char args[256];
  snprintf(args, sizeof args,
           "{\"n\": %d, \"m\": %d, \"shard_size\": %llu, \"batch\": %d}",
           n, m, (unsigned long long)shard_size, batch);
  std::vector<uint8_t> resp;
  int st = http_post(host, port, "encode", args, data,
                     (size_t)batch * n * shard_size, &resp);
  if (st != 200) return -1;
  if (resp.size() != (size_t)batch * m * shard_size) {
    nc_set_err("unexpected parity size");
    return -1;
  }
  memcpy(parity_out, resp.data(), resp.size());
  return 0;
}

// Batched CRC32 offload: blocks of block_len; out = count u32le CRCs.
int cfs_codec_crc32(const char* host, int port, uint64_t block_len,
                    const uint8_t* data, uint64_t data_len, uint32_t* out) {
  char args[128];
  snprintf(args, sizeof args, "{\"block_len\": %llu}",
           (unsigned long long)block_len);
  std::vector<uint8_t> resp;
  int st = http_post(host, port, "crc32", args, data, data_len, &resp);
  if (st != 200) return -1;
  // exact-size check: the caller sized `out` for data_len/block_len CRCs
  size_t expect = (size_t)(data_len / block_len) * 4;
  if (resp.size() != expect) {
    nc_set_err("unexpected crc payload size");
    return -1;
  }
  memcpy(out, resp.data(), resp.size());
  return (int)(resp.size() / 4);
}

}  // extern "C"
