// Native CPU GF(2^8) matrix-apply: the AVX2 Reed-Solomon fallback.
//
// Role parity: vendor/github.com/klauspost/reedsolomon/galois_amd64.s —
// the reference's CPU hot path is SIMD GF multiply-accumulate. This is
// an original implementation of the standard split-nibble table-lookup
// technique (Plank, Greenan, Miller: "Screaming Fast Galois Field
// Arithmetic Using Intel SIMD Instructions", FAST'13): for each
// coefficient c, two 16-entry tables map the low/high nibble of every
// input byte through PSHUFB/VPSHUFB, and products accumulate with XOR.
// Field: poly 0x11D, generator 2 — bit-identical with ops/gf256.py and
// the device kernels (verified against the pinned independent goldens).
//
// Used as the `cpp` codec engine (codec/engine.py): the CPU leg of the
// measured size-class crossover policy — the numpy table path does
// ~0.08 GiB/s, far below the single-stripe dispatch cost of the device
// path, which made the crossover a foregone conclusion instead of a
// real policy.

#include <cstdint>
#include <cstring>
#include <mutex>

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>
#define GF_X86 1
#endif

namespace {

constexpr uint16_t POLY = 0x11D;

uint8_t MUL[256][256];
std::once_flag mul_once;

void build_tables() {
  // call_once: ctypes drops the GIL, so concurrent first encodes would
  // otherwise read MUL mid-build (silent wrong parity)
  std::call_once(mul_once, [] {
    uint8_t exp[512];
    int log[256] = {0};
    int x = 1;
    for (int i = 0; i < 255; i++) {
      exp[i] = (uint8_t)x;
      log[x] = i;
      x <<= 1;
      if (x & 0x100) x ^= POLY;
    }
    for (int i = 255; i < 510; i++) exp[i] = exp[i - 255];
    for (int a = 0; a < 256; a++)
      for (int b = 0; b < 256; b++)
        MUL[a][b] = (a && b) ? exp[log[a] + log[b]] : 0;
  });
}

// scalar accumulate: out ^= c * in  (last-resort portable path)
void mulacc_scalar(uint8_t c, const uint8_t* in, uint8_t* out, uint64_t s) {
  const uint8_t* row = MUL[c];
  for (uint64_t k = 0; k < s; k++) out[k] ^= row[in[k]];
}

#ifdef GF_X86
__attribute__((target("ssse3"))) void mulacc_ssse3(uint8_t c,
                                                   const uint8_t* in,
                                                   uint8_t* out, uint64_t s) {
  uint8_t lo[16], hi[16];
  for (int v = 0; v < 16; v++) {
    lo[v] = MUL[c][v];
    hi[v] = MUL[c][v << 4];
  }
  __m128i tlo = _mm_loadu_si128((const __m128i*)lo);
  __m128i thi = _mm_loadu_si128((const __m128i*)hi);
  __m128i mask = _mm_set1_epi8(0x0F);
  uint64_t k = 0;
  for (; k + 16 <= s; k += 16) {
    __m128i x = _mm_loadu_si128((const __m128i*)(in + k));
    __m128i y = _mm_loadu_si128((const __m128i*)(out + k));
    __m128i pl = _mm_shuffle_epi8(tlo, _mm_and_si128(x, mask));
    __m128i ph = _mm_shuffle_epi8(
        thi, _mm_and_si128(_mm_srli_epi64(x, 4), mask));
    y = _mm_xor_si128(y, _mm_xor_si128(pl, ph));
    _mm_storeu_si128((__m128i*)(out + k), y);
  }
  for (; k < s; k++) out[k] ^= MUL[c][in[k]];
}

__attribute__((target("avx2"))) void mulacc_avx2(uint8_t c, const uint8_t* in,
                                                 uint8_t* out, uint64_t s) {
  uint8_t lo[16], hi[16];
  for (int v = 0; v < 16; v++) {
    lo[v] = MUL[c][v];
    hi[v] = MUL[c][v << 4];
  }
  __m256i tlo = _mm256_broadcastsi128_si256(
      _mm_loadu_si128((const __m128i*)lo));
  __m256i thi = _mm256_broadcastsi128_si256(
      _mm_loadu_si128((const __m128i*)hi));
  __m256i mask = _mm256_set1_epi8(0x0F);
  uint64_t k = 0;
  for (; k + 32 <= s; k += 32) {
    __m256i x = _mm256_loadu_si256((const __m256i*)(in + k));
    __m256i y = _mm256_loadu_si256((const __m256i*)(out + k));
    __m256i pl = _mm256_shuffle_epi8(tlo, _mm256_and_si256(x, mask));
    __m256i ph = _mm256_shuffle_epi8(
        thi, _mm256_and_si256(_mm256_srli_epi64(x, 4), mask));
    y = _mm256_xor_si256(y, _mm256_xor_si256(pl, ph));
    _mm256_storeu_si256((__m256i*)(out + k), y);
  }
  for (; k < s; k++) out[k] ^= MUL[c][in[k]];
}
#endif

using MulAccFn = void (*)(uint8_t, const uint8_t*, uint8_t*, uint64_t);

MulAccFn pick_mulacc() {
#ifdef GF_X86
  if (__builtin_cpu_supports("avx2")) return mulacc_avx2;
  if (__builtin_cpu_supports("ssse3")) return mulacc_ssse3;
#endif
  return mulacc_scalar;
}

}  // namespace

extern "C" {

// out[b,i,:] = XOR_j mat[i*n+j] (x) in[b,j,:]   (contiguous uint8 views)
void gf_apply(const uint8_t* mat, uint64_t m, uint64_t n, const uint8_t* in,
              uint8_t* out, uint64_t s, uint64_t batch) {
  build_tables();
  MulAccFn mulacc = pick_mulacc();
  for (uint64_t b = 0; b < batch; b++) {
    const uint8_t* ib = in + b * n * s;
    uint8_t* ob = out + b * m * s;
    for (uint64_t i = 0; i < m; i++) {
      uint8_t* dst = ob + i * s;
      memset(dst, 0, s);
      for (uint64_t j = 0; j < n; j++) {
        uint8_t c = mat[i * n + j];
        if (c == 0) continue;
        mulacc(c, ib + j * s, dst, s);
      }
    }
  }
}

// Scheduled XOR-program executor — the native replay of the schedules
// ops/xorprog.py compiles (the arXiv 2108.02692 direction). The op
// stream is int32 [dst, nsrc, src...]* over plane slots: slots
// [0, 8*cin) are input bit-planes (shard j bit k -> slot 8j+k,
// LSB-first, matching ops/bitlin.py), the LAST 8*rout slots are output
// planes (row i bit b -> nslots-8*rout+8i+b), temps in between. Per
// block, input shards are split to bit-planes with the 8x8 SWAR bit
// transpose, the ops replay as word-wide XOR (auto-vectorized at -O3),
// and output planes transpose back to bytes. s and block must be
// multiples of 64 (the python caller pads); the plane workspace is
// sized nslots*block/8 so the whole block stays cache-resident.

static inline uint64_t xp_transpose8(uint64_t x) {
  uint64_t t;
  t = (x ^ (x >> 7)) & 0x00AA00AA00AA00AAULL;
  x = x ^ t ^ (t << 7);
  t = (x ^ (x >> 14)) & 0x0000CCCC0000CCCCULL;
  x = x ^ t ^ (t << 14);
  t = (x ^ (x >> 28)) & 0x00000000F0F0F0F0ULL;
  x = x ^ t ^ (t << 28);
  return x;
}

void xor_apply(const int32_t* ops, uint64_t ops_words, const uint8_t* in,
               uint8_t* out, uint64_t cin, uint64_t rout, uint64_t nslots,
               uint64_t s, uint64_t batch, uint64_t block) {
  if (s % 64 || block % 64 || block == 0) return;  // caller contract
  const uint64_t plane_w = block / 64;  // uint64 words per plane slot
  uint64_t* ws = new uint64_t[nslots * plane_w];
  const uint64_t obase = nslots - 8 * rout;
  for (uint64_t b = 0; b < batch; b++) {
    for (uint64_t off = 0; off < s; off += block) {
      const uint64_t cur = (s - off < block) ? (s - off) : block;
      const uint64_t nw = cur / 8;   // words per shard block
      const uint64_t pw = cur / 64;  // words per plane this block
      // split: shard bytes -> 8 bit-planes each
      for (uint64_t j = 0; j < cin; j++) {
        const uint8_t* src = in + (b * cin + j) * s + off;
        uint8_t* pl = (uint8_t*)(ws + 8 * j * plane_w);
        const uint64_t pb = plane_w * 8;  // plane stride in bytes
        for (uint64_t w = 0; w < nw; w++) {
          uint64_t x;
          memcpy(&x, src + w * 8, 8);
          x = xp_transpose8(x);
          for (int k = 0; k < 8; k++)
            pl[(uint64_t)k * pb + w] = (uint8_t)(x >> (8 * k));
        }
      }
      // replay the schedule
      const int32_t* p = ops;
      const int32_t* end = ops + ops_words;
      while (p < end) {
        const int32_t dst = *p++;
        const int32_t n = *p++;
        uint64_t* d = ws + (uint64_t)dst * plane_w;
        if (n == 0) {
          memset(d, 0, pw * 8);
        } else {
          memcpy(d, ws + (uint64_t)p[0] * plane_w, pw * 8);
          for (int32_t i = 1; i < n; i++) {
            const uint64_t* si = ws + (uint64_t)p[i] * plane_w;
            for (uint64_t w = 0; w < pw; w++) d[w] ^= si[w];
          }
          p += n;
        }
      }
      // join: output planes -> bytes
      for (uint64_t i = 0; i < rout; i++) {
        uint8_t* dst = out + (b * rout + i) * s + off;
        const uint8_t* pl = (const uint8_t*)(ws + (obase + 8 * i) * plane_w);
        const uint64_t pb = plane_w * 8;
        for (uint64_t w = 0; w < nw; w++) {
          uint64_t x = 0;
          for (int k = 0; k < 8; k++)
            x |= (uint64_t)pl[(uint64_t)k * pb + w] << (8 * k);
          x = xp_transpose8(x);
          memcpy(dst + w * 8, &x, 8);
        }
      }
    }
  }
  delete[] ws;
}

// which SIMD path gf_apply will take: 2=avx2, 1=ssse3, 0=scalar
int gf_cpu_level() {
#ifdef GF_X86
  if (__builtin_cpu_supports("avx2")) return 2;
  if (__builtin_cpu_supports("ssse3")) return 1;
#endif
  return 0;
}

}  // extern "C"
