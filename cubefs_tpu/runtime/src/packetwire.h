// Shared 64-byte binary packet wire helpers (utils/packet.py parity),
// used by the native meta read plane (metaserve.cc) and the native data
// read plane (dataserve.cc). Header-only; everything inline.
#pragma once

#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <string>

extern "C" uint32_t rt_crc32(uint32_t crc, const uint8_t* p, size_t n);

namespace pktwire {

#pragma pack(push, 1)
struct PacketHdr {
  uint8_t magic, opcode, flags, result;
  uint32_t crc, psize, asize;
  uint64_t partition, extent, offset, req_id;
  uint8_t reserved[16];
};
#pragma pack(pop)
static_assert(sizeof(PacketHdr) == 64, "header must be 64 bytes");

constexpr uint8_t MAGIC = 0xCF;
constexpr uint8_t RESULT_RPC = 0xE1;
constexpr uint32_t MAX_FRAME = 16u << 20;

inline bool recv_exact(int fd, void* buf, size_t n) {
  uint8_t* b = (uint8_t*)buf;
  while (n) {
    ssize_t r = recv(fd, b, n, 0);
    if (r <= 0) return false;
    b += r;
    n -= (size_t)r;
  }
  return true;
}

inline bool send_all(int fd, const void* buf, size_t n) {
  const uint8_t* b = (const uint8_t*)buf;
  while (n) {
    ssize_t r = send(fd, b, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    b += r;
    n -= (size_t)r;
  }
  return true;
}

inline void reply(int fd, const PacketHdr& req, uint8_t result,
                  const std::string& args,
                  const uint8_t* payload = nullptr, size_t plen = 0) {
  PacketHdr h{};
  h.magic = MAGIC;
  h.opcode = req.opcode;
  h.result = result;
  h.crc = rt_crc32(0, payload, plen);
  h.psize = (uint32_t)plen;
  h.asize = (uint32_t)args.size();
  h.req_id = req.req_id;
  // header+args coalesce into one small send; the payload goes straight
  // from the caller's buffer — no multi-MiB frame copy
  std::string head((const char*)&h, sizeof h);
  head += args;
  if (!send_all(fd, head.data(), head.size())) return;
  if (plen) send_all(fd, payload, plen);
}

}  // namespace pktwire
