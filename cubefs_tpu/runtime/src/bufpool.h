// Size-classed slab buffer pool for the native runtime's IO scratch
// paths — the tcmalloc/resourcepool role (see bufpool.cc). C ABI plus a
// RAII helper for in-runtime use.
#pragma once

#include <cstddef>
#include <cstdint>

extern "C" {
// pooled alloc/free: n may be any size; buffers come from power-of-two
// size classes (oversize requests fall through to the system allocator)
void* bp_alloc(size_t n);
void bp_free(void* p, size_t n);
// tcmalloc_manage.cc parity: drop all cached free buffers, returning
// the number of bytes released to the system
size_t bp_release_free_memory();
// JSON stats {classes: [{size, cached, hits, misses}], held_bytes};
// returns bytes written (truncated to cap-1), 0 on bad args
size_t bp_stats_json(char* out, size_t cap);
}

// RAII wrapper for runtime-internal scratch buffers
struct PoolBuf {
  uint8_t* data = nullptr;
  size_t cap = 0;

  explicit PoolBuf(size_t n) : data((uint8_t*)bp_alloc(n)), cap(n) {}
  ~PoolBuf() {
    if (data) bp_free(data, cap);
  }
  PoolBuf(const PoolBuf&) = delete;
  PoolBuf& operator=(const PoolBuf&) = delete;
};
