// Native metanode read plane: the hot-loop half of manager_op.go.
//
// Role parity: metanode/manager_op.go — the reference serves every meta
// op from a Go TCP demux over in-RAM trees (metanode/btree.go). The
// Python op loop tops out ~1-4k ops/s under the GIL; this server owns
// the read-side demux and the inode/dentry trees in C++, serving
// lookup / inode_get / readdir / dentry_count / walk on the same
// 64-byte binary packet protocol (utils/packet.py) with wire-identical
// errno / leader-redirect encodings, entirely off the GIL.
//
// The Python MetaPartition stays the FSM of record: every apply mirrors
// its tree mutation into this store under the partition lock (inodes as
// pre-serialized JSON blobs, dentries as parent -> name -> ino maps),
// and raft role transitions flip the per-partition serving flag
// synchronously — so the native plane serves exactly what a leader-
// routed Python read would, or answers 421 "leader=<addr>".
//
// Writes (submit / alloc_ino) stay on the Python packet/HTTP planes:
// they are raft-bound, so the GIL is not their ceiling.

#include <arpa/inet.h>

#include "packetwire.h"

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// ------------------------------------------------------------- tiny JSON
// Parses the flat-ish args objects the meta SDK sends ({"pid":1,
// "names":["a"],"stat":true}). Full escape handling (incl. \uXXXX
// surrogate pairs -> UTF-8) because Python's json.dumps default is
// ensure_ascii=True, so every non-ASCII filename arrives escaped.
struct JVal {
  enum Kind { NUM, STR, BOOL, NUL, ARR, OBJ } kind = NUL;
  uint64_t num = 0;
  bool b = false;
  std::string str;
  std::vector<JVal> arr;
  std::vector<std::pair<std::string, JVal>> obj;

  const JVal* get(const char* key) const {
    for (auto& kv : obj)
      if (kv.first == key) return &kv.second;
    return nullptr;
  }
};

struct JParser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit JParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) p++;
  }
  bool lit(const char* s) {
    size_t n = strlen(s);
    if ((size_t)(end - p) < n || memcmp(p, s, n) != 0) return false;
    p += n;
    return true;
  }

  void utf8_append(std::string& out, uint32_t cp) {
    if (cp < 0x80) {
      out += (char)cp;
    } else if (cp < 0x800) {
      out += (char)(0xC0 | (cp >> 6));
      out += (char)(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      out += (char)(0xE0 | (cp >> 12));
      out += (char)(0x80 | ((cp >> 6) & 0x3F));
      out += (char)(0x80 | (cp & 0x3F));
    } else {
      out += (char)(0xF0 | (cp >> 18));
      out += (char)(0x80 | ((cp >> 12) & 0x3F));
      out += (char)(0x80 | ((cp >> 6) & 0x3F));
      out += (char)(0x80 | (cp & 0x3F));
    }
  }

  int hex4() {
    if (end - p < 4) return -1;
    int v = 0;
    for (int i = 0; i < 4; i++) {
      char c = *p++;
      v <<= 4;
      if (c >= '0' && c <= '9') v |= c - '0';
      else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
      else return -1;
    }
    return v;
  }

  bool str(std::string& out) {
    if (p >= end || *p != '"') return false;
    p++;
    while (p < end && *p != '"') {
      if (*p == '\\') {
        p++;
        if (p >= end) return false;
        char c = *p++;
        switch (c) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            int v = hex4();
            if (v < 0) return false;
            uint32_t cp = (uint32_t)v;
            if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 6 && p[0] == '\\' &&
                p[1] == 'u') {
              p += 2;
              int lo = hex4();
              if (lo < 0) return false;
              cp = 0x10000 + ((cp - 0xD800) << 10) + ((uint32_t)lo - 0xDC00);
            }
            utf8_append(out, cp);
            break;
          }
          default: return false;
        }
      } else {
        out += *p++;
      }
    }
    if (p >= end) return false;
    p++;  // closing quote
    return true;
  }

  JVal value() {
    JVal v;
    ws();
    if (p >= end) { ok = false; return v; }
    char c = *p;
    if (c == '"') {
      v.kind = JVal::STR;
      if (!str(v.str)) ok = false;
    } else if (c == '{') {
      p++;
      v.kind = JVal::OBJ;
      ws();
      if (p < end && *p == '}') { p++; return v; }
      while (ok) {
        ws();
        std::string key;
        if (!str(key)) { ok = false; break; }
        ws();
        if (p >= end || *p++ != ':') { ok = false; break; }
        v.obj.emplace_back(std::move(key), value());
        ws();
        if (p < end && *p == ',') { p++; continue; }
        if (p < end && *p == '}') { p++; break; }
        ok = false;
      }
    } else if (c == '[') {
      p++;
      v.kind = JVal::ARR;
      ws();
      if (p < end && *p == ']') { p++; return v; }
      while (ok) {
        v.arr.push_back(value());
        ws();
        if (p < end && *p == ',') { p++; continue; }
        if (p < end && *p == ']') { p++; break; }
        ok = false;
      }
    } else if (c == 't') {
      v.kind = JVal::BOOL; v.b = true; ok = lit("true");
    } else if (c == 'f') {
      v.kind = JVal::BOOL; v.b = false; ok = lit("false");
    } else if (c == 'n') {
      v.kind = JVal::NUL; ok = lit("null");
    } else {
      // number: meta args only carry non-negative integers; floats and
      // negatives are accepted syntactically (truncated toward zero)
      v.kind = JVal::NUM;
      bool neg = (*p == '-');
      if (neg) p++;
      uint64_t n = 0;
      bool any = false;
      while (p < end && *p >= '0' && *p <= '9') { n = n * 10 + (*p++ - '0'); any = true; }
      if (p < end && (*p == '.' || *p == 'e' || *p == 'E')) {
        // skip fraction/exponent
        while (p < end && (*p == '.' || *p == 'e' || *p == 'E' || *p == '+' ||
                           *p == '-' || (*p >= '0' && *p <= '9')))
          p++;
      }
      if (!any) ok = false;
      v.num = neg ? 0 : n;
    }
    return v;
  }
};

void j_escape(std::string& out, const std::string& s) {
  out += '"';
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += (char)c;  // raw UTF-8 passes through: valid JSON
        }
    }
  }
  out += '"';
}

// ---------------------------------------------------------------- store
struct Partition {
  uint64_t pid, start, end;
  mutable std::shared_mutex mu;
  bool serving = false;       // leader (or standalone): reads allowed
  std::string leader;         // advertised redirect target when not
  std::unordered_map<uint64_t, std::string> inodes;  // ino -> JSON blob
  std::unordered_map<uint64_t, std::map<std::string, uint64_t>> dentries;
};

struct MetaServe {
  mutable std::shared_mutex pmu;
  std::unordered_map<uint64_t, std::shared_ptr<Partition>> parts;

  int listen_fd = -1;
  std::thread accepter;
  std::atomic<bool> stopping{false};
  std::atomic<int> live_conns{0};
  std::atomic<uint64_t> ops{0};
  std::mutex conn_mu;
  std::vector<int> conn_fds;

  std::shared_ptr<Partition> by_pid(uint64_t pid) const {
    std::shared_lock l(pmu);
    auto it = parts.find(pid);
    return it == parts.end() ? nullptr : it->second;
  }
  std::shared_ptr<Partition> by_ino(uint64_t ino) const {
    std::shared_lock l(pmu);
    for (auto& kv : parts)
      if (kv.second->start <= ino && ino < kv.second->end) return kv.second;
    return nullptr;
  }
};

using pktwire::PacketHdr;
using pktwire::recv_exact;
using pktwire::send_all;

constexpr uint8_t MAGIC = pktwire::MAGIC;
constexpr uint8_t RESULT_RPC = pktwire::RESULT_RPC;
constexpr uint8_t OP_META_LOOKUP = 0x20;
constexpr uint8_t OP_META_INODE_GET = 0x21;
constexpr uint8_t OP_META_READDIR = 0x22;
constexpr uint8_t OP_META_DENTRY_COUNT = 0x24;
constexpr uint8_t OP_META_WALK = 0x26;
constexpr uint8_t OP_PING = 0x7F;
constexpr uint32_t MAX_FRAME = pktwire::MAX_FRAME;

// errno -> wire code, matching utils/rpc.py errno_error: 400+errno for
// small errnos (404/421 never arise from ENOENT/ENOTDIR), else 499
int errno_code(int e) { return (e < 99) ? 400 + e : 499; }

struct RpcReject {
  int code;
  std::string msg;
};

void reply_err(int fd, const PacketHdr& req, const RpcReject& e) {
  std::string args = "{\"error\": ";
  j_escape(args, e.msg);
  args += ", \"code\": " + std::to_string(e.code) + "}";
  reply(fd, req, RESULT_RPC, args);
}

// serving gate: Python's _mp_leader analog (404 when absent, 421 when
// not leader-served). Returns the partition with mu held shared.
std::shared_ptr<Partition> gate(MetaServe* ms, uint64_t pid,
                                std::shared_lock<std::shared_mutex>& lk) {
  auto p = ms->by_pid(pid);
  if (!p) throw RpcReject{404, "meta partition " + std::to_string(pid) +
                                   " not on this node"};
  std::shared_lock l(p->mu);
  if (!p->serving) throw RpcReject{421, "leader=" + p->leader};
  lk = std::move(l);
  return p;
}

uint64_t need_num(const JVal& args, const char* key) {
  const JVal* v = args.get(key);
  if (!v || v->kind != JVal::NUM)
    throw RpcReject{400, std::string("missing/bad arg ") + key};
  return v->num;
}

std::string need_str(const JVal& args, const char* key) {
  const JVal* v = args.get(key);
  if (!v || v->kind != JVal::STR)
    throw RpcReject{400, std::string("missing/bad arg ") + key};
  return v->str;
}

std::string op_lookup(MetaServe* ms, const JVal& args) {
  uint64_t pid = need_num(args, "pid");
  uint64_t parent = need_num(args, "parent");
  std::string name = need_str(args, "name");
  std::shared_lock<std::shared_mutex> lk;
  auto p = gate(ms, pid, lk);
  auto d = p->dentries.find(parent);
  if (d == p->dentries.end())
    throw RpcReject{errno_code(2), name + " not in " + std::to_string(parent)};
  auto it = d->second.find(name);
  if (it == d->second.end())
    throw RpcReject{errno_code(2), name + " not in " + std::to_string(parent)};
  return "{\"ino\": " + std::to_string(it->second) + "}";
}

std::string op_inode_get(MetaServe* ms, const JVal& args) {
  uint64_t pid = need_num(args, "pid");
  uint64_t ino = need_num(args, "ino");
  std::shared_lock<std::shared_mutex> lk;
  auto p = gate(ms, pid, lk);
  auto it = p->inodes.find(ino);
  if (it == p->inodes.end())
    throw RpcReject{errno_code(2), "inode " + std::to_string(ino)};
  return "{\"inode\": " + it->second + "}";
}

std::string op_readdir(MetaServe* ms, const JVal& args) {
  uint64_t pid = need_num(args, "pid");
  uint64_t parent = need_num(args, "parent");
  std::shared_lock<std::shared_mutex> lk;
  auto p = gate(ms, pid, lk);
  auto d = p->dentries.find(parent);
  if (d == p->dentries.end())
    throw RpcReject{errno_code(20),
                    std::to_string(parent) + " is not a dir here"};
  std::string out = "{\"entries\": {";
  bool first = true;
  for (auto& kv : d->second) {
    if (!first) out += ", ";
    first = false;
    j_escape(out, kv.first);
    out += ": " + std::to_string(kv.second);
  }
  out += "}}";
  return out;
}

std::string op_dentry_count(MetaServe* ms, const JVal& args) {
  uint64_t pid = need_num(args, "pid");
  uint64_t parent = need_num(args, "parent");
  std::shared_lock<std::shared_mutex> lk;
  auto p = gate(ms, pid, lk);
  auto d = p->dentries.find(parent);
  size_t n = d == p->dentries.end() ? 0 : d->second.size();
  return "{\"count\": " + std::to_string(n) + "}";
}

std::string op_walk(MetaServe* ms, const JVal& args) {
  // Python rpc_walk parity: consume names while the owning partition is
  // local AND leader-served; hand back {ino, remaining} otherwise.
  uint64_t ino = need_num(args, "ino");
  const JVal* names_v = args.get("names");
  if (!names_v || names_v->kind != JVal::ARR)
    throw RpcReject{400, "missing/bad arg names"};
  const JVal* stat_v = args.get("stat");
  bool want_stat = stat_v && stat_v->kind == JVal::BOOL && stat_v->b;
  std::vector<std::string> names;
  names.reserve(names_v->arr.size());
  for (auto& v : names_v->arr) {
    if (v.kind != JVal::STR) throw RpcReject{400, "missing/bad arg names"};
    names.push_back(v.str);
  }
  size_t i = 0;
  while (i < names.size()) {
    auto p = ms->by_ino(ino);
    if (!p) break;
    std::shared_lock l(p->mu);
    if (!p->serving) break;
    auto d = p->dentries.find(ino);
    if (d == p->dentries.end())
      throw RpcReject{errno_code(2),
                      names[i] + " not in " + std::to_string(ino)};
    auto it = d->second.find(names[i]);
    if (it == d->second.end())
      throw RpcReject{errno_code(2),
                      names[i] + " not in " + std::to_string(ino)};
    ino = it->second;
    i++;
  }
  std::string out = "{\"ino\": " + std::to_string(ino) + ", \"remaining\": [";
  for (size_t k = i; k < names.size(); k++) {
    if (k > i) out += ", ";
    j_escape(out, names[k]);
  }
  out += "]";
  if (i == names.size() && want_stat) {
    auto p = ms->by_ino(ino);
    if (p) {
      std::shared_lock l(p->mu);
      if (p->serving) {
        auto it = p->inodes.find(ino);
        if (it == p->inodes.end())
          throw RpcReject{errno_code(2), "inode " + std::to_string(ino)};
        out += ", \"inode\": " + it->second;
      }
    }
  }
  out += "}";
  return out;
}

void serve_conn(MetaServe* ms, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  std::string args_buf, payload_buf;
  while (!ms->stopping.load(std::memory_order_relaxed)) {
    PacketHdr h;
    if (!recv_exact(fd, &h, sizeof h)) break;
    if (h.magic != MAGIC || h.asize > MAX_FRAME || h.psize > MAX_FRAME)
      break;  // framing lost: drop the connection (packet.py discipline)
    args_buf.resize(h.asize);
    if (h.asize && !recv_exact(fd, &args_buf[0], h.asize)) break;
    payload_buf.resize(h.psize);
    if (h.psize && !recv_exact(fd, &payload_buf[0], h.psize)) break;
    if (rt_crc32(0, (const uint8_t*)payload_buf.data(),
                 payload_buf.size()) != h.crc)
      break;  // corrupt payload: drop
    ms->ops.fetch_add(1, std::memory_order_relaxed);
    JVal args;
    if (h.asize) {
      JParser jp(args_buf);
      args = jp.value();
      if (!jp.ok || args.kind != JVal::OBJ) {
        reply_err(fd, h, {400, "bad args json"});
        continue;
      }
    } else {
      args.kind = JVal::OBJ;
    }
    try {
      std::string out;
      switch (h.opcode) {
        case OP_PING: out = "{}"; break;
        case OP_META_LOOKUP: out = op_lookup(ms, args); break;
        case OP_META_INODE_GET: out = op_inode_get(ms, args); break;
        case OP_META_READDIR: out = op_readdir(ms, args); break;
        case OP_META_DENTRY_COUNT: out = op_dentry_count(ms, args); break;
        case OP_META_WALK: out = op_walk(ms, args); break;
        default: {
          // not a native read op: this plane doesn't serve it (the SDK
          // routes writes to the Python packet plane); 0xFD matches the
          // Python server's unknown-opcode result
          PacketHdr rh = h;
          std::string eargs = "{\"error\": \"no opcode on native read plane\"}";
          reply(fd, rh, 0xFD, eargs);
          continue;
        }
      }
      reply(fd, h, 0, out);
    } catch (const RpcReject& e) {
      reply_err(fd, h, e);
    } catch (const std::exception& e) {
      reply_err(fd, h, {500, std::string("native metaserve: ") + e.what()});
    }
  }
  {
    // deregister BEFORE closing: ms_stop only shutdown()s registered
    // fds and never closes them, so an fd number freed by this close
    // can never be shut down after the kernel reuses it
    std::lock_guard<std::mutex> g(ms->conn_mu);
    auto& v = ms->conn_fds;
    v.erase(std::remove(v.begin(), v.end(), fd), v.end());
  }
  close(fd);
  ms->live_conns.fetch_sub(1);
}

void accept_loop(MetaServe* ms) {
  while (!ms->stopping.load()) {
    int fd = accept(ms->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (ms->stopping.load()) return;
      std::this_thread::sleep_for(std::chrono::milliseconds(5));
      continue;
    }
    ms->live_conns.fetch_add(1);
    {
      std::lock_guard<std::mutex> g(ms->conn_mu);
      ms->conn_fds.push_back(fd);
    }
    // ms_stop sets `stopping` BEFORE sweeping conn_fds under conn_mu:
    // either our push landed before the sweep (fd gets shut down
    // there), or we observe `stopping` here and shut it down ourselves
    // — a conn can never slip past both and block recv forever
    if (ms->stopping.load()) shutdown(fd, SHUT_RDWR);
    std::thread(serve_conn, ms, fd).detach();
  }
}

}  // namespace

extern "C" {

void* ms_create() { return new MetaServe(); }

void ms_destroy(void* h) {
  auto* ms = (MetaServe*)h;
  delete ms;
}

void ms_add_partition(void* h, uint64_t pid, uint64_t start, uint64_t end) {
  auto* ms = (MetaServe*)h;
  auto p = std::make_shared<Partition>();
  p->pid = pid;
  p->start = start;
  p->end = end;
  std::unique_lock l(ms->pmu);
  ms->parts[pid] = std::move(p);
}

void ms_drop_partition(void* h, uint64_t pid) {
  auto* ms = (MetaServe*)h;
  std::unique_lock l(ms->pmu);
  ms->parts.erase(pid);
}

void ms_set_serving(void* h, uint64_t pid, int serving, const char* leader) {
  auto* ms = (MetaServe*)h;
  auto p = ms->by_pid(pid);
  if (!p) return;
  std::unique_lock l(p->mu);
  p->serving = serving != 0;
  p->leader = leader ? leader : "";
}

void ms_put_inode(void* h, uint64_t pid, uint64_t ino, const char* blob,
                  uint32_t len) {
  auto* ms = (MetaServe*)h;
  auto p = ms->by_pid(pid);
  if (!p) return;
  std::unique_lock l(p->mu);
  p->inodes[ino].assign(blob, len);
}

void ms_del_inode(void* h, uint64_t pid, uint64_t ino) {
  auto* ms = (MetaServe*)h;
  auto p = ms->by_pid(pid);
  if (!p) return;
  std::unique_lock l(p->mu);
  p->inodes.erase(ino);
}

void ms_ensure_dir(void* h, uint64_t pid, uint64_t ino) {
  auto* ms = (MetaServe*)h;
  auto p = ms->by_pid(pid);
  if (!p) return;
  std::unique_lock l(p->mu);
  p->dentries.try_emplace(ino);
}

void ms_del_dir(void* h, uint64_t pid, uint64_t ino) {
  auto* ms = (MetaServe*)h;
  auto p = ms->by_pid(pid);
  if (!p) return;
  std::unique_lock l(p->mu);
  p->dentries.erase(ino);
}

void ms_put_dentry(void* h, uint64_t pid, uint64_t parent, const char* name,
                   uint32_t nlen, uint64_t ino) {
  auto* ms = (MetaServe*)h;
  auto p = ms->by_pid(pid);
  if (!p) return;
  std::unique_lock l(p->mu);
  p->dentries[parent][std::string(name, nlen)] = ino;
}

void ms_del_dentry(void* h, uint64_t pid, uint64_t parent, const char* name,
                   uint32_t nlen) {
  auto* ms = (MetaServe*)h;
  auto p = ms->by_pid(pid);
  if (!p) return;
  std::unique_lock l(p->mu);
  auto d = p->dentries.find(parent);
  if (d != p->dentries.end()) d->second.erase(std::string(name, nlen));
}

void ms_clear(void* h, uint64_t pid) {
  auto* ms = (MetaServe*)h;
  auto p = ms->by_pid(pid);
  if (!p) return;
  std::unique_lock l(p->mu);
  p->inodes.clear();
  p->dentries.clear();
}

uint64_t ms_op_count(void* h) { return ((MetaServe*)h)->ops.load(); }

// Returns the bound port, or -1 on failure.
int ms_serve(void* h, const char* host, int port) {
  auto* ms = (MetaServe*)h;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  if (bind(fd, (sockaddr*)&addr, sizeof addr) != 0 || listen(fd, 128) != 0) {
    close(fd);
    return -1;
  }
  socklen_t alen = sizeof addr;
  getsockname(fd, (sockaddr*)&addr, &alen);
  ms->listen_fd = fd;
  ms->stopping.store(false);
  ms->accepter = std::thread(accept_loop, ms);
  return (int)ntohs(addr.sin_port);
}

// Load generator for capacity measurement (the mdtest-shape driver):
// `conns` connections (one thread each) issue `iters` serial
// round-trips of the same request frame. Returns elapsed seconds, or
// -1 when any connection fails or any reply is an error. Lives here so
// capacity numbers measure the server without a Python client's GIL in
// the loop (this box benches on one core).
double ms_bench(const char* host, int port, int opcode,
                const char* args_json, int iters, int conns) {
  std::string args = args_json ? args_json : "";
  PacketHdr h{};
  h.magic = MAGIC;
  h.opcode = (uint8_t)opcode;
  h.crc = rt_crc32(0, nullptr, 0);
  h.asize = (uint32_t)args.size();
  std::string frame((const char*)&h, sizeof h);
  frame += args;
  std::atomic<bool> failed{false};
  auto worker = [&]() {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in a{};
    a.sin_family = AF_INET;
    a.sin_port = htons((uint16_t)port);
    if (fd < 0 || inet_pton(AF_INET, host, &a.sin_addr) != 1 ||
        connect(fd, (sockaddr*)&a, sizeof a) != 0) {
      failed.store(true);
      if (fd >= 0) close(fd);
      return;
    }
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    std::string body;
    for (int i = 0; i < iters && !failed.load(); i++) {
      PacketHdr rh;
      if (!send_all(fd, frame.data(), frame.size()) ||
          !recv_exact(fd, &rh, sizeof rh)) {
        failed.store(true);
        break;
      }
      size_t rest = (size_t)rh.asize + rh.psize;
      body.resize(rest);
      if (rest && !recv_exact(fd, &body[0], rest)) {
        failed.store(true);
        break;
      }
      if (rh.result != 0) failed.store(true);
    }
    close(fd);
  };
  auto t0 = std::chrono::steady_clock::now();
  std::vector<std::thread> ts;
  for (int c = 0; c < conns; c++) ts.emplace_back(worker);
  for (auto& t : ts) t.join();
  auto t1 = std::chrono::steady_clock::now();
  if (failed.load()) return -1;
  return std::chrono::duration<double>(t1 - t0).count();
}

void ms_stop(void* h) {
  auto* ms = (MetaServe*)h;
  ms->stopping.store(true);
  if (ms->listen_fd >= 0) {
    shutdown(ms->listen_fd, SHUT_RDWR);
    close(ms->listen_fd);
    ms->listen_fd = -1;
  }
  {
    std::lock_guard<std::mutex> g(ms->conn_mu);
    for (int fd : ms->conn_fds) shutdown(fd, SHUT_RDWR);
    ms->conn_fds.clear();
  }
  if (ms->accepter.joinable()) ms->accepter.join();
  while (ms->live_conns.load() > 0)
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
}

}  // extern "C"
