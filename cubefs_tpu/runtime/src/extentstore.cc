// Native extent-store runtime: the datanode disk engine.
//
// Role parity: reference datanode/storage (128MiB extents, random writes,
// per-128KiB-block CRC32 header maintained on write — extent_store.go:665
// Write, extent.go CRC header, persistence_crc.go). Reimplemented as C++
// with a C ABI for ctypes:
//   <dir>/extents/e_<id>.data — sparse extent payload
//   <dir>/extents/e_<id>.crc  — uint32 CRC per 128KiB block (+ size hdr)
// A write covering byte range [off, off+len) re-CRCs only the touched
// blocks (read-modify over block boundaries). Reads verify block CRCs
// for fully-covered blocks. Whole-extent CRC = IEEE CRC over the block
// CRC array (matching the reference's crc-of-crcs discipline).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cerrno>
#include <string>
#include <unordered_map>
#include <mutex>
#include <vector>
#include <algorithm>
#include <fcntl.h>
#include <unistd.h>
#include <sys/stat.h>

#include "bufpool.h"

namespace {

constexpr uint64_t kBlockSize = 128 * 1024;  // util.BlockSize parity
constexpr uint64_t kMaxExtent = 128ull << 20;

// CRC32 delegates to the shared native kernel (crc32cpu.cc): CLMUL
// folding at ~13 GB/s with a table fallback, bit-identical with zlib.
extern "C" uint32_t rt_crc32(uint32_t crc, const uint8_t* p, size_t n);

uint32_t crc32_ieee(uint32_t crc, const uint8_t* p, size_t n) {
  return rt_crc32(crc, p, n);
}

struct Extent {
  int data_fd = -1;
  int crc_fd = -1;
  uint64_t size = 0;               // logical size (max written end)
  std::vector<uint32_t> block_crc;  // per-block
  std::mutex mu;
};

struct EStore {
  std::string dir;
  std::unordered_map<uint64_t, Extent*> extents;
  std::mutex mu;
  char err[256] = {0};
};

void es_set_err(EStore* s, const char* msg) {
  snprintf(s->err, 256, "%s (errno=%d %s)", msg, errno,
           errno ? strerror(errno) : "");
}

std::string epath(EStore* s, uint64_t id, const char* ext) {
  char buf[64];
  snprintf(buf, sizeof buf, "/e_%016llx.%s", (unsigned long long)id, ext);
  return s->dir + buf;
}

bool load_extent(EStore* s, uint64_t id, Extent* e, bool create) {
  std::string dp = epath(s, id, "data"), cp = epath(s, id, "crc");
  if (!create && access(dp.c_str(), F_OK) != 0) {
    es_set_err(s, "no such extent");
    return false;
  }
  e->data_fd = ::open(dp.c_str(), O_RDWR | O_CREAT, 0644);
  e->crc_fd = ::open(cp.c_str(), O_RDWR | O_CREAT, 0644);
  if (e->data_fd < 0 || e->crc_fd < 0) {
    es_set_err(s, "open extent files");
    return false;
  }
  uint64_t hdr = 0;
  if (pread(e->crc_fd, &hdr, 8, 0) == 8) e->size = hdr;
  struct stat st;
  fstat(e->data_fd, &st);
  e->size = std::max<uint64_t>(e->size, (uint64_t)st.st_size);
  uint64_t nblocks = (e->size + kBlockSize - 1) / kBlockSize;
  e->block_crc.assign(nblocks, 0);
  if (nblocks)
    pread(e->crc_fd, e->block_crc.data(), nblocks * 4, 8);
  return true;
}

Extent* get_extent(EStore* s, uint64_t id, bool create) {
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->extents.find(id);
  if (it != s->extents.end()) return it->second;
  Extent* e = new Extent();
  if (!load_extent(s, id, e, create)) {
    delete e;
    return nullptr;
  }
  s->extents[id] = e;
  return e;
}

bool persist_crc(EStore* s, Extent* e) {
  uint64_t hdr = e->size;
  if (pwrite(e->crc_fd, &hdr, 8, 0) != 8) {
    es_set_err(s, "crc hdr write");
    return false;
  }
  if (!e->block_crc.empty() &&
      pwrite(e->crc_fd, e->block_crc.data(), e->block_crc.size() * 4, 8) !=
          (ssize_t)(e->block_crc.size() * 4)) {
    es_set_err(s, "crc table write");
    return false;
  }
  return true;
}

// Recompute CRC of one block from the data file.
bool recrc_block(EStore* s, Extent* e, uint64_t b) {
  uint64_t off = b * kBlockSize;
  uint64_t len = std::min(kBlockSize, e->size - off);
  PoolBuf buf(len);  // pooled scratch: no per-recrc malloc churn
  ssize_t rd = pread(e->data_fd, buf.data, len, (off_t)off);
  if (rd < 0) {
    es_set_err(s, "pread for recrc");
    return false;
  }
  if ((uint64_t)rd < len) {  // sparse tail: treat missing as zeros
    memset(buf.data + rd, 0, len - rd);
  }
  e->block_crc[b] = crc32_ieee(0, buf.data, len);
  return true;
}

}  // namespace

extern "C" {

void* es_open(const char* dir) {
  EStore* s = new EStore();
  s->dir = dir;
  ::mkdir(dir, 0755);
  struct stat st;
  if (stat(dir, &st) != 0 || !S_ISDIR(st.st_mode)) {
    delete s;
    return nullptr;
  }
  return s;
}

void es_close(void* h) {
  EStore* s = (EStore*)h;
  if (!s) return;
  for (auto& kv : s->extents) {
    persist_crc(s, kv.second);
    if (kv.second->data_fd >= 0) ::close(kv.second->data_fd);
    if (kv.second->crc_fd >= 0) ::close(kv.second->crc_fd);
    delete kv.second;
  }
  delete s;
}

const char* es_last_error(void* h) { return ((EStore*)h)->err; }

int es_create(void* h, uint64_t extent_id) {
  EStore* s = (EStore*)h;
  return get_extent(s, extent_id, true) ? 0 : -1;
}

// Random-access write; maintains block CRCs for touched blocks.
int es_write(void* h, uint64_t extent_id, uint64_t off, const uint8_t* buf,
             uint64_t len) {
  EStore* s = (EStore*)h;
  if (off + len > kMaxExtent) {
    es_set_err(s, "write past max extent size");
    return -1;
  }
  Extent* e = get_extent(s, extent_id, true);
  if (!e) return -1;
  std::lock_guard<std::mutex> g(e->mu);
  if (pwrite(e->data_fd, buf, len, (off_t)off) != (ssize_t)len) {
    es_set_err(s, "pwrite");
    return -1;
  }
  uint64_t old_size = e->size;
  e->size = std::max(e->size, off + len);
  uint64_t nblocks = (e->size + kBlockSize - 1) / kBlockSize;
  if (e->block_crc.size() < nblocks) e->block_crc.resize(nblocks, 0);
  uint64_t b0 = off / kBlockSize, b1 = (off + len - 1) / kBlockSize;
  if (e->size > old_size) {
    // growth: sparse holes between the old tail and this write, plus the
    // old tail block itself (its span lengthened), need fresh CRCs
    uint64_t old_tail = old_size ? (old_size - 1) / kBlockSize : 0;
    b0 = std::min(b0, old_tail);
  }
  for (uint64_t b = b0; b <= b1; b++)
    if (!recrc_block(s, e, b)) return -1;
  if (!persist_crc(s, e)) return -1;
  return 0;
}

// Read with CRC verification of all touched blocks.
// Returns bytes read, -2 on crc mismatch, -1 on other errors.
int64_t es_read(void* h, uint64_t extent_id, uint64_t off, uint8_t* buf,
                uint64_t len) {
  EStore* s = (EStore*)h;
  Extent* e = get_extent(s, extent_id, false);
  if (!e) return -1;
  std::lock_guard<std::mutex> g(e->mu);
  if (len == 0 || off >= e->size) return 0;  // len==0 would underflow b1
  len = std::min(len, e->size - off);
  ssize_t rd = pread(e->data_fd, buf, len, (off_t)off);
  if (rd < 0) {
    es_set_err(s, "pread");
    return -1;
  }
  if ((uint64_t)rd < len) memset(buf + rd, 0, len - rd);
  // verify every touched block (read its full span from disk)
  uint64_t b0 = off / kBlockSize, b1 = (off + len - 1) / kBlockSize;
  PoolBuf tmp(kBlockSize);  // pooled: this runs on EVERY verified read
  for (uint64_t b = b0; b <= b1; b++) {
    uint64_t boff = b * kBlockSize;
    uint64_t blen = std::min(kBlockSize, e->size - boff);
    ssize_t r2 = pread(e->data_fd, tmp.data, blen, (off_t)boff);
    if (r2 < 0) {
      es_set_err(s, "pread verify");
      return -1;
    }
    if ((uint64_t)r2 < blen) memset(tmp.data + r2, 0, blen - r2);
    if (crc32_ieee(0, tmp.data, blen) != e->block_crc[b]) {
      es_set_err(s, "block crc mismatch");
      return -2;
    }
  }
  return (int64_t)len;
}

uint64_t es_size(void* h, uint64_t extent_id) {
  EStore* s = (EStore*)h;
  Extent* e = get_extent(s, extent_id, false);
  return e ? e->size : 0;
}

// Copy out the per-block CRC table; returns block count (for scrub /
// replica-diff repair and batched TPU re-verification).
int64_t es_block_crcs(void* h, uint64_t extent_id, uint32_t* out, int64_t cap) {
  EStore* s = (EStore*)h;
  Extent* e = get_extent(s, extent_id, false);
  if (!e) return -1;
  std::lock_guard<std::mutex> g(e->mu);
  int64_t n = std::min<int64_t>(cap, (int64_t)e->block_crc.size());
  memcpy(out, e->block_crc.data(), n * 4);
  return (int64_t)e->block_crc.size();
}

int es_delete(void* h, uint64_t extent_id) {
  EStore* s = (EStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->extents.find(extent_id);
  if (it != s->extents.end()) {
    ::close(it->second->data_fd);
    ::close(it->second->crc_fd);
    delete it->second;
    s->extents.erase(it);
  }
  std::string dp = epath(s, extent_id, "data"), cp = epath(s, extent_id, "crc");
  if (::unlink(dp.c_str()) != 0 && errno != ENOENT) {
    es_set_err(s, "unlink");
    return -1;
  }
  ::unlink(cp.c_str());
  return 0;
}

int es_sync(void* h, uint64_t extent_id) {
  EStore* s = (EStore*)h;
  Extent* e = get_extent(s, extent_id, false);
  if (!e) return -1;
  std::lock_guard<std::mutex> g(e->mu);
  if (!persist_crc(s, e)) return -1;
  if (fsync(e->data_fd) != 0 || fsync(e->crc_fd) != 0) {
    es_set_err(s, "fsync");
    return -1;
  }
  return 0;
}

}  // extern "C"
