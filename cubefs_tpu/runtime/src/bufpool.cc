// Slab buffer pool: size-classed freelists for the runtime's IO scratch
// allocations.
//
// Role parity: blobstore/common/resourcepool + util/bytespool (slab mem
// pools for shard/block buffers) and blobstore/common/tcmalloc
// (tcmalloc_manage.cc: allocator stats + ReleaseFreeMemory as an ops
// surface). The reference links gperftools process-wide; this runtime's
// hot allocations are the store scratch buffers (extent read-verify,
// CRC rebuild, chunk compaction), so a focused pool gives the same
// steady-state behavior — no per-IO malloc/free churn — with an
// inspectable stats/release surface instead of an opaque allocator.
//
// Build: part of libcubefs_rt.so (see runtime/build.py).

#include "bufpool.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

constexpr int kMinShift = 12;  // 4 KiB
constexpr int kMaxShift = 23;  // 8 MiB
constexpr int kClasses = kMaxShift - kMinShift + 1;
// per-class cap on cached buffers, sized so the whole pool holds at
// most ~2x the largest class per class (small classes cache more)
constexpr size_t kMaxCachedBytesPerClass = 16 << 20;

struct SizeClass {
  std::mutex mu;
  std::vector<void*> free_list;
  uint64_t hits = 0;
  uint64_t misses = 0;
};

SizeClass g_classes[kClasses];

int class_for(size_t n) {
  if (n == 0 || n > ((size_t)1 << kMaxShift)) return -1;
  int shift = kMinShift;
  while (((size_t)1 << shift) < n) shift++;
  return shift - kMinShift;
}

}  // namespace

extern "C" {

void* bp_alloc(size_t n) {
  int cls = class_for(n);
  if (cls < 0) return malloc(n);  // oversize: system allocator
  SizeClass& sc = g_classes[cls];
  {
    std::lock_guard<std::mutex> g(sc.mu);
    if (!sc.free_list.empty()) {
      void* p = sc.free_list.back();
      sc.free_list.pop_back();
      sc.hits++;
      return p;
    }
    sc.misses++;
  }
  return malloc((size_t)1 << (cls + kMinShift));
}

void bp_free(void* p, size_t n) {
  if (p == nullptr) return;
  int cls = class_for(n);
  if (cls < 0) {
    free(p);
    return;
  }
  size_t buf_bytes = (size_t)1 << (cls + kMinShift);
  SizeClass& sc = g_classes[cls];
  {
    std::lock_guard<std::mutex> g(sc.mu);
    if (sc.free_list.size() * buf_bytes < kMaxCachedBytesPerClass) {
      sc.free_list.push_back(p);
      return;
    }
  }
  free(p);  // class cache full
}

size_t bp_release_free_memory() {
  size_t released = 0;
  for (int i = 0; i < kClasses; i++) {
    SizeClass& sc = g_classes[i];
    std::vector<void*> drop;
    {
      std::lock_guard<std::mutex> g(sc.mu);
      drop.swap(sc.free_list);
    }
    for (void* p : drop) free(p);
    released += drop.size() * ((size_t)1 << (i + kMinShift));
  }
  return released;
}

size_t bp_stats_json(char* out, size_t cap) {
  if (out == nullptr || cap == 0) return 0;
  std::string s = "{\"classes\": [";
  size_t held = 0;
  for (int i = 0; i < kClasses; i++) {
    SizeClass& sc = g_classes[i];
    size_t cached;
    uint64_t hits, misses;
    {
      std::lock_guard<std::mutex> g(sc.mu);
      cached = sc.free_list.size();
      hits = sc.hits;
      misses = sc.misses;
    }
    size_t bytes = (size_t)1 << (i + kMinShift);
    held += cached * bytes;
    char item[128];
    snprintf(item, sizeof item,
             "%s{\"size\": %zu, \"cached\": %zu, \"hits\": %llu, "
             "\"misses\": %llu}",
             i ? ", " : "", bytes, cached, (unsigned long long)hits,
             (unsigned long long)misses);
    s += item;
  }
  char tail[48];
  snprintf(tail, sizeof tail, "], \"held_bytes\": %zu}", held);
  s += tail;
  size_t n = s.size() < cap - 1 ? s.size() : cap - 1;
  memcpy(out, s.data(), n);
  out[n] = 0;
  return n;
}

}  // extern "C"
