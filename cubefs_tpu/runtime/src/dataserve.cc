// Native datanode read plane: GIL-free extent reads.
//
// Role parity: datanode/server.go's TCP packet serving for read ops —
// the reference serves extent reads from Go directly over the native
// store. Here the Python DataNode keeps the write path (chain
// replication + per-dp raft need the Python planes), while this C++
// thread-per-connection server answers OP_READ from the SAME native
// extent-store handles (extentstore.cc es_read: internally locked,
// CRC-verified per block) with zero Python in the loop.
//
// Registration mirrors the meta plane: the Python DataNode registers
// each partition's es handle; serving flags flip with node/disk health
// (a broken disk's partitions answer 503-coded errors so clients fail
// over to another replica). ds_drop_partition BLOCKS until in-flight
// reads drain — the caller closes the store right after, and a read
// racing a close would touch freed memory.

#include <arpa/inet.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "packetwire.h"

extern "C" int64_t es_read(void* h, uint64_t extent_id, uint64_t off,
                           uint8_t* buf, uint64_t len);
extern "C" uint64_t es_size(void* h, uint64_t extent_id);
extern "C" const char* es_last_error(void* h);

namespace {

using pktwire::PacketHdr;

constexpr uint8_t OP_READ = 0x02;
constexpr uint8_t OP_PING = 0x7F;
// reads span up to a whole extent (128 MiB, extentstore kMaxExtent) —
// the inbound-frame cap stays small, this bounds only the reply
constexpr uint64_t MAX_READ = 128ull << 20;

struct Partition {
  void* es = nullptr;
  mutable std::shared_mutex mu;  // readers shared; drop exclusive
  bool serving = true;
};

struct DataServe {
  mutable std::shared_mutex pmu;
  std::unordered_map<uint64_t, std::shared_ptr<Partition>> parts;
  std::atomic<bool> down{false};  // node-level kill switch
  std::atomic<bool> stopping{false};
  std::atomic<int> live_conns{0};
  std::atomic<uint64_t> ops{0};
  int listen_fd = -1;
  std::thread accepter;
  std::mutex conn_mu;
  std::vector<int> conn_fds;
  std::mutex fail_mu;
  // DISTINCT dps with es_read failures since the last drain: a set, so
  // one dying dp's failure storm can neither grow memory nor push other
  // dps' signals past the drain cap
  std::unordered_set<uint64_t> failed_dps;

  std::shared_ptr<Partition> get(uint64_t dp) const {
    std::shared_lock l(pmu);
    auto it = parts.find(dp);
    return it == parts.end() ? nullptr : it->second;
  }
};

// args are tiny ({"length": N}); scan out one integer field
uint64_t parse_length(const std::string& args) {
  size_t k = args.find("\"length\"");
  if (k == std::string::npos) return 0;
  k = args.find(':', k);
  if (k == std::string::npos) return 0;
  k++;
  while (k < args.size() && (args[k] == ' ')) k++;
  uint64_t v = 0;
  while (k < args.size() && args[k] >= '0' && args[k] <= '9')
    v = v * 10 + (args[k++] - '0');
  return v;
}

void err_reply(int fd, const PacketHdr& req, int code, const char* msg) {
  std::string args = "{\"error\": \"";
  for (const char* p = msg; *p; p++)
    if (*p != '"' && *p != '\\' && (unsigned char)*p >= 0x20) args += *p;
  args += "\", \"code\": " + std::to_string(code) + "}";
  pktwire::reply(fd, req, pktwire::RESULT_RPC, args);
}

void serve_conn(DataServe* ds, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  std::string args_buf, payload_buf;
  std::vector<uint8_t> data;
  while (!ds->stopping.load(std::memory_order_relaxed)) {
    PacketHdr h;
    if (!pktwire::recv_exact(fd, &h, sizeof h)) break;
    if (h.magic != pktwire::MAGIC || h.asize > pktwire::MAX_FRAME ||
        h.psize > pktwire::MAX_FRAME)
      break;  // framing lost: drop the connection
    args_buf.resize(h.asize);
    if (h.asize && !pktwire::recv_exact(fd, &args_buf[0], h.asize)) break;
    payload_buf.resize(h.psize);
    if (h.psize && !pktwire::recv_exact(fd, &payload_buf[0], h.psize)) break;
    if (rt_crc32(0, (const uint8_t*)payload_buf.data(),
                 payload_buf.size()) != h.crc)
      break;  // corrupt payload: drop
    ds->ops.fetch_add(1, std::memory_order_relaxed);
    if (h.opcode == OP_PING) {
      pktwire::reply(fd, h, 0, "{}");
      continue;
    }
    if (h.opcode != OP_READ) {
      // not a native read op: this plane doesn't serve it (writes ride
      // the Python planes)
      pktwire::reply(fd, h, 0xFD,
                     "{\"error\": \"no opcode on native read plane\"}");
      continue;
    }
    if (ds->down.load()) {
      err_reply(fd, h, 503, "datanode is down");
      continue;
    }
    auto p = ds->get(h.partition);
    if (!p) {
      err_reply(fd, h, 404, "dp not on this node");
      continue;
    }
    std::shared_lock pl(p->mu);
    if (!p->serving || p->es == nullptr) {
      err_reply(fd, h, 503, "partition not served (disk broken?)");
      continue;
    }
    uint64_t want = parse_length(args_buf);
    if (want > MAX_READ) {
      err_reply(fd, h, 400, "length too large");
      continue;
    }
    // clamp the allocation to what the extent can actually yield: an
    // unauthenticated request must not commit 128 MiB for a bogus
    // extent/offset (es_read clamps identically, so replies match)
    uint64_t esz = es_size(p->es, h.extent);
    if (h.offset >= esz)
      want = 0;
    else if (want > esz - h.offset)
      want = esz - h.offset;
    data.resize(want);
    int64_t got = want ? es_read(p->es, h.extent, h.offset, data.data(),
                                 want)
                       : 0;
    if (got < 0) {
      const char* e = es_last_error(p->es);
      {
        // surface the failure to the Python disk triage: ds_take_failed
        // drains this set so a dying disk that only serves native reads
        // still gets probed, marked and migrated
        std::lock_guard<std::mutex> g(ds->fail_mu);
        ds->failed_dps.insert(h.partition);
      }
      err_reply(fd, h, 409, e ? e : "extent read failed");
      continue;
    }
    pktwire::reply(fd, h, 0, "{}", data.data(), (size_t)got);
    if (data.capacity() > (8u << 20) && want < (1u << 20)) {
      // don't pin a large-read high-water mark for an idle connection
      data.shrink_to_fit();
    }
  }
  {
    std::lock_guard<std::mutex> g(ds->conn_mu);
    auto& v = ds->conn_fds;
    for (size_t i = 0; i < v.size(); i++)
      if (v[i] == fd) {
        v.erase(v.begin() + (long)i);
        break;
      }
  }
  close(fd);
  ds->live_conns.fetch_sub(1);
}

void accept_loop(DataServe* ds) {
  while (!ds->stopping.load()) {
    int fd = accept(ds->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (ds->stopping.load()) return;
      std::this_thread::sleep_for(std::chrono::milliseconds(5));
      continue;
    }
    ds->live_conns.fetch_add(1);
    {
      std::lock_guard<std::mutex> g(ds->conn_mu);
      ds->conn_fds.push_back(fd);
    }
    if (ds->stopping.load()) shutdown(fd, SHUT_RDWR);
    std::thread(serve_conn, ds, fd).detach();
  }
}

}  // namespace

extern "C" {

void* ds_create() { return new DataServe(); }

void ds_destroy(void* h) { delete (DataServe*)h; }

void ds_add_partition(void* h, uint64_t dp_id, void* es, int serving) {
  auto* ds = (DataServe*)h;
  auto p = std::make_shared<Partition>();
  p->es = es;
  p->serving = serving != 0;
  std::unique_lock l(ds->pmu);
  ds->parts[dp_id] = std::move(p);
}

void ds_set_serving(void* h, uint64_t dp_id, int serving) {
  auto* ds = (DataServe*)h;
  auto p = ds->get(dp_id);
  if (!p) return;
  std::unique_lock l(p->mu);
  p->serving = serving != 0;
}

// Blocks until in-flight reads on the partition drain, then forgets it
// — the caller closes the extent store right after, so a racing read
// must never still hold the handle.
void ds_drop_partition(void* h, uint64_t dp_id) {
  auto* ds = (DataServe*)h;
  std::shared_ptr<Partition> p;
  {
    std::unique_lock l(ds->pmu);
    auto it = ds->parts.find(dp_id);
    if (it == ds->parts.end()) return;
    p = it->second;
    ds->parts.erase(it);
  }
  std::unique_lock l(p->mu);  // waits for shared holders (reads)
  p->es = nullptr;
}

void ds_set_down(void* h, int down) {
  ((DataServe*)h)->down.store(down != 0);
}

uint64_t ds_op_count(void* h) { return ((DataServe*)h)->ops.load(); }

// Drain dp_ids whose native reads hit store errors since the last call
// (clear-on-read); returns the count written into out (<= cap).
int ds_take_failed(void* h, uint64_t* out, int cap) {
  auto* ds = (DataServe*)h;
  std::lock_guard<std::mutex> g(ds->fail_mu);
  int n = 0;
  for (auto it = ds->failed_dps.begin();
       it != ds->failed_dps.end() && n < cap;) {
    out[n++] = *it;
    it = ds->failed_dps.erase(it);  // entries past cap stay for next drain
  }
  return n;
}

int ds_serve(void* h, const char* host, int port) {
  auto* ds = (DataServe*)h;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  if (bind(fd, (sockaddr*)&addr, sizeof addr) != 0 || listen(fd, 128) != 0) {
    close(fd);
    return -1;
  }
  socklen_t alen = sizeof addr;
  getsockname(fd, (sockaddr*)&addr, &alen);
  ds->listen_fd = fd;
  ds->stopping.store(false);
  ds->accepter = std::thread(accept_loop, ds);
  return (int)ntohs(addr.sin_port);
}

void ds_stop(void* h) {
  auto* ds = (DataServe*)h;
  ds->stopping.store(true);
  if (ds->listen_fd >= 0) {
    shutdown(ds->listen_fd, SHUT_RDWR);
    close(ds->listen_fd);
    ds->listen_fd = -1;
  }
  {
    std::lock_guard<std::mutex> g(ds->conn_mu);
    for (int fd : ds->conn_fds) shutdown(fd, SHUT_RDWR);
    ds->conn_fds.clear();
  }
  if (ds->accepter.joinable()) ds->accepter.join();
  while (ds->live_conns.load() > 0)
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
}

}  // extern "C"
