"""Build + load the native runtime (ctypes, no pybind11).

g++ compiles cubefs_tpu/runtime/src/*.cc into libcubefs_rt.so next to
this file. The .so is never committed (gitignored): it is always built
from the reviewed sources, and rebuilt whenever the content hash of the
sources (recorded beside the .so) changes — mtimes are useless after a
git clone, which does not preserve them.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src")
_SO = os.path.join(_DIR, "libcubefs_rt.so")
_STAMP = _SO + ".srchash"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


def _src_hash() -> str:
    h = hashlib.sha256()
    for f in sorted(os.listdir(_SRC)):
        if f.endswith((".cc", ".h")):
            h.update(f.encode() + b"\0")
            with open(os.path.join(_SRC, f), "rb") as fh:
                h.update(fh.read())
    return h.hexdigest()


def _needs_build() -> bool:
    if not os.path.exists(_SO) or not os.path.exists(_STAMP):
        return True
    with open(_STAMP) as f:
        return f.read().strip() != _src_hash()


def build() -> str:
    # hash BEFORE compiling: if a source changes mid-compile, the stamp
    # reflects the pre-edit inputs and the next check rebuilds
    src_hash = _src_hash()
    srcs = [
        os.path.join(_SRC, f) for f in sorted(os.listdir(_SRC)) if f.endswith(".cc")
    ]
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", _SO, *srcs]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    with open(_STAMP, "w") as f:
        f.write(src_hash)
    return _SO


def load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            if _needs_build():
                build()
            lib = ctypes.CDLL(_SO)
            c = ctypes
            lib.cs_open.restype = c.c_void_p
            lib.cs_open.argtypes = [c.c_char_p]
            lib.cs_close.argtypes = [c.c_void_p]
            lib.cs_last_error.restype = c.c_char_p
            lib.cs_last_error.argtypes = [c.c_void_p]
            lib.cs_create_chunk.argtypes = [c.c_void_p, c.c_uint64]
            lib.cs_put_shard.argtypes = [
                c.c_void_p, c.c_uint64, c.c_uint64,
                c.c_char_p, c.c_uint32, c.POINTER(c.c_uint32),
            ]
            lib.cs_get_shard.restype = c.c_int64
            lib.cs_get_shard.argtypes = [
                c.c_void_p, c.c_uint64, c.c_uint64,
                c.c_void_p, c.c_uint32, c.POINTER(c.c_uint32),
            ]
            lib.cs_delete_shard.argtypes = [c.c_void_p, c.c_uint64, c.c_uint64]
            lib.cs_list_shards.restype = c.c_int64
            lib.cs_list_shards.argtypes = [
                c.c_void_p, c.c_uint64,
                c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64,
            ]
            lib.cs_shard_count.restype = c.c_int64
            lib.cs_shard_count.argtypes = [c.c_void_p, c.c_uint64]
            lib.cs_sync.argtypes = [c.c_void_p, c.c_uint64]
            lib.cs_crc32.restype = c.c_uint32
            lib.cs_crc32.argtypes = [c.c_char_p, c.c_uint64]
            lib.cs_compact_chunk.restype = c.c_int64
            lib.cs_compact_chunk.argtypes = [c.c_void_p, c.c_uint64]
            # extent store (datanode engine)
            lib.es_open.restype = c.c_void_p
            lib.es_open.argtypes = [c.c_char_p]
            lib.es_close.argtypes = [c.c_void_p]
            lib.es_last_error.restype = c.c_char_p
            lib.es_last_error.argtypes = [c.c_void_p]
            lib.es_create.argtypes = [c.c_void_p, c.c_uint64]
            lib.es_write.argtypes = [
                c.c_void_p, c.c_uint64, c.c_uint64, c.c_char_p, c.c_uint64,
            ]
            lib.es_read.restype = c.c_int64
            lib.es_read.argtypes = [
                c.c_void_p, c.c_uint64, c.c_uint64, c.c_void_p, c.c_uint64,
            ]
            lib.es_size.restype = c.c_uint64
            lib.es_size.argtypes = [c.c_void_p, c.c_uint64]
            lib.es_block_crcs.restype = c.c_int64
            lib.es_block_crcs.argtypes = [c.c_void_p, c.c_uint64, c.c_void_p, c.c_int64]
            lib.es_delete.argtypes = [c.c_void_p, c.c_uint64]
            lib.es_sync.argtypes = [c.c_void_p, c.c_uint64]
            # native client (libcfs-analog C ABI over the RPC wire)
            lib.cfs_last_error.restype = c.c_char_p
            lib.cfs_last_meta.restype = c.c_char_p
            lib.cfs_blob_put.argtypes = [
                c.c_char_p, c.c_int, c.c_char_p, c.c_uint64, c.c_char_p, c.c_uint64]
            lib.cfs_blob_get.restype = c.c_int64
            lib.cfs_blob_get.argtypes = [
                c.c_char_p, c.c_int, c.c_char_p, c.c_void_p, c.c_uint64]
            lib.cfs_blob_delete.argtypes = [c.c_char_p, c.c_int, c.c_char_p]
            lib.cfs_codec_encode.argtypes = [
                c.c_char_p, c.c_int, c.c_int, c.c_int, c.c_uint64, c.c_int,
                c.c_char_p, c.c_void_p]
            lib.cfs_codec_encode_shm.restype = c.c_int
            lib.cfs_codec_encode_shm.argtypes = [
                c.c_char_p, c.c_int, c.c_int, c.c_int, c.c_uint64, c.c_int,
                c.c_void_p, c.c_void_p]
            lib.cfs_codec_crc32.argtypes = [
                c.c_char_p, c.c_int, c.c_uint64, c.c_char_p, c.c_uint64, c.c_void_p]
            # POSIX file surface over the FsGateway (libcfs analog)
            lib.cfs_mount.restype = c.c_void_p
            lib.cfs_mount.argtypes = [c.c_char_p, c.c_int]
            lib.cfs_unmount.argtypes = [c.c_void_p]
            lib.cfs_open.restype = c.c_int
            lib.cfs_open.argtypes = [c.c_void_p, c.c_char_p, c.c_int, c.c_int]
            lib.cfs_close.restype = c.c_int
            lib.cfs_close.argtypes = [c.c_void_p, c.c_int]
            lib.cfs_read.restype = c.c_int64
            lib.cfs_read.argtypes = [c.c_void_p, c.c_int, c.c_void_p,
                                     c.c_uint64]
            lib.cfs_pread.restype = c.c_int64
            lib.cfs_pread.argtypes = [c.c_void_p, c.c_int, c.c_void_p,
                                      c.c_uint64, c.c_uint64]
            lib.cfs_write.restype = c.c_int64
            lib.cfs_write.argtypes = [c.c_void_p, c.c_int, c.c_char_p,
                                      c.c_uint64]
            lib.cfs_pwrite.restype = c.c_int64
            lib.cfs_pwrite.argtypes = [c.c_void_p, c.c_int, c.c_char_p,
                                       c.c_uint64, c.c_uint64]
            lib.cfs_lseek.restype = c.c_int64
            lib.cfs_lseek.argtypes = [c.c_void_p, c.c_int, c.c_int64, c.c_int]
            lib.cfs_stat_path.restype = c.c_int
            lib.cfs_stat_path.argtypes = [
                c.c_void_p, c.c_char_p, c.POINTER(c.c_uint64),
                c.POINTER(c.c_uint32), c.POINTER(c.c_uint32),
                c.POINTER(c.c_uint64)]
            lib.cfs_mkdirs.restype = c.c_int
            lib.cfs_mkdirs.argtypes = [c.c_void_p, c.c_char_p]
            lib.cfs_readdir.restype = c.c_int64
            lib.cfs_readdir.argtypes = [c.c_void_p, c.c_char_p, c.c_void_p,
                                        c.c_uint64]
            lib.cfs_unlink.restype = c.c_int
            lib.cfs_unlink.argtypes = [c.c_void_p, c.c_char_p]
            lib.cfs_rmdir.restype = c.c_int
            lib.cfs_rmdir.argtypes = [c.c_void_p, c.c_char_p]
            lib.cfs_rename.restype = c.c_int
            lib.cfs_rename.argtypes = [c.c_void_p, c.c_char_p, c.c_char_p]
            lib.cfs_truncate.restype = c.c_int
            lib.cfs_truncate.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
            lib.cfs_flush.restype = c.c_int
            lib.cfs_flush.argtypes = [c.c_void_p, c.c_int]
            # ordered KV store (RocksDB-analog shard/state engine)
            lib.kv_open.restype = c.c_void_p
            lib.kv_open.argtypes = [c.c_char_p]
            lib.kv_close.argtypes = [c.c_void_p]
            lib.kv_put.restype = c.c_int
            lib.kv_put.argtypes = [c.c_void_p, c.c_char_p, c.c_uint32,
                                   c.c_char_p, c.c_uint32]
            lib.kv_del.restype = c.c_int
            lib.kv_del.argtypes = [c.c_void_p, c.c_char_p, c.c_uint32]
            lib.kv_get.restype = c.c_int64
            lib.kv_get.argtypes = [c.c_void_p, c.c_char_p, c.c_uint32,
                                   c.c_void_p, c.c_uint32]
            lib.kv_count.restype = c.c_uint64
            lib.kv_count.argtypes = [c.c_void_p]
            lib.kv_scan.restype = c.c_int64
            lib.kv_scan.argtypes = [
                c.c_void_p, c.c_char_p, c.c_uint32, c.c_char_p, c.c_uint32,
                c.c_uint32, c.c_void_p, c.c_uint32,
                c.POINTER(c.c_uint32), c.POINTER(c.c_uint32)]
            lib.kv_median.restype = c.c_int64
            lib.kv_median.argtypes = [c.c_void_p, c.c_char_p, c.c_uint32,
                                      c.c_char_p, c.c_uint32, c.c_void_p,
                                      c.c_uint32]
            lib.kv_batch.restype = c.c_int64
            lib.kv_batch.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
            lib.kv_compact.restype = c.c_int
            lib.kv_compact.argtypes = [c.c_void_p]
            lib.kv_clear.restype = c.c_int
            lib.kv_clear.argtypes = [c.c_void_p]
            lib.kv_wal_bytes.restype = c.c_uint64
            lib.kv_wal_bytes.argtypes = [c.c_void_p]
            lib.kv_snap_bytes.restype = c.c_uint64
            lib.kv_snap_bytes.argtypes = [c.c_void_p]
            # native metanode read plane (manager_op.go hot-loop analog)
            lib.ms_create.restype = c.c_void_p
            lib.ms_destroy.argtypes = [c.c_void_p]
            lib.ms_add_partition.argtypes = [
                c.c_void_p, c.c_uint64, c.c_uint64, c.c_uint64]
            lib.ms_drop_partition.argtypes = [c.c_void_p, c.c_uint64]
            lib.ms_set_serving.argtypes = [
                c.c_void_p, c.c_uint64, c.c_int, c.c_char_p]
            lib.ms_put_inode.argtypes = [
                c.c_void_p, c.c_uint64, c.c_uint64, c.c_char_p, c.c_uint32]
            lib.ms_del_inode.argtypes = [c.c_void_p, c.c_uint64, c.c_uint64]
            lib.ms_ensure_dir.argtypes = [c.c_void_p, c.c_uint64, c.c_uint64]
            lib.ms_del_dir.argtypes = [c.c_void_p, c.c_uint64, c.c_uint64]
            lib.ms_put_dentry.argtypes = [
                c.c_void_p, c.c_uint64, c.c_uint64, c.c_char_p, c.c_uint32,
                c.c_uint64]
            lib.ms_del_dentry.argtypes = [
                c.c_void_p, c.c_uint64, c.c_uint64, c.c_char_p, c.c_uint32]
            lib.ms_clear.argtypes = [c.c_void_p, c.c_uint64]
            lib.ms_op_count.restype = c.c_uint64
            lib.ms_op_count.argtypes = [c.c_void_p]
            lib.ms_serve.restype = c.c_int
            lib.ms_serve.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
            lib.ms_stop.argtypes = [c.c_void_p]
            lib.ms_bench.restype = c.c_double
            lib.ms_bench.argtypes = [c.c_char_p, c.c_int, c.c_int,
                                     c.c_char_p, c.c_int, c.c_int]
            # native CPU GF(2^8) engine (klauspost AVX2 fallback role);
            # mat/in are raw numpy buffer pointers (zero-copy)
            lib.gf_apply.argtypes = [
                c.c_void_p, c.c_uint64, c.c_uint64, c.c_void_p,
                c.c_void_p, c.c_uint64, c.c_uint64]
            lib.gf_cpu_level.restype = c.c_int
            # scheduled XOR-program executor (ops/xorprog.py schedules
            # replayed natively; the cpp-xor codec leg)
            lib.xor_apply.argtypes = [
                c.c_void_p, c.c_uint64, c.c_void_p, c.c_void_p,
                c.c_uint64, c.c_uint64, c.c_uint64,
                c.c_uint64, c.c_uint64, c.c_uint64]
            # shared native CRC32 (clmul folding; crc32cpu.cc)
            lib.rt_crc32.restype = c.c_uint32
            lib.rt_crc32.argtypes = [c.c_uint32, c.c_void_p, c.c_size_t]
            lib.rt_crc32_level.restype = c.c_int
            # native datanode read plane (dataserve.cc)
            lib.ds_create.restype = c.c_void_p
            lib.ds_destroy.argtypes = [c.c_void_p]
            lib.ds_add_partition.argtypes = [
                c.c_void_p, c.c_uint64, c.c_void_p, c.c_int]
            lib.ds_set_serving.argtypes = [c.c_void_p, c.c_uint64, c.c_int]
            lib.ds_drop_partition.argtypes = [c.c_void_p, c.c_uint64]
            lib.ds_set_down.argtypes = [c.c_void_p, c.c_int]
            lib.ds_op_count.restype = c.c_uint64
            lib.ds_op_count.argtypes = [c.c_void_p]
            lib.ds_take_failed.restype = c.c_int
            lib.ds_take_failed.argtypes = [c.c_void_p, c.c_void_p, c.c_int]
            lib.ds_serve.restype = c.c_int
            lib.ds_serve.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
            lib.ds_stop.argtypes = [c.c_void_p]
            _lib = lib
    return _lib
