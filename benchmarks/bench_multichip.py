"""Multichip codec benchmark: sharded vs single-chip at REAL sizes.

Round-1 VERDICT flagged that the (dp, tp, sp) mesh sharding was only
ever validated at toy sizes — nothing showed the split is PROFITABLE
(splitting a 16-shard stripe across chips may be ICI-latency-bound).
This script measures exactly that, whenever more than one device is
visible:

  * single-device RS(12+4) repair throughput (the bench.py config)
  * the same work sharded over the full mesh (stripes over dp, shards
    over tp with psum XOR-combine, bytes over sp)

and reports the speedup. On one device it measures the single-chip
number only and says so. Usable today on the virtual CPU mesh
(JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
python benchmarks/bench_multichip.py — numbers are NOT meaningful perf,
only a plumbing check) and on real multi-chip hardware unchanged.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time(fn, *args, iters: int = 3) -> float:
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main(
    shard_bytes: int | None = None,
    batch: int | None = None,
    out_path: str | None = None,
) -> dict:
    import jax
    import numpy as np

    from cubefs_tpu.models import repair
    from cubefs_tpu.ops import rs_kernel
    from cubefs_tpu.parallel import mesh as meshlib

    n_dev = jax.device_count()
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    S = shard_bytes or ((4 << 20) if on_tpu else (1 << 18))
    B = batch or (8 if on_tpu else 4)
    n, m = 12, 4
    plan = repair.make_plan(n, m, bad=[1, 7])
    rows = plan.rows
    rng = np.random.default_rng(3)
    surv = rng.integers(0, 256, (B, n, S), dtype=np.uint8)

    dev0 = jax.devices()[0]
    x1 = jax.device_put(surv, dev0)
    dt = _time(lambda a: rs_kernel.gf_matrix_apply(rows, a), x1)
    single_gibs = B * n * S / dt / (1 << 30)

    out = {"devices": n_dev, "platform": jax.devices()[0].platform,
           "shard_bytes": S, "stripes": B,
           "single_device_gibs": round(single_gibs, 3)}
    if not on_tpu:
        out["note"] = (
            "virtual CPU mesh: devices share host cores, so speedups are "
            "NOT meaningful perf — this artifact is a sharding-plumbing "
            "check only; rerun on a real multi-chip mesh for profitability"
        )
    if n_dev > 1:
        mesh = meshlib.make_mesh(n_dev)
        dp, tp, sp = (mesh.shape[a] for a in ("dp", "tp", "sp"))
        # batch/shape must divide the mesh axes
        Bm = max(B, dp) - (max(B, dp) % dp or 0) or dp
        Sm = S - (S % sp)
        surv_m = rng.integers(0, 256, (Bm, n, Sm), dtype=np.uint8)
        xs = jax.device_put(surv_m, meshlib.stripe_sharding(mesh))

        def sharded(a):
            rec, _ = repair.sharded_repair_step(mesh, plan, a)
            return rec

        dt = _time(sharded, xs)
        sharded_gibs = Bm * n * Sm / dt / (1 << 30)
        out.update({
            "mesh": {"dp": dp, "tp": tp, "sp": sp},
            "sharded_gibs": round(sharded_gibs, 3),
            "speedup_vs_single": round(sharded_gibs / single_gibs, 2),
        })

        # dp-only mesh: stripes are independent, so this axis has no
        # collectives at all — the profitable default for repair fleets
        dpm = meshlib.make_mesh(n_dev, dims={"dp": n_dev, "tp": 1, "sp": 1})
        Bd = ((B + n_dev - 1) // n_dev) * n_dev
        surv_d = rng.integers(0, 256, (Bd, n, S), dtype=np.uint8)
        xd = jax.device_put(surv_d, meshlib.stripe_sharding(dpm))

        def dp_sharded(a):
            rec, _ = repair.sharded_repair_step(dpm, plan, a)
            return rec

        dt = _time(dp_sharded, xd)
        dp_gibs = Bd * n * S / dt / (1 << 30)
        out.update({
            "dp_only_stripes": Bd,
            "dp_only_gibs": round(dp_gibs, 3),
            "dp_only_speedup_vs_single": round(dp_gibs / single_gibs, 2),
        })
    else:
        out["note"] = "one device visible: sharded comparison skipped"
    print(json.dumps(out))
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1)
    return out


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--shard-bytes", type=int, default=None)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--out", default=None)
    a = ap.parse_args()
    main(a.shard_bytes, a.batch, a.out)
