"""Timing-fidelity calibration for the axon/TPU relay.

Findings this file exists to encode (measured 2026-07-29 on the live
relay, TPU v5 lite):

  * ``jax.block_until_ready`` under the relay returns on ENQUEUE, not on
    device completion — an unchained timing loop reports physically
    impossible rates (4868 "TFLOP/s" bf16 on a ~197 TFLOP/s chip).
  * device->host fetches ride the tunnel at single-digit MB/s, so any
    timing that ends with a bulk ``device_get`` is dominated by the
    tunnel, not the chip.

The honest measurement is therefore the CHAIN-SLOPE method (shared
implementation: cubefs_tpu/utils/benchtime.py, also used by bench.py):
run K dependency-chained iterations, force completion
by fetching ONE element of the final output, do that for two values of
K, and report (T(K2)-T(K1))/(K2-K1).  Enqueue lies and the fixed fetch
cost cancel in the subtraction; what remains is per-iteration device
execution time.  bench.py uses the same method.

Prints one JSON object.  Not part of the judged bench; this is the
measurement-integrity artifact backing BENCH_r03.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from cubefs_tpu.models import repair
from cubefs_tpu.ops import rs_kernel
from cubefs_tpu.utils.benchtime import timed_slope


def timed_enqueue_style(fn, x, iters: int = 8) -> float:
    """The broken bench-style loop, kept to document the discrepancy."""
    out = fn(x)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    dev = jax.devices()[0]
    rng = np.random.default_rng(3)
    report = {"device": str(dev), "platform": dev.platform}

    # --- roofline 1: bf16 matmul, 4096^3 ------------------------------
    k = 4096
    a = jax.device_put(jnp.full((k, k), 0.5, jnp.bfloat16), dev)
    mm = jax.jit(lambda x: x @ x * 0.000244)  # keep values bounded
    flops = 2 * k**3
    report["matmul_tflops_enqueue_style"] = round(
        flops / timed_enqueue_style(mm, a) / 1e12, 1
    )
    report["matmul_tflops_slope"] = round(flops / timed_slope(mm, a, k1=4, k2=68) / 1e12, 1)

    # --- roofline 2: HBM stream (x + 1 over 512 MiB) ------------------
    big = jax.device_put(jnp.zeros((512 << 20) // 4, jnp.float32), dev)
    inc = jax.jit(lambda x: x + 1)
    nbytes = big.size * 4 * 2  # read + write
    report["hbm_gbs_slope"] = round(nbytes / timed_slope(inc, big, k1=2, k2=34) / 1e9, 1)

    # --- the judged config-3 graph ------------------------------------
    n, m, S, Br = 12, 4, 4 << 20, 4
    plan = repair.make_plan(n, m, bad=[1, 7])
    surv = jax.device_put(rng.integers(0, 256, (Br, n, S), dtype=np.uint8), dev)
    # self-composable wrapper: tile the reconstructed rows back up to n
    # pseudo-shards so out feeds in again with a constant graph shape
    reps = -(-n // len(plan.rows))
    chain = jax.jit(
        lambda a: jnp.tile(rs_kernel.gf_matrix_apply(plan.rows, a), (1, reps, 1))[
            :, :n, :
        ]
    )
    dt = timed_slope(chain, surv, k1=2, k2=34)
    report["repair_gibs_slope"] = round(Br * n * S / dt / (1 << 30), 2)
    report["repair_gibs_enqueue_style"] = round(
        Br
        * n
        * S
        / timed_enqueue_style(lambda a: rs_kernel.gf_matrix_apply(plan.rows, a), surv)
        / (1 << 30),
        2,
    )

    # --- correctness on-chip: bit-identical vs numpy GF golden --------
    from cubefs_tpu.codec import engine as ec_engine

    small = rng.integers(0, 256, (6, 1 << 16), dtype=np.uint8)
    golden = ec_engine.get_engine("numpy").encode_parity(small, 3)
    got = np.asarray(rs_kernel.encode_parity(jax.device_put(small, dev), 3))
    report["encode_bit_identical_on_tpu"] = bool(np.array_equal(golden, got))

    print(json.dumps(report))


if __name__ == "__main__":
    main()
