"""On-chip tuning experiments for the fused GF(2) Pallas kernel.

The shipped kernel (cubefs_tpu/ops/pallas_gf.py) measured ~17 GiB/s on
the judged RS(12+4)-reconstruct shape while the HBM roofline for a truly
fused kernel (read payload + write parity only) is ~300+ GiB/s at the
~434 GB/s streaming rate the chip sustains. Variants probed here, each a
hypothesis about where the time goes:

  base          — shipped kernel as-is (byte-major bit interleave)
  bitmajor      — unpack to (8, N, T)->reshape(8N, T) [plane-major, no
                  per-byte interleave] with the coefficient matrix
                  permuted to match; packs from plane-major rows too
  bitmajor-u8   — same, but shifts/masks on uint8 (no int32 blowup)
  flatgrid      — bitmajor + batch folded into the pallas grid instead
                  of vmap (one pallas_call, 2D grid)

each x tile sizes. Prints one JSON line per (variant, tile) with slope-
timed GiB/s on the judged shape (Br=4, RS(12+4), 2 missing, 4MiB).
"""

from __future__ import annotations

import functools
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cubefs_tpu.models import repair
from cubefs_tpu.ops import bitlin, pallas_gf
from cubefs_tpu.ops.bitlin import bitmajor_perm, w_to_bitmajor
from cubefs_tpu.utils.benchtime import timed_slope

N, M, S, BR = 12, 4, 4 << 20, 4


def _kernel_bitmajor(use_u8: bool, w_ref, x_ref, o_ref):
    x = x_ref[:]  # (N, T) uint8
    n, t = x.shape
    if use_u8:
        planes = [((x >> k) & 1).astype(jnp.int8) for k in range(8)]
    else:
        xi = x.astype(jnp.int32)
        planes = [((xi >> k) & 1).astype(jnp.int8) for k in range(8)]
    bits = jnp.concatenate(planes, axis=0)  # (8N, T) plane-major
    y = jax.lax.dot_general(
        w_ref[:], bits, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (8M, T) plane-major rows
    y = y & 1
    m8, _ = y.shape
    r = m8 // 8
    acc = y[0:r, :]
    for k in range(1, 8):
        acc = acc | (y[k * r : (k + 1) * r, :] << k)
    o_ref[:] = acc.astype(jnp.uint8)


@functools.lru_cache(maxsize=None)
def bitmajor_fn(coeff_bytes: bytes, rows: int, cols: int, tile: int,
                use_u8: bool):
    coeff = np.frombuffer(coeff_bytes, dtype=np.uint8).reshape(rows, cols)
    w = bitlin.gf_matrix_to_bits(coeff)
    wb = jnp.asarray(w_to_bitmajor(w, rows, cols), dtype=jnp.int8)

    @jax.jit
    def apply(shards):
        n, s = shards.shape
        return pl.pallas_call(
            functools.partial(_kernel_bitmajor, use_u8),
            out_shape=jax.ShapeDtypeStruct((rows, s), jnp.uint8),
            grid=(s // tile,),
            in_specs=[
                pl.BlockSpec((8 * rows, 8 * cols), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((n, tile), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((rows, tile), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel",)),
        )(wb, shards)

    return apply


@functools.lru_cache(maxsize=None)
def flatgrid_fn(coeff_bytes: bytes, rows: int, cols: int, tile: int):
    coeff = np.frombuffer(coeff_bytes, dtype=np.uint8).reshape(rows, cols)
    w = bitlin.gf_matrix_to_bits(coeff)
    wb = jnp.asarray(w_to_bitmajor(w, rows, cols), dtype=jnp.int8)

    @jax.jit
    def apply(shards):  # (B, N, S)
        b, n, s = shards.shape
        return pl.pallas_call(
            functools.partial(_kernel_bitmajor, True),
            out_shape=jax.ShapeDtypeStruct((b, rows, s), jnp.uint8),
            grid=(b, s // tile),
            in_specs=[
                pl.BlockSpec((8 * rows, 8 * cols), lambda i, j: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, n, tile), lambda i, j: (i, 0, j),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, rows, tile), lambda i, j: (i, 0, j),
                                   memory_space=pltpu.VMEM),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel")),
        )(wb, shards)

    return apply


def main():
    rng = np.random.default_rng(5)
    plan = repair.make_plan(N, M, bad=[1, 7])
    rows = plan.rows
    coeff = np.ascontiguousarray(rows, dtype=np.uint8)
    r, c = coeff.shape
    surv = jax.device_put(
        rng.integers(0, 256, (BR, N, S), dtype=np.uint8), jax.devices()[0]
    )
    reps = -(-N // r)

    # correctness golden PER TILE (two grid steps of the tile being
    # tested — a fixed-size golden smaller than the tile never executes
    # the kernel and silently skips validation)
    from cubefs_tpu.ops import gf256

    _golden_cache = {}

    def golden(tile):
        if tile in _golden_cache:
            return _golden_cache[tile]
        small = rng.integers(0, 256, (2, N, 2 * tile), dtype=np.uint8)
        _golden_cache[tile] = (
            small, np.stack([gf256.gf_matmul(coeff, s) for s in small]))
        return _golden_cache[tile]

    def check(apply2d, name, tile):
        small, want = golden(tile)
        got = np.asarray(jax.vmap(apply2d)(jax.device_put(small)))
        okay = np.array_equal(got, want)
        if not okay:
            print(f"{name} tile={tile}: WRONG OUTPUT", file=sys.stderr)
        return okay

    def bench(chain):
        dt = timed_slope(chain, surv, k1=1, k2=9, repeats=2)
        return BR * N * S / dt / (1 << 30)

    results = []
    for tile in (8192, 16384, 32768, 65536, 131072):
        # base (shipped)
        try:
            chain = jax.jit(lambda a, _t=tile: jnp.tile(
                pallas_gf.gf_matrix_apply_pallas(rows, a, tile=_t),
                (1, reps, 1))[:, :N, :])
            results.append({"variant": "base", "tile": tile,
                            "gibs": round(bench(chain), 2)})
        except Exception as e:
            results.append({"variant": "base", "tile": tile,
                            "error": str(e)[:120]})
        # bitmajor int32 / uint8
        for u8 in (False, True):
            name = "bitmajor-u8" if u8 else "bitmajor"
            try:
                fn2d = bitmajor_fn(coeff.tobytes(), r, c, tile, u8)
                if not check(fn2d, name, tile):
                    results.append({"variant": name, "tile": tile,
                                    "error": "wrong output"})
                    continue
                chain = jax.jit(lambda a, _f=fn2d: jnp.tile(
                    jax.vmap(_f)(a), (1, reps, 1))[:, :N, :])
                results.append({"variant": name, "tile": tile,
                                "gibs": round(bench(chain), 2)})
            except Exception as e:
                results.append({"variant": name, "tile": tile,
                                "error": str(e)[:120]})
        # flatgrid
        try:
            fn3d = flatgrid_fn(coeff.tobytes(), r, c, tile)
            small, want = golden(tile)
            got = np.asarray(fn3d(jax.device_put(small)))
            if not np.array_equal(got, want):
                results.append({"variant": "flatgrid", "tile": tile,
                                "error": "wrong output"})
            else:
                chain = jax.jit(lambda a, _f=fn3d: jnp.tile(
                    _f(a), (1, reps, 1))[:, :N, :])
                results.append({"variant": "flatgrid", "tile": tile,
                                "gibs": round(bench(chain), 2)})
        except Exception as e:
            results.append({"variant": "flatgrid", "tile": tile,
                            "error": str(e)[:120]})
        print(json.dumps(results[-4:]), flush=True)

    best = max((x for x in results if "gibs" in x), key=lambda x: x["gibs"])
    print("BEST:", json.dumps(best))


if __name__ == "__main__":
    main()
