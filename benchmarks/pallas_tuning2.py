"""Round 2: squeeze the VPU bit-extraction in the bit-major kernel.

Round 1 found plane-major (bit-major) layout 4x's the shipped kernel
(65 vs 17 GiB/s): the per-byte interleave reshape was the bottleneck.
Remaining cost model: bit extraction is ~3 VPU ops/bit (shift, and,
astype-to-i8); variants here try to shave ops and check whether the
dot or the extraction dominates:

  bm-loop     — round-1 winner (8 separate shift/and, concatenate)
  bm-bcast    — one broadcast shift over (8,1,1) iota, one and, one
                astype, reshape (plane-major, no concat copy)
  bm-bool     — (x & mask) != 0 -> bool -> astype int8
  bm-flat     — bm-bcast with batch folded into the grid (no vmap)
  bm-nodot    — extraction only, dot replaced by a cheap slice: bounds
                how much of the time is extraction vs MXU
  bm-noext    — dot only, bits faked by a cheap cast: bounds the dot
"""

from __future__ import annotations

import functools
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from cubefs_tpu.models import repair
from cubefs_tpu.ops import bitlin, gf256
from cubefs_tpu.utils.benchtime import timed_slope
from cubefs_tpu.ops.bitlin import w_to_bitmajor

N, M, S, BR = 12, 4, 4 << 20, 4


def _extract(x, mode):
    n, t = x.shape
    if mode == "loop":
        planes = [((x.astype(jnp.int32) >> k) & 1).astype(jnp.int8)
                  for k in range(8)]
        return jnp.concatenate(planes, axis=0)
    if mode == "bcast":
        sh = jnp.arange(8, dtype=jnp.int32)[:, None, None]
        bits = (x[None].astype(jnp.int32) >> sh) & 1
        return bits.astype(jnp.int8).reshape(8 * n, t)
    if mode == "bool":
        mask = (1 << jnp.arange(8, dtype=jnp.int32))[:, None, None]
        bits = (x[None].astype(jnp.int32) & mask) != 0
        return bits.astype(jnp.int8).reshape(8 * n, t)
    raise ValueError(mode)


def _mk_kernel(mode, probe):
    def kernel(w_ref, x_ref, o_ref):
        x = x_ref[:] if x_ref.shape[0] != 1 or len(x_ref.shape) == 2 else x_ref[0]
        if len(x.shape) == 3:
            x = x[0]
        n, t = x.shape
        w = w_ref[:]
        m8 = w.shape[0]
        r = m8 // 8
        if probe == "nodot":
            bits = _extract(x, mode)
            # consume bits cheaply: strided slice + cast (keeps Mosaic
            # from DCE-ing the extraction)
            acc = bits[: 8 * r : 8, :].astype(jnp.int32)
            for k in range(1, 8):
                acc = acc | (bits[k : 8 * r : 8, :].astype(jnp.int32) << k)
            out = acc
        else:
            if probe == "noext":
                bits = jnp.broadcast_to(
                    x[:1].astype(jnp.int8), (8 * n, t))
            else:
                bits = _extract(x, mode)
            y = jax.lax.dot_general(
                w, bits, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32) & 1
            acc = y[0:r, :]
            for k in range(1, 8):
                acc = acc | (y[k * r : (k + 1) * r, :] << k)
            out = acc
        if len(o_ref.shape) == 3:
            o_ref[0] = out.astype(jnp.uint8)
        else:
            o_ref[:] = out.astype(jnp.uint8)

    return kernel


@functools.lru_cache(maxsize=None)
def make_fn(coeff_bytes, rows, cols, tile, mode, probe, flat):
    coeff = np.frombuffer(coeff_bytes, dtype=np.uint8).reshape(rows, cols)
    wb = jnp.asarray(
        w_to_bitmajor(bitlin.gf_matrix_to_bits(coeff), rows, cols),
        dtype=jnp.int8)
    kern = _mk_kernel(mode, probe)

    if flat:
        @jax.jit
        def apply(shards):  # (B, N, S)
            b, n, s = shards.shape
            return pl.pallas_call(
                kern,
                out_shape=jax.ShapeDtypeStruct((b, rows, s), jnp.uint8),
                grid=(b, s // tile),
                in_specs=[
                    pl.BlockSpec((8 * rows, 8 * cols), lambda i, j: (0, 0),
                                 memory_space=pltpu.VMEM),
                    pl.BlockSpec((1, n, tile), lambda i, j: (i, 0, j),
                                 memory_space=pltpu.VMEM),
                ],
                out_specs=pl.BlockSpec((1, rows, tile),
                                       lambda i, j: (i, 0, j),
                                       memory_space=pltpu.VMEM),
                compiler_params=pltpu.CompilerParams(
                    dimension_semantics=("parallel", "parallel")),
            )(wb, shards)
        return apply

    @jax.jit
    def apply2d(shards):  # (N, S)
        n, s = shards.shape
        return pl.pallas_call(
            kern,
            out_shape=jax.ShapeDtypeStruct((rows, s), jnp.uint8),
            grid=(s // tile,),
            in_specs=[
                pl.BlockSpec((8 * rows, 8 * cols), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((n, tile), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((rows, tile), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel",)),
        )(wb, shards)

    return jax.jit(lambda a: jax.vmap(apply2d)(a))


def main():
    rng = np.random.default_rng(5)
    plan = repair.make_plan(N, M, bad=[1, 7])
    coeff = np.ascontiguousarray(plan.rows, dtype=np.uint8)
    r, c = coeff.shape
    dev = jax.devices()[0]
    surv = jax.device_put(
        rng.integers(0, 256, (BR, N, S), dtype=np.uint8), dev)
    reps = -(-N // r)

    _golden_cache = {}

    def golden(tile):
        if tile in _golden_cache:
            return _golden_cache[tile]
        # golden sized to the tile under test: a fixed 32KiB golden is
        # SMALLER than the 64/128KiB tiles (grid=0, kernel never runs),
        # which silently skipped validation for 2/3 of the sweep
        small = rng.integers(0, 256, (2, N, 2 * tile), dtype=np.uint8)
        _golden_cache[tile] = (
            small, np.stack([gf256.gf_matmul(coeff, s) for s in small]))
        return _golden_cache[tile]

    cases = [
        ("bm-loop", "loop", None, False),
        ("bm-bcast", "bcast", None, False),
        ("bm-bool", "bool", None, False),
        ("bm-flat", "bcast", None, True),
        ("bm-nodot", "bcast", "nodot", False),
        ("bm-noext", "bcast", "noext", False),
    ]
    results = []
    for tile in (32768, 65536, 131072):
        for name, mode, probe, flat in cases:
            try:
                fn = make_fn(coeff.tobytes(), r, c, tile, mode, probe, flat)
                if probe is None:
                    small, want = golden(tile)
                    got = np.asarray(fn(jax.device_put(small)))
                    if not np.array_equal(got, want):
                        results.append({"v": name, "tile": tile,
                                        "error": "wrong output"})
                        continue
                chain = jax.jit(lambda a, _f=fn: jnp.tile(
                    _f(a), (1, reps, 1))[:, :N, :])
                dt = timed_slope(chain, surv, k1=2, k2=18, repeats=2)
                results.append({"v": name, "tile": tile,
                                "gibs": round(BR * N * S / dt / (1 << 30), 2)})
            except Exception as e:
                results.append({"v": name, "tile": tile,
                                "error": str(e)[:100]})
        print(json.dumps(results[-len(cases):]), flush=True)

    best = max((x for x in results if "gibs" in x and "no" not in x["v"]),
               key=lambda x: x["gibs"])
    print("BEST:", json.dumps(best))


if __name__ == "__main__":
    main()
