"""Repo tooling (linters, watchers). Not part of the cubefs_tpu runtime."""
