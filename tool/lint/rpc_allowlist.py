"""Explicit allowlist for the rpc-idempotency checker (CFR001).

Every entry asserts that a transport-level retry of the named mutating
RPC is harmless WITHOUT an op_id, and says why. Keys are
``(repo-relative caller path, method)``; the path ``"*"`` means the
SERVER-side contract itself is idempotent, independent of who calls it.
An empty justification is itself a violation (CFR002) — the point of
the list is the recorded reasoning, not the exemption.

Accepted justification families (cite one):
  * absolute-value write — the op sets state to a given value
    (set_*, kv_set); applying it twice lands on the same state.
  * keyed / natural idempotency — the op is keyed by a caller-chosen
    id (pid, dp_id, name, task_id); the server treats a duplicate as
    get-or-refresh, or rejects it without re-allocating.
  * server-side guard — the server deduplicates through other state
    (lease expiry, snapshot re-check), so the duplicate is absorbed.

Anything that MINTS an id or appends to a sequence does NOT belong
here — thread an op_id instead (see utils/fsm.py _apply_deduped and
fs/metanode.py MetaPartition.apply for the server-side dedup doors).
"""

ALLOWLIST: dict[tuple[str, str], str] = {
    # ---- keyed by caller-chosen id: duplicate = get-or-refresh ----
    ("*", "create_partition"):
        "keyed by caller-chosen pid/dp_id; meta/datanodes treat a "
        "duplicate create of a known partition as get-or-refresh "
        "(fs/metanode.py create_partition, fs/datanode.py "
        "create_partition)",
    ("*", "drop_partition"):
        "idempotent delete by pid/dp_id; dropping an already-dropped "
        "partition is a no-op",
    ("*", "create_shard"):
        "keyed by caller-chosen shard id; duplicate create is "
        "get-or-refresh on the shardnode",
    ("*", "put_shard"):
        "keyed by (vid, bid, shard index) with absolute bytes; a "
        "retry overwrites the identical payload",
    ("*", "delete_shard"):
        "idempotent delete by (vid, bid)",
    ("*", "put"):
        "blob put is keyed by an allocated (vid, bid) location with "
        "absolute bytes; a retry rewrites the same shards",
    ("*", "delete"):
        "idempotent delete by location/key",
    ("*", "delete_extent"):
        "idempotent delete by (dp_id, extent_id)",
    ("*", "write_replica"):
        "chain-replication leg keyed by (dp_id, extent_id, offset) "
        "with absolute bytes; a retry rewrites the same range",
    ("*", "update_shard_peers"):
        "absolute-value write of the peer set (epoch-guarded on the "
        "shardnode); last write wins either way",
    ("*", "create_volume"):
        "name-keyed; the master rejects a duplicate name "
        "(MasterError 'exists') instead of allocating a second volume",
    ("*", "create_user"):
        "user-id-keyed; duplicate create returns/conflicts on the "
        "existing user, never mints a second identity",
    ("*", "delete_user"):
        "idempotent delete by user id",
    ("*", "register_group"):
        "name-keyed registry upsert",
    ("*", "remove_group"):
        "idempotent delete by group name",

    # ---- absolute-value writes: replay lands on the same state ----
    ("*", "set_vol_capacity"): "absolute-value write (capacity)",
    ("*", "set_quota"): "absolute-value write (quota record)",
    ("*", "delete_quota"): "idempotent delete by quota id",
    ("*", "set_disk_status"): "absolute-value write (disk status enum)",
    ("*", "set_config"): "absolute-value write (config key)",
    ("*", "delete_config"): "idempotent delete by config key",
    ("*", "kv_set"): "absolute-value write (kv key)",
    ("*", "kv_delete"): "idempotent delete by kv key",
    ("*", "set_group_status"): "absolute-value write (group status)",
    ("*", "set_enforcement"):
        "absolute-value push of the advisory enforcement flag set; "
        "recomputed by every quota sweep anyway",
    ("*", "enforce_quotas"):
        "triggers a recompute from current usage — rerunning it "
        "reaches the same flags",
    ("*", "invalidate"):
        "cache invalidation; invalidating an already-dropped entry "
        "is a no-op",

    # ---- sticky state transitions ----
    ("*", "decommission_datanode"):
        "sticky transition: decommissioning an already-decommissioned "
        "node is a no-op",
    ("*", "offline_disk"): "sticky transition: offline is absorbing",
    ("*", "mark_disk_broken"): "sticky transition: broken is absorbing",
    ("*", "split_meta_partition"):
        "snapshot-guarded: the split re-checks after_end under "
        "_propose_lock and returns None if someone (incl. a retry's "
        "first send) already split past it",

    # ---- geo-replication stream (fs/georepl.py) ----
    ("*", "geo_ship"):
        "sequence-numbered stream records: the GeoApplier skips every "
        "record with seq <= applied_seq, so a transport retry "
        "re-presenting a shipped batch is absorbed as duplicates "
        "(utils/georepl.py GeoApplier.deliver)",
    ("*", "geo_resync"):
        "convergent by contract: the bootstrap pull lands the "
        "primary's CURRENT snapshot with its atomic (state, seq, "
        "epoch) triple — replaying the transfer re-lands the same or "
        "a newer consistent image, never a fork",

    # ---- server-side guards ----
    ("*", "register"):
        "addr-keyed registry refresh (master/scheduler register): a "
        "re-register updates the same node record",
    ("*", "register_service"):
        "name+addr-keyed: the addr appends only if absent",
    ("*", "acquire_task"):
        "lease-based: a duplicate acquisition leases a second task "
        "whose lease expires and requeues (scheduler LEASE_SECONDS); "
        "no task is lost or double-completed",
    ("*", "renew_task"): "task-id-keyed lease refresh",
    ("*", "complete_task"):
        "task-id-keyed terminal transition; completing a completed "
        "task is a no-op",

    # ---- per-caller entries ----
    ("cubefs_tpu/fs/client.py", "submit"):
        "MetaWrapper._call_wire setdefaults a uuid op_id into every "
        "submit record before the replica loop (fs/client.py "
        "_call_wire); the call sites just don't spell the token",
    ("cubefs_tpu/fs/client.py", "submit_batch"):
        "MetaWrapper._call_wire stamps a uuid op_id into every batch "
        "record before the replica loop, so a transport retry "
        "re-presents the same ids to the FSM dedup window",
    ("cubefs_tpu/sdk/clients.py", "submit"):
        "MetaNodeClient.submit setdefaults a uuid op_id into the "
        "record in its own body before dialing; retries re-present it",
    ("cubefs_tpu/sdk/clients.py", "submit_batch"):
        "MetaNodeClient.submit_batch setdefaults a uuid op_id into "
        "every record in its own body before dialing",
    ("cubefs_tpu/tool/bench_fs.py", "submit"):
        "scale-bench control-leg records carry deterministic op_ids "
        "stamped by _rec ('sc<thread>-<i>'); a retry dedups in the FSM",
    ("cubefs_tpu/blob/access.py", "alloc"):
        "the proxy serves alloc from locally leased volume/bid ranges "
        "(blob/proxy.py); a duplicate burns leased ids only — the "
        "clustermgr-facing lease refills themselves carry op_ids",
}
