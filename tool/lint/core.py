"""Core infrastructure for the cubefs-tpu lint suite.

One `Module` per source file (AST + source lines + import alias map),
a `Checker` interface, inline suppressions, and the baseline store.

Inline suppression: append ``# lint: allow[CODE] <justification>`` to
the flagged line (or the line directly above it). The justification is
MANDATORY — a bare ``allow[...]`` does not suppress and is itself
reported (CFA001), so every intentional violation carries its why.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re

# tool/lint/core.py -> repo root
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_SKIP_DIRS = {".git", "__pycache__", "artifacts", "node_modules", ".claude",
              "fixtures"}  # tests/fixtures/lint holds INTENTIONAL violations

_ALLOW_RE = re.compile(
    r"#\s*lint:\s*allow\[(?P<codes>[A-Za-z0-9_,\s-]+)\]\s*(?P<why>.*?)\s*$"
)


@dataclasses.dataclass(frozen=True)
class Violation:
    code: str  # e.g. "CFL003"
    rule: str  # checker family, e.g. "lock-discipline"
    path: str  # repo-relative posix path
    line: int
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.code}:{self.path}:{self.line}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} [{self.rule}] {self.message}"


class Module:
    """A parsed source file as handed to checkers."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        # alias -> full module name, for "import time as _t" resolution
        self.import_aliases: dict[str, str] = {}
        # name -> "module.name" for "from time import sleep [as s]"
        self.from_imports: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = f"{node.module}.{a.name}"

    # ---- suppression ----
    def allow_at(self, line: int) -> dict[str, str] | None:
        """{code_or_rule: justification} if the line (or the one above)
        carries a lint: allow[...] comment with a justification."""
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                m = _ALLOW_RE.search(self.lines[ln - 1])
                if m:
                    why = m.group("why").strip()
                    codes = [c.strip() for c in m.group("codes").split(",")]
                    return {c: why for c in codes if c}
        return None

    def suppressed(self, v: Violation) -> bool:
        allow = self.allow_at(v.line)
        if not allow:
            return False
        for key, why in allow.items():
            if key in (v.code, v.rule, "*") and why:
                return True
        return False

    def segment(self, node: ast.AST) -> str:
        # Slice from the cached line list instead of
        # ast.get_source_segment, which re-splits the whole source on
        # every call (it dominated lint wall time at ~60%). Form feeds
        # make str.splitlines disagree with ast line numbers, so those
        # rare files take the slow path.
        if "\f" in self.source:
            return ast.get_source_segment(self.source, node) or ""
        try:
            lo, hi = node.lineno, node.end_lineno
            if hi == lo:
                return self.lines[lo - 1][node.col_offset:node.end_col_offset]
            parts = [self.lines[lo - 1][node.col_offset:]]
            parts.extend(self.lines[lo:hi - 1])
            parts.append(self.lines[hi - 1][:node.end_col_offset])
            return "\n".join(parts)
        except (AttributeError, IndexError, TypeError):
            return ast.get_source_segment(self.source, node) or ""


class Checker:
    """One checker family. Subclasses set `rule`, `dirs` (repo-relative
    prefixes the checker applies to) and implement `check(module)`."""

    rule = "base"
    dirs: tuple[str, ...] = ()

    def applies(self, relpath: str) -> bool:
        return any(relpath.startswith(d) for d in self.dirs)

    def check(self, mod: Module) -> list[Violation]:  # pragma: no cover
        raise NotImplementedError

    def violation(self, mod: Module, code: str, node_or_line,
                  message: str) -> Violation:
        line = (node_or_line if isinstance(node_or_line, int)
                else node_or_line.lineno)
        return Violation(code, self.rule, mod.relpath, line, message)


def iter_py_files(roots: list[str]) -> list[str]:
    """Repo-relative paths of every .py under the given roots."""
    out: list[str] = []
    for root in roots:
        absroot = os.path.join(REPO_ROOT, root)
        if os.path.isfile(absroot):
            if absroot.endswith(".py"):
                out.append(os.path.relpath(absroot, REPO_ROOT))
            continue
        for dirpath, dirnames, filenames in os.walk(absroot):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, fn), REPO_ROOT))
    return sorted(set(p.replace(os.sep, "/") for p in out))


def bare_allow_violations(mod: Module) -> list[Violation]:
    """CFA001: an allow[...] comment with no justification — it does NOT
    suppress anything, and silently believing it does is worse.
    (Renamed from CFG001 when the geo-discipline family claimed the CFG
    prefix; the baseline carries no fingerprints under either code.)"""
    out = []
    for i, text in enumerate(mod.lines, start=1):
        m = _ALLOW_RE.search(text)
        if m and not m.group("why").strip():
            out.append(Violation(
                "CFA001", "lint-config", mod.relpath, i,
                "allow[...] suppression without a justification "
                "(write `# lint: allow[CODE] <why>`)"))
    return out


# ---------------- baseline ----------------

def baseline_path() -> str:
    return os.path.join(REPO_ROOT, "tool", "lint", "baseline.json")


def load_baseline(path: str | None = None) -> dict[str, int]:
    """fingerprint -> allowed count (a multiset: two identical findings
    on one line baseline independently)."""
    path = path or baseline_path()
    if not os.path.exists(path):
        return {}
    data = json.load(open(path))
    counts: dict[str, int] = {}
    for fp in data.get("violations", []):
        counts[fp] = counts.get(fp, 0) + 1
    return counts


def save_baseline(violations: list[Violation], path: str | None = None) -> None:
    """Entries are sorted by (path, code, line) — NOT lexically on the
    fingerprint string, where line numbers sort as text ("12" < "3") and
    a one-line shift reshuffles the whole file's block. Deterministic
    positional order keeps baseline diffs minimal and reviewable."""
    path = path or baseline_path()
    payload = {
        "comment": "Pre-existing lint findings recorded, not blocking. "
                   "Regenerate with: python -m tool.lint --update-baseline",
        "violations": [v.fingerprint for v in sorted(
            violations, key=lambda v: (v.path, v.code, v.line))],
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


def apply_baseline(violations: list[Violation],
                   baseline: dict[str, int]) -> list[Violation]:
    """Violations not covered by the baseline multiset."""
    budget = dict(baseline)
    fresh = []
    for v in violations:
        if budget.get(v.fingerprint, 0) > 0:
            budget[v.fingerprint] -= 1
        else:
            fresh.append(v)
    return fresh
