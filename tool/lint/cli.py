"""cubefs-tpu-lint CLI: run the repo's checker families over the tree.

Usage:
  python -m tool.lint [paths...]     lint (default roots: cubefs_tpu/,
                                     tests/, tool/), baseline applied
  python -m tool.lint --no-baseline  strict mode: report EVERYTHING
  python -m tool.lint --update-baseline
                                     re-record current findings as the
                                     accepted baseline (entries sorted
                                     by path, code, line)
  python -m tool.lint --select CFL101,fsm-purity
                                     only the named codes/rules
  python -m tool.lint --report json  machine-readable report (findings,
                                     lock-order graph edges + cycles,
                                     suppression counts) written to
                                     artifacts/LINT_REPORT_r16.json
  python -m tool.lint --no-cache     skip the per-module summary cache

Exit status: 0 = no non-baselined violations, 1 = findings, 2 = a file
failed to parse (always fatal: an unparseable file is unlinted code).

The run is ONE parse pass: every file is parsed once into a
core.Module, the per-module (lexical) checkers consume it directly, and
the same objects feed the interprocedural engine (tool/lint/graph.py)
that backs the project-wide families (lock-graph CFL1xx, fsm-purity
CFM). Engine summaries are cached under tool/lint/.cache/ keyed by
content hash, so re-runs skip re-extraction for unchanged files.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from . import core, graph as graphlib
from .checkers import ALL_CHECKERS, PROJECT_CHECKERS

DEFAULT_ROOTS = ("cubefs_tpu", "tests", "tool")

# The engine only models the package itself — tests and tooling are not
# part of the concurrency/determinism surface the graph families check.
GRAPH_PREFIX = "cubefs_tpu/"


def _parse_modules(relpaths: list[str]) -> tuple[dict, list[str]]:
    """relpath -> core.Module for every parseable file, + error strings.
    Reading+parsing fans out across threads (I/O overlaps; parse itself
    is GIL-bound but cheap next to checking)."""
    import concurrent.futures

    def load(relpath):
        try:
            with open(os.path.join(core.REPO_ROOT, relpath),
                      encoding="utf-8") as f:
                source = f.read()
            return relpath, core.Module(relpath, source), None
        except (SyntaxError, UnicodeDecodeError) as e:
            return relpath, None, f"{relpath}: {type(e).__name__}: {e}"

    modules: dict[str, core.Module] = {}
    errors: list[str] = []
    if len(relpaths) > 4:
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, (os.cpu_count() or 2))) as pool:
            results = list(pool.map(load, relpaths))
    else:
        results = [load(p) for p in relpaths]
    for relpath, mod, err in results:
        if err is not None:
            errors.append(err)
        else:
            modules[relpath] = mod
    return modules, errors


def run_lint(paths: list[str] | None = None,
             select: set[str] | None = None,
             use_cache: bool = True,
             collect_graph: bool = False):
    """(violations after inline suppressions, parse-error strings).
    With collect_graph=True returns (violations, errors, stats) where
    stats carries the engine graph + suppression counts for --report."""
    checkers = [cls() for cls in ALL_CHECKERS]
    project_checkers = [cls() for cls in PROJECT_CHECKERS]
    relpaths = core.iter_py_files(list(paths or DEFAULT_ROOTS))
    modules, errors = _parse_modules(relpaths)

    violations: list[core.Violation] = []
    suppressed_count = 0
    for relpath in sorted(modules):
        mod = modules[relpath]
        found: list[core.Violation] = []
        for checker in checkers:
            if checker.applies(relpath):
                found.extend(checker.check(mod))
        found.extend(core.bare_allow_violations(mod))
        for v in found:
            if mod.suppressed(v):
                suppressed_count += 1
            else:
                violations.append(v)

    # ---- whole-program pass ----
    # The engine wants the full package picture even when the user lints
    # a single file, so graph modules are loaded independently of the
    # requested paths (summary cache keeps this cheap).
    graph_stats: dict = {}
    g = None
    if project_checkers:
        graph_modules = {p: m for p, m in modules.items()
                         if p.startswith(GRAPH_PREFIX)}
        missing = [p for p in core.iter_py_files([GRAPH_PREFIX.rstrip("/")])
                   if p not in graph_modules]
        if missing:
            extra, extra_errs = _parse_modules(missing)
            graph_modules.update(extra)
            errors.extend(extra_errs)
        t0 = time.perf_counter()
        g = graphlib.ProjectGraph.build(
            graph_modules,
            cache_dir=graphlib.default_cache_dir() if use_cache else None)
        graph_stats["graph_build_seconds"] = round(
            time.perf_counter() - t0, 4)
        graph_stats["functions"] = len(g.funcs)
        only_requested = {p for p in modules}
        for checker in project_checkers:
            for v in checker.check_project(g, graph_modules):
                if v.path not in only_requested:
                    continue  # user linted specific paths: stay scoped
                mod = graph_modules.get(v.path) or modules.get(v.path)
                if mod is not None and mod.suppressed(v):
                    suppressed_count += 1
                else:
                    violations.append(v)

    if select:
        violations = [v for v in violations
                      if v.code in select or v.rule in select]
    violations.sort(key=lambda v: (v.path, v.line, v.code))
    if collect_graph:
        graph_stats["inline_suppressions_honored"] = suppressed_count
        graph_stats["graph"] = g
        return violations, errors, graph_stats
    return violations, errors


def write_report(path: str, violations, fresh, errors, stats) -> None:
    g = stats.get("graph")
    payload = {
        "generated_by": "python -m tool.lint --report json",
        "findings": [
            {"code": v.code, "rule": v.rule, "path": v.path,
             "line": v.line, "message": v.message,
             "baselined": v not in fresh}
            for v in violations],
        "counts": {
            "total": len(violations),
            "fresh": len(fresh),
            "baselined": len(violations) - len(fresh),
            "inline_suppressions_honored":
                stats.get("inline_suppressions_honored", 0),
            "parse_errors": len(errors),
        },
        "lock_order_graph": {
            "edges": g.edges_json() if g is not None else [],
            "cycles": [
                [{"src": e.src, "dst": e.dst,
                  "at": f"{e.relpath}:{e.line}"} for e in cyc]
                for cyc in (g.lock_cycles() if g is not None else [])],
        },
        "engine": {
            "functions": stats.get("functions", 0),
            "graph_build_seconds": stats.get("graph_build_seconds"),
        },
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="cubefs-tpu-lint",
        description="repo-specific static analysis "
                    "(tracer-safety, lock-discipline + interprocedural "
                    "lock-graph, fsm-purity, rpc-idempotency, "
                    "retry-discipline, tier1-purity, witness-discipline)")
    p.add_argument("paths", nargs="*", help="files/dirs to lint "
                   f"(default: {', '.join(DEFAULT_ROOTS)})")
    p.add_argument("--no-baseline", action="store_true",
                   help="strict mode: ignore baseline.json")
    p.add_argument("--update-baseline", action="store_true",
                   help="record current findings as the new baseline")
    p.add_argument("--baseline", default=None,
                   help="alternate baseline file path")
    p.add_argument("--select", default=None,
                   help="comma-separated codes/rules to report")
    p.add_argument("--report", choices=("json",), default=None,
                   help="also write a machine-readable report")
    p.add_argument("--report-path",
                   default=os.path.join(core.REPO_ROOT, "artifacts",
                                        "LINT_REPORT_r16.json"),
                   help="where --report json writes")
    p.add_argument("--no-cache", action="store_true",
                   help="bypass the per-module summary cache")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the per-violation listing")
    args = p.parse_args(argv)

    select = (set(s.strip() for s in args.select.split(",") if s.strip())
              if args.select else None)
    violations, errors, stats = run_lint(
        args.paths or None, select, use_cache=not args.no_cache,
        collect_graph=True)

    for err in errors:
        print(f"PARSE ERROR {err}", file=sys.stderr)

    if args.update_baseline:
        core.save_baseline(violations, args.baseline)
        print(f"baseline updated: {len(violations)} finding(s) recorded")
        return 2 if errors else 0

    if args.no_baseline:
        fresh = violations
    else:
        fresh = core.apply_baseline(
            violations, core.load_baseline(args.baseline))

    if args.report == "json":
        write_report(args.report_path, violations, fresh, errors, stats)
        if not args.quiet:
            print(f"report written: {args.report_path}")

    if not args.quiet:
        for v in fresh:
            print(v.render())
    baselined = len(violations) - len(fresh)
    tail = f" ({baselined} baselined)" if baselined else ""
    print(f"cubefs-tpu-lint: {len(fresh)} finding(s){tail}")
    if errors:
        return 2
    return 1 if fresh else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
