"""cubefs-tpu-lint CLI: run the repo's checker families over the tree.

Usage:
  python -m tool.lint [paths...]     lint (default roots: cubefs_tpu/,
                                     tests/, tool/), baseline applied
  python -m tool.lint --no-baseline  strict mode: report EVERYTHING
  python -m tool.lint --update-baseline
                                     re-record current findings as the
                                     accepted baseline
  python -m tool.lint --select CFL001,rpc-idempotency
                                     only the named codes/rules

Exit status: 0 = no non-baselined violations, 1 = findings, 2 = a file
failed to parse (always fatal: an unparseable file is unlinted code).
"""

from __future__ import annotations

import argparse
import os
import sys

from . import core
from .checkers import ALL_CHECKERS

DEFAULT_ROOTS = ("cubefs_tpu", "tests", "tool")


def run_lint(paths: list[str] | None = None,
             select: set[str] | None = None
             ) -> tuple[list[core.Violation], list[str]]:
    """(violations after inline suppressions, parse-error strings)."""
    checkers = [cls() for cls in ALL_CHECKERS]
    violations: list[core.Violation] = []
    errors: list[str] = []
    for relpath in core.iter_py_files(list(paths or DEFAULT_ROOTS)):
        try:
            with open(os.path.join(core.REPO_ROOT, relpath),
                      encoding="utf-8") as f:
                source = f.read()
            mod = core.Module(relpath, source)
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append(f"{relpath}: {type(e).__name__}: {e}")
            continue
        found: list[core.Violation] = []
        for checker in checkers:
            if checker.applies(relpath):
                found.extend(checker.check(mod))
        found.extend(core.bare_allow_violations(mod))
        violations.extend(v for v in found if not mod.suppressed(v))
    if select:
        violations = [v for v in violations
                      if v.code in select or v.rule in select]
    violations.sort(key=lambda v: (v.path, v.line, v.code))
    return violations, errors


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="cubefs-tpu-lint",
        description="repo-specific static analysis "
                    "(tracer-safety, lock-discipline, rpc-idempotency, "
                    "retry-discipline, tier1-purity)")
    p.add_argument("paths", nargs="*", help="files/dirs to lint "
                   f"(default: {', '.join(DEFAULT_ROOTS)})")
    p.add_argument("--no-baseline", action="store_true",
                   help="strict mode: ignore baseline.json")
    p.add_argument("--update-baseline", action="store_true",
                   help="record current findings as the new baseline")
    p.add_argument("--baseline", default=None,
                   help="alternate baseline file path")
    p.add_argument("--select", default=None,
                   help="comma-separated codes/rules to report")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the per-violation listing")
    args = p.parse_args(argv)

    select = (set(s.strip() for s in args.select.split(",") if s.strip())
              if args.select else None)
    violations, errors = run_lint(args.paths or None, select)

    for err in errors:
        print(f"PARSE ERROR {err}", file=sys.stderr)

    if args.update_baseline:
        core.save_baseline(violations, args.baseline)
        print(f"baseline updated: {len(violations)} finding(s) recorded")
        return 2 if errors else 0

    if args.no_baseline:
        fresh = violations
    else:
        fresh = core.apply_baseline(
            violations, core.load_baseline(args.baseline))

    if not args.quiet:
        for v in fresh:
            print(v.render())
    baselined = len(violations) - len(fresh)
    tail = f" ({baselined} baselined)" if baselined else ""
    print(f"cubefs-tpu-lint: {len(fresh)} finding(s){tail}")
    if errors:
        return 2
    return 1 if fresh else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
