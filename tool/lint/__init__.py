"""cubefs-lint: repo-specific static analysis for cubefs-tpu.

Four checker families, each encoding an invariant this codebase has
already shipped (and fixed) a bug against:

  tracer-safety    Python coercions / host syncs inside jit- or
                   Pallas-traced functions (ops/, codec/, parallel/)
  lock-discipline  blocking or native-plane (ctypes) calls made while
                   lexically holding a threading lock (fs/, blob/,
                   parallel/) — the raft-heartbeat regression shape
  rpc-idempotency  mutating rpc.call() sites must thread an op_id or
                   carry an allowlisted justification (the transport
                   retries stale-connection failures)
  tier1-purity     non-slow tests must not compile the native runtime
                   or touch TPU clients at collection time

Run `python -m tool.lint --help`; see tool/lint/README.md.
"""

from .cli import main, run_lint  # noqa: F401
from .core import Violation  # noqa: F401
