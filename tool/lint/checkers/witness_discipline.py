"""Lock-witness discipline (rule: witness-discipline, codes CFS00x).

The runtime half of the concurrency sanitizer
(`cubefs_tpu/utils/lockwitness.py`) can only watch locks that were
allocated through its factories — `make_lock(name)` / `make_rlock(name)`
return plain `threading.Lock`/`RLock` objects when `CUBEFS_SANITIZE` is
off (zero overhead) and witness-wrapped ones when it's on. A raw
`threading.Lock()` allocation in the concurrent planes is a blind spot:
every chaos drill would silently skip it in the dynamic deadlock hunt.

  CFS001  raw threading.Lock()/RLock() allocation in fs/ blob/
          parallel/ (or utils/fsm.py) — route it through
          utils/lockwitness.make_lock("Class.attr") so CUBEFS_SANITIZE
          runs witness it; the name should match the static lock-order
          graph's node (`Class.attr`)

`threading.Condition(existing_lock)` is fine — the witness wrapper
implements the Condition protocol. A bare `threading.Condition()`
allocates its own invisible RLock; pass it a witnessed lock.
"""

from __future__ import annotations

import ast

from ..core import Checker, Module, Violation

_EXEMPT = ("cubefs_tpu/utils/lockwitness.py",)


class WitnessDisciplineChecker(Checker):
    rule = "witness-discipline"
    dirs = ("cubefs_tpu/fs/", "cubefs_tpu/blob/", "cubefs_tpu/parallel/",
            "cubefs_tpu/utils/fsm.py")

    def applies(self, relpath: str) -> bool:
        return super().applies(relpath) and relpath not in _EXEMPT

    def check(self, mod: Module) -> list[Violation]:
        threading_aliases = {a for a, full in mod.import_aliases.items()
                             if full == "threading"} | {"threading"}
        ctor_names = {alias for alias, full in mod.from_imports.items()
                      if full in ("threading.Lock", "threading.RLock")}
        out: list[Violation] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            kind = None
            if isinstance(func, ast.Attribute) and func.attr in (
                    "Lock", "RLock"):
                if isinstance(func.value, ast.Name) and \
                        func.value.id in threading_aliases:
                    kind = func.attr
            elif isinstance(func, ast.Name) and func.id in ctor_names:
                kind = mod.from_imports[func.id].rsplit(".", 1)[-1]
            if kind is None:
                continue
            factory = "make_rlock" if kind == "RLock" else "make_lock"
            out.append(self.violation(
                mod, "CFS001", node,
                f"raw threading.{kind}() is invisible to the lock "
                f"witness — allocate via lockwitness.{factory}"
                f"(\"Class.attr\") so CUBEFS_SANITIZE=1 runs watch it"))
        return out
