"""Fs-plane placement + cache-population checker (rule: fs-placement).

PR 11 ported failure-domain scoring to the fs master: fs/topology.py is
now the single authority for "which datanode/metanode takes this
replica" (``select_hosts`` / ``pick_destination`` / ``order_by_load``),
exactly as blob/topology.py is for the blob plane (CFZ001). The same
regression shape applies — an ad-hoc ``min(cands, key=lambda a:
load.get(a, 0))`` dropped into the fs plane is load-balanced and
AZ-blind:

  CFZ002  sorted()/min()/max()/.sort() over a load map in
          cubefs_tpu/fs/ outside fs/topology.py

The hot-read tier has a companion fence: CachedReader._populate is the
ONE place that admits bytes into the flash ring (it owns hotness
admission, breaker accounting, and the fill-outcome counters). A stray
``cache_put`` anywhere else bypasses admission and poisons the
AZ-copy invalidation contract:

  CFZ003  `.cache_put(...)` (or `.call("cache_put", ...)`) outside
          fs/remotecache.py and sdk/clients.py (the thin rpc wrapper)

Both analyses are syntactic. CFZ002 matches a load-map token
(``load`` / ``dp_load`` / ``meta_load`` / ``intra_load``) inside the
call's source segment — ``payload``, ``json.loads`` and friends do not
match.
"""

from __future__ import annotations

import ast
import re

from ..core import Checker, Module, Violation

_LOAD_TOKEN = re.compile(
    r"(?<![A-Za-z0-9_])(?:dp_|meta_|intra_)?load(?![A-Za-z0-9_])")
_CFZ002_SCOPE = "cubefs_tpu/fs/"
_CFZ002_EXEMPT = ("cubefs_tpu/fs/topology.py",)
_CFZ003_SANCTIONED = ("cubefs_tpu/fs/remotecache.py",
                      "cubefs_tpu/sdk/clients.py")


class FsPlacementChecker(Checker):
    rule = "fs-placement"
    dirs = ("cubefs_tpu/",)

    def check(self, mod: Module) -> list[Violation]:
        out: list[Violation] = []
        sort_scoped = (mod.relpath.startswith(_CFZ002_SCOPE)
                       and mod.relpath not in _CFZ002_EXEMPT)
        put_scoped = mod.relpath not in _CFZ003_SANCTIONED
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if sort_scoped:
                what = None
                if isinstance(func, ast.Name) and func.id in (
                        "sorted", "min", "max"):
                    what = f"{func.id}()"
                elif isinstance(func, ast.Attribute) and func.attr == "sort":
                    what = ".sort()"
                if what is not None and _LOAD_TOKEN.search(mod.segment(node)):
                    out.append(self.violation(
                        mod, "CFZ002", node,
                        f"{what} over a load map outside fs/topology.py "
                        f"— route the selection through "
                        f"topology.select_hosts / pick_destination / "
                        f"order_by_load so AZ and rack constraints "
                        f"apply"))
                    continue
            if not put_scoped:
                continue
            if isinstance(func, ast.Attribute) and func.attr == "cache_put":
                out.append(self.violation(
                    mod, "CFZ003", node,
                    "direct cache_put outside fs/remotecache.py — flash "
                    "population must go through CachedReader._populate "
                    "(hotness admission + fill accounting + the "
                    "per-AZ invalidation contract)"))
            elif (isinstance(func, ast.Attribute) and func.attr == "call"
                  and node.args
                  and isinstance(node.args[0], ast.Constant)
                  and node.args[0].value == "cache_put"):
                out.append(self.violation(
                    mod, "CFZ003", node,
                    'raw .call("cache_put", ...) outside '
                    "fs/remotecache.py — flash population must go "
                    "through CachedReader._populate"))
        return out
