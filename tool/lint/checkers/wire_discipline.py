"""Wire-plane discipline (rule: wire-discipline, codes CFX00x).

The binary packet plane multiplexes many streams over one persistent
connection per peer (utils/packet.py). That property only holds if
connections are actually SHARED: a stray `PacketClient(...)` spawns a
private socket + reader thread per call site, silently splitting the
mux and defeating the windowed pipelining the fs client and sdk tune
around. Frame assembly has the same trap in the other direction — the
transport ships scatter-gather buffer lists through sendmsg, so a
`sock.sendall(a + b)` that coalesces by concatenation reintroduces the
copy the wire layer exists to avoid.

  CFX001  `PacketClient(...)` constructed outside the wire sanctums
          (utils/packet.py itself, the fs client plumbing, the sdk's
          WireClient) — route it through sdk.WireClient, or the fs
          client's per-plane cache, so connections stay shared and
          accounted
  CFX002  `.sendall(a + b)` — a concatenated send copies the payload
          to glue a header on; build a buffer list and use the
          transport's scatter-gather path (packet._sendmsg_all)
"""

from __future__ import annotations

import ast

from ..core import Checker, Module, Violation

_SANCTUMS = (
    "cubefs_tpu/utils/packet.py",
    "cubefs_tpu/fs/client.py",
    "cubefs_tpu/sdk/clients.py",
)


class WireDisciplineChecker(Checker):
    rule = "wire-discipline"
    dirs = ("cubefs_tpu/",)

    def applies(self, relpath: str) -> bool:
        return super().applies(relpath) and relpath not in _SANCTUMS

    def check(self, mod: Module) -> list[Violation]:
        # names bound to the packet module: `import ...utils.packet
        # [as pkt]` or `from ..utils import packet [as pkt]`
        pkt_aliases = {a for a, full in mod.import_aliases.items()
                       if full == "packet" or full.endswith("utils.packet")}
        pkt_aliases |= {a for a, full in mod.from_imports.items()
                        if full == "utils.packet"
                        or full.endswith(".utils.packet")}
        # names bound to the class itself: `from ...packet import
        # PacketClient [as PC]`
        ctor_names = {a for a, full in mod.from_imports.items()
                      if full.endswith("packet.PacketClient")}
        out: list[Violation] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            hit = False
            if isinstance(func, ast.Attribute) and \
                    func.attr == "PacketClient":
                if isinstance(func.value, ast.Name) and \
                        func.value.id in pkt_aliases:
                    hit = True
            elif isinstance(func, ast.Name) and func.id in ctor_names:
                hit = True
            if hit:
                out.append(self.violation(
                    mod, "CFX001", node,
                    "PacketClient() outside the wire sanctums spawns a "
                    "private connection + reader thread per call site — "
                    "go through sdk.WireClient (or the fs client's "
                    "per-plane cache) so the mux stays shared"))
                continue
            if isinstance(func, ast.Attribute) and func.attr == "sendall" \
                    and node.args and isinstance(node.args[0], ast.BinOp) \
                    and isinstance(node.args[0].op, ast.Add):
                out.append(self.violation(
                    mod, "CFX002", node,
                    "sendall(a + b) copies the payload to glue buffers "
                    "together — pass a buffer list through the "
                    "transport's scatter-gather send instead"))
        return out
