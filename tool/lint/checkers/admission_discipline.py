"""QoS admission-discipline checker (rule: admission-discipline, CFQ0xx).

Overload protection only works if every external-facing request
handler passes through the QoS gate (utils/qos.py): one handler that
skips admission is an unshaped side door an abusive tenant will find,
and its traffic is invisible to the per-tenant counters and the
burn-rate brownout logic. The two front doors are the objectnode/S3
verb handlers (`do_*`) and the blob access RPC surface (`rpc_*`).

  CFQ001  an external-facing handler whose body never reaches the
          admission layer — objectnode `do_*` must call `_begin()` /
          `_admit_qos()` (the per-request auth+admission door), access
          `rpc_*` must call `.admit(` or route through the admitted
          public methods (`self.put` / `self.get` / `self.delete`)
  CFQ002  `.admit(` called outside the sanctioned door functions —
          each front door has ONE admission choke point; a second
          admit in a helper double-counts the inflight slot and can
          deadlock the queue-depth bound

Health/metrics-style endpoints are allowlisted (`do_OPTIONS` CORS
preflight, `rpc_health` / `rpc_stats` / `rpc_metrics`): shedding a
probe would flap monitors exactly when the operator needs them.

The analysis is syntactic (call names inside the handler body), like
the other discipline families: new handlers must either route through
an existing door or be added here deliberately.
"""

from __future__ import annotations

import ast
import re

from ..core import Checker, Module, Violation

# endpoints exempt from admission: no data path / must not be shed
_ALLOWLIST = {"do_OPTIONS", "rpc_health", "rpc_stats", "rpc_metrics"}

# calls that count as "reached the admission layer" per front door
_S3_DOORS = {"_begin", "_admit_qos"}
_ACCESS_DOORS = {"admit", "put", "get", "delete"}

# functions allowed to call .admit( directly (the choke points)
_ADMIT_SANCTUMS = {"_admit_qos", "put", "get", "delete", "admit"}

_S3_HANDLER = re.compile(r"^do_[A-Z]+$")


def _called_names(fn_node: ast.AST) -> set[str]:
    """Bare/attribute call names appearing anywhere in a function body
    (nested defs included — a handler may admit inside a closure)."""
    names: set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                names.add(f.attr)
            elif isinstance(f, ast.Name):
                names.add(f.id)
    return names


class AdmissionDisciplineChecker(Checker):
    rule = "admission-discipline"
    dirs = ("cubefs_tpu/fs/objectnode.py", "cubefs_tpu/blob/access.py")

    def check(self, mod: Module) -> list[Violation]:
        out: list[Violation] = []
        is_s3 = mod.relpath.endswith("objectnode.py")

        def handler_kind(name: str) -> str | None:
            if is_s3 and _S3_HANDLER.match(name):
                return "s3"
            if not is_s3 and name.startswith("rpc_"):
                return "access"
            return None

        def visit(node: ast.AST, fn: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                kind = handler_kind(node.name)
                if kind and node.name not in _ALLOWLIST:
                    doors = _S3_DOORS if kind == "s3" else _ACCESS_DOORS
                    if not (_called_names(node) & doors):
                        out.append(self.violation(
                            mod, "CFQ001", node,
                            f"external-facing handler `{node.name}` never "
                            f"reaches QoS admission — route through "
                            f"{', '.join(sorted(doors))} or allowlist it "
                            f"as a health endpoint"))
                fn = node.name
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "admit" \
                    and fn not in _ADMIT_SANCTUMS:
                out.append(self.violation(
                    mod, "CFQ002", node,
                    f".admit() in `{fn or '<module>'}` is a second "
                    f"admission choke point — each front door admits "
                    f"exactly once ({', '.join(sorted(_ADMIT_SANCTUMS))})"))
            for child in ast.iter_child_nodes(node):
                visit(child, fn)

        visit(mod.tree, "")
        return out
