"""Verified-read fencing checker (rule: integrity-discipline, CFI0xx).

The silent-corruption defense rests on every at-rest payload read in
the fs and blob planes flowing through the verifying helpers —
`extent_store.verified_read` and `chunkstore.verified_get_shard` —
which CRC-check the bytes, count detections, and let the read-repair
path heal the bad copy. A raw `store.read()` / `store.get_shard()`
outside the store modules hands corrupt bytes straight to a caller
(or worse, to a repair writer) with no detection and no heal.

  CFI001  `.get_shard()` called on anything but the node's own wrapper
          outside the store modules — use
          `chunkstore.verified_get_shard`
  CFI002  `.read()` called on a store-named receiver outside the store
          modules — use `extent_store.verified_read`

Like the other discipline families the analysis is syntactic. CFI002
keys on the receiver NAME (`store`, `_store`, `extent_store`, ...)
because `.read()` is too common a method to flag unconditionally;
CFI001 flags every `.get_shard()` attribute call (the name is unique
to chunkstores) except `self.get_shard(...)`, a node's own verified
wrapper dispatching for its RPC surface.
"""

from __future__ import annotations

import ast

from ..core import Checker, Module, Violation

# the store modules themselves: raw reads live here, under the CRC
# checks that make the verified helpers verified
_SANCTIONED = {
    "cubefs_tpu/fs/extent_store.py",
    "cubefs_tpu/blob/chunkstore.py",
}

# receiver names that denote an at-rest store
_STORE_NAMES = {"store", "_store", "extent_store", "chunkstore", "es"}


def _terminal_name(func: ast.Attribute) -> str | None:
    """`X.read` -> "X", `self.X.read` -> "X", `a.b.read` -> "b"."""
    v = func.value
    if isinstance(v, ast.Name):
        return v.id
    if isinstance(v, ast.Attribute):
        return v.attr
    return None


class IntegrityDisciplineChecker(Checker):
    rule = "integrity-discipline"
    dirs = ("cubefs_tpu/fs/", "cubefs_tpu/blob/")

    def check(self, mod: Module) -> list[Violation]:
        if mod.relpath in _SANCTIONED:
            return []
        out: list[Violation] = []
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            recv = _terminal_name(node.func)
            if node.func.attr == "get_shard" and recv != "self":
                out.append(self.violation(
                    mod, "CFI001", node,
                    f"raw `{recv}.get_shard()` bypasses the CRC check — "
                    f"at-rest shard reads must flow through "
                    f"chunkstore.verified_get_shard (detection + "
                    f"read-repair accounting live there)"))
            elif node.func.attr == "read" and recv in _STORE_NAMES:
                out.append(self.violation(
                    mod, "CFI002", node,
                    f"raw `{recv}.read()` bypasses the CRC check — "
                    f"at-rest extent reads must flow through "
                    f"extent_store.verified_read (detection + "
                    f"read-repair accounting live there)"))
        return out
