"""Retry-discipline checker (rule: retry-discipline, codes CFB0xx).

PR 3 replaced the ad-hoc ``sleep(0.05)``/``sleep(0.1)``/3-attempt loops
in the RPC failover paths with ONE ``utils.retry.RetryPolicy`` (capped
backoff + jitter + budget + deadline, metered through utils.metrics).
This family keeps new code from regressing to bare sleeps:

  CFB001  time.sleep inside an except handler of an unbounded retry
          loop (``while True``-style) with no deadline/budget evidence
          — the loop can spin forever; route it through RetryPolicy
  CFB002  direct time.sleep in a function that handles RPC failover
          errors (RpcError / ServiceUnavailable / NotLeaderError /
          FsError) — backoff in failover paths belongs to RetryPolicy,
          which bounds it and exports retry counts

"Deadline/budget evidence" that exempts a ``while True`` loop: a
``.tick(...)`` call (the Retrier API), or a comparison against a
deadline-ish name (``deadline``/``end``/``until``/``remaining``) or the
wall clock (time.time/time.monotonic). ``for _ in range(n)`` loops are
budget-bounded by construction. Pacing loops whose sleep sits at loop
level (heartbeats, pollers) are NOT flagged — only sleep-on-failure.

utils/retry.py itself is exempt: its Clock.sleep IS the one sanctioned
sleep everything else must route through.
"""

from __future__ import annotations

import ast

from ..core import Checker, Module, Violation

_RPC_ERROR_NAMES = {"RpcError", "ServiceUnavailable", "NotLeaderError",
                    "FsError"}
_DEADLINE_NAME_HINTS = ("deadline", "end", "until", "remaining", "due")
_CLOCK_CALLS = {"time.time", "time.monotonic", "time.perf_counter"}
_EXEMPT = {"cubefs_tpu/utils/retry.py"}


def _dotted(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_time_sleep(call: ast.Call, mod: Module) -> bool:
    dotted = _dotted(call.func)
    if not dotted:
        return False
    if "." in dotted:
        head, tail = dotted.split(".", 1)
        return tail == "sleep" and mod.import_aliases.get(head) == "time"
    return mod.from_imports.get(dotted) == "time.sleep"


def _walk_no_funcs(node: ast.AST):
    """Descend without crossing into nested function/class bodies."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


def _const_true(test: ast.AST) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _mentions_deadline(node: ast.AST, mod: Module) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and any(
                h in sub.id.lower() for h in _DEADLINE_NAME_HINTS):
            return True
        if isinstance(sub, ast.Call) and _dotted(sub.func) in _CLOCK_CALLS:
            return True
    return False


def _loop_is_bounded(loop: ast.While, mod: Module) -> bool:
    """Deadline/budget evidence anywhere in the loop (test or body)."""
    if not _const_true(loop.test):
        return True  # a real condition: assume the author bounds it
    for node in _walk_no_funcs(loop):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "tick"):
            return True  # Retrier.tick: RetryPolicy governs this loop
        if isinstance(node, ast.Compare) and _mentions_deadline(node, mod):
            return True
    return False


def _handler_types(handler: ast.ExceptHandler) -> set[str]:
    names: set[str] = set()
    if handler.type is None:
        return names
    for sub in ast.walk(handler.type):
        d = _dotted(sub)
        if d:
            names.add(d.split(".")[-1])
    return names


class RetryDisciplineChecker(Checker):
    rule = "retry-discipline"
    dirs = ("cubefs_tpu/",)

    def applies(self, relpath: str) -> bool:
        return super().applies(relpath) and relpath not in _EXEMPT

    def check(self, mod: Module) -> list[Violation]:
        out: list[Violation] = []
        out += self._check_unbounded_loops(mod)
        out += self._check_failover_sleeps(mod)
        return out

    # -- CFB001 --
    def _check_unbounded_loops(self, mod: Module) -> list[Violation]:
        out = []
        for loop in ast.walk(mod.tree):
            if not isinstance(loop, ast.While):
                continue
            if _loop_is_bounded(loop, mod):
                continue
            for node in _walk_no_funcs(loop):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                for sub in node.body:
                    for call in ast.walk(sub):
                        if (isinstance(call, ast.Call)
                                and _is_time_sleep(call, mod)):
                            out.append(self.violation(
                                mod, "CFB001", call,
                                "time.sleep in an unbounded retry loop "
                                "(no deadline/budget): start a "
                                "utils.retry.RetryPolicy Retrier and "
                                "gate the retry on r.tick(...)"))
        return out

    # -- CFB002 --
    def _check_failover_sleeps(self, mod: Module) -> list[Violation]:
        out = []
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            handles_rpc = any(
                isinstance(node, ast.ExceptHandler)
                and _handler_types(node) & _RPC_ERROR_NAMES
                for node in _walk_no_funcs(fn))
            if not handles_rpc:
                continue
            for node in _walk_no_funcs(fn):
                if isinstance(node, ast.Call) and _is_time_sleep(node, mod):
                    out.append(self.violation(
                        mod, "CFB002", node,
                        f"direct time.sleep in RPC failover path "
                        f"'{fn.name}': backoff belongs to "
                        f"utils.retry.RetryPolicy (bounded, metered)"))
        return out
