"""Placement-discipline checker (rule: placement-discipline, CFZ0xx).

blob/topology.py is the single authority for failure-domain-aware
disk selection: every "which disk is least loaded" decision must go
through its selectors (``order_by_load`` / ``place_volume`` /
``pick_destination``) so AZ/rack/host constraints are never silently
dropped by an ad-hoc sort. The regression shape this catches is a
quick ``min(disks, key=lambda d: d.chunk_count)`` added to a blob-plane
module — correct-looking, load-balanced, and completely blind to the
volume's failure domains:

  CFZ001  sorted()/min()/max()/.sort() over disk load fields
          (.chunk_count / .free_chunks) outside blob/topology.py

The analysis is syntactic: any of those call forms whose source
segment mentions a load field is flagged. Plain arithmetic on the
fields (skew thresholds, deltas) is not a selection and is not
flagged. topology.py itself is exempt — it is where the sorts belong.
"""

from __future__ import annotations

import ast

from ..core import Checker, Module, Violation

_LOAD_FIELDS = (".chunk_count", ".free_chunks")
_EXEMPT = ("cubefs_tpu/blob/topology.py",)


class PlacementDisciplineChecker(Checker):
    rule = "placement-discipline"
    dirs = ("cubefs_tpu/blob/",)

    def applies(self, relpath: str) -> bool:
        return super().applies(relpath) and relpath not in _EXEMPT

    def check(self, mod: Module) -> list[Violation]:
        out: list[Violation] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name) and func.id in ("sorted", "min",
                                                          "max"):
                what = f"{func.id}()"
            elif isinstance(func, ast.Attribute) and func.attr == "sort":
                what = ".sort()"
            else:
                continue
            seg = mod.segment(node)
            field = next((f for f in _LOAD_FIELDS if f in seg), None)
            if field is None:
                continue
            out.append(self.violation(
                mod, "CFZ001", node,
                f"{what} over disk load field `{field[1:]}` outside "
                f"blob/topology.py — route the selection through "
                f"topology.order_by_load / pick_destination so "
                f"failure-domain constraints apply"))
        return out
