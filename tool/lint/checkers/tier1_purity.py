"""Tier-1 purity checker (rule: tier1-purity, codes CFP0xx).

Tier-1 runs as `pytest -m 'not slow'` on CPU with a hard timeout; a
test module that compiles the native runtime or initializes a TPU
client AT COLLECTION TIME (module level) drags that cost/flake into
every tier-1 run — even when its tests would be deselected or skipped.
Such work belongs inside fixtures or test bodies, where skips and
marker selection still guard it:

  CFP001  module-level import of a TPU-client module (initializes or
          probes accelerator runtimes on import)
  CFP002  module-level call into the native build/load path
          (runtime.build.build()/load() compiles libcubefs_rt.so;
          ctypes.CDLL of the runtime .so loads it) at collection time
  CFP003  module-level TPU topology/client construction
          (aot_tpu.v5e_topology(), jax.devices("tpu"),
          get_topology_desc(...)) at collection time

Modules whose top-level ``pytestmark`` marks them `slow` are exempt —
they are not collected into tier-1.
"""

from __future__ import annotations

import ast

from ..core import Checker, Module, Violation

_TPU_IMPORTS = {
    "jax.experimental.topologies",
    "libtpu",
    "torch_xla",
}
_NATIVE_LOAD_FUNCS = {"load", "build"}
_NATIVE_MODULE_HINTS = {"build", "rt_build", "_build"}
_TOPOLOGY_FUNCS = {"v5e_topology", "get_topology_desc"}


def _dotted(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _marked_slow(tree: ast.Module) -> bool:
    """True when top-level pytestmark includes pytest.mark.slow."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if "pytestmark" in targets:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Attribute) and sub.attr == "slow":
                        return True
    return False


def _walk_module_level(tree: ast.Module):
    """Every node reached at import time: descends into if/try/with
    bodies (those run on import) but never into function/class bodies."""
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class Tier1PurityChecker(Checker):
    rule = "tier1-purity"
    dirs = ("tests/",)

    def check(self, mod: Module) -> list[Violation]:
        if _marked_slow(mod.tree):
            return []
        out: list[Violation] = []
        for node in _walk_module_level(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in _TPU_IMPORTS:
                        out.append(self.violation(
                            mod, "CFP001", node,
                            f"module-level import of TPU-client module "
                            f"'{a.name}' runs at collection time; import "
                            f"inside the fixture/test that needs it"))
                continue
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module in _TPU_IMPORTS or any(
                        f"{node.module}.{a.name}" in _TPU_IMPORTS
                        for a in node.names):
                    out.append(self.violation(
                        mod, "CFP001", node,
                        f"module-level import from TPU-client module "
                        f"'{node.module}' runs at collection time"))
                continue
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            tail = dotted.split(".")[-1]
            head = dotted.split(".")[0] if "." in dotted else ""
            if tail in _NATIVE_LOAD_FUNCS and head in _NATIVE_MODULE_HINTS:
                out.append(self.violation(
                    mod, "CFP002", node,
                    f"{dotted}() at module level compiles/loads "
                    f"libcubefs_rt.so at collection time; move it into "
                    f"a fixture so skips still guard it"))
            elif tail == "CDLL" and any(
                    isinstance(a, ast.Constant)
                    and isinstance(a.value, str)
                    and "libcubefs_rt" in a.value for a in node.args):
                out.append(self.violation(
                    mod, "CFP002", node,
                    "ctypes.CDLL of libcubefs_rt.so at collection time"))
            elif tail in _TOPOLOGY_FUNCS:
                out.append(self.violation(
                    mod, "CFP003", node,
                    f"{dotted}() at module level constructs a TPU "
                    f"client at collection time"))
            elif (tail == "devices" and head == "jax" and node.args
                  and isinstance(node.args[0], ast.Constant)
                  and node.args[0].value == "tpu"):
                out.append(self.violation(
                    mod, "CFP003", node,
                    'jax.devices("tpu") at module level probes the TPU '
                    "runtime at collection time"))
        return out
