"""Batch-discipline checker (rule: batch-discipline, codes CFC0xx).

codec/batcher.py is the single admission surface for device math: it
coalesces concurrent stripes into device-sized steps, meters occupancy
and admission wait, applies bounded-queue backpressure, and keeps the
CUBEFS_CODEC_BATCH A/B door honest. A blob-plane module that grabs a
raw engine handle and dispatches on it silently opts its stripes out of
all of that — each call is its own device step, invisible to the codec
metrics and to backpressure. The regression shape:

  CFC001  blob-plane import of the raw engine layer (codec.engine /
          get_engine / engine_for) — holding a raw handle is how the
          bypass starts
  CFC002  .encode_parity() / .matrix_apply() dispatched on a receiver
          that is not the admitted facade — blob code must call these
          on an ``admit()``-returned handle (held as ``.codec`` by
          convention) or through BatchCodec.submit_*
  CFC003  raw sub-shard reconstruction (msr_repair_rows /
          msr_reconstruct_rows / msr_helper_rows / msr_verify_rows /
          msr_repair_shard) outside blob/worker.py — the worker is the
          single orchestrator of MSR repair: it owns helper election,
          the pre-writeback verify, the conventional fallback, and the
          repair-traffic metrics; a second call-site forks that
          protocol (helpers serve opaque coefficient rows over
          read_subshard, they never build repair matrices themselves)
  CFC004  ad-hoc XOR-program construction (XorProgFenceChecker, below)
          outside ops/xorprog.py — bitmatrix expansion and schedule
          compilation are fenced there so every leg replays ONE cached,
          CSE'd, digest-stamped schedule; a second expansion site can
          silently disagree with the compiled program

The analysis is syntactic. The admitted receiver convention is a final
attribute/name of ``codec`` (``self.codec``, ``enc.codec``) or an
obvious batcher handle (``batcher``/``admitted``); anything else that
dispatches device math from cubefs_tpu/blob/ is flagged. codemode /
encoder config imports are fine — only the engine layer is fenced.
"""

from __future__ import annotations

import ast

from ..core import Checker, Module, Violation

# names whose import from the codec package hands out raw engine access
_ENGINE_NAMES = {"get_engine", "engine_for", "Engine", "NumpyEngine",
                 "CppEngine", "TpuEngine"}
# receiver final names allowed to dispatch device math in the blob plane
_ADMITTED_RECV = {"codec", "batcher", "admitted"}
_DEVICE_CALLS = {"encode_parity", "matrix_apply"}
# MSR repair-protocol primitives: row construction + one-shot repair.
# Only blob/worker.py may call these (CFC003).
_MSR_CALLS = {"msr_repair_rows", "msr_reconstruct_rows", "msr_helper_rows",
              "msr_verify_rows", "msr_repair_shard"}
_MSR_SANCTIONED = "cubefs_tpu/blob/worker.py"


def _final_name(node: ast.AST) -> str:
    """`self.codec` -> 'codec'; `eng` -> 'eng'."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


class BatchDisciplineChecker(Checker):
    rule = "batch-discipline"
    dirs = ("cubefs_tpu/blob/",)

    def check(self, mod: Module) -> list[Violation]:
        out: list[Violation] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if "codec.engine" in a.name:
                        out.append(self.violation(
                            mod, "CFC001", node,
                            f"import of `{a.name}` from the blob plane — "
                            f"raw engine handles bypass the codec "
                            f"admission surface (codec/batcher.py)"))
            elif isinstance(node, ast.ImportFrom):
                modname = node.module or ""
                if modname.endswith("codec.engine"):
                    out.append(self.violation(
                        mod, "CFC001", node,
                        "import from codec.engine in the blob plane — "
                        "route device math through codec.batcher.admit() "
                        "so stripes coalesce, meter, and backpressure"))
                elif modname.endswith("codec") or ".codec." in modname \
                        or modname == "codec":
                    for a in node.names:
                        if a.name == "engine" or a.name in _ENGINE_NAMES:
                            out.append(self.violation(
                                mod, "CFC001", node,
                                f"import of `{a.name}` from the codec "
                                f"package in the blob plane — raw engine "
                                f"access bypasses the admission surface"))
            elif isinstance(node, ast.Call):
                func = node.func
                called = (func.attr if isinstance(func, ast.Attribute)
                          else func.id if isinstance(func, ast.Name) else "")
                if (called in _MSR_CALLS
                        and mod.relpath != _MSR_SANCTIONED):
                    out.append(self.violation(
                        mod, "CFC003", node,
                        f"`{called}()` outside {_MSR_SANCTIONED} — "
                        f"sub-shard reconstruction is the repair worker's "
                        f"protocol (helper election, pre-writeback verify, "
                        f"conventional fallback, traffic metrics); helpers "
                        f"only apply opaque coefficient rows via "
                        f"read_subshard"))
                if (isinstance(func, ast.Attribute)
                        and func.attr in _DEVICE_CALLS
                        and _final_name(func.value) not in _ADMITTED_RECV):
                    recv = _final_name(func.value) or mod.segment(func.value)
                    out.append(self.violation(
                        mod, "CFC002", node,
                        f".{func.attr}() on raw receiver `{recv}` — blob "
                        f"code must dispatch device math through the "
                        f"admitted facade (codec.batcher.admit(), held "
                        f"as `.codec`) so submissions coalesce into "
                        f"device-sized steps"))
        return out


# names whose call (or import) means "I am expanding GF(256) rows into
# GF(2) bitmatrices / building an XOR schedule by hand"
_XORPROG_NAMES = {"gf_matrix_to_bits", "coeff_bitmatrix", "XorProgram"}
_XORPROG_HOME = "cubefs_tpu/ops/xorprog.py"


class XorProgFenceChecker(Checker):
    """CFC004: XOR-program construction is fenced to ops/xorprog.py.

    The scheduled-XOR path (ops/xorprog.py) owns the bitmatrix
    expansion, the CSE pass, and the slot layout shared with the native
    executor; blob- and codec-plane modules consume compiled programs
    via ``xorprog.apply`` / ``xorprog.program_for`` only. A second
    expansion site (calling ``gf_matrix_to_bits`` on coefficient rows,
    or constructing ``XorProgram`` ad hoc) forks the schedule contract:
    it bypasses the program cache, the schedule digest the chaos drill
    replays, and the bit-identity guarantee the compiled program
    carries. Note rs_kernel.py (ops plane, device bit-matmul) also uses
    gf_matrix_to_bits legitimately — only blob/ and codec/ are fenced.
    """

    rule = "batch-discipline"
    dirs = ("cubefs_tpu/blob/", "cubefs_tpu/codec/")

    def check(self, mod: Module) -> list[Violation]:
        out: list[Violation] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name in _XORPROG_NAMES:
                        out.append(self.violation(
                            mod, "CFC004", node,
                            f"import of `{a.name}` outside "
                            f"{_XORPROG_HOME} — XOR schedules are "
                            f"compiled there; consume them via "
                            f"xorprog.apply()/program_for()"))
            elif isinstance(node, ast.Call):
                func = node.func
                called = (func.attr if isinstance(func, ast.Attribute)
                          else func.id if isinstance(func, ast.Name) else "")
                if called in _XORPROG_NAMES:
                    out.append(self.violation(
                        mod, "CFC004", node,
                        f"`{called}()` outside {_XORPROG_HOME} — ad-hoc "
                        f"bitmatrix expansion forks the compiled-schedule "
                        f"contract (program cache, schedule digest, "
                        f"bit-identity); call xorprog.apply() or "
                        f"xorprog.program_for() instead"))
        return out
