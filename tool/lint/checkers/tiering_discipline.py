"""Cold-tier fencing checker (rule: tiering-discipline, CFD0xx).

The crash-safety story of the cold tier rests on ONE invariant: every
blob-plane operation the fs plane performs goes through
`cubefs_tpu/fs/tiering.py` (TieringEngine). That module is where the
two-phase state machine lives — generation fencing, CRC verification
before hot-extent release, and deferred blob deletion. A second code
path that puts/gets/deletes blobs from the fs plane directly (the old
lcnode `_transition` shape: read -> put -> truncate) silently bypasses
all three and reintroduces the lost-bytes / leaked-blob windows the
state machine closed.

  CFD001  a blob-plane import (`cubefs_tpu.blob.*` / `..blob.*`)
          anywhere in the fs plane outside the sanctioned bridge
  CFD002  `.put()` / `.get()` / `.delete()` called on a blob-client
          receiver (a name like `blob`, `blob_access`, `blob_client`)
          outside the sanctioned bridge

Like the other discipline families the analysis is syntactic: CFD002
keys on the receiver NAME, so a blob client smuggled through an
innocuous variable name escapes it — CFD001 (the import) is the
backstop, since the client class has to come from somewhere.
"""

from __future__ import annotations

import ast

from ..core import Checker, Module, Violation

# the ONE module allowed to talk to the blob plane from the fs plane
_SANCTIONED = "cubefs_tpu/fs/tiering.py"

# receiver names that denote a blob client (self.X attribute or bare)
_BLOB_NAMES = {"blob", "blob_access", "blob_client", "_blob"}

_BLOB_OPS = {"put", "get", "delete"}


def _receiver_name(func: ast.Attribute) -> str | None:
    """`X.put` -> "X", `self.X.put` -> "X", else None."""
    v = func.value
    if isinstance(v, ast.Name):
        return v.id
    if isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name) \
            and v.value.id == "self":
        return v.attr
    return None


class TieringDisciplineChecker(Checker):
    rule = "tiering-discipline"
    dirs = ("cubefs_tpu/fs/",)

    def check(self, mod: Module) -> list[Violation]:
        if mod.relpath == _SANCTIONED:
            return []
        out: list[Violation] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith("cubefs_tpu.blob"):
                        out.append(self.violation(
                            mod, "CFD001", node,
                            f"blob-plane import `{a.name}` in the fs "
                            f"plane — only {_SANCTIONED} may cross the "
                            f"fs->blob bridge (fencing + verify + "
                            f"deferred delete live there)"))
            elif isinstance(node, ast.ImportFrom):
                m = node.module or ""
                # absolute `cubefs_tpu.blob...` or relative `..blob...`
                # (level >= 2 from cubefs_tpu/fs/* resolves to the pkg root)
                if m.startswith("cubefs_tpu.blob") or (
                        node.level >= 2
                        and (m == "blob" or m.startswith("blob."))):
                    out.append(self.violation(
                        mod, "CFD001", node,
                        f"blob-plane import `{'.' * node.level}{m}` in "
                        f"the fs plane — route through {_SANCTIONED}"))
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _BLOB_OPS:
                recv = _receiver_name(node.func)
                if recv in _BLOB_NAMES:
                    out.append(self.violation(
                        mod, "CFD002", node,
                        f"direct blob-plane `{recv}.{node.func.attr}()` "
                        f"in the fs plane bypasses the tiering state "
                        f"machine (no gen fence, no CRC verify, no "
                        f"deferred delete) — use TieringEngine"))
        return out
