"""Checker registry: every family the suite ships, in report order."""

from .admission_discipline import AdmissionDisciplineChecker
from .batch_discipline import BatchDisciplineChecker
from .fanout_discipline import FanoutDisciplineChecker
from .fs_placement import FsPlacementChecker
from .integrity_discipline import IntegrityDisciplineChecker
from .lock_discipline import LockDisciplineChecker
from .placement_discipline import PlacementDisciplineChecker
from .retry_discipline import RetryDisciplineChecker
from .rpc_idempotency import RpcIdempotencyChecker
from .tier1_purity import Tier1PurityChecker
from .tiering_discipline import TieringDisciplineChecker
from .tracer_safety import TraceClockChecker, TracerSafetyChecker

ALL_CHECKERS = (
    TracerSafetyChecker,
    TraceClockChecker,
    LockDisciplineChecker,
    RpcIdempotencyChecker,
    RetryDisciplineChecker,
    Tier1PurityChecker,
    PlacementDisciplineChecker,
    FsPlacementChecker,
    BatchDisciplineChecker,
    FanoutDisciplineChecker,
    AdmissionDisciplineChecker,
    TieringDisciplineChecker,
    IntegrityDisciplineChecker,
)
