"""Checker registry: every family the suite ships, in report order."""

from .admission_discipline import AdmissionDisciplineChecker
from .batch_discipline import BatchDisciplineChecker, XorProgFenceChecker
from .fanout_discipline import FanoutDisciplineChecker
from .fs_placement import FsPlacementChecker
from .fsm_purity import FsmPurityChecker
from .geo_discipline import GeoDisciplineChecker
from .integrity_discipline import IntegrityDisciplineChecker
from .lock_discipline import LockDisciplineChecker
from .lock_graph import LockGraphChecker
from .placement_discipline import PlacementDisciplineChecker
from .retry_discipline import RetryDisciplineChecker
from .rpc_idempotency import RpcIdempotencyChecker
from .split_discipline import SplitDisciplineChecker
from .tier1_purity import Tier1PurityChecker
from .tiering_discipline import TieringDisciplineChecker
from .tracer_safety import TraceClockChecker, TracerSafetyChecker
from .wire_discipline import WireDisciplineChecker
from .witness_discipline import WitnessDisciplineChecker

ALL_CHECKERS = (
    TracerSafetyChecker,
    TraceClockChecker,
    LockDisciplineChecker,
    RpcIdempotencyChecker,
    RetryDisciplineChecker,
    Tier1PurityChecker,
    PlacementDisciplineChecker,
    FsPlacementChecker,
    BatchDisciplineChecker,
    XorProgFenceChecker,
    FanoutDisciplineChecker,
    AdmissionDisciplineChecker,
    TieringDisciplineChecker,
    IntegrityDisciplineChecker,
    WitnessDisciplineChecker,
    WireDisciplineChecker,
    GeoDisciplineChecker,
    SplitDisciplineChecker,
)

# Checkers that need the whole-program graph (tool/lint/graph.py); the
# cli runs them once over the linked project, not per module.
PROJECT_CHECKERS = (
    LockGraphChecker,
    FsmPurityChecker,
)
