"""Lock-discipline checker (rule: lock-discipline, codes CFL0xx).

The raft-heartbeat regression shape: a blocking or native-plane call
made while LEXICALLY inside a ``with <lock>:`` block. Python-plane
locks here guard hot paths (raft node lock, partition lock, pool
locks); anything that can block — a sleep, a network round-trip, a
ctypes call that takes a C++ mutex — stalls every thread queued on
that lock for the full duration:

  CFL001  time.sleep() while holding a lock
  CFL002  blocking RPC / socket call while holding a lock
          (rpc.call / rpc.call_replicas / pool.get(...).call(...) /
          socket.create_connection)
  CFL003  native-plane ctypes call (lib.ms_* / cfs_* / cs_* / ds_* /
          es_* / kv_*) while holding a Python lock — these take the
          C++ side's mutex (often exclusively) and block its readers

The analysis is syntactic: a lock is "held" inside the body of a
``with`` whose context expression's final name looks lock-ish
(…lock/…mutex/…mu). Calls inside nested function definitions are NOT
flagged (the closure may run after release); callbacks invoked under a
lock must be audited at their definition site.
"""

from __future__ import annotations

import ast
import re

from ..core import Checker, Module, Violation

_LOCK_NAME_RE = re.compile(r"(?:^|_)(?:lock|locks?|mu|mutex)$", re.IGNORECASE)
_NATIVE_PREFIX_RE = re.compile(r"^(?:ms|cfs|cs|ds|es|kv|bp|gf|rt)_")
_LIBLIKE_RE = re.compile(r"(?:^|_)lib$|^lib|_lib\b")


def _dotted(node: ast.AST) -> str:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _final_name(node: ast.AST) -> str:
    """`self._wal_mu` -> '_wal_mu'; `vlock` -> 'vlock'."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_lockish(expr: ast.AST) -> bool:
    name = _final_name(expr)
    return bool(name) and (_LOCK_NAME_RE.search(name) is not None
                           or "lock" in name.lower())


class LockDisciplineChecker(Checker):
    rule = "lock-discipline"
    dirs = ("cubefs_tpu/fs/", "cubefs_tpu/blob/", "cubefs_tpu/parallel/")

    def check(self, mod: Module) -> list[Violation]:
        out: list[Violation] = []
        # resolve aliases of the time module ("import time as _t")
        time_aliases = {alias for alias, full in mod.import_aliases.items()
                        if full == "time"}
        time_aliases.add("time")
        sleep_names = {alias for alias, full in mod.from_imports.items()
                       if full == "time.sleep"}
        rpc_aliases = {alias for alias, full in mod.import_aliases.items()
                       if full.endswith("rpc")} | {"rpc"}

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.With):
                continue
            lock_names = [_final_name(item.context_expr)
                          for item in node.items
                          if _is_lockish(item.context_expr)]
            if not lock_names:
                continue
            held = lock_names[0]
            for stmt in node.body:
                out.extend(self._scan(mod, stmt, held, time_aliases,
                                      sleep_names, rpc_aliases))
        return out

    def _scan(self, mod: Module, root: ast.AST, held: str,
              time_aliases: set[str], sleep_names: set[str],
              rpc_aliases: set[str]) -> list[Violation]:
        out: list[Violation] = []
        for node in _walk_no_nested_defs(root):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            dotted = _dotted(func)
            # CFL001: time.sleep under lock
            if (dotted.endswith(".sleep")
                    and dotted.rsplit(".", 1)[0].split(".")[-1] in time_aliases) \
                    or (isinstance(func, ast.Name) and func.id in sleep_names):
                out.append(self.violation(
                    mod, "CFL001", node,
                    f"time.sleep() while holding `{held}` stalls every "
                    f"thread queued on the lock"))
                continue
            if isinstance(func, ast.Attribute):
                attr = func.attr
                # CFL003: ctypes native-plane call under a Python lock
                if (_NATIVE_PREFIX_RE.match(attr)
                        and _LIBLIKE_RE.search(_final_name(func.value) or "")):
                    out.append(self.violation(
                        mod, "CFL003", node,
                        f"native-plane call {attr}() while holding "
                        f"`{held}` — it takes the C++ mutex and blocks "
                        f"native readers for the lock's hold time"))
                    continue
                # CFL002: blocking RPC / socket call under lock
                recv_src = mod.segment(func.value)
                if attr == "call" and (".get(" in recv_src
                                       or "get_direct(" in recv_src
                                       or _dotted(func.value).split(".")[-1]
                                       in rpc_aliases):
                    out.append(self.violation(
                        mod, "CFL002", node,
                        f"blocking RPC .call() while holding `{held}`"))
                    continue
                if (attr in ("call", "call_replicas")
                        and _dotted(func.value) in rpc_aliases):
                    out.append(self.violation(
                        mod, "CFL002", node,
                        f"blocking rpc.{attr}() while holding `{held}`"))
                    continue
                if dotted.endswith("socket.create_connection") or (
                        attr == "create_connection"
                        and _dotted(func.value).split(".")[-1] == "socket"):
                    out.append(self.violation(
                        mod, "CFL002", node,
                        f"socket connect while holding `{held}`"))
        return out


def _walk_no_nested_defs(root: ast.AST):
    """ast.walk, but do not descend into nested function/class bodies —
    a closure defined under a lock is not necessarily CALLED under it."""
    stack = [root]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
